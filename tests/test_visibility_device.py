"""Device-visibility tier (ISSUE 12): columnar scan vs host parity.

The contract under test (engine/visibility_device.py + ops/scan.py):

- PARITY: for every query the device path serves, the result-id set
  (and for pages, the exact sequence + resume token) must equal the
  host `VisibilityStore` evaluation — fuzzed over random queries (all
  six ops, AND/OR nesting, custom search attributes, numeric + string
  values) and randomized record sets. Queries the kernels can't express
  fall back to the host and are COUNTED (never silently divergent).
- FRESHNESS: writes enqueue column deltas; a query flushes the backlog
  when it exceeds the staleness bound (and records the backlog it saw),
  or serves the stale view inside the bound.
- LIFECYCLE: capacity growth restages, attr columns past the budget or
  type-poisoned fall back, the kill switch routes straight to the host,
  and the admin rollup + tpu.visibility series surface all of it.
"""
import random

import pytest

from cadence_tpu.engine import visibility_device as vd
from cadence_tpu.engine.persistence import (
    VisibilityRecord,
    VisibilityStore,
)
from cadence_tpu.engine.visibility_query import (
    compile_query_with_hints,
    parse_query,
)
from cadence_tpu.utils import metrics as m

DOMAIN = "d-test"


@pytest.fixture
def vis_env(monkeypatch):
    monkeypatch.setenv("CADENCE_TPU_VISIBILITY", "1")
    monkeypatch.setenv("CADENCE_TPU_VISIBILITY_PARITY", "1")
    # a wide appender window: tests drive drains deterministically
    # through the query-path flush, never by racing the thread
    monkeypatch.setenv("CADENCE_TPU_VISIBILITY_WAIT_US", "5000000")
    yield


def _mk_record(rng: random.Random, i: int, attr_pool) -> VisibilityRecord:
    attrs = {}
    for name, kind in attr_pool:
        r = rng.random()
        if r < 0.4:
            continue  # absent on this record
        if kind == "num":
            attrs[name] = (rng.randrange(-5, 15) if rng.random() < 0.7
                           else round(rng.uniform(-2, 8), 2))
        elif kind == "str":
            attrs[name] = f"v{rng.randrange(6)}"
        else:  # mixed: poisons the device column, host handles per-row
            attrs[name] = (rng.randrange(4) if rng.random() < 0.5
                           else f"m{rng.randrange(3)}")
    rec = VisibilityRecord(
        domain_id=DOMAIN, workflow_id=f"wf-{i}", run_id=f"run-{i}",
        workflow_type=f"type-{rng.randrange(5)}",
        start_time=rng.randrange(0, 50) * 1_000 + rng.randrange(3),
        search_attrs=attrs)
    return rec


def _seed_store(rng: random.Random, n: int, attr_pool) -> VisibilityStore:
    store = VisibilityStore()
    for i in range(n):
        store.record_started(_mk_record(rng, i, attr_pool))
        if rng.random() < 0.45:
            store.record_closed(DOMAIN, f"wf-{i}", f"run-{i}",
                                close_time=rng.randrange(1, 10**6),
                                close_status=rng.randrange(0, 6))
    return store


_FIELDS = ("WorkflowID", "WorkflowType", "RunID", "CloseStatus",
           "StartTime", "CloseTime", "Num", "Str", "Mixed", "Absent")
_OPS = ("=", "!=", "<", "<=", ">", ">=")


def _rand_value(rng: random.Random, field: str):
    r = rng.random()
    if field == "WorkflowType" and r < 0.6:
        return f"'type-{rng.randrange(6)}'"
    if field in ("WorkflowID", "RunID") and r < 0.6:
        kind = "wf" if field == "WorkflowID" else "run"
        return f"'{kind}-{rng.randrange(40)}'"
    if field == "CloseStatus" and r < 0.4:
        return rng.choice(["'Completed'", "'Failed'", "-1", "0", "5"])
    if field == "Str" and r < 0.7:
        return f"'v{rng.randrange(8)}'"
    if r < 0.25:
        return f"'s{rng.randrange(4)}'"  # cross-type string
    if r < 0.5:
        return str(round(rng.uniform(-3, 12), 2))  # float
    if r < 0.6:
        return str(rng.randrange(0, 50) * 1_000)  # start-time-shaped
    return str(rng.randrange(-5, 15))


def _rand_query(rng: random.Random, depth: int = 2) -> str:
    if depth <= 0 or rng.random() < 0.45:
        field = rng.choice(_FIELDS)
        return f"{field} {rng.choice(_OPS)} {_rand_value(rng, field)}"
    left = _rand_query(rng, depth - 1)
    right = _rand_query(rng, depth - 1)
    joiner = "AND" if rng.random() < 0.5 else "OR"
    q = f"{left} {joiner} {right}"
    return f"({q})" if rng.random() < 0.3 else q


def _host_truth(store: VisibilityStore, query: str):
    """Ground truth WITHOUT the device tier: the compiled predicate
    over the raw record map (no index planner, no device)."""
    pred, _ = compile_query_with_hints(query)
    with store._lock:
        return {(r.workflow_id, r.run_id)
                for r in store._records.values()
                if r.domain_id == DOMAIN and pred(r)}


class TestFuzzParity:
    """The acceptance fuzz: random queries over random record sets must
    return identical result-id sets from the host predicate path and
    the device mask path — fallbacks counted, divergence pinned at 0."""

    ATTR_POOL = (("Num", "num"), ("Str", "str"), ("Mixed", "mixed"))

    @pytest.mark.parametrize("seed", [11, 23])
    def test_random_queries_identical_id_sets(self, vis_env, seed):
        rng = random.Random(seed)
        store = _seed_store(rng, 150, self.ATTR_POOL)
        reg = m.DEFAULT_REGISTRY
        queries = 0
        # shape pool: a bounded set of structures reused with fresh
        # values, so the run also proves variant-cache reuse
        shapes = [_rand_query(rng) for _ in range(18)]
        corpus = shapes + [_rand_query(rng) for _ in range(12)]
        for q in corpus:
            try:
                parse_query(q)
            except Exception:
                continue
            device_ids = {(r.workflow_id, r.run_id)
                          for r in store.query(DOMAIN, q)}
            assert device_ids == _host_truth(store, q), q
            queries += 1
            assert store.count(DOMAIN, q) == len(device_ids), q
        assert queries >= 25
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_DIVERGENCE) == 0
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_PARITY_CHECKS) > 0
        # the Mixed attr column poisons → those queries are COUNTED
        # fallbacks, not silent divergence
        served = reg.counter(m.SCOPE_TPU_VISIBILITY,
                             m.M_VIS_DEVICE_SERVED)
        fallbacks = reg.counter(m.SCOPE_TPU_VISIBILITY,
                                m.M_VIS_HOST_FALLBACKS)
        assert served > 0
        assert served + fallbacks >= 2 * queries
        store._device.stop()

    def test_string_ordering_falls_back_counted(self, vis_env):
        store = _seed_store(random.Random(5), 40, self.ATTR_POOL)
        reg = m.DEFAULT_REGISTRY
        ids = {(r.workflow_id, r.run_id)
               for r in store.query(DOMAIN, "WorkflowType > 'type-2'")}
        assert ids == _host_truth(store, "WorkflowType > 'type-2'")
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_FALLBACK_PREDICATE) >= 1
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_DIVERGENCE) == 0
        store._device.stop()


class TestIncrementalAppends:
    def test_writes_visible_through_device_path(self, vis_env):
        store = VisibilityStore()
        assert store.query(DOMAIN, "") == []  # empty store, staged view
        rec = VisibilityRecord(DOMAIN, "wf-a", "r-1", "order", 100)
        store.record_started(rec)
        assert store.count(DOMAIN, "CloseStatus = -1") == 1
        store.record_closed(DOMAIN, "wf-a", "r-1", close_time=200,
                            close_status=0)
        assert store.count(DOMAIN, "CloseStatus = -1") == 0
        assert store.count(DOMAIN, "CloseStatus = 0") == 1
        store.upsert_search_attributes(DOMAIN, "wf-a", "r-1",
                                       {"Priority": 7})
        assert [r.workflow_id
                for r in store.query(DOMAIN, "Priority >= 7")] == ["wf-a"]
        store.delete_record(DOMAIN, "wf-a", "r-1")
        assert store.count(DOMAIN, "") == 0
        assert m.DEFAULT_REGISTRY.counter(m.SCOPE_TPU_VISIBILITY,
                                          m.M_VIS_DIVERGENCE) == 0
        store._device.stop()

    def test_nan_attr_value_poisons_column(self, vis_env):
        """A NaN VALUE would alias the float column's null sentinel
        (host: nan != 3 matches; a device presence guard would drop
        the row) — the column must poison and fall back, counted."""
        store = VisibilityStore()
        store.record_started(VisibilityRecord(
            DOMAIN, "w0", "r0", "t", 1,
            search_attrs={"P": float("nan")}))
        store.record_started(VisibilityRecord(
            DOMAIN, "w1", "r1", "t", 2, search_attrs={"P": 3.0}))
        for q in ("P != 3", "P = 3", "P > 1"):
            got = {(r.workflow_id, r.run_id)
                   for r in store.query(DOMAIN, q)}
            assert got == _host_truth(store, q), q
        reg = m.DEFAULT_REGISTRY
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_FALLBACK_COLUMN) >= 1
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_DIVERGENCE) == 0
        assert not store._device._quarantined
        store._device.stop()

    def test_deleted_rows_are_reused(self, vis_env):
        """Churn (retention deletes + new starts) must not grow the
        table: freed rows go back to the pool."""
        store = VisibilityStore()
        for i in range(8):
            store.record_started(VisibilityRecord(
                DOMAIN, f"w{i}", f"r{i}", "t", i))
        assert store.count(DOMAIN, "") == 8
        view = store._device
        high_water = view._rows
        for i in range(4):
            store.delete_record(DOMAIN, f"w{i}", f"r{i}")
        for i in range(8, 12):
            store.record_started(VisibilityRecord(
                DOMAIN, f"w{i}", f"r{i}", "t", i))
        assert store.count(DOMAIN, "") == 8
        assert {r.workflow_id for r in store.query(DOMAIN, "")} == \
            {f"w{i}" for i in range(4, 12)}
        assert view._rows == high_water  # reused, not appended
        assert m.DEFAULT_REGISTRY.counter(m.SCOPE_TPU_VISIBILITY,
                                          m.M_VIS_DIVERGENCE) == 0
        view.stop()

    def test_capacity_growth_restages(self, vis_env, monkeypatch):
        monkeypatch.setenv("CADENCE_TPU_VISIBILITY_CAPACITY", "64")
        store = _seed_store(random.Random(3), 300,
                            (("Num", "num"),))
        assert store.count(DOMAIN, "") == 300
        view = store._device
        assert view.capacity >= 300
        assert store.count(DOMAIN, "CloseStatus = -1") == \
            len(_host_truth(store, "CloseStatus = -1"))
        assert m.DEFAULT_REGISTRY.counter(m.SCOPE_TPU_VISIBILITY,
                                          m.M_VIS_DIVERGENCE) == 0
        view.stop()

    def test_attr_named_like_builtin_never_aliases(self, vis_env):
        """A search attribute literally named "domain"/"start_time"
        must get its own prefixed device column — it can never alias
        the builtin column it shadows by name."""
        store = VisibilityStore()
        for i in range(30):
            store.record_started(VisibilityRecord(
                DOMAIN, f"w{i}", f"r{i}", "t", start_time=100 + i,
                search_attrs={"domain": i, "start_time": f"s{i % 3}"}))
        for q in ("domain > 15", "start_time = 's1'", "StartTime > 110",
                  "domain > 15 AND StartTime > 110"):
            got = {(r.workflow_id, r.run_id) for r in store.query(DOMAIN, q)}
            assert got == _host_truth(store, q), q
        assert m.DEFAULT_REGISTRY.counter(m.SCOPE_TPU_VISIBILITY,
                                          m.M_VIS_DIVERGENCE) == 0
        store._device.stop()

    def test_attr_budget_overflow_falls_back(self, vis_env, monkeypatch):
        monkeypatch.setenv("CADENCE_TPU_VISIBILITY_ATTR_COLUMNS", "2")
        store = VisibilityStore()
        for i in range(6):
            store.record_started(VisibilityRecord(
                DOMAIN, f"wf-{i}", f"r-{i}", "t", i,
                search_attrs={"A": i, "B": i * 2, "C": f"c{i}"}))
        # A and B claim the two columns; C overflows → host fallback
        assert store.count(DOMAIN, "A >= 3") == 3
        reg = m.DEFAULT_REGISTRY
        pre = reg.counter(m.SCOPE_TPU_VISIBILITY, m.M_VIS_FALLBACK_COLUMN)
        ids = {r.workflow_id for r in store.query(DOMAIN, "C = 'c2'")}
        assert ids == {"wf-2"}
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_FALLBACK_COLUMN) == pre + 1
        assert reg.counter(m.SCOPE_TPU_VISIBILITY, m.M_VIS_DIVERGENCE) == 0
        store._device.stop()

    def test_attr_budget_lfu_replacement_stops_permanent_fallback(
            self, vis_env, monkeypatch):
        """ISSUE 15 satellite: a repeatedly-queried over-budget attr
        out-demands the least-queried column and takes its slot — the
        fallback is transient, not permanent. The swap is counted under
        tpu.visibility/attr-column-replacements, the promoted column
        backfills the values already staged, and parity stays clean
        (the evicted column now falls back instead)."""
        monkeypatch.setenv("CADENCE_TPU_VISIBILITY_ATTR_COLUMNS", "2")
        store = VisibilityStore()
        for i in range(6):
            store.record_started(VisibilityRecord(
                DOMAIN, f"wf-{i}", f"r-{i}", "t", i,
                search_attrs={"A": i, "B": i * 2, "C": f"c{i}"}))
        reg = m.DEFAULT_REGISTRY
        # A earns use; B never queried; C (overflowed) accrues demand
        assert store.count(DOMAIN, "A >= 3") == 3
        assert {r.workflow_id for r in store.query(DOMAIN, "C = 'c2'")} \
            == {"wf-2"}  # fallback #1: demand C=1 > use B=0
        pre_swaps = reg.counter(m.SCOPE_TPU_VISIBILITY,
                                m.M_VIS_ATTR_REPLACEMENTS)
        pre_fb = reg.counter(m.SCOPE_TPU_VISIBILITY,
                             m.M_VIS_FALLBACK_COLUMN)
        # the next query triggers the swap (B evicted, C admitted with
        # backfill) and serves from the DEVICE
        assert {r.workflow_id for r in store.query(DOMAIN, "C = 'c4'")} \
            == {"wf-4"}
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_ATTR_REPLACEMENTS) == pre_swaps + 1
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_FALLBACK_COLUMN) == pre_fb
        view = store._device
        assert set(view._attr_cols) == {"A", "C"}
        assert "B" in view._overflow_attrs
        # the evicted column's use became its comeback demand, and
        # LATE WRITES to the promoted column keep applying
        store.record_started(VisibilityRecord(
            DOMAIN, "wf-9", "r-9", "t", 9, search_attrs={"C": "c9"}))
        assert {r.workflow_id for r in store.query(DOMAIN, "C = 'c9'")} \
            == {"wf-9"}
        # B now falls back (transiently, until it out-demands someone)
        assert {r.workflow_id for r in store.query(DOMAIN, "B = 4")} \
            == {"wf-2"}
        assert reg.counter(m.SCOPE_TPU_VISIBILITY, m.M_VIS_DIVERGENCE) == 0
        assert view.stats()["attr_overflow_demand"].get("B", 0) >= 1
        view.stop()


class TestStaleness:
    def test_bound_zero_flushes_before_serving(self, vis_env):
        store = VisibilityStore()
        store.record_started(VisibilityRecord(DOMAIN, "w0", "r0", "t", 1))
        assert store.count(DOMAIN, "") == 1
        view = store._device
        # writes queue behind the (wide) appender window...
        for i in range(1, 9):
            store.record_started(VisibilityRecord(DOMAIN, f"w{i}",
                                                  f"r{i}", "t", i))
        # ...and the next query flushes them inline (bound 0)
        assert store.count(DOMAIN, "") == 9
        assert view.staleness_max >= 1
        assert m.DEFAULT_REGISTRY.counter(m.SCOPE_TPU_VISIBILITY,
                                          m.M_VIS_DIVERGENCE) == 0
        view.stop()

    def test_bounded_staleness_serves_stale_then_flushes(self, vis_env,
                                                         monkeypatch):
        monkeypatch.setenv("CADENCE_TPU_VISIBILITY_STALENESS", "100")
        store = VisibilityStore()
        store.record_started(VisibilityRecord(DOMAIN, "w0", "r0", "t", 1))
        assert store.count(DOMAIN, "") == 1  # attaches + drains
        view = store._device
        store.record_started(VisibilityRecord(DOMAIN, "w1", "r1", "t", 2))
        # inside the bound: the device view may lag (served without a
        # flush; parity is skipped because the views differ by design)
        stale = store.count(DOMAIN, "")
        assert stale in (1, 2)  # 2 only if the appender raced the query
        view.flush()
        assert store.count(DOMAIN, "") == 2
        assert m.DEFAULT_REGISTRY.counter(m.SCOPE_TPU_VISIBILITY,
                                          m.M_VIS_DIVERGENCE) == 0
        view.stop()


class TestPagination:
    def _walk(self, store, query: str, page_size: int):
        out, token, pages = [], None, 0
        while True:
            recs, token = store.query_page(DOMAIN, query, page_size,
                                           token)
            out.extend((r.workflow_id, r.run_id) for r in recs)
            pages += 1
            if token is None or pages > 100:
                return out, pages

    def test_page_walk_identical_to_host(self, vis_env, monkeypatch):
        rng = random.Random(9)
        store = _seed_store(rng, 120, (("Num", "num"),))
        dev_walk, _ = self._walk(store, "CloseStatus = -1", 7)
        store._device.stop()
        monkeypatch.setenv("CADENCE_TPU_VISIBILITY", "0")
        host_walk, _ = self._walk(store, "CloseStatus = -1", 7)
        assert dev_walk == host_walk
        assert m.DEFAULT_REGISTRY.counter(m.SCOPE_TPU_VISIBILITY,
                                          m.M_VIS_DIVERGENCE) == 0

    def test_start_time_ties_escalate_to_bitmap(self, vis_env,
                                                monkeypatch):
        # 200 records ALL sharing one start_time: the device argsort
        # cannot resolve the (workflow_id, run_id) tie order past the
        # top-k boundary — the page path must escalate, and the walk
        # must still be byte-identical to the host
        store = VisibilityStore()
        for i in range(200):
            store.record_started(VisibilityRecord(
                DOMAIN, f"wf-{i:03d}", f"r-{i:03d}", "t", 777))
        reg = m.DEFAULT_REGISTRY
        dev_walk, pages = self._walk(store, "", 10)
        assert pages >= 20
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_TOPK_ESCALATIONS) > 0
        store._device.stop()
        monkeypatch.setenv("CADENCE_TPU_VISIBILITY", "0")
        host_walk, _ = self._walk(store, "", 10)
        assert dev_walk == host_walk
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_DIVERGENCE) == 0

    def test_topk_fast_path_serves_distinct_times(self, vis_env):
        store = VisibilityStore()
        for i in range(300):
            store.record_started(VisibilityRecord(
                DOMAIN, f"wf-{i:03d}", f"r-{i:03d}", "t", 1000 + i))
        reg = m.DEFAULT_REGISTRY
        recs, token = store.query_page(DOMAIN, "", 10, None)
        assert [r.start_time for r in recs] == list(
            range(1299, 1289, -1))
        assert token is not None
        assert reg.counter(m.SCOPE_TPU_VISIBILITY, m.M_VIS_TOPK) >= 1
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_DIVERGENCE) == 0
        store._device.stop()


class TestRoutingAndOps:
    def test_kill_switch_routes_host(self, vis_env, monkeypatch):
        store = _seed_store(random.Random(2), 30, ())
        assert store.count(DOMAIN, "") == 30
        view = store._device
        reg = m.DEFAULT_REGISTRY
        served = reg.counter(m.SCOPE_TPU_VISIBILITY, m.M_VIS_DEVICE_SERVED)
        monkeypatch.setenv("CADENCE_TPU_VISIBILITY", "0")
        assert store.count(DOMAIN, "") == 30
        assert reg.counter(m.SCOPE_TPU_VISIBILITY,
                           m.M_VIS_DEVICE_SERVED) == served
        view.stop()

    def test_onebox_frontend_and_admin_rollup(self, vis_env):
        from cadence_tpu.engine.admin import AdminHandler
        from cadence_tpu.engine.onebox import Onebox

        box = Onebox(num_hosts=1, num_shards=2)
        box.frontend.register_domain("vis-box")
        box.frontend.start_workflow_execution("vis-box", "wf-1", "order",
                                              "tl")
        box.pump_once()
        recs = box.frontend.list_workflow_executions(
            "vis-box", "WorkflowType = 'order'")
        assert [r.workflow_id for r in recs] == ["wf-1"]
        assert box.frontend.count_workflow_executions(
            "vis-box", "CloseStatus = -1") == 1
        rollup = AdminHandler(box).visibility()
        assert rollup["enabled"] and rollup["attached"]
        assert rollup["parity_divergence"] == 0
        assert rollup["device_served"] >= 1
        assert rollup["rows"] >= 1
        # the series ride the box registry, prometheus-exposable
        body = box.metrics.to_prometheus()
        assert "tpu.visibility" in str(box.metrics.snapshot()) or body
        view = box.stores.visibility._device
        assert view is not None
        view.stop()

    def test_query_heavy_loadgen_ops(self, vis_env):
        """QUERY_HEAVY_MIX drives list/scan/count through the open-loop
        generator against a live box with the device tier on: per-op
        loadgen scopes populated, zero divergence, zero errors."""
        from cadence_tpu.engine.onebox import Onebox
        from cadence_tpu.loadgen.generator import LoadGenerator
        from cadence_tpu.loadgen.mixes import (
            QUERY_HEAVY_MIX,
            VIS_OPS,
            DomainPlan,
            build_schedule,
            trace_digest,
        )

        plans = [DomainPlan("lg-q", 24.0, mix=QUERY_HEAVY_MIX,
                            pool_size=3)]
        schedule = build_schedule(plans, 1.5, seed=42)
        assert trace_digest(schedule) == trace_digest(
            build_schedule(plans, 1.5, seed=42))
        vis_ops = [op for op in schedule if op.kind in VIS_OPS]
        assert vis_ops and all(op.arg for op in vis_ops)
        box = Onebox(num_hosts=1, num_shards=2)
        gen = LoadGenerator([box.frontend], schedule, plans, workers=4,
                            pump=box.pump_once)
        gen.prepare(setup_deadline_s=60.0)
        load = gen.run()
        t = load.totals()
        assert t.errors == 0, load.as_dict()
        sent_vis = sum(load.stats[(k, "lg-q")].sent
                       for k in ("list", "scan", "count")
                       if (k, "lg-q") in load.stats)
        assert sent_vis > 0
        reg = box.metrics
        assert reg.counter(m.SCOPE_TPU_VISIBILITY, m.M_VIS_DIVERGENCE) == 0
        assert reg.counter(m.SCOPE_TPU_VISIBILITY, m.M_VIS_QUERIES) > 0
        view = box.stores.visibility._device
        if view is not None:
            view.stop()
