"""Live HBM state migration across the host cluster (ISSUE 13).

Covers: the out→in migration round trip (losing host snapshots its
resident rows through the shared store, gaining host hydrates + replays
only the appended suffix, payloads byte-identical to the oracle); cold
steals and stale snapshots counted and never served; closed workflows
skipped; hydration parity divergence detected, dropped, counted; the
ShardController's release/acquire membership hooks; fenced-engine
eviction under a ring flap (a deposed host that re-acquires must never
serve the stale shard context); and the routing drift guard pinning the
host-shard and device-shard hash paths against golden values.
"""
import numpy as np
import pytest

from cadence_tpu.core.checksum import (
    DEFAULT_LAYOUT,
    STICKY_ROW_INDEX,
    payload_row,
)
from cadence_tpu.engine.cache import batch_crc
from cadence_tpu.engine.membership import HashRing, shard_id_for_workflow
from cadence_tpu.engine.migration import InReport, MigrationManager
from cadence_tpu.engine.persistence import Stores
from cadence_tpu.engine.tpu_engine import TPUReplayEngine
from cadence_tpu.gen.corpus import generate_corpus
from cadence_tpu.oracle.mutable_state import MutableState
from cadence_tpu.oracle.state_builder import StateBuilder
from cadence_tpu.parallel.mesh import workflow_shard
from cadence_tpu.utils import metrics as m

NUM_SHARDS = 4


# ---------------------------------------------------------------------------
# routing drift guard: the two shard hash paths pinned against goldens
# ---------------------------------------------------------------------------


class TestRoutingDriftGuard:
    """Host-side routing (membership.shard_id_for_workflow — the ring's
    unit of shard movement) and device-side placement
    (parallel/mesh.workflow_shard — the resident pool's device axis) are
    DIFFERENT hash functions over different inputs by design; each is
    pinned against golden values so neither can silently change under a
    refactor. Every persisted snapshot, resident slice, and frontend
    route keys on one of these — a drifted hash after an upgrade would
    scatter ownership and orphan every pinned state."""

    #: workflow_id → shard over (1, 4, 8, 16, 1024) host shards
    HOST_GOLDENS = {
        "wf-0": [0, 1, 1, 9, 361],
        "wf-1": [0, 3, 3, 11, 875],
        "order-12345": [0, 2, 2, 10, 42],
        "lg-victim-pool-3": [0, 2, 6, 14, 590],
        "a": [0, 1, 5, 5, 117],
        "": [0, 0, 4, 12, 636],
    }
    #: (domain, workflow, run) key → mesh position over (1, 2, 4, 8)
    DEVICE_GOLDENS = {
        ("d", "wf-0", "r1"): [0, 1, 1, 5],
        ("dom", "order-12345", "run-7"): [0, 1, 1, 5],
        ("d2", "lg-victim-pool-3", "r"): [0, 1, 3, 3],
    }

    def test_host_shard_goldens(self):
        for wf, expected in self.HOST_GOLDENS.items():
            got = [shard_id_for_workflow(wf, n)
                   for n in (1, 4, 8, 16, 1024)]
            assert got == expected, (wf, got, expected)

    def test_device_shard_goldens(self):
        for key, expected in self.DEVICE_GOLDENS.items():
            got = [workflow_shard(key, n) for n in (1, 2, 4, 8)]
            assert got == expected, (key, got, expected)

    def test_hash_paths_are_intentionally_distinct(self):
        """The two paths must not be conflated BY CODE either: host
        routing hashes the workflow id alone (a workflow's every run
        lands on one host shard), device placement hashes the full run
        key (runs spread across the mesh)."""
        a = ("d", "wf-0", "r1")
        b = ("d", "wf-0", "r2")
        assert shard_id_for_workflow(a[1], 1024) \
            == shard_id_for_workflow(b[1], 1024)
        spread = {workflow_shard(("d", "wf-0", f"r{i}"), 8)
                  for i in range(64)}
        assert len(spread) > 1  # runs do NOT pin to one mesh position


# ---------------------------------------------------------------------------
# the migration round trip
# ---------------------------------------------------------------------------


def _seed_open(stores, n=4, target_events=30, drop_tail=2, seed=7):
    """Open (still-running) workflows: full histories generated, only a
    prefix appended — the dropped tail is the live suffix later tests
    append. Returns (keys, tails)."""
    hists = generate_corpus("basic", num_workflows=n, seed=seed,
                            target_events=target_events)
    keys, tails = [], {}
    for h in hists:
        b0 = h[0]
        key = (b0.domain_id, b0.workflow_id, b0.run_id)
        kept = h[:len(h) - drop_tail]
        tails[key] = h[len(kept):]
        for b in kept:
            stores.history.append_batch(*key, list(b.events))
        _refresh_oracle(stores, key)
        keys.append(key)
    return keys, tails


def _refresh_oracle(stores, key):
    ms = StateBuilder().replay_history(
        stores.history.as_history_batches(*key))
    info = ms.execution_info
    info.domain_id, info.workflow_id, info.run_id = key
    stores.execution.upsert_workflow(ms)


def _oracle_row(stores, key, layout=DEFAULT_LAYOUT):
    row = payload_row(stores.execution.get_workflow(*key), layout)
    row[STICKY_ROW_INDEX] = 0
    return row


class TestMigrationRoundTrip:
    def test_out_then_hydrate_exact_byte_parity(self):
        """Planned rebalance with no traffic in between: the gaining
        host hydrates every row at the snapshot point — zero suffix
        events, payloads byte-identical to the oracle."""
        stores = Stores()
        keys, _tails = _seed_open(stores)
        loser = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        assert loser.verify_all().ok
        out = MigrationManager("h-a", NUM_SHARDS, loser).migrate_out(
            range(NUM_SHARDS), evict=True)
        assert out.snapshotted == len(keys) and out.skipped == 0
        assert len(loser.resident) == 0  # moved state never served here

        gainer = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        rep = MigrationManager("h-b", NUM_SHARDS, gainer).hydrate_shards(
            range(NUM_SHARDS))
        assert rep.hydrated == len(keys)
        assert rep.suffix_events == 0 and rep.cold == 0 and rep.stale == 0
        assert rep.parity_divergence == 0
        for key in keys:
            entry = gainer.resident.entry_for(key)
            assert entry is not None
            assert (np.asarray(entry.payload)
                    == _oracle_row(stores, key)).all()

    def test_hydrate_replays_only_the_appended_suffix(self):
        """A commit lands between snapshot and steal: hydration seeds at
        the snapshot point and replays ONLY the new batches (the
        O(suffix) contract), still byte-identical to the oracle."""
        stores = Stores()
        keys, tails = _seed_open(stores)
        loser = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        assert loser.verify_all().ok
        MigrationManager("h-a", NUM_SHARDS, loser).migrate_out(
            range(NUM_SHARDS))
        for key in keys:
            stores.history.append_batch(*key,
                                        list(tails[key][0].events))
            _refresh_oracle(stores, key)
        gainer = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        rep = MigrationManager("h-b", NUM_SHARDS, gainer).hydrate_shards(
            range(NUM_SHARDS))
        assert rep.hydrated == len(keys) and rep.parity_divergence == 0
        assert rep.suffix_events > 0
        for key in keys:
            assert (np.asarray(gainer.resident.entry_for(key).payload)
                    == _oracle_row(stores, key)).all()
        # the hydrated pool serves the next verify as resident hits
        r = gainer.verify_all()
        assert r.ok and len(r.resident) == len(keys)

    def test_cold_steal_and_stale_snapshot_counted(self):
        """No record → cold steal; a record whose bytes were rewritten
        under it (tail overwrite past the store's derived invalidation
        window) → stale, never served."""
        stores = Stores()
        keys, tails = _seed_open(stores, n=3)
        loser = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        assert loser.verify_all().ok
        mgr = MigrationManager("h-a", NUM_SHARDS, loser)
        mgr.migrate_out(range(NUM_SHARDS))
        # key 0: drop its record entirely → cold steal
        stores.snapshot.drop(keys[0])
        # key 1: doctor the stored record's address so it no longer
        # prefixes the stored bytes (the store's own derived
        # invalidation would catch a real overwrite; this pins the
        # hydration-side CRC check too)
        rec = stores.snapshot.get(keys[1])
        rec.last_batch_crc ^= 0x5A5A5A5A
        gainer = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        rep = MigrationManager("h-b", NUM_SHARDS, gainer).hydrate_shards(
            range(NUM_SHARDS))
        assert rep.cold == 1
        assert rep.stale == 1
        assert rep.hydrated == 1
        assert gainer.resident.entry_for(keys[0]) is None
        assert gainer.resident.entry_for(keys[1]) is None

    def test_closed_workflows_skipped(self):
        stores = Stores()
        hists = generate_corpus("basic", num_workflows=2, seed=9,
                                target_events=24)
        for h in hists:
            b0 = h[0]
            key = (b0.domain_id, b0.workflow_id, b0.run_id)
            for b in h:
                stores.history.append_batch(*key, list(b.events))
            _refresh_oracle(stores, key)
        loser = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        assert loser.verify_all().ok
        MigrationManager("h-a", NUM_SHARDS, loser).migrate_out(
            range(NUM_SHARDS))
        gainer = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        rep = MigrationManager("h-b", NUM_SHARDS, gainer).hydrate_shards(
            range(NUM_SHARDS))
        assert rep.skipped_closed == 2 and rep.hydrated == 0

    def test_hydration_parity_divergence_dropped_and_counted(self):
        """A snapshot that disagrees with the oracle over a STABLE store
        (doctored payload bytes) must be detected at hydration, dropped,
        and counted — never pinned."""
        stores = Stores()
        keys, _tails = _seed_open(stores, n=1)
        key = keys[0]
        loser = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        assert loser.verify_all().ok
        MigrationManager("h-a", NUM_SHARDS, loser).migrate_out(
            range(NUM_SHARDS))
        rec = stores.snapshot.get(key)
        rec.payload = np.array(rec.payload, copy=True)
        rec.payload[3] += 1  # a lie the blob CRC does not cover
        gainer = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        reg = gainer.metrics
        pre = reg.counter(m.SCOPE_TPU_MIGRATION, m.M_MIG_DIVERGENCE)
        rep = MigrationManager("h-b", NUM_SHARDS, gainer).hydrate_shards(
            range(NUM_SHARDS))
        assert rep.parity_divergence == 1 and rep.hydrated == 0
        assert reg.counter(m.SCOPE_TPU_MIGRATION,
                           m.M_MIG_DIVERGENCE) == pre + 1
        assert gainer.resident.entry_for(key) is None

    def test_shard_scoped_out_migration(self):
        """migrate_out touches ONLY the moving shards' rows; the rest
        stay resident and serving."""
        stores = Stores()
        keys, _tails = _seed_open(stores, n=6, seed=11)
        tpu = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        assert tpu.verify_all().ok
        mgr = MigrationManager("h-a", NUM_SHARDS, tpu)
        by_shard = {}
        for key in keys:
            by_shard.setdefault(mgr.shard_of(key), []).append(key)
        moved = sorted(by_shard)[0]
        mgr.migrate_out([moved], evict=True)
        for key in keys:
            entry = tpu.resident.entry_for(key)
            if mgr.shard_of(key) == moved:
                assert entry is None
                assert stores.snapshot.get(key) is not None
            else:
                assert entry is not None

    def test_background_hook_hydrates_and_drains(self):
        """shards_acquired is the controller hook: background thread,
        coalesced queue, drain() settles it."""
        stores = Stores()
        keys, _tails = _seed_open(stores, n=2, seed=13)
        loser = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        assert loser.verify_all().ok
        MigrationManager("h-a", NUM_SHARDS, loser).migrate_out(
            range(NUM_SHARDS))
        gainer = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        mgr = MigrationManager("h-b", NUM_SHARDS, gainer)
        mgr.shards_acquired(range(NUM_SHARDS))
        assert mgr.drain(timeout=120.0)
        assert mgr.last_in.hydrated == len(keys)

    def test_kill_switch_disables_both_directions(self, monkeypatch):
        monkeypatch.setenv("CADENCE_TPU_MIGRATION", "0")
        stores = Stores()
        keys, _tails = _seed_open(stores, n=1, seed=15)
        tpu = TPUReplayEngine(stores, DEFAULT_LAYOUT)
        assert tpu.verify_all().ok
        mgr = MigrationManager("h-a", NUM_SHARDS, tpu)
        out = mgr.shards_released(list(range(NUM_SHARDS)))
        assert out.snapshotted == 0 and len(stores.snapshot) == 0
        assert tpu.resident.entry_for(keys[0]) is not None
        mgr.shards_acquired(range(NUM_SHARDS))
        assert mgr.drain(timeout=10.0)
        assert mgr.last_in.considered == 0


# ---------------------------------------------------------------------------
# controller membership hooks + fenced-engine eviction under a ring flap
# ---------------------------------------------------------------------------


class TestControllerHooks:
    def _controller(self, host, ring, stores):
        from cadence_tpu.engine.controller import ShardController
        from cadence_tpu.utils.clock import ManualTimeSource
        return ShardController(host, NUM_SHARDS, stores, ring,
                               ManualTimeSource())

    def test_release_and_acquire_hooks_fire(self):
        stores = Stores()
        ring = HashRing(["h-a"])
        ctrl = self._controller("h-a", ring, stores)
        released, acquired = [], []
        ctrl.on_shards_released = released.extend
        ctrl.on_shards_acquired = acquired.extend
        ctrl.ensure_assigned()
        assert sorted(acquired) == list(range(NUM_SHARDS))
        acquired.clear()
        ring.add_member("h-b")  # rebalance: some shards move away
        stolen = [s for s in range(NUM_SHARDS)
                  if ring.lookup(f"shard-{s}") == "h-b"]
        assert stolen, "ring never moved a shard (degenerate test)"
        assert sorted(released) == sorted(stolen)
        ring.remove_member("h-b")  # flap back: the shards return
        assert sorted(acquired) == sorted(stolen)

    def test_hook_failure_never_blocks_convergence(self):
        stores = Stores()
        ring = HashRing(["h-a"])
        ctrl = self._controller("h-a", ring, stores)

        def boom(_ids):
            raise RuntimeError("migration exploded")

        ctrl.on_shards_released = boom
        ctrl.on_shards_acquired = boom
        ring.add_member("h-b")
        ring.remove_member("h-b")
        assert sorted(ctrl.owned_shards()) == list(range(NUM_SHARDS))

    def test_fenced_engine_evicted_on_reacquire_after_flap(self):
        """The deposed-owner fencing probe, exercised DIRECTLY at the
        controller (previously only through cluster tests): host A's
        cached engine is fenced by a usurper while A is partitioned;
        when the ring flaps A's shard back, engine_for_shard must evict
        the stale (closed) context and build a fresh engine on a fresh
        range — never serve the deposed one."""
        from cadence_tpu.engine.persistence import ShardOwnershipLostError
        from cadence_tpu.engine.shard import ShardContext

        from cadence_tpu.engine.persistence import DomainInfo

        stores = Stores()
        stores.domain.register(DomainInfo(domain_id="mig-d", name="mig-d"))
        ring = HashRing(["h-a"])
        ctrl = self._controller("h-a", ring, stores)
        wf = "wf-flap"
        sid = ctrl.shard_for(wf)
        engine = ctrl.engine_for_shard(sid)
        old_range = engine.shard.range_id
        engine.start_workflow("mig-d", wf, "t", "tl")

        # partition: the ring drops h-a (it does not notice — the
        # listener fires, but the cached engine object is what a stale
        # in-flight request would still hold); a usurper bumps the range
        ring.add_member("usurper")
        usurper_ctx = ShardContext(sid, "usurper", stores)
        usurper_ctx.acquire()

        # the deposed context is fenced at the store on its next write
        with pytest.raises(ShardOwnershipLostError):
            engine.signal_workflow("mig-d", wf, "stale-probe")
        assert engine.shard.is_closed

        # flap: the shard comes back to h-a — the controller must NOT
        # hand out the fenced engine it still caches
        ring.remove_member("usurper")
        fresh = ctrl.engine_for_shard(sid)
        assert fresh is not engine
        assert not fresh.shard.is_closed
        assert fresh.shard.range_id > old_range
        fresh.signal_workflow("mig-d", wf, "post-flap")  # serves again


class TestShardExecutionIndex:
    """ISSUE 15 satellite: migration hydration is O(stolen keys) via the
    store's per-shard execution index — never a `list_executions` walk
    per steal."""

    def _seed(self, n=24, num_shards=8):
        from cadence_tpu.engine.membership import shard_id_for_workflow
        stores = Stores()
        expected = {}
        for i in range(n):
            wf = f"idx-wf-{i}"
            ms = MutableState()
            ms.execution_info.domain_id = "idx-d"
            ms.execution_info.workflow_id = wf
            ms.execution_info.run_id = f"r-{i}"
            stores.execution.upsert_workflow(ms)
            expected.setdefault(
                shard_id_for_workflow(wf, num_shards), set()).add(
                    ("idx-d", wf, f"r-{i}"))
        return stores, expected

    def test_index_matches_filter_and_stays_incremental(self):
        stores, expected = self._seed()
        for shard, keys in expected.items():
            got = stores.execution.list_executions_for_shards([shard], 8)
            assert set(got) == keys
            assert got == sorted(got)
        # incremental maintenance: writes and deletes after the build
        from cadence_tpu.engine.membership import shard_id_for_workflow
        ms = MutableState()
        ms.execution_info.domain_id = "idx-d"
        ms.execution_info.workflow_id = "idx-new"
        ms.execution_info.run_id = "r-new"
        stores.execution.upsert_workflow(ms)
        s = shard_id_for_workflow("idx-new", 8)
        assert ("idx-d", "idx-new", "r-new") in \
            stores.execution.list_executions_for_shards([s], 8)
        victim = next(iter(expected[s])) if expected.get(s) else None
        if victim is not None:
            stores.execution.delete_workflow(*victim)
            assert victim not in \
                stores.execution.list_executions_for_shards([s], 8)

    def test_access_pattern_pinned_no_full_walk_after_build(self):
        """The regression pin: once a shard space's index is built,
        reads never touch the full execution table again — a steal's
        hydration cost is the stolen buckets, not the fleet."""
        stores, expected = self._seed()
        stores.execution.list_executions_for_shards([0], 8)  # build

        class _Boom(dict):
            def keys(self):
                raise AssertionError("full-table walk after index build")
            def __iter__(self):
                raise AssertionError("full-table walk after index build")

        real = stores.execution._executions
        stores.execution._executions = _Boom(real)
        try:
            for shard in range(8):
                got = stores.execution.list_executions_for_shards([shard], 8)
                assert set(got) == expected.get(shard, set())
        finally:
            stores.execution._executions = real

    def test_migration_hydration_uses_the_index(self, monkeypatch):
        """MigrationManager.hydrate_shards must read through the index
        path, not list_executions (pre-index stores keep the fallback)."""
        from cadence_tpu.engine.migration import MigrationManager
        from cadence_tpu.engine.tpu_engine import TPUReplayEngine

        stores, expected = self._seed(n=6, num_shards=4)
        tpu = TPUReplayEngine(stores, chunk_workflows=8)
        mgr = MigrationManager("h-idx", 4, tpu)

        def boom():
            raise AssertionError("hydration walked list_executions")

        monkeypatch.setattr(stores.execution, "list_executions", boom,
                            raising=False)
        report = mgr.hydrate_shards([0, 1])
        want = len(expected.get(0, ())) + len(expected.get(1, ()))
        assert report.considered == want
