"""Cross-cluster replication + streaming replay tests: the NDC tier
(host/ndc/integration_test.go analog) plus the long-context chunked path."""
import numpy as np
import pytest

from cadence_tpu.core.checksum import payload_row
from cadence_tpu.core.enums import CloseStatus, WorkflowState
from cadence_tpu.engine.multicluster import ReplicatedClusters
from cadence_tpu.models.deciders import EchoDecider, SignalDecider
from tests.taskpoller import TaskPoller

DOMAIN = "global-domain"
TL = "xdc-tasklist"


@pytest.fixture()
def clusters():
    c = ReplicatedClusters(num_hosts=1, num_shards=4)
    c.register_global_domain(DOMAIN)
    return c


def run_echo(clusters, workflow_id):
    box = clusters.active
    box.frontend.start_workflow_execution(DOMAIN, workflow_id, "echo", TL)
    poller = TaskPoller(box, DOMAIN, TL, {workflow_id: EchoDecider(TL)})
    poller.drain()
    return poller


class TestReplication:
    def test_standby_state_matches_active(self, clusters):
        run_echo(clusters, "xdc-1")
        applied = clusters.replicate()
        assert applied > 0
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, "xdc-1")
        active_ms = clusters.active.stores.execution.get_workflow(
            domain_id, "xdc-1", run_id)
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, "xdc-1", run_id)
        assert standby_ms.execution_info.close_status == CloseStatus.Completed
        assert (payload_row(active_ms) == payload_row(standby_ms)).all()
        # histories byte-equal event-for-event
        a = clusters.active.stores.history.read_events(domain_id, "xdc-1", run_id)
        s = clusters.standby.stores.history.read_events(domain_id, "xdc-1", run_id)
        assert [(e.id, e.event_type, e.version) for e in a] == \
               [(e.id, e.event_type, e.version) for e in s]

    def test_events_carry_active_failover_version(self, clusters):
        run_echo(clusters, "xdc-v")
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, "xdc-v")
        events = clusters.active.stores.history.read_events(
            domain_id, "xdc-v", run_id)
        assert all(e.version == 1 for e in events)  # primary initial version

    def test_gap_triggers_resend(self, clusters):
        """Drop mid-stream tasks: the resender must pull the missing range
        (history_resender.go:111 path)."""
        run_echo(clusters, "xdc-gap")
        # skip the first 3 replication tasks → guaranteed gap
        clusters.processor.ack_index = 3
        clusters.replicate()
        assert clusters.processor.resends >= 1
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, "xdc-gap")
        active_ms = clusters.active.stores.execution.get_workflow(
            domain_id, "xdc-gap", run_id)
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, "xdc-gap", run_id)
        assert (payload_row(active_ms) == payload_row(standby_ms)).all()

    def test_duplicate_delivery_deduped(self, clusters):
        run_echo(clusters, "xdc-dup")
        clusters.replicate()
        # replay the whole stream again (at-least-once delivery)
        clusters.processor.ack_index = 0
        clusters.replicate()
        assert clusters.processor.deduped > 0

    def test_standby_bulk_verified_on_device(self, clusters):
        """BASELINE config 5: the standby's replicated histories replay on
        device with zero divergence (the kernel as the NDC bulk-apply)."""
        for i in range(4):
            run_echo(clusters, f"xdc-bulk-{i}")
        clusters.replicate()
        result = clusters.standby.tpu.verify_all()
        assert result.total == 4
        assert result.ok and result.verified_on_device == 4

    def test_corrupt_task_goes_to_dlq(self, clusters):
        from cadence_tpu.engine.replication import ReplicationTask
        from cadence_tpu.core.codec import serialize_history
        from cadence_tpu.core.events import HistoryBatch, HistoryEvent
        from cadence_tpu.core.enums import EventType

        run_echo(clusters, "xdc-dlq")
        clusters.replicate()
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, "xdc-dlq")
        # craft a poison batch: contiguity holds but semantics are invalid
        ms = clusters.standby.stores.execution.get_workflow(
            domain_id, "xdc-dlq", run_id)
        next_id = ms.execution_info.next_event_id
        bad = HistoryBatch(domain_id=domain_id, workflow_id="xdc-dlq",
                           run_id=run_id, events=[
            HistoryEvent(id=next_id, event_type=EventType.ActivityTaskCompleted,
                         version=1, timestamp=1,
                         attrs=dict(scheduled_event_id=9999,
                                    started_event_id=9998)),
        ])
        clusters.publisher.stores.queue.enqueue(
            "replication",
            ReplicationTask(domain_id=domain_id, workflow_id="xdc-dlq",
                            run_id=run_id, first_event_id=next_id,
                            next_event_id=next_id + 1, version=1,
                            events_blob=serialize_history([bad])))
        clusters.replicate()
        dlq = clusters.processor.read_dlq()
        assert len(dlq) == 1
        assert "missing activity" in dlq[0].error


class TestFailover:
    def test_failover_continues_workflow_on_standby(self, clusters):
        """Active runs half the workflow; failover; the standby (now active)
        finishes it; event versions cross the failover boundary and the
        version history records both items."""
        box = clusters.active
        box.frontend.start_workflow_execution(DOMAIN, "xdc-fo", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"xdc-fo": SignalDecider(expected_signals=1)})
        poller.drain()
        clusters.replicate()

        new_version = clusters.failover(DOMAIN, "standby")
        assert new_version == 12  # standby initial 2 + increment 10

        sbox = clusters.standby
        spoller = TaskPoller(sbox, DOMAIN, TL,
                             {"xdc-fo": SignalDecider(expected_signals=1)})
        sbox.frontend.signal_workflow_execution(DOMAIN, "xdc-fo", "wake")
        spoller.drain()
        domain_id = sbox.stores.domain.by_name(DOMAIN).domain_id
        ms = sbox.frontend.describe_workflow_execution(DOMAIN, "xdc-fo")
        assert ms.execution_info.close_status == CloseStatus.Completed
        items = ms.version_histories.current().items
        assert [i.version for i in items] == [1, 12]

    def test_failover_with_inflight_activity(self, clusters):
        """Activity scheduled (dispatched, never started) on the active;
        after failover the promoted standby must regenerate the activity
        transfer task (RefreshTasks) so a standby-side worker can run it."""
        from cadence_tpu.models.deciders import EchoDecider
        box = clusters.active
        box.frontend.start_workflow_execution(DOMAIN, "xdc-act", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"xdc-act": EchoDecider(TL)})
        box.pump_once()                       # decision → matching
        assert poller.poll_and_decide_once()  # schedules the activity
        box.pump_once()                       # activity task → active matching
        clusters.replicate()
        clusters.failover(DOMAIN, "standby")

        sbox = clusters.standby
        spoller = TaskPoller(sbox, DOMAIN, TL, {"xdc-act": EchoDecider(TL)})
        spoller.drain()
        ms = sbox.frontend.describe_workflow_execution(DOMAIN, "xdc-act")
        assert ms.execution_info.close_status == CloseStatus.Completed
        # the activity ran exactly once, on the standby side
        events = sbox.frontend.get_workflow_execution_history(DOMAIN, "xdc-act")
        starts = [e for e in events
                  if e.event_type.name == "ActivityTaskStarted"]
        assert len(starts) == 1
        assert starts[0].version == 12  # post-failover version

    def test_failover_with_pending_user_timer(self, clusters):
        """User timer started on the active fires on the promoted standby:
        the refresher must recreate the UserTimer task in the standby's
        timer queue with the original expiry."""
        from cadence_tpu.models.deciders import TimerDecider
        box = clusters.active
        box.frontend.start_workflow_execution(DOMAIN, "xdc-timer", "timer", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"xdc-timer": TimerDecider(fire_seconds=5)})
        box.pump_once()
        assert poller.poll_and_decide_once()  # starts the 5s timer
        clusters.replicate()
        clusters.failover(DOMAIN, "standby")

        sbox = clusters.standby
        spoller = TaskPoller(sbox, DOMAIN, TL,
                             {"xdc-timer": TimerDecider(fire_seconds=5)})
        sbox.advance_time(6)
        spoller.drain()
        ms = sbox.frontend.describe_workflow_execution(DOMAIN, "xdc-timer")
        assert ms.execution_info.close_status == CloseStatus.Completed
        events = sbox.frontend.get_workflow_execution_history(DOMAIN, "xdc-timer")
        assert any(e.event_type.name == "TimerFired" for e in events)

    def test_sync_activity_replicates_transient_attempts(self, clusters):
        """Transient activity retries write no history events; the standby
        learns attempt counts and last-failure state through SyncActivity
        tasks (ndc/activity_replicator.go:77)."""
        from cadence_tpu.models.deciders import RetryActivityDecider
        box = clusters.active
        box.frontend.start_workflow_execution(DOMAIN, "xdc-sync", "retry", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"xdc-sync": RetryActivityDecider(TL)})
        box.pump_once()
        assert poller.poll_and_decide_once()
        box.pump_once()
        resp = box.frontend.poll_for_activity_task(DOMAIN, TL)
        box.frontend.respond_activity_task_failed(resp.token, "boom")
        clusters.replicate()

        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "xdc-sync")
        sms = clusters.standby.stores.execution.get_workflow(
            domain_id, "xdc-sync", run_id)
        assert len(sms.pending_activity_info_ids) == 1
        ai = next(iter(sms.pending_activity_info_ids.values()))
        assert ai.attempt == 1
        assert ai.last_failure_reason == "boom"
        # stale re-delivery of an older attempt must not regress the standby
        ams = box.stores.execution.get_workflow(domain_id, "xdc-sync", run_id)
        aai = next(iter(ams.pending_activity_info_ids.values()))
        stale_attempt = aai.attempt - 1
        from cadence_tpu.engine.replication import SyncActivityTask
        items = tuple((i.event_id, i.version)
                      for i in ams.version_histories.current().items)
        stale = SyncActivityTask(
            domain_id=domain_id, workflow_id="xdc-sync", run_id=run_id,
            version=aai.version, schedule_id=aai.schedule_id,
            scheduled_time=0, started_id=-1, started_time=0,
            last_heartbeat_time=0, attempt=stale_attempt,
            last_failure_reason="old", version_history_items=items)
        assert clusters.processor.replicator.sync_activity(stale) is False
        sms = clusters.standby.stores.execution.get_workflow(
            domain_id, "xdc-sync", run_id)
        ai = next(iter(sms.pending_activity_info_ids.values()))
        assert ai.attempt == 1 and ai.last_failure_reason == "boom"


class TestStreamingReplay:
    def test_chunked_matches_single_shot(self):
        from cadence_tpu.gen.corpus import generate_corpus
        from cadence_tpu.ops.encode import encode_corpus
        from cadence_tpu.ops.replay import replay_to_payload
        from cadence_tpu.ops.streaming import replay_streamed
        import jax.numpy as jnp

        histories = generate_corpus("basic", 8, seed=17, target_events=120)
        events = encode_corpus(histories)
        single, errs1 = replay_to_payload(jnp.asarray(events))
        for chunk in (16, 33, 120, 500):
            rows, errs = replay_streamed(events, chunk_events=chunk)
            assert (errs == 0).all()
            assert (rows == np.asarray(single)).all(), f"chunk={chunk} diverged"


def _open_signal_workflow(clusters, wf, signals=2):
    """Start `wf` on the active side and leave it OPEN with a few signals
    applied (closed runs take no device work — the applier invalidates)."""
    box = clusters.active
    box.frontend.start_workflow_execution(DOMAIN, wf, "signal", TL)
    poller = TaskPoller(box, DOMAIN, TL,
                        {wf: SignalDecider(expected_signals=99)})
    poller.drain()
    for i in range(signals):
        box.frontend.signal_workflow_execution(DOMAIN, wf, f"{wf}-s{i}")
    poller.drain()


class TestDeviceStandbyApply:
    """ISSUE 17 tentpole 1: the batch processor drains applied histories
    through the device tier; host replicator stays the sole authority."""

    def test_cold_keys_stay_host_only(self, clusters):
        """No resident entry and no shipped snapshot: the device twin
        counts the key cold and the host path remains complete."""
        from cadence_tpu.utils import metrics as m
        _open_signal_workflow(clusters, "dev-cold")
        clusters.replicate()
        scope = clusters.standby.metrics.snapshot().get(
            m.SCOPE_REPLICATION, {})
        assert scope.get(m.M_REPL_DEVICE_COLD, 0) > 0
        assert scope.get(m.M_REPL_DEVICE_APPLIED, 0) == 0
        assert scope.get(m.M_REPL_DEVICE_DIVERGENCE, 0) == 0
        # host state complete regardless
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, "dev-cold")
        a = clusters.active.stores.history.read_events(
            domain_id, "dev-cold", run_id)
        s = clusters.standby.stores.history.read_events(
            domain_id, "dev-cold", run_id)
        assert [(e.id, e.event_type) for e in a] == \
               [(e.id, e.event_type) for e in s]

    def test_kill_switch_restores_host_only_path(self, clusters,
                                                 monkeypatch):
        """CADENCE_TPU_REPL_DEVICE=0: zero device work, byte-identical
        host apply."""
        from cadence_tpu.utils import metrics as m
        monkeypatch.setenv("CADENCE_TPU_REPL_DEVICE", "0")
        _open_signal_workflow(clusters, "dev-off")
        clusters.replicate()
        scope = clusters.standby.metrics.snapshot().get(
            m.SCOPE_REPLICATION, {})
        for name in (m.M_REPL_DEVICE_APPLIED, m.M_REPL_DEVICE_COLD,
                     m.M_REPL_DEVICE_DIVERGENCE):
            assert scope.get(name, 0) == 0
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, "dev-off")
        active_ms = clusters.active.stores.execution.get_workflow(
            domain_id, "dev-off", run_id)
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, "dev-off", run_id)
        assert (payload_row(active_ms) == payload_row(standby_ms)).all()

    def test_shipped_snapshot_seeds_device_bulk_apply(self, monkeypatch):
        """Tentpole 1+2 end to end: the active's serving tier ships
        snapshot records down the stream; the standby installs them and
        subsequent drains become device suffix applies, parity-clean."""
        from cadence_tpu.engine.multicluster import ReplicatedClusters
        from cadence_tpu.utils import metrics as m
        monkeypatch.setenv("CADENCE_TPU_SNAPSHOT_MIN_EVENTS", "1")
        monkeypatch.setenv("CADENCE_TPU_SNAPSHOT_EVERY_EVENTS", "4")
        clusters = ReplicatedClusters(num_hosts=1, num_shards=4)
        clusters.active.enable_serving()
        try:
            clusters.register_global_domain(DOMAIN)
            _open_signal_workflow(clusters, "dev-bulk")
            clusters.active.serving.drain(timeout=30)
            # the deploy warm-up sweep: force past the due()-defer policy
            # (timing-dependent at this tiny scale); records still ship
            # through the same Snapshotter.shipper hook
            report = clusters.active.tpu.snapshotter().sweep(force=True)
            assert report.written > 0
            clusters.replicate()
            assert clusters.processor.snapshots_installed > 0
            # more traffic → the next drain rides the installed seed
            for i in range(3):
                clusters.active.frontend.signal_workflow_execution(
                    DOMAIN, "dev-bulk", f"more-{i}")
            poller = TaskPoller(clusters.active, DOMAIN, TL,
                                {"dev-bulk": SignalDecider(
                                    expected_signals=99)})
            poller.drain()
            clusters.active.serving.drain(timeout=30)
            clusters.replicate()
            scope = clusters.standby.metrics.snapshot().get(
                m.SCOPE_REPLICATION, {})
            assert scope.get(m.M_REPL_SNAP_INSTALLED, 0) > 0
            assert scope.get(m.M_REPL_DEVICE_APPLIED, 0) > 0
            assert scope.get(m.M_REPL_DEVICE_DIVERGENCE, 0) == 0
            assert clusters.standby.tpu.verify_all().ok
        finally:
            clusters.active.serving.stop()


class TestSnapshotShipping:
    """ISSUE 17 tentpole 2: torn/stale/foreign shipped records are
    detected, counted, and never installed."""

    def _base_record(self, clusters, wf):
        import zlib

        import numpy as np

        from cadence_tpu.engine.snapshot import SnapshotRecord, layout_signature
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, wf)
        key = (domain_id, wf, run_id)
        blob = b"shipped-state"
        return key, dict(
            key=key, batch_count=1,
            last_batch_crc=0xBAD, events=4, history_size=64, branch=0,
            payload=np.zeros(8, dtype=np.int64), state_blob=blob,
            blob_crc=zlib.crc32(blob), interner={},
            layout=layout_signature(clusters.standby.tpu.layout))

    def test_torn_stale_foreign_ignored(self, clusters):
        from cadence_tpu.engine.snapshot import SnapshotRecord
        from cadence_tpu.utils import metrics as m
        _open_signal_workflow(clusters, "ship-bad")
        clusters.replicate()
        key, base = self._base_record(clusters, "ship-bad")

        torn = SnapshotRecord(**{**base, "blob_crc": base["blob_crc"] ^ 1})
        foreign_ver = SnapshotRecord(**base)
        foreign_ver.version = 999
        foreign_lay = SnapshotRecord(**{**base, "layout": (7, 7, 7)})
        # batch_count 1 <= stored total, boundary CRC wrong → stale
        stale = SnapshotRecord(**base)
        for rec in (torn, foreign_ver, foreign_lay, stale):
            clusters.publisher.publish_snapshot(rec, "primary")
        clusters.replicate()

        scope = clusters.standby.metrics.snapshot().get(
            m.SCOPE_REPLICATION, {})
        assert scope.get(m.M_REPL_SNAP_SHIPPED, 0) == 4
        assert scope.get(m.M_REPL_SNAP_IGNORED_TORN, 0) == 1
        assert scope.get(m.M_REPL_SNAP_IGNORED_FOREIGN, 0) == 2
        assert scope.get(m.M_REPL_SNAP_IGNORED_STALE, 0) == 1
        assert scope.get(m.M_REPL_SNAP_INSTALLED, 0) == 0
        assert clusters.processor.snapshots_installed == 0
        assert clusters.standby.stores.snapshot.get(key) is None


class TestDLQObservability:
    """ISSUE 17 satellite: depth gauge, rollup, and the redrive arm."""

    def _poison(self, clusters, wf):
        from cadence_tpu.core.codec import serialize_history
        from cadence_tpu.core.enums import EventType
        from cadence_tpu.core.events import HistoryBatch, HistoryEvent
        from cadence_tpu.engine.replication import ReplicationTask
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, wf)
        ms = clusters.standby.stores.execution.get_workflow(
            domain_id, wf, run_id)
        next_id = ms.execution_info.next_event_id
        bad = HistoryBatch(domain_id=domain_id, workflow_id=wf,
                           run_id=run_id, events=[
            HistoryEvent(id=next_id,
                         event_type=EventType.ActivityTaskCompleted,
                         version=1, timestamp=1,
                         attrs=dict(scheduled_event_id=9999,
                                    started_event_id=9998))])
        clusters.publisher.stores.queue.enqueue(
            "replication",
            ReplicationTask(domain_id=domain_id, workflow_id=wf,
                            run_id=run_id, first_event_id=next_id,
                            next_event_id=next_id + 1, version=1,
                            events_blob=serialize_history([bad])))

    def test_summary_and_depth_gauge(self, clusters):
        from cadence_tpu.utils import metrics as m
        run_echo(clusters, "dlq-obs")
        clusters.replicate()
        self._poison(clusters, "dlq-obs")
        clusters.replicate()
        summary = clusters.processor.dlq_summary()
        assert summary["depth"] == 1
        assert summary["oldest"]["workflow_id"] == "dlq-obs"
        assert "missing activity" in summary["oldest"]["error"]
        assert sum(summary["error_classes"].values()) == 1
        scope = clusters.standby.metrics.snapshot().get(
            m.SCOPE_REPLICATION, {})
        assert scope.get(m.M_REPL_DLQ_DEPTH, 0) == 1.0

    def test_redrive_requeues_still_poison(self, clusters):
        run_echo(clusters, "dlq-re")
        clusters.replicate()
        self._poison(clusters, "dlq-re")
        clusters.replicate()
        out = clusters.processor.redrive_dlq()
        assert out == {"read": 1, "redriven": 0, "requeued": 1}
        assert len(clusters.processor.read_dlq()) == 1

    def test_redrive_clears_healed_entries(self, clusters):
        """An entry whose task now applies (or dedups) leaves the DLQ."""
        from cadence_tpu.engine.replication import (
            REPLICATION_DLQ,
            DLQEntry,
        )
        from cadence_tpu.utils import metrics as m
        run_echo(clusters, "dlq-heal")
        clusters.replicate()
        # quarantine a COPY of an already-applied stream task: on
        # redrive it dedups cleanly and must not requeue
        _, applied_task = clusters.publisher.stores.queue.read(
            "replication", 0, 1)[0]
        clusters.standby.stores.queue.enqueue(
            REPLICATION_DLQ, DLQEntry(task=applied_task,
                                      error="transient: peer flapped"))
        out = clusters.processor.redrive_dlq()
        assert out == {"read": 1, "redriven": 1, "requeued": 0}
        assert clusters.processor.read_dlq() == []
        scope = clusters.standby.metrics.snapshot().get(
            m.SCOPE_REPLICATION, {})
        assert scope.get(m.M_REPL_REDRIVEN, 0) == 1
        assert scope.get(m.M_REPL_DLQ_DEPTH, 1) == 0.0


class TestDomainBackpressure:
    """ISSUE 18 satellite: per-domain apply budget in the replication
    pump — a healed partition's monolithic one-domain flood sheds
    (typed, counted, ack NOT advanced past the cut) instead of
    monopolizing the pump tick."""

    def _flood(self, clusters, signals=8):
        _open_signal_workflow(clusters, "bp-wf", signals=signals)

    def test_over_budget_pass_sheds_typed_and_resumes(self, clusters):
        from cadence_tpu.engine.replication import (
            ReplicationBackpressureShed,
        )
        from cadence_tpu.utils import metrics as cm

        self._flood(clusters)
        proc = clusters.processor
        backlog = clusters.active.stores.queue.size("replication")
        assert backlog > 3
        proc.domain_budget = 2
        first = proc.process_once()
        # the pass stopped at the budget: typed shed recorded, ack held
        assert first <= proc.domain_budget
        assert proc.sheds == 1
        assert isinstance(proc.last_shed, ReplicationBackpressureShed)
        assert proc.last_shed.deferred == backlog - first
        reg = clusters.standby.metrics
        assert reg.counter(cm.SCOPE_REPLICATION, cm.M_REPL_BP_SHED) == 1
        assert reg.counter(cm.SCOPE_REPLICATION,
                           cm.M_REPL_BP_DEFERRED) == backlog - first
        # the ordered queue redelivers from the cut: repeated passes
        # drain the flood completely, nothing lost or reordered
        total = first
        for _ in range(backlog):
            n = proc.process_once()
            if n == 0 and proc.last_shed is None:
                break
            total += n
        assert total == backlog
        assert proc.last_shed is None
        # converged: standby state byte-matches the active
        wf = "bp-wf"
        a = clusters.active.stores
        s = clusters.standby.stores
        domain_id = a.domain.by_name(DOMAIN).domain_id
        run = a.execution.get_current_run_id(domain_id, wf)
        assert np.array_equal(
            payload_row(a.execution.get_workflow(domain_id, wf, run)),
            payload_row(s.execution.get_workflow(domain_id, wf, run)))

    def test_raise_on_shed_surfaces_typed_exception(self, clusters):
        from cadence_tpu.engine.replication import (
            ReplicationBackpressureShed,
        )

        self._flood(clusters)
        proc = clusters.processor
        proc.domain_budget = 1
        with pytest.raises(ReplicationBackpressureShed) as exc:
            proc.process_once(raise_on_shed=True)
        assert exc.value.applied == 1
        assert exc.value.deferred >= 1

    def test_zero_budget_disables_the_bound(self, clusters):
        self._flood(clusters)
        proc = clusters.processor
        proc.domain_budget = 0
        backlog = clusters.active.stores.queue.size("replication")
        assert proc.process_once() == backlog
        assert proc.sheds == 0
        assert proc.last_shed is None

    def test_env_sets_default_budget(self, monkeypatch):
        from cadence_tpu.engine import replication as repl_mod

        monkeypatch.setenv(repl_mod.DOMAIN_BUDGET_ENV, "7")
        c = ReplicatedClusters(num_hosts=1, num_shards=4)
        assert c.processor.domain_budget == 7
        monkeypatch.setenv(repl_mod.DOMAIN_BUDGET_ENV, "bogus")
        c2 = ReplicatedClusters(num_hosts=1, num_shards=4)
        assert c2.processor.domain_budget == repl_mod.DEFAULT_DOMAIN_BUDGET
