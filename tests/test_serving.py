"""Device-serving transaction tier (engine/serving.py).

Two layers:

1. Scheduler-seam tests: a ServingScheduler with injected read seams and
   the drain thread disabled, so every flush is driven synchronously —
   coalescing, parity gating, divergence invalidation, tail-moved
   re-reads, multi-branch bypass, bounded-queue backpressure, shutdown.

2. Cluster integration: an Onebox with the tier wired into its history
   engines — committed start/signal/decision transactions flow through
   `_Txn.commit`'s handoff, the resident pool stays parity-clean, and
   the full oracle<->device verify stays green over tier-maintained
   state.
"""
import threading

import numpy as np
import pytest

from cadence_tpu.core.checksum import (
    DEFAULT_LAYOUT,
    STICKY_ROW_INDEX,
    crc32_of_row,
    payload_row,
)
from cadence_tpu.engine.cache import batch_crc
from cadence_tpu.engine.persistence import Stores
from cadence_tpu.engine.serving import ServingScheduler, ServingTicket
from cadence_tpu.engine.tpu_engine import TPUReplayEngine
from cadence_tpu.gen.corpus import generate_corpus
from cadence_tpu.oracle.state_builder import StateBuilder
from cadence_tpu.utils import metrics as m
from cadence_tpu.utils.quotas import ServiceBusyError

LAYOUT = DEFAULT_LAYOUT


class _Harness:
    """Scheduler over injected histories; flushes driven by hand."""

    def __init__(self, workflows=3, target_events=24, **kw):
        self.hists = generate_corpus("basic", num_workflows=workflows,
                                     seed=11, target_events=target_events)
        self.keys = [("t", f"wf-{i}", "r") for i in range(workflows)]
        self.counts = {k: len(h) for k, h in zip(self.keys, self.hists)}
        self.by_key = dict(zip(self.keys, self.hists))
        self.tpu = TPUReplayEngine(Stores(), LAYOUT)
        self.sched = ServingScheduler(
            self.tpu, read_batches=self.read_batches,
            read_live_row=self.read_live_row, **kw)
        # drain by hand: deterministic single-threaded flushes
        self.sched._ensure_thread = lambda: None

    def read_batches(self, key):
        return self.by_key[key][:self.counts[key]]

    def read_live_row(self, key):
        ms = StateBuilder().replay_history(self.read_batches(key))
        row = payload_row(ms, LAYOUT)
        row[STICKY_ROW_INDEX] = 0
        return row, int(ms.version_histories.current_index), \
            int(ms.execution_info.next_event_id)

    def oracle(self, key):
        row, br, _ = self.read_live_row(key)
        return row, br

    def submit(self, key, row=None, branch=None, tail_crc=None):
        if row is None:
            row, branch = self.oracle(key)
        if tail_crc is None:
            tail_crc = batch_crc(self.read_batches(key)[-1])
        return self.sched.submit(key, row, branch, tail_crc)

    def flush(self):
        with self.sched._cv:
            batch = list(self.sched._pending.values())
            self.sched._pending.clear()
        if batch:
            self.sched._flush(batch)

    def counter(self, name):
        return self.sched.metrics.counter(m.SCOPE_TPU_SERVING, name)


class TestSchedulerSeam:
    def test_cold_admit_then_suffix_serve_checksums_match_oracle(self):
        h = _Harness(workflows=2)
        k = h.keys[0]
        h.counts[k] = len(h.by_key[k]) - 1
        t_cold = h.submit(k)
        h.flush()
        res = t_cold.result(timeout=1)
        assert res.ok and res.parity_ok and res.path == "cold"
        assert res.checksum == int(crc32_of_row(h.oracle(k)[0]))
        assert h.counter(m.M_SERVING_COLD) == 1
        # append one batch: the next transaction replays ONLY the suffix
        # against the resident state
        h.counts[k] += 1
        t_sfx = h.submit(k)
        h.flush()
        res = t_sfx.result(timeout=1)
        assert res.ok and res.parity_ok and res.path == "suffix"
        assert res.checksum == int(crc32_of_row(h.oracle(k)[0]))
        assert h.counter(m.M_SERVING_SUFFIX) == 1
        assert h.counter(m.M_SERVING_DIVERGENCE) == 0

    def test_same_key_transactions_coalesce_into_one_pass(self):
        h = _Harness(workflows=1)
        k = h.keys[0]
        h.counts[k] = len(h.by_key[k]) - 2
        h.submit(k)
        h.flush()  # seed resident
        tickets = []
        for _ in range(2):
            h.counts[k] += 1
            tickets.append(h.submit(k))
        assert h.counter(m.M_SERVING_COALESCED) == 1
        assert len(h.sched._pending) == 1  # one queue slot per workflow
        h.flush()
        results = [t.result(timeout=1) for t in tickets]
        assert all(r.ok for r in results)
        # both tickets settle from the SAME device pass at the newest
        # committed state
        assert results[0].checksum == results[1].checksum
        assert results[1].coalesced

    def test_exact_serve_zero_device_work(self):
        h = _Harness(workflows=1)
        k = h.keys[0]
        h.submit(k)
        h.flush()
        launches = h.counter(m.M_SERVING_LAUNCHES)
        # same committed state again (e.g. a fold already covered it)
        t = h.submit(k)
        h.flush()
        res = t.result(timeout=1)
        assert res.ok and res.path == "exact"
        assert h.counter(m.M_SERVING_LAUNCHES) == launches
        assert h.counter(m.M_SERVING_EXACT) == 1

    def test_parity_divergence_invalidates_never_serves(self):
        h = _Harness(workflows=1)
        k = h.keys[0]
        h.submit(k)
        h.flush()
        assert h.tpu.resident.lookup(k, h.read_batches(k)) is not None
        wrong = h.oracle(k)[0].copy()
        wrong[0] += 1
        t = h.submit(k, row=wrong, branch=h.oracle(k)[1])
        h.flush()
        res = t.result(timeout=1)
        assert not res.ok and not res.parity_ok
        assert h.counter(m.M_SERVING_DIVERGENCE) == 1
        # the entry was dropped — wrong state is never retained
        assert h.tpu.resident.lookup(k, h.read_batches(k)) is None
        assert h.tpu.resident.metrics.counter(
            m.SCOPE_TPU_RESIDENT, m.M_CACHE_INVALIDATIONS) >= 1

    def test_tail_moved_re_reads_live_state(self):
        h = _Harness(workflows=1)
        k = h.keys[0]
        h.counts[k] = len(h.by_key[k]) - 1
        h.submit(k)
        h.flush()
        # a "newer commit" lands after submit: the enqueued tail_crc no
        # longer matches the store tail — the drain must re-read the
        # live row instead of comparing a stale expectation
        stale_tail = batch_crc(h.read_batches(k)[-1])
        row, br = h.oracle(k)
        h.counts[k] += 1  # store moves first
        t = h.sched.submit(k, row, br, stale_tail)
        h.flush()
        res = t.result(timeout=1)
        assert res.ok and res.parity_ok
        assert res.checksum == int(crc32_of_row(h.oracle(k)[0]))

    def test_multi_branch_bypasses_and_invalidates(self):
        h = _Harness(workflows=1)
        k = h.keys[0]
        h.submit(k)
        h.flush()
        # simulate an NDC branch switch: the read seam reports
        # "not single-lineage" (None), same as the stores-backed seam
        h.by_key[k] = None
        h.counts[k] = 0

        def read_none(key):
            return None
        h.sched._read_batches = read_none
        t = h.sched.submit(k, np.zeros(LAYOUT.width, np.int64), 0, 1)
        h.flush()
        res = t.result(timeout=1)
        assert not res.ok and res.path == "bypass"
        assert h.counter(m.M_SERVING_BYPASSED) == 1
        assert h.tpu.resident.metrics.counter(
            m.SCOPE_TPU_RESIDENT, m.M_CACHE_INVALIDATIONS) >= 1

    def test_bounded_queue_sheds_typed_service_busy(self):
        h = _Harness(workflows=3, max_queue=2)
        h.submit(h.keys[0])
        h.submit(h.keys[1])
        with pytest.raises(ServiceBusyError) as exc:
            h.submit(h.keys[2])
        assert exc.value.retry_after_s > 0
        assert h.counter(m.M_SERVING_REJECTED) == 1
        # a SAME-key submit still folds — backpressure never blocks
        # coalescing into an existing slot
        t = h.submit(h.keys[0])
        assert isinstance(t, ServingTicket)
        assert h.counter(m.M_SERVING_COALESCED) == 1

    def test_chained_append_reads_nothing_from_the_store(self):
        """The zero-read chain: when the engine hands the committed
        batches and the resident tail matches the submit ledger, the
        flush must touch neither the history store nor the serializer —
        pinned by a read seam that RAISES if consulted."""
        h = _Harness(workflows=1)
        k = h.keys[0]
        h.counts[k] = len(h.by_key[k]) - 2
        h.submit(k)
        h.flush()  # cold admit (store reads allowed here)

        boom = {"armed": False}
        real_read = h.read_batches

        def guarded_read(key):
            if boom["armed"]:
                raise AssertionError("chain path read the store")
            return real_read(key)
        h.sched._read_batches = guarded_read

        for _ in range(2):  # two chained appends, zero store reads
            h.counts[k] += 1
            row, br = h.oracle(k)
            batch = h.by_key[k][h.counts[k] - 1]
            t = h.sched.submit(k, row, br, batch_crc(batch), batch=batch)
            boom["armed"] = True
            h.flush()
            boom["armed"] = False
            res = t.result(timeout=1)
            assert res.ok and res.parity_ok and res.path == "suffix"
            assert res.checksum == int(crc32_of_row(h.oracle(k)[0]))
        assert h.counter(m.M_SERVING_DIVERGENCE) == 0

    def test_stop_resolves_pending_not_ok(self):
        h = _Harness(workflows=1)
        t = h.submit(h.keys[0])
        h.sched.stop()
        res = t.result(timeout=1)
        assert not res.ok and res.error == "stopped"

    def test_drain_thread_end_to_end(self):
        """The real drain loop (no manual flushes): lazy thread start,
        adaptive window, drain() settling."""
        h = _Harness(workflows=2, max_wait_us=1000)
        del h.sched._ensure_thread  # restore the real lazy-start
        tickets = [h.submit(k) for k in h.keys]
        assert h.sched.drain(timeout=120.0)
        for t in tickets:
            res = t.result(timeout=1)
            assert res.ok and res.parity_ok
        h.sched.stop()


class TestOneboxServingTier:
    def _box(self):
        from cadence_tpu.engine.onebox import Onebox
        box = Onebox(num_hosts=1, num_shards=2)
        sched = box.enable_serving()
        return box, sched

    def test_committed_transactions_flow_through_tier(self):
        box, sched = self._box()
        fe = box.frontend
        fe.register_domain("svd")
        fe.start_workflow_execution("svd", "wf-a", "t", "tl")
        assert sched.drain(timeout=300.0)
        for i in range(3):
            fe.signal_workflow_execution("svd", "wf-a", f"s{i}",
                                         request_id=f"r{i}")
        assert sched.drain(timeout=300.0)
        stats = sched.stats()
        assert stats["transactions"] >= 4
        assert stats["parity_divergence"] == 0
        assert stats["cold_admits"] >= 1
        # every engine handoff carried a resolvable ticket
        eng = box.route("wf-a")
        res = eng.last_serving_ticket.result(timeout=60)
        assert res.ok and res.parity_ok
        # the tier-maintained resident state verifies against the oracle
        r = box.tpu.verify_all()
        assert r.ok
        assert len(r.resident) >= 1
        sched.stop()

    def test_admin_serving_rollup(self):
        from cadence_tpu.engine.admin import AdminHandler
        box, sched = self._box()
        fe = box.frontend
        fe.register_domain("svd")
        fe.start_workflow_execution("svd", "wf-b", "t", "tl")
        assert sched.drain(timeout=300.0)
        doc = AdminHandler(box).serving()
        assert doc["tier_wired"]
        assert doc["transactions"] >= 1
        assert doc["parity_divergence"] == 0
        assert "coalescing_factor" in doc and "queue_depth" in doc
        assert doc["resident_entries"] >= 1
        sched.stop()

    def test_handoff_is_fire_and_forget_on_backpressure(self):
        """A full serving queue must never fail the transaction: the
        oracle commit already happened; the handoff sheds and the engine
        carries on."""
        box, sched = self._box()
        sched.max_queue = 0  # every distinct-key submit sheds
        fe = box.frontend
        fe.register_domain("svd")
        run_id = fe.start_workflow_execution("svd", "wf-c", "t", "tl")
        assert run_id  # the transaction itself succeeded
        assert box.metrics.counter(m.SCOPE_TPU_SERVING,
                                   m.M_SERVING_REJECTED) >= 1
        eng = box.route("wf-c")
        assert eng.last_serving_ticket is None
        sched.stop()
