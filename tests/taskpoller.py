"""Worker simulation: hand-rolled poll/respond loops.

Reference: host/taskpoller.go — integration tests drive workers by polling
decision/activity tasks directly and responding, with no SDK in between.
"""
from __future__ import annotations

from typing import Dict

from cadence_tpu.engine.onebox import Onebox


class TaskPoller:
    def __init__(self, box: Onebox, domain: str, task_list: str,
                 deciders: Dict[str, object]) -> None:
        """`deciders` maps workflow_id → decider object with .decide(history)."""
        self.box = box
        self.domain = domain
        self.task_list = task_list
        self.deciders = deciders

    def _answer_queries(self, resp) -> dict:
        """Compute answers for queries attached to a poll response via the
        decider's optional .query(query_type, history) hook."""
        results = {}
        for qid, qtype, _args in resp.queries:
            wf = resp.execution[1] if resp.execution else None
            decider = self.deciders.get(wf)
            if decider is not None and hasattr(decider, "query"):
                results[qid] = decider.query(qtype, resp.history)
            else:
                results[qid] = b""
        return results

    def poll_and_decide_once(self) -> bool:
        resp = self.box.frontend.poll_for_decision_task(self.domain, self.task_list)
        if resp is None:
            return False
        if resp.query_only:
            # query-only task (no decision token): answer directly
            for qid, result in self._answer_queries(resp).items():
                self.box.frontend.respond_query_task_completed(
                    resp.execution, qid, result)
            return True
        decider = self.deciders[resp.token.workflow_id]
        decisions = decider.decide(resp.history)
        self.box.frontend.respond_decision_task_completed(
            resp.token, decisions, query_results=self._answer_queries(resp))
        return True

    def poll_and_run_activity_once(self, fail: bool = False) -> bool:
        resp = self.box.frontend.poll_for_activity_task(self.domain, self.task_list)
        if resp is None:
            return False
        if fail:
            self.box.frontend.respond_activity_task_failed(resp.token, "boom")
        else:
            self.box.frontend.respond_activity_task_completed(resp.token)
        return True

    def drain(self, max_rounds: int = 500) -> None:
        """Pump queues + worker polls until the cluster goes quiet."""
        for _ in range(max_rounds):
            progressed = self.box.pump_once() > 0
            while self.poll_and_decide_once():
                progressed = True
            while self.poll_and_run_activity_once():
                progressed = True
            if not progressed and self.box.matching.backlog() == 0:
                return
        raise RuntimeError("cluster did not drain")
