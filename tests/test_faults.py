"""Persistence fault injection (VERDICT missing #10).

Reference: persistenceErrorInjectionClients.go:51-101 — every manager
wrapped with configurable error injection; callers' retry semantics get
exercised against REAL mid-transaction failures, and the scanner detects
what a torn write leaves behind.

Every faulted cluster here is DURABLE, parametrized over both open_log
backends (the `wal` fixture): injected faults raise before the target
store method runs, so the WAL must stay consistent through the whole
soak — each test's teardown recovers it and requires a clean fsck, which
is the crash/fault/recovery matrix meeting the fault injector."""
import pytest

from cadence_tpu.core.enums import CloseStatus
from cadence_tpu.engine.durability import open_durable_stores, recover_stores
from cadence_tpu.engine.faults import (
    FaultInjector,
    TransientStoreError,
    inject_faults,
    instrument_stores,
)
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import CompleteDecider
from tests.taskpoller import TaskPoller

DOMAIN = "fault-domain"
TL = "fault-tl"

# the dual-backend `wal` fixture lives in tests/conftest.py


def make_box(injector=None, wal=None):
    stores = open_durable_stores(wal) if wal else None
    box = Onebox(num_hosts=1, num_shards=4, stores=stores)
    if injector is not None:
        inject_faults(box.stores, injector, metrics=box.metrics)
    box.frontend.register_domain(DOMAIN)
    return box


def assert_recovers_clean(wal):
    """Post-soak gate: the WAL the faulted cluster leaves behind recovers
    with zero divergence and zero fsck findings."""
    from cadence_tpu.engine import walcheck
    stores, report = recover_stores(wal, verify_on_device=False,
                                    rebuild_on_device=False)
    assert report.ok, report.divergent
    findings = (walcheck.audit_records(walcheck.read_raw_lines(wal))
                + walcheck.audit_stores(stores))
    assert findings == [], [f.as_dict() for f in findings]


class TestScriptedFaults:
    def test_failed_create_leaves_no_state_and_retry_succeeds(self, wal):
        injector = FaultInjector()
        box = make_box(injector, wal)
        injector.fail_next("execution", "create_workflow")
        with pytest.raises(TransientStoreError):
            box.frontend.start_workflow_execution(DOMAIN, "f-1", "t", TL)
        # nothing persisted: the id is still startable and no history exists
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        assert (domain_id, "f-1") not in dict(
            box.stores.execution.list_current_pointers())
        box.frontend.start_workflow_execution(DOMAIN, "f-1", "t", TL)
        TaskPoller(box, DOMAIN, TL, {"f-1": CompleteDecider()}).drain()
        assert box.tpu.verify_all().ok
        assert_recovers_clean(wal)

    def test_failed_update_mid_transaction_is_clean(self, wal):
        """An injected failure at the commit point leaves committed STATE
        untouched; the retried request overwrites the torn history tail
        and lands cleanly."""
        injector = FaultInjector()
        box = make_box(injector, wal)
        box.frontend.start_workflow_execution(DOMAIN, "f-2", "signal", TL)
        injector.fail_next("execution", "update_workflow")
        with pytest.raises(TransientStoreError):
            box.frontend.signal_workflow_execution(DOMAIN, "f-2", "sig")
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "f-2")
        ms = box.stores.execution.get_workflow(domain_id, "f-2", run_id)
        assert ms.execution_info.signal_count == 0  # nothing applied
        box.frontend.signal_workflow_execution(DOMAIN, "f-2", "sig")
        ms = box.stores.execution.get_workflow(domain_id, "f-2", run_id)
        assert ms.execution_info.signal_count == 1
        assert box.tpu.verify_all().ok
        assert_recovers_clean(wal)

    def test_torn_tail_detected_then_healed_by_retry(self, wal):
        """A fault at the COMMIT POINT (the conditional state update, last
        write of a transaction) leaves an orphan history tail — the
        scanner's device-replay invariant flags it, and the caller's retry
        OVERWRITES the tail (append node-overwrite semantics) and commits,
        after which the cluster verifies clean."""
        injector = FaultInjector()
        box = make_box(injector, wal)
        box.frontend.start_workflow_execution(DOMAIN, "f-3", "signal", TL)
        injector.fail_next("execution", "update_workflow")
        with pytest.raises(TransientStoreError):
            box.frontend.signal_workflow_execution(DOMAIN, "f-3", "sig")
        report = box.scanner.run_once()
        assert not report.ok
        assert len(report.state_divergent) == 1
        # retry heals: same event ids rewrite the torn tail, then commit
        box.frontend.signal_workflow_execution(DOMAIN, "f-3", "sig")
        assert box.scanner.run_once().ok
        assert_recovers_clean(wal)

    def test_injected_faults_counted_in_metrics(self):
        injector = FaultInjector()
        box = make_box(injector)
        injector.fail_next("execution", "create_workflow")
        with pytest.raises(TransientStoreError):
            box.frontend.start_workflow_execution(DOMAIN, "f-4", "t", TL)
        assert box.metrics.counter("persistence.execution",
                                   "errors-injected") == 1


class TestRateFaults:
    def test_workload_survives_random_write_faults_with_retries(self, wal):
        """10% write-failure rate; a client-side retry tier (the reference
        wraps every service client in retryable decorators) pushes every
        workflow to completion and the cluster verifies clean."""
        injector = FaultInjector(rate=0.1, seed=42)
        box = make_box(injector, wal)

        from cadence_tpu.engine.persistence import WorkflowAlreadyStartedError

        def retry(fn, attempts=8):
            for i in range(attempts):
                try:
                    return fn()
                except TransientStoreError:
                    continue
                except WorkflowAlreadyStartedError:
                    # a prior attempt's create committed (with history-first
                    # ordering the run is fully usable): treat as success
                    return None
            raise AssertionError("retries exhausted")

        for i in range(6):
            retry(lambda i=i: box.frontend.start_workflow_execution(
                DOMAIN, f"rf-{i}", "t", TL))
        poller = TaskPoller(box, DOMAIN, TL,
                            {f"rf-{i}": CompleteDecider() for i in range(6)})
        # drive manually with retries (drain() assumes a fault-free pump):
        # a failed record-started requeues the task; a failed respond loses
        # the worker's answer, and the decision's start-to-close timeout
        # re-dispatches it — so the clock advances every round
        for _ in range(300):
            retry(lambda: box.pump_once())
            while True:
                try:
                    if not poller.poll_and_decide_once():
                        break
                except TransientStoreError:
                    continue
            while True:
                try:
                    if not poller.poll_and_run_activity_once():
                        break
                except TransientStoreError:
                    continue
            box.advance_time(11)  # decision timeout: 10s
            if box.matching.backlog() == 0 and retry(lambda: box.pump_once()) == 0:
                break
        assert injector.injected > 0
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        done = 0
        for i in range(6):
            run = box.stores.execution.get_current_run_id(domain_id, f"rf-{i}")
            ms = box.stores.execution.get_workflow(domain_id, f"rf-{i}", run)
            if ms.execution_info.close_status == CloseStatus.Completed:
                done += 1
        assert done == 6
        assert box.tpu.verify_all().ok
        assert_recovers_clean(wal)


class TestMetricsDecorator:
    def test_store_call_counters(self):
        box = Onebox(num_hosts=1, num_shards=2)
        instrument_stores(box.stores, box.metrics)
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "m-1", "t", TL)
        assert box.metrics.counter("persistence.execution", "requests") > 0
        assert box.metrics.counter("persistence.history", "requests") > 0
        assert box.metrics.counter("persistence.domain", "requests") > 0
