"""Active-active multi-region (ISSUE 17): warm failover end to end.

The in-process tests gate the managed-failover coordinator's new warm
path at tier-1 size: snapshot-shipping replication keeps the standby's
snapshot store fresh, promotion pre-hydrates the serving tier from it
BEFORE the active flip (warm steals, parity gated), and the bounded
replication drain degrades to NDC conflict resolution instead of
blocking. The slow/load tier runs the full two-region wire scenario —
standard-mix traffic, kill -9 of every active-region process
mid-traffic, warm standby promotion under SLO — the repo's analog of a
region evacuation drill."""
import pytest

from cadence_tpu.core.checksum import payload_row
from cadence_tpu.engine.failovermanager import FailoverManager
from cadence_tpu.engine.multicluster import ReplicatedClusters
from cadence_tpu.models.deciders import SignalDecider
from cadence_tpu.utils import metrics as m
from tests.taskpoller import TaskPoller

DOMAIN = "mr-domain"
TL = "mr-tasklist"


@pytest.fixture()
def warm_clusters(monkeypatch):
    """Two regions with live traffic replicated AND snapshot-shipped:
    the standby's snapshot store is warm, its serving tier is not (yet)."""
    monkeypatch.setenv("CADENCE_TPU_SNAPSHOT_MIN_EVENTS", "1")
    monkeypatch.setenv("CADENCE_TPU_SNAPSHOT_EVERY_EVENTS", "4")
    clusters = ReplicatedClusters(num_hosts=1, num_shards=4)
    clusters.active.enable_serving()
    clusters.register_global_domain(DOMAIN)
    deciders = {}
    poller = TaskPoller(clusters.active, DOMAIN, TL, deciders)
    for i in range(3):
        wf = f"mr-wf-{i}"
        deciders[wf] = SignalDecider(expected_signals=99)
        clusters.active.frontend.start_workflow_execution(
            DOMAIN, wf, "signal", TL)
        poller.drain()
        for s in range(2):
            clusters.active.frontend.signal_workflow_execution(
                DOMAIN, wf, f"{wf}-s{s}")
        poller.drain()
    clusters.active.serving.drain(timeout=30)
    # deploy warm-up sweep: every resident row snapshots and SHIPS
    assert clusters.active.tpu.snapshotter().sweep(force=True).written >= 3
    clusters.replicate()
    assert clusters.processor.snapshots_installed >= 3
    yield clusters
    clusters.active.serving.stop()


class TestWarmPromotion:
    def test_managed_failover_prehydrates_before_flip(self, warm_clusters):
        clusters = warm_clusters
        fm = FailoverManager(clusters)
        report = fm.managed_failover([DOMAIN], to_cluster="standby")
        assert report.ok and report.succeeded == 1
        assert report.drain_degraded == 0
        # the pre-flip hydration pass seeded the promoting serving tier
        # from the shipped snapshots — warm, not cold
        hyd = report.prehydration
        assert hyd is not None
        assert hyd["hydrated"] + hyd["already_resident"] >= 3
        assert hyd["parity_divergence"] == 0
        # post-flip: both sides agree, the standby is authoritative
        for box in (clusters.active, clusters.standby):
            assert box.stores.domain.by_name(
                DOMAIN).active_cluster == "standby"
        # the hydrated rows are genuinely resident and parity-clean
        assert len(list(clusters.standby.tpu.resident.keys())) >= 3
        assert clusters.standby.tpu.verify_all().ok

    def test_promoted_region_serves_live_traffic_warm(self, warm_clusters):
        """After the warm flip, the promoted side completes live work on
        the pre-hydrated state and stays byte-converged with the old
        active once replication drains back."""
        clusters = warm_clusters
        FailoverManager(clusters).managed_failover([DOMAIN])
        box = clusters.standby
        poller = TaskPoller(box, DOMAIN, TL,
                            {"mr-wf-0": SignalDecider(expected_signals=3)})
        box.frontend.signal_workflow_execution(DOMAIN, "mr-wf-0", "after")
        poller.drain()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(
            domain_id, "mr-wf-0")
        promoted_ms = box.stores.execution.get_workflow(
            domain_id, "mr-wf-0", run_id)
        assert promoted_ms.execution_info.signal_count == 3
        # reverse stream reconverges the demoted region
        clusters.replicate_reverse()
        old_ms = clusters.active.stores.execution.get_workflow(
            domain_id, "mr-wf-0", run_id)
        assert (payload_row(old_ms) == payload_row(promoted_ms)).all()

    def test_drain_deadline_degrades_to_ndc_not_blocking(self, warm_clusters):
        """A zero drain budget cannot stop the failover: the batch counts
        a degraded drain and the flip proceeds (late arrivals reconcile
        via NDC conflict resolution, which the replicator runs anyway)."""
        clusters = warm_clusters
        # in-flight backlog the drain will NOT be given time to move
        clusters.active.frontend.signal_workflow_execution(
            DOMAIN, "mr-wf-1", "late")
        report = FailoverManager(clusters).managed_failover(
            [DOMAIN], drain_deadline_s=0.0)
        assert report.ok and report.succeeded == 1
        assert report.drain_degraded == 1
        assert clusters.standby.stores.domain.by_name(
            DOMAIN).active_cluster == "standby"
        # the late suffix lands after the flip and reconciles cleanly
        clusters.replicate()
        assert clusters.standby.tpu.verify_all().ok

    def test_prehydration_failure_never_fails_failover(self, warm_clusters,
                                                       monkeypatch):
        clusters = warm_clusters
        import cadence_tpu.engine.failovermanager as fmod
        monkeypatch.setattr(
            fmod, "prehydrate_serving",
            lambda box: (_ for _ in ()).throw(RuntimeError("hbm gone")))
        report = FailoverManager(clusters).managed_failover([DOMAIN])
        assert report.ok and report.succeeded == 1
        assert report.prehydration is None  # optimization lost, not the flip


class TestReplicationSeamFuzz:
    def test_profile_gates_hold(self):
        """The ISSUE 17 fuzz profile: replication apply interleaved with
        live standby signals/resets and NDC promotion; byte-identical
        cross-region checksums, DLQ-only quarantine, zero divergence."""
        from cadence_tpu.gen.interleave import replication_interleave_scenario
        doc = replication_interleave_scenario(seed=7, length=12, poisons=1)
        assert doc["ok"], doc
        assert doc["checksums_identical"]
        assert doc["dlq_exact"] and doc["dlq_depth"] == 1
        assert doc["replication"]["device_divergence"] == 0
        assert doc["serving_divergence"] == 0

    @pytest.mark.slow
    @pytest.mark.fuzz
    def test_profile_wide(self):
        from cadence_tpu.gen.interleave import replication_interleave_scenario
        for seed in (3, 20260806):
            doc = replication_interleave_scenario(seed=seed, length=48,
                                                  poisons=2)
            assert doc["ok"], (seed, doc)


@pytest.mark.slow
@pytest.mark.load
class TestRegionFailoverWire:
    def test_region_kill_promote_warm(self):
        """The gate scenario at smoke size: two wire regions, standard
        mix on the active, kill -9 every active-region process
        mid-traffic, promote the standby warm, verify both regions."""
        from cadence_tpu.loadgen.scenarios import region_failover_scenario
        doc = region_failover_scenario(duration_s=6.0, num_hosts=2,
                                       rps=8.0, pool_size=8, workers=8)
        assert doc["ok"], {k: doc[k] for k in
                           ("slo", "replication", "failover", "parity",
                            "verify") if k in doc}
        assert doc["failover"]["warm_steals"] > 0
        assert doc["parity"]["serving_divergence"] == 0
        assert doc["parity"]["replication_device_divergence"] == 0
