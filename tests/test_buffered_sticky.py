"""Buffered events + sticky execution.

Round-3 VERDICT ask #3:
- events arriving while a decision is IN FLIGHT buffer in mutable state
  and flush at decision close with reference event ordering
  (mutable_state_builder.go:415 FlushBufferedEvents, completion events
  reordered to the back);
- close decisions racing a non-empty buffer fail with UNHANDLED_DECISION;
- sticky task lists pin decision dispatch to the last worker; the sticky
  schedule-to-start timeout falls back to the normal task list WITHOUT
  incrementing the attempt (mutable_state_decision_task_manager.go:256-271).
"""
import pytest

from cadence_tpu.core.enums import (
    EMPTY_EVENT_ID,
    CloseStatus,
    DecisionType,
    EventType,
    TimeoutType,
    WorkflowState,
)
from cadence_tpu.engine.history_engine import Decision, InvalidRequestError
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import EchoDecider, SignalDecider
from tests.taskpoller import TaskPoller

DOMAIN = "buf-domain"
TL = "buf-tl"
STICKY = "buf-tl-sticky"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


def _poll_decision(box, wf):
    box.pump_once()
    resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
    assert resp is not None and resp.token.workflow_id == wf
    return resp


class TestBufferedEvents:
    def test_signal_during_decision_buffers_and_flushes(self, box):
        """A signal landing mid-decision appears AFTER DecisionTaskCompleted
        in history — the reference's persisted ordering — and triggers a
        fresh decision."""
        box.frontend.start_workflow_execution(DOMAIN, "buf-1", "signal", TL)
        resp = _poll_decision(box, "buf-1")  # decision 1 now in flight
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "buf-1")

        box.frontend.signal_workflow_execution(DOMAIN, "buf-1", "mid-flight")
        ms = box.stores.execution.get_workflow(domain_id, "buf-1", run_id)
        assert len(ms.buffered_events) == 1
        # the signal is NOT in history yet
        kinds = [e.event_type for e in
                 box.stores.history.read_events(domain_id, "buf-1", run_id)]
        assert EventType.WorkflowExecutionSignaled not in kinds

        box.frontend.respond_decision_task_completed(resp.token, [])
        events = box.stores.history.read_events(domain_id, "buf-1", run_id)
        kinds = [e.event_type for e in events]
        i_completed = kinds.index(EventType.DecisionTaskCompleted)
        i_signal = kinds.index(EventType.WorkflowExecutionSignaled)
        assert i_signal == i_completed + 1
        # flushed buffer scheduled a follow-up decision
        assert kinds[i_signal + 1] == EventType.DecisionTaskScheduled
        ms = box.stores.execution.get_workflow(domain_id, "buf-1", run_id)
        assert not ms.buffered_events
        assert ms.execution_info.signal_count == 1
        assert box.tpu.verify_all().ok

    def test_close_decision_with_buffer_fails_unhandled(self, box):
        """CompleteWorkflow racing a buffered signal → UNHANDLED_DECISION:
        the decision fails, the buffer flushes, and the workflow completes
        only after re-deciding with the signal visible."""
        box.frontend.start_workflow_execution(DOMAIN, "buf-2", "signal", TL)
        resp = _poll_decision(box, "buf-2")
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "buf-2")
        box.frontend.signal_workflow_execution(DOMAIN, "buf-2", "racer")

        box.frontend.respond_decision_task_completed(
            resp.token, [Decision(DecisionType.CompleteWorkflowExecution, {})])
        ms = box.stores.execution.get_workflow(domain_id, "buf-2", run_id)
        # still running: the close was rejected
        assert ms.execution_info.state == WorkflowState.Running
        kinds = [e.event_type for e in
                 box.stores.history.read_events(domain_id, "buf-2", run_id)]
        i_failed = kinds.index(EventType.DecisionTaskFailed)
        assert kinds[i_failed + 1] == EventType.WorkflowExecutionSignaled
        assert kinds[i_failed + 2] == EventType.DecisionTaskScheduled

        # the re-decision sees the signal and completes
        poller = TaskPoller(box, DOMAIN, TL,
                            {"buf-2": SignalDecider(expected_signals=1)})
        poller.drain()
        ms = box.stores.execution.get_workflow(domain_id, "buf-2", run_id)
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert box.tpu.verify_all().ok

    def test_activity_completion_reorders_behind_started(self, box):
        """An activity started AND completed while a decision is in flight:
        both buffer; the flush emits started before completed (reorderBuffer
        moves completion events to the back) with patched started IDs."""
        box.frontend.start_workflow_execution(DOMAIN, "buf-3", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"buf-3": EchoDecider(TL)})
        # decision 1 schedules the activity
        box.pump_once()
        assert poller.poll_and_decide_once()
        box.pump_once()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "buf-3")

        # force decision 2 in flight via a signal
        box.frontend.signal_workflow_execution(DOMAIN, "buf-3", "hold")
        box.pump_once()
        resp2 = box.frontend.poll_for_decision_task(DOMAIN, TL)
        assert resp2 is not None

        # activity starts AND completes while decision 2 runs
        act = box.frontend.poll_for_activity_task(DOMAIN, TL)
        assert act is not None
        box.frontend.respond_activity_task_completed(act.token)
        ms = box.stores.execution.get_workflow(domain_id, "buf-3", run_id)
        types_buf = [e.event_type for e in ms.buffered_events]
        assert types_buf == [EventType.ActivityTaskStarted,
                             EventType.ActivityTaskCompleted]

        box.frontend.respond_decision_task_completed(resp2.token, [])
        events = box.stores.history.read_events(domain_id, "buf-3", run_id)
        kinds = [e.event_type for e in events]
        i_started = kinds.index(EventType.ActivityTaskStarted)
        i_closed = kinds.index(EventType.ActivityTaskCompleted)
        assert i_started < i_closed
        started_ev = events[i_started]
        closed_ev = events[i_closed]
        # the buffered completion's started reference was patched to the
        # flushed started event's real ID
        assert closed_ev.get("started_event_id") == started_ev.id
        # drain to completion: decider sees the completion and closes
        poller.drain()
        ms = box.stores.execution.get_workflow(domain_id, "buf-3", run_id)
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert box.tpu.verify_all().ok

    def test_double_respond_buffered_close_rejected(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "buf-4", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"buf-4": EchoDecider(TL)})
        box.pump_once()
        assert poller.poll_and_decide_once()
        box.pump_once()
        box.frontend.signal_workflow_execution(DOMAIN, "buf-4", "hold")
        box.pump_once()
        resp2 = box.frontend.poll_for_decision_task(DOMAIN, TL)
        act = box.frontend.poll_for_activity_task(DOMAIN, TL)
        box.frontend.respond_activity_task_completed(act.token)
        with pytest.raises(InvalidRequestError):
            box.frontend.respond_activity_task_completed(act.token)

    def test_buffered_start_token_survives_flush(self, box):
        """An activity token minted while its start was buffered must stay
        valid after the flush assigns the real started event ID."""
        box.frontend.start_workflow_execution(DOMAIN, "buf-6", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"buf-6": EchoDecider(TL)})
        box.pump_once()
        assert poller.poll_and_decide_once()  # schedules the activity
        box.pump_once()
        box.frontend.signal_workflow_execution(DOMAIN, "buf-6", "hold")
        box.pump_once()
        resp2 = box.frontend.poll_for_decision_task(DOMAIN, TL)
        act = box.frontend.poll_for_activity_task(DOMAIN, TL)  # start buffers
        box.frontend.respond_decision_task_completed(resp2.token, [])  # flush
        # respond with the pre-flush token: must be accepted
        box.frontend.respond_activity_task_completed(act.token)
        poller.drain()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "buf-6")
        ms = box.stores.execution.get_workflow(domain_id, "buf-6", run_id)
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert box.tpu.verify_all().ok

    def test_cancel_timer_scrubs_buffered_fire(self, box):
        """CancelTimer racing a buffered TimerFired: the buffered fire is
        scrubbed (checkAndClearTimerFiredEvent) and the cancel wins."""
        from cadence_tpu.models.deciders import TimerDecider

        box.frontend.start_workflow_execution(DOMAIN, "buf-7", "timer", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"buf-7": TimerDecider(fire_seconds=30)})
        box.pump_once()
        assert poller.poll_and_decide_once()  # starts timer t-0
        box.pump_once()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "buf-7")
        ms = box.stores.execution.get_workflow(domain_id, "buf-7", run_id)
        started_id = next(iter(ms.pending_timer_info_ids.values())).started_id

        box.frontend.signal_workflow_execution(DOMAIN, "buf-7", "hold")
        box.pump_once()
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        # fire lands while the decision is in flight → buffered
        box.route("buf-7").fire_user_timer(domain_id, "buf-7", run_id,
                                           started_id)
        ms = box.stores.execution.get_workflow(domain_id, "buf-7", run_id)
        assert any(e.event_type == EventType.TimerFired
                   for e in ms.buffered_events)
        # worker decides to cancel that very timer
        box.frontend.respond_decision_task_completed(
            resp.token, [Decision(DecisionType.CancelTimer,
                                  dict(timer_id="t-0"))])
        kinds = [e.event_type for e in
                 box.stores.history.read_events(domain_id, "buf-7", run_id)]
        assert EventType.TimerCanceled in kinds
        assert EventType.TimerFired not in kinds
        ms = box.stores.execution.get_workflow(domain_id, "buf-7", run_id)
        assert not ms.pending_timer_info_ids
        assert box.tpu.verify_all().ok

    def test_child_started_and_closed_both_buffered(self, box):
        """Child start + close both landing behind one in-flight decision:
        the flushed close links to the flushed started event's real ID."""
        box.frontend.start_workflow_execution(DOMAIN, "buf-8", "parent", TL)
        box.pump_once()
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        box.frontend.respond_decision_task_completed(
            resp.token, [Decision(DecisionType.StartChildWorkflowExecution,
                                  dict(workflow_id="buf-8-child",
                                       workflow_type="child-type"))])
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "buf-8")
        events = box.stores.history.read_events(domain_id, "buf-8", run_id)
        initiated = next(
            e.id for e in events
            if e.event_type == EventType.StartChildWorkflowExecutionInitiated)

        # the signal schedules a decision; inject its matching task WITHOUT
        # pumping the queues, so the child-start transfer task stays parked
        # until the decision is in flight
        box.frontend.signal_workflow_execution(DOMAIN, "buf-8", "hold")
        ms = box.stores.execution.get_workflow(domain_id, "buf-8", run_id)
        box.matching.add_decision_task(
            domain_id, TL, "buf-8", run_id,
            ms.execution_info.decision_schedule_id)
        resp2 = box.frontend.poll_for_decision_task(DOMAIN, TL)
        assert resp2 is not None
        engine = box.route("buf-8")
        engine.on_child_started(domain_id, "buf-8", run_id, initiated, "c-run")
        # redelivery while buffered is a no-op (already-started guard)
        engine.on_child_started(domain_id, "buf-8", run_id, initiated, "c-run")
        engine.on_child_closed(domain_id, "buf-8", run_id, initiated,
                               EventType.ChildWorkflowExecutionCompleted)
        ms = box.stores.execution.get_workflow(domain_id, "buf-8", run_id)
        assert len(ms.buffered_events) == 2

        box.frontend.respond_decision_task_completed(resp2.token, [])
        events = box.stores.history.read_events(domain_id, "buf-8", run_id)
        started_ev = next(e for e in events if e.event_type
                          == EventType.ChildWorkflowExecutionStarted)
        closed_ev = next(e for e in events if e.event_type
                         == EventType.ChildWorkflowExecutionCompleted)
        assert started_ev.id < closed_ev.id
        assert closed_ev.get("started_event_id") == started_ev.id
        kinds = [e.event_type for e in events]
        assert kinds.count(EventType.ChildWorkflowExecutionStarted) == 1
        assert box.tpu.verify_all().ok

    def test_terminate_discards_buffer(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "buf-5", "signal", TL)
        resp = _poll_decision(box, "buf-5")
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "buf-5")
        box.frontend.signal_workflow_execution(DOMAIN, "buf-5", "dropped")
        box.frontend.terminate_workflow_execution(DOMAIN, "buf-5")
        ms = box.stores.execution.get_workflow(domain_id, "buf-5", run_id)
        assert ms.execution_info.close_status == CloseStatus.Terminated
        assert not ms.buffered_events
        kinds = [e.event_type for e in
                 box.stores.history.read_events(domain_id, "buf-5", run_id)]
        assert EventType.WorkflowExecutionSignaled not in kinds
        assert box.tpu.verify_all().ok


class TestSticky:
    def test_sticky_pins_next_decision(self, box):
        """After a completion with sticky attributes, the next decision
        dispatches on the STICKY task list."""
        box.frontend.start_workflow_execution(DOMAIN, "st-1", "signal", TL)
        resp = _poll_decision(box, "st-1")
        box.frontend.respond_decision_task_completed(
            resp.token, [], sticky_task_list=STICKY,
            sticky_schedule_to_start_timeout=5)
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "st-1")
        ms = box.stores.execution.get_workflow(domain_id, "st-1", run_id)
        assert ms.execution_info.sticky_task_list == STICKY

        box.frontend.signal_workflow_execution(DOMAIN, "st-1", "go")
        box.pump_once()
        # nothing on the normal list; the decision is on the sticky list
        assert box.frontend.poll_for_decision_task(DOMAIN, TL) is None
        resp2 = box.frontend.poll_for_decision_task(DOMAIN, STICKY)
        assert resp2 is not None
        box.frontend.respond_decision_task_completed(
            resp2.token, [Decision(DecisionType.CompleteWorkflowExecution, {})])
        ms = box.stores.execution.get_workflow(domain_id, "st-1", run_id)
        assert ms.execution_info.close_status == CloseStatus.Completed
        # verify_all masks the sticky hash (replay clears stickyness)
        assert box.tpu.verify_all().ok

    def test_sticky_schedule_to_start_timeout_falls_back(self, box):
        """Sticky worker dies: the schedule-to-start timer fires, the
        decision re-dispatches on the NORMAL list with attempt NOT
        incremented (the non-increment FailDecision path) and stickyness
        cleared."""
        box.frontend.start_workflow_execution(DOMAIN, "st-2", "signal", TL)
        resp = _poll_decision(box, "st-2")
        box.frontend.respond_decision_task_completed(
            resp.token, [], sticky_task_list=STICKY,
            sticky_schedule_to_start_timeout=5)
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "st-2")

        box.frontend.signal_workflow_execution(DOMAIN, "st-2", "go")
        box.pump_once()  # decision scheduled on sticky list; nobody polls it
        box.advance_time(6)
        box.pump_once()  # schedule-to-start timer fires

        events = box.stores.history.read_events(domain_id, "st-2", run_id)
        kinds = [e.event_type for e in events]
        i_timeout = kinds.index(EventType.DecisionTaskTimedOut)
        timed_out = events[i_timeout]
        assert timed_out.get("timeout_type") == int(TimeoutType.ScheduleToStart)
        # explicit re-schedule follows, attempt stays 0, sticky cleared
        assert kinds[i_timeout + 1] == EventType.DecisionTaskScheduled
        assert events[i_timeout + 1].get("attempt") == 0
        assert events[i_timeout + 1].get("task_list") == TL
        ms = box.stores.execution.get_workflow(domain_id, "st-2", run_id)
        assert ms.execution_info.sticky_task_list == ""
        assert ms.execution_info.decision_attempt == 0

        # the normal list serves it now
        box.pump_once()
        resp2 = box.frontend.poll_for_decision_task(DOMAIN, TL)
        assert resp2 is not None
        box.frontend.respond_decision_task_completed(
            resp2.token, [Decision(DecisionType.CompleteWorkflowExecution, {})])
        ms = box.stores.execution.get_workflow(domain_id, "st-2", run_id)
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert box.tpu.verify_all().ok

    def test_completion_without_sticky_clears(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "st-3", "signal", TL)
        resp = _poll_decision(box, "st-3")
        box.frontend.respond_decision_task_completed(
            resp.token, [], sticky_task_list=STICKY,
            sticky_schedule_to_start_timeout=5)
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "st-3")
        box.frontend.signal_workflow_execution(DOMAIN, "st-3", "a")
        box.pump_once()
        resp2 = box.frontend.poll_for_decision_task(DOMAIN, STICKY)
        box.frontend.respond_decision_task_completed(resp2.token, [])
        ms = box.stores.execution.get_workflow(domain_id, "st-3", run_id)
        assert ms.execution_info.sticky_task_list == ""
