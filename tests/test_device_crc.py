"""Device-side CRC32 parity with the host checksum (ops/crc.py).

The device hash must be bit-identical to core.checksum.crc32_of_rows
(zlib IEEE CRC32 over little-endian int64 bytes) — it replaces the host
pull of full payload rows on the bench/verify paths.
"""
import numpy as np

from cadence_tpu.core.checksum import DEFAULT_LAYOUT, crc32_of_rows
from cadence_tpu.ops.crc import crc32_rows, replay_to_crc


class TestDeviceCRC:
    def test_matches_zlib_on_random_rows(self):
        rng = np.random.default_rng(7)
        rows = rng.integers(np.iinfo(np.int64).min, np.iinfo(np.int64).max,
                            size=(64, 89), dtype=np.int64)
        assert (np.asarray(crc32_rows(rows)) == crc32_of_rows(rows)).all()

    def test_matches_zlib_on_payload_values(self):
        # realistic payload rows incl. the PAD sentinel (1<<62) and zeros
        from cadence_tpu.core.checksum import PAD
        rows = np.full((8, 89), PAD, dtype=np.int64)
        rows[:, :11] = np.arange(88).reshape(8, 11)
        rows[3] = 0
        assert (np.asarray(crc32_rows(rows)) == crc32_of_rows(rows)).all()

    def test_replay_to_crc_equals_host_pipeline(self):
        import jax.numpy as jnp

        from cadence_tpu.gen.corpus import generate_corpus
        from cadence_tpu.ops.encode import encode_corpus
        from cadence_tpu.ops.replay import replay_to_payload

        hist = generate_corpus("echo_signal", num_workflows=24, seed=3,
                               target_events=60)
        ev = jnp.asarray(encode_corpus(hist))
        rows, errors = replay_to_payload(ev, DEFAULT_LAYOUT)
        want = crc32_of_rows(np.asarray(rows))
        crc, errors2 = replay_to_crc(ev, DEFAULT_LAYOUT)
        assert (np.asarray(crc) == want).all()
        assert (np.asarray(errors2) == np.asarray(errors)).all()
