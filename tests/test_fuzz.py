"""Generative history & interleaving fuzzer (ISSUE 15).

The contract under test (gen/fuzz.py, gen/shrink.py, gen/interleave.py):

- GRAMMAR: the seeded walker composes ALL 13 decision types plus the
  arrival/transient/close surface into legal histories, byte-identical
  per (seed, workflow_index) — the coverage counter is the acceptance
  counter, the digest is the reproducibility witness.
- PARITY: every generated corpus replays with zero oracle↔device
  divergence on the dense and wirec paths, through verify_all
  (resident/ladder engine tier, mesh-of-1 AND sharded), and through
  NDC two-branch conflict forks (replay_tree_payloads arbitration).
- SHRINKING: an injected divergence on a 200-event history reduces to a
  ≤3-batch witness that reproduces from the reported seed alone.
- INTERLEAVING: a seeded live-transaction schedule against a durable
  serving-enabled Onebox under op chaos + store faults + crashpoint
  kills converges to checksums byte-identical to a fault-free run, with
  tpu.serving/parity-divergence == 0 and a clean recovery fsck at every
  kill.
- PROMOTION: `fuzz promote` specs regenerate byte-identically (drift
  guarded by digest) and feed bench.py as permanent suites.
"""
import numpy as np
import pytest

from cadence_tpu.core.checksum import DEFAULT_LAYOUT, payload_row
from cadence_tpu.core.enums import DecisionType
from cadence_tpu.gen import fuzz, shrink
from cadence_tpu.gen.corpus import generate_corpus

pytestmark = pytest.mark.fuzz


class TestGrammar:
    def test_reproducible_byte_identical(self):
        """Same (seed, workflow_index) → byte-identical history; a
        different index or seed perturbs it."""
        a = fuzz.generate_fuzz_history(9, 2, 120)
        b = fuzz.generate_fuzz_history(9, 2, 120)
        assert fuzz.history_digest(a) == fuzz.history_digest(b)
        assert (fuzz.history_digest(a)
                != fuzz.history_digest(fuzz.generate_fuzz_history(9, 3, 120)))
        assert (fuzz.history_digest(a)
                != fuzz.history_digest(fuzz.generate_fuzz_history(10, 2, 120)))

    def test_fifty_seed_corpus_covers_all_13_decision_types(self):
        """The acceptance counter: 50 seeds (profiles rotating) emit
        evidence events for every DecisionType member."""
        histories = [
            fuzz.generate_fuzz_history(seed, 0, 80,
                                       fuzz.PROFILES[seed % len(fuzz.PROFILES)])
            for seed in range(50)
        ]
        cov = fuzz.coverage(histories)
        assert not cov["missing_decisions"], cov["missing_decisions"]
        assert set(cov["decisions"]) == {d.name for d in DecisionType}
        assert len(cov["decisions"]) == 13

    def test_corpus_suite_addressing(self):
        """generate_corpus("fuzz:<profile>") routes to the fuzzer — the
        addressing every downstream consumer (bench, specs) speaks."""
        via_suite = generate_corpus("fuzz:signal_storm", 2, seed=4,
                                    target_events=60)
        direct = fuzz.generate_fuzz_corpus(2, seed=4, target_events=60,
                                           profile="signal_storm")
        assert ([fuzz.history_digest(h) for h in via_suite]
                == [fuzz.history_digest(h) for h in direct])

    def test_unknown_profile_rejected(self):
        with pytest.raises(ValueError):
            fuzz.generate_fuzz_history(1, 0, 50, profile="nope")

    def test_capacities_respected(self):
        """The walker keeps every pending table within the payload
        layout — generated corpora exercise the BASE kernel, never the
        overflow suite's fallback path."""
        from cadence_tpu.core.enums import EventType
        for seed in range(6):
            h = fuzz.generate_fuzz_history(seed, 0, 150)
            pend = {k: 0 for k in ("act", "timer", "child")}
            peak = dict(pend)
            for b in h:
                for e in b.events:
                    et = e.event_type
                    if et == EventType.ActivityTaskScheduled:
                        pend["act"] += 1
                    elif et in (EventType.ActivityTaskCompleted,
                                EventType.ActivityTaskFailed,
                                EventType.ActivityTaskTimedOut,
                                EventType.ActivityTaskCanceled):
                        pend["act"] -= 1
                    elif et == EventType.TimerStarted:
                        pend["timer"] += 1
                    elif et in (EventType.TimerFired,
                                EventType.TimerCanceled):
                        pend["timer"] -= 1
                    for k in pend:
                        peak[k] = max(peak[k], pend[k])
            assert peak["act"] <= DEFAULT_LAYOUT.max_activities
            assert peak["timer"] <= DEFAULT_LAYOUT.max_timers


class TestHistoryParity:
    def test_parity_run_smoke(self):
        """The bounded tier-1 sweep: dense + wirec + verify_all + NDC
        forks over every profile, zero divergence, full decision
        coverage asserted by the driver itself."""
        doc = fuzz.parity_run(seeds=7, workflows_per_seed=2,
                              target_events=80)
        assert doc["ok"], {k: doc[k] for k in (
            "dense_divergent", "wirec_divergent", "device_errors",
            "verify_divergent", "ndc_divergent", "missing_decisions")}
        assert doc["workflows"] == 14
        assert doc["ndc_forked"] > 0

    def test_verify_all_sharded_matches_mesh_of_1(self):
        """The engine tier on the conftest 8-device mesh: sharded
        verify_all and mesh-of-1 verify_all agree (both clean) over one
        fuzz corpus — the serving-mesh configuration of the parity
        driver."""
        import jax

        from cadence_tpu.engine.persistence import Stores
        from cadence_tpu.engine.tpu_engine import TPUReplayEngine
        from cadence_tpu.parallel.mesh import make_mesh

        hists = fuzz.generate_fuzz_corpus(12, seed=21, target_events=70)
        for devices in (1, 4):
            stores = Stores()
            keys = fuzz.seed_stores(stores, hists)
            engine = TPUReplayEngine(
                stores, chunk_workflows=8,
                mesh=make_mesh(jax.devices()[:devices]))
            result = engine.verify_all(keys)
            assert result.ok, (devices, result.divergent)
            assert result.verified_on_device + len(result.fallback) \
                == result.total

    @pytest.mark.slow
    def test_wide_sweep(self):
        """The full 50-seed acceptance corpus (also run by
        deploy/smoke_fuzz.sh via the CLI)."""
        doc = fuzz.parity_run(seeds=50, workflows_per_seed=4,
                              target_events=100)
        assert doc["ok"]
        assert not doc["missing_decisions"]


class TestShrinker:
    def test_injected_divergence_shrinks_to_minimal_batches(self):
        """ISSUE 15 satellite: a planted device-side defect on a
        200-event generated history must shrink to ≤3 batches and stay
        reproducible from the reported seed."""
        poison = shrink.inject_poison_signal(5, 0, target_events=200)
        assert poison, "seed 5 emitted no signals — pick another seed"
        pred = shrink.poisoned_parity_predicate(poison)
        report = shrink.shrink_history(5, 0, pred, target_events=200)
        assert report.original_events >= 150
        assert report.shrunk_batches <= 3, report.summary()
        # reproducibility: the minimal slice regenerates from the seed
        minimal = report.reproduce()
        assert shrink.history_digest(minimal) == report.digest
        assert pred(minimal), "reproduced slice no longer fails"
        # 1-minimality: dropping any kept batch kills the failure
        for i in range(len(minimal)):
            assert not pred(minimal[:i] + minimal[i + 1:])

    def test_shrink_rejects_non_failing_input(self):
        with pytest.raises(ValueError):
            shrink.shrink_batches(
                fuzz.generate_fuzz_history(3, 0, 60), lambda b: False)

    def test_real_parity_predicate_clean_on_generated(self):
        """The non-poisoned predicate finds nothing to chase on a clean
        corpus (so `fuzz shrink` without --poison is a no-op today —
        the kernel has no known divergence)."""
        pred = shrink.parity_predicate()
        assert not pred(fuzz.generate_fuzz_history(2, 0, 60))


class TestInterleaving:
    def test_zero_divergence_under_combined_chaos(self):
        """The serving-tier acceptance bar: one seeded schedule, run
        fault-free then under op chaos + store faults + crashpoint
        kills — final checksums byte-identical, parity-divergence 0,
        recovery fsck clean at every kill, closing verify_all clean."""
        from cadence_tpu.gen.interleave import interleave_scenario

        doc = interleave_scenario(
            seed=11, num_workflows=3, length=20, kills=2,
            chaos_spec="drop=0.05,delay=0.05,delay_ms=1,seed=5",
            store_fault_rate=0.04)
        assert doc["ok"], doc
        assert doc["checksums_identical"]
        chaos = doc["chaos"]
        assert chaos["kills_fired"] >= 1
        assert chaos["kills_fired"] == chaos["fsck_clean"]
        assert not chaos["fsck_findings"]
        assert chaos["parity_divergence"] == 0
        assert chaos["serving_transactions"] > 0
        assert chaos["verify_divergent"] == 0
        # the fault families actually fired (the run is not vacuous)
        assert chaos["retries"] > 0
        assert chaos["op_drops"] + chaos["store_faults"] > 0

    def test_schedule_reproducible(self):
        from cadence_tpu.gen.interleave import build_schedule

        assert build_schedule(3, 4, 50, 2) == build_schedule(3, 4, 50, 2)
        assert build_schedule(3, 4, 50, 2) != build_schedule(4, 4, 50, 2)

    @pytest.mark.slow
    def test_wide_interleaving(self):
        from cadence_tpu.gen.interleave import interleave_scenario

        for seed in (7, 23):
            doc = interleave_scenario(
                seed=seed, num_workflows=4, length=60, kills=3,
                chaos_spec="drop=0.05,delay=0.08,delay_ms=2,seed=3",
                store_fault_rate=0.04)
            assert doc["ok"], (seed, doc["chaos"])


class TestPromotion:
    def test_spec_roundtrip_and_drift_guard(self, tmp_path):
        spec = fuzz.make_spec("adversarial-1", seed=13, workflows=4,
                              target_events=60, profile="ndc_conflict",
                              note="found by sweep r01")
        path = fuzz.save_spec(spec, root=str(tmp_path))
        assert path.endswith("fuzz_specs/adversarial-1.json")
        loaded = fuzz.load_specs(str(tmp_path))
        assert [s.name for s in loaded] == ["adversarial-1"]
        histories = loaded[0].generate()
        assert len(histories) == 4
        assert fuzz.history_digest(histories[0]) == spec.digest
        # drift guard: a tampered digest refuses to regenerate
        import dataclasses
        bad = dataclasses.replace(loaded[0], digest="0" * 64)
        with pytest.raises(ValueError):
            bad.generate()

    def test_promoted_spec_parity(self, tmp_path):
        """A promoted corpus replays parity-clean — the gate bench.py's
        fuzz suite re-asserts on every run."""
        spec = fuzz.make_spec("bench-feed", seed=3, workflows=6,
                              target_events=60, profile="chain")
        histories = spec.generate()
        from cadence_tpu.ops.replay import replay_corpus

        rows, _crcs, errors = replay_corpus(histories)
        expected = np.stack([fuzz.oracle_final_row(h) for h in histories])
        assert (errors == 0).all()
        assert (rows == expected).all()

    def test_cli_promote_then_run(self, tmp_path, capsys):
        """The operator loop: `fuzz promote` writes the spec; `fuzz
        shrink` on a clean history reports nothing to shrink."""
        from cadence_tpu.cli import main

        rc = main(["fuzz", "promote", "--name", "cli-spec", "--seed", "8",
                   "--workflows", "3", "--events", "50",
                   "--root", str(tmp_path)])
        assert rc == 0
        assert fuzz.load_specs(str(tmp_path))[0].name == "cli-spec"
        rc = main(["fuzz", "shrink", "--seed", "8", "--events", "50"])
        assert rc == 0


class TestOracleChainFollowing:
    def test_oracle_final_row_follows_continue_as_new(self):
        """A chain-profile history's device row is the NEW run's state
        (FLAG_RUN_RESET chaining); oracle_final_row must follow."""
        from cadence_tpu.oracle.state_builder import StateBuilder

        for seed in range(12):
            h = fuzz.generate_fuzz_history(seed, 0, 60, "chain")
            if not h[-1].new_run_events:
                continue
            sb = StateBuilder()
            sb.replay_history(h)
            assert sb.new_run_state is not None
            from cadence_tpu.core.checksum import STICKY_ROW_INDEX
            row = fuzz.oracle_final_row(h)
            direct = payload_row(sb.new_run_state)
            direct[STICKY_ROW_INDEX] = 0
            assert (row == direct).all()
            break
        else:
            pytest.skip("no chain-closing seed in range — widen it")
