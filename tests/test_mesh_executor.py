"""Mesh-aware serving executor (ISSUE 7).

Covers, on the conftest-provided 8-device virtual CPU mesh:

- mesh-of-1 byte parity with the unsharded kernel (dense payload rows
  AND wirec CRCs) — the serving path at N=1 is the pre-mesh single-chip
  executor, bit for bit;
- mesh-of-2/4 checksum identity with mesh-of-1 on the basic /
  timer_retry / ndc suites — sharding the workflow axis never changes a
  row's result;
- the engine's verify path under a mesh: escalated (capacity-flagged)
  rows resolve identically at every mesh width, and resident suffix
  appends land on — and stay on — the owning device
  (parallel/mesh.workflow_shard);
- per-device observability series under tpu.executor/* and the sharded
  resident pool's per-device byte gauges;
- feeder and rebuilder parity through the same mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cadence_tpu.engine.executor import replay_corpus_mesh, stream_wirec_mesh
from cadence_tpu.engine.persistence import Stores
from cadence_tpu.engine.tpu_engine import TPUReplayEngine
from cadence_tpu.gen.corpus import generate_corpus
from cadence_tpu.ops.encode import encode_corpus
from cadence_tpu.oracle.state_builder import StateBuilder
from cadence_tpu.parallel.mesh import (
    make_mesh,
    mesh_devices_requested,
    serving_mesh,
    workflow_shard,
)
from cadence_tpu.utils import metrics as m

SEED = 20260730


def _events(suite="basic", n=24, seed=3, target=24):
    return encode_corpus(generate_corpus(suite, num_workflows=n, seed=seed,
                                         target_events=target))


def _stores_with(hists):
    stores = Stores()
    keys = []
    for h in hists:
        key = (h[0].domain_id, h[0].workflow_id, h[0].run_id)
        for b in h:
            stores.history.append_batch(*key, list(b.events))
        stores.execution.upsert_workflow(StateBuilder().replay_history(h))
        keys.append(key)
    return stores, keys


class TestServingPathParity:
    def test_mesh_of_1_dense_byte_identical_to_unsharded(self):
        """The pre-change invariant: the serving executor on a mesh of 1
        must produce the exact payload rows (and CRC XOR) of the
        unsharded single-chip kernel."""
        from cadence_tpu.core.checksum import crc32_of_rows
        from cadence_tpu.ops.replay import replay_to_payload

        ev = _events()
        rows_ref, err_ref = replay_to_payload(jnp.asarray(ev))
        rows_ref, err_ref = np.asarray(rows_ref), np.asarray(err_ref)
        rows, errors, _branch, report = replay_corpus_mesh(
            ev, make_mesh(jax.devices()[:1]), chunk_workflows=8)
        assert report.chunks == 3  # genuinely chunked, not one launch
        assert (rows == rows_ref).all()
        assert (errors == err_ref).all()
        assert (int(np.bitwise_xor.reduce(
            crc32_of_rows(rows).astype(np.uint32)))
            == int(np.bitwise_xor.reduce(
                crc32_of_rows(rows_ref).astype(np.uint32))))

    def test_mesh_of_1_wirec_crc_identical_to_oneshot(self):
        from cadence_tpu.ops.replay import replay_wirec_to_crc
        from cadence_tpu.ops.wirec import pack_wirec

        corpus = pack_wirec(_events(n=24))
        crc_ref, err_ref = replay_wirec_to_crc(
            jnp.asarray(corpus.slab), jnp.asarray(corpus.bases),
            jnp.asarray(corpus.n_events), corpus.profile)
        crc_ref = np.asarray(crc_ref).astype(np.uint32)
        crcs, errors, _rep = stream_wirec_mesh(
            corpus, make_mesh(jax.devices()[:1]), n_chunks=2)
        assert (crcs == crc_ref).all()
        assert (errors == np.asarray(err_ref)).all()

    @pytest.mark.parametrize("suite", ["basic", "timer_retry", "ndc"])
    @pytest.mark.parametrize("n_dev", [2, 4])
    def test_mesh_n_checksum_identity(self, suite, n_dev):
        """Mesh-of-N payload rows equal mesh-of-1 on the same corpus —
        the PR-5 diagnostic invariant, now on the serving path."""
        devices = jax.devices()
        assert len(devices) >= n_dev
        ev = _events(suite=suite, n=16, seed=11)
        rows_1, err_1, _b1, _ = replay_corpus_mesh(
            ev, make_mesh(devices[:1]), chunk_workflows=8)
        rows_n, err_n, _bn, _ = replay_corpus_mesh(
            ev, make_mesh(devices[:n_dev]), chunk_workflows=8)
        assert (rows_n == rows_1).all()
        assert (err_n == err_1).all()


class TestEngineMeshVerify:
    def test_verify_all_mesh2_with_escalated_rows(self):
        """The engine's full verify path at mesh-of-2 vs mesh-of-1 on an
        overflow corpus: identical verified counts, the SAME keys
        resolved by the widened-K ladder (escalation rides the sharded
        kernels), zero divergence either way."""
        hists = generate_corpus("overflow", num_workflows=96, seed=SEED,
                                target_events=60)
        devices = jax.devices()
        stores1, keys1 = _stores_with(hists)
        r1 = TPUReplayEngine(stores1, chunk_workflows=32, pipeline_depth=2,
                             mesh=make_mesh(devices[:1])).verify_all(keys1)
        stores2, keys2 = _stores_with(hists)
        r2 = TPUReplayEngine(stores2, chunk_workflows=32, pipeline_depth=2,
                             mesh=make_mesh(devices[:2])).verify_all(keys2)
        assert r1.ok and r2.ok
        assert r1.verified_on_device == r2.verified_on_device == len(keys1)
        assert sorted(r1.escalated) == sorted(r2.escalated)
        assert len(r1.escalated) >= 1
        assert r1.fallback == r2.fallback == []

    def test_resident_suffix_append_lands_on_owning_device(self):
        """Verify seeds the sharded resident pool, an appended batch
        takes the suffix path, and the re-admitted state row lives on
        the device its key hashes to — before AND after the append."""
        hists = generate_corpus("basic", num_workflows=12, seed=7,
                                target_events=30)
        devices = jax.devices()
        mesh = make_mesh(devices[:2])
        stores = Stores()
        keys = []
        for h in hists:
            key = (h[0].domain_id, h[0].workflow_id, h[0].run_id)
            for b in h[:-1]:
                stores.history.append_batch(*key, list(b.events))
            stores.execution.upsert_workflow(
                StateBuilder().replay_history(h[:-1]))
            keys.append(key)
        engine = TPUReplayEngine(stores, chunk_workflows=8,
                                 pipeline_depth=2, mesh=mesh)
        assert engine.verify_all(keys).ok
        assert len(engine.resident) >= 1

        def owning_ok(key):
            shard = workflow_shard(key, 2)
            entry = engine.resident._slices[shard].get(key)
            if entry is None:
                return None
            leaf = jax.tree_util.tree_leaves(entry.state)[0]
            return leaf.devices() == {mesh.devices.flat[shard]}

        seeded = [k for k in keys if owning_ok(k)]
        assert seeded, "no resident entries on their owning device"
        assert all(owning_ok(k) for k in seeded)

        # append the held-back last batch: the suffix path must serve it
        # and the widened/re-admitted row must STAY on the owning device
        for h, key in zip(hists, keys):
            stores.history.append_batch(*key, list(h[-1].events))
            stores.execution.upsert_workflow(
                StateBuilder().replay_history(h), set_current=False)
        result = engine.verify_all(keys)
        assert result.ok
        reg = engine.metrics
        assert reg.counter(m.SCOPE_TPU_RESIDENT,
                           m.M_RESIDENT_SUFFIX_HITS) >= 1
        for k in keys:
            assert owning_ok(k) in (True, None)
        assert any(owning_ok(k) for k in keys)

    def test_per_device_series_on_metrics(self):
        """tpu.executor/* gains device-labelled series (chunks, rows,
        busy gauge) and the sharded resident pool exports per-device
        byte gauges — all reachable through prometheus exposition."""
        hists = generate_corpus("basic", num_workflows=16, seed=5,
                                target_events=24)
        stores, keys = _stores_with(hists)
        engine = TPUReplayEngine(stores, chunk_workflows=8,
                                 pipeline_depth=2,
                                 mesh=make_mesh(jax.devices()[:2]))
        assert engine.verify_all(keys).ok
        reg = engine.metrics
        assert reg.counter(m.SCOPE_TPU_EXECUTOR, m.M_EXEC_CHUNKS) >= 2
        for d in range(2):
            assert reg.counter(
                m.SCOPE_TPU_EXECUTOR,
                m.device_metric(m.M_EXEC_CHUNKS, d)) >= 2
            assert reg.counter(
                m.SCOPE_TPU_EXECUTOR,
                m.device_metric(m.M_EXEC_ROWS, d)) >= 1
        # busy gauge settled back to zero after the run
        assert reg.gauge_value(m.SCOPE_TPU_EXECUTOR,
                               m.M_EXEC_DEVICE_BUSY) == 0.0
        prom = reg.to_prometheus()
        assert 'cadence_chunks_dispatched_dev0_total{scope="tpu.executor"}' \
            in prom
        assert 'cadence_device_busy_dev1{scope="tpu.executor"}' in prom
        # sharded resident pool: per-device occupancy gauges
        assert reg.gauge_value(m.SCOPE_TPU_RESIDENT,
                               m.device_metric(m.M_RESIDENT_BYTES, 0)) \
            + reg.gauge_value(m.SCOPE_TPU_RESIDENT,
                              m.device_metric(m.M_RESIDENT_BYTES, 1)) > 0

    def test_resident_budget_splits_per_device(self):
        from cadence_tpu.engine.resident import ResidentStateCache

        cache = ResidentStateCache(budget_bytes=1 << 20,
                                   mesh=make_mesh(jax.devices()[:4]))
        assert cache.n_shards == 4
        assert cache.slice_budget == (1 << 20) // 4
        # rebinding to a different width drops entries (placement moved)
        cache.set_mesh(make_mesh(jax.devices()[:2]))
        assert cache.n_shards == 2 and len(cache) == 0


class TestMeshConsumers:
    def test_rebuilder_mesh_parity(self):
        from cadence_tpu.core.checksum import STICKY_ROW_INDEX, payload_row
        from cadence_tpu.engine.rebuild import DeviceRebuilder

        hists = generate_corpus("timer_retry", num_workflows=10, seed=9,
                                target_events=24)
        rb = DeviceRebuilder(chunk_jobs=4,
                             mesh=make_mesh(jax.devices()[:2]))
        states = rb.rebuild([(h, None) for h in hists])
        assert rb.stats.device == len(hists)
        assert rb.stats.oracle_fallback == 0
        for ms, h in zip(states, hists):
            got = payload_row(ms)
            got[STICKY_ROW_INDEX] = 0
            expected = payload_row(StateBuilder().replay_history(h))
            expected[STICKY_ROW_INDEX] = 0
            assert (got == expected).all()

    def test_feeder_mesh_parity(self):
        from cadence_tpu.native import packing
        from cadence_tpu.native.feeder import feed_corpus
        from cadence_tpu.ops.replay import replay_corpus

        if not packing.native_available():
            pytest.skip("native packer unavailable")
        hists = generate_corpus("basic", num_workflows=18, seed=7,
                                target_events=24)
        rows_direct, _, errors_direct = replay_corpus(hists)
        rows, errors, report = feed_corpus(
            hists, chunk_workflows=6, depth=3,
            mesh=make_mesh(jax.devices()[:2]))
        assert report.chunks == 3
        assert (errors == errors_direct).all()
        assert (rows == rows_direct).all()

    def test_serving_mesh_env_knob(self, monkeypatch):
        monkeypatch.delenv("CADENCE_TPU_MESH_DEVICES", raising=False)
        assert mesh_devices_requested() == 1
        assert int(serving_mesh().devices.size) == 1
        monkeypatch.setenv("CADENCE_TPU_MESH_DEVICES", "4")
        assert mesh_devices_requested() == 4
        assert int(serving_mesh().devices.size) == 4
        monkeypatch.setenv("CADENCE_TPU_MESH_DEVICES", "all")
        assert mesh_devices_requested() == 0
        assert int(serving_mesh().devices.size) == len(jax.devices())

    def test_workflow_shard_stable(self):
        key = ("d", "wf", "run")
        assert workflow_shard(key, 1) == 0
        for n in (2, 4, 8):
            s = workflow_shard(key, n)
            assert 0 <= s < n
            assert workflow_shard(key, n) == s  # deterministic
