"""Crash-consistency harness: kill-anywhere cut points, recovery fsck,
and dual-backend recovery parity.

The durability guarantee under test: for ANY prefix of the WAL — the
process may die between any two record writes, mid-record, or by SIGKILL
at an armed crashpoint — full recovery yields mutable states
byte-identical to ones the fault-free run committed, the recovery fsck
reports zero findings, and the task refresher regenerates work for
exactly the current runs. Everything here runs over BOTH open_log
backends (JSONL and SQLite) unless a case is physically backend-specific
(only JSONL has torn tails)."""
import json
import os

import pytest

from cadence_tpu.core.enums import CloseStatus, DecisionType
from cadence_tpu.engine import crashpoints, walcheck
from cadence_tpu.engine.crashpoints import CrashPoint, SimulatedCrash
from cadence_tpu.engine.crashsim import CrashSim, seed_workload
from cadence_tpu.engine.durability import (
    open_durable_stores,
    read_log,
    recover_stores,
)
from cadence_tpu.engine.history_engine import Decision
from cadence_tpu.engine.onebox import Onebox

pytestmark = pytest.mark.crash

BACKENDS = ("jsonl", "sqlite")
DOMAIN = "crash-domain"
TL = "crash-tl"


def _wal_name(backend: str) -> str:
    return "wal.db" if backend == "sqlite" else "wal.jsonl"


# per-test dual-backend `wal` fixture: tests/conftest.py


@pytest.fixture(scope="module", params=BACKENDS)
def seeded_wal(request, tmp_path_factory):
    """One recorded workload per backend, shared by the read-only tests."""
    path = str(tmp_path_factory.mktemp("crashsim") / _wal_name(request.param))
    seed_workload(path, num_workflows=4)
    return path


def _zero_findings(path, stores):
    findings = (walcheck.audit_records(walcheck.read_raw_lines(path))
                + walcheck.audit_stores(stores))
    assert findings == [], [f.as_dict() for f in findings]


class TestCutPointMatrix:
    def test_every_cut_recovers_prefix_consistent(self, seeded_wal):
        """The tentpole gate: recovery at EVERY record boundary (and, on
        JSONL, at every torn mid-record tail) yields checksums that are a
        prefix-consistent subset of the fault-free run, with zero fsck
        findings and a refresher task for every current run."""
        sim = CrashSim(seeded_wal)
        report = sim.run(torn=True, stride=1)
        assert report.records > 40
        assert report.ok, report.summary()
        if sim.backend == "jsonl":
            assert any(c.torn for c in report.cuts)
        else:
            assert not any(c.torn for c in report.cuts)  # atomic appends
        # the full-log cut recovered everything the workload committed
        final = report.cuts[-1]
        assert final.cut == report.records and final.recovered_runs >= 4

    def test_recovered_workload_drives_to_completion(self, seeded_wal):
        """Recovery of the full log is not just checksum-clean — the open
        workflows actually finish on the recovered cluster."""
        stores, report = recover_stores(seeded_wal, verify_on_device=False,
                                        rebuild_on_device=False)
        assert report.ok and report.open_workflows >= 1
        box = Onebox(num_hosts=1, num_shards=4, stores=stores)
        assert box.refresh_all_tasks() > 0
        box.pump_once()
        complete = Decision(DecisionType.CompleteWorkflowExecution)
        for _ in range(100):
            resp = box.frontend.poll_for_activity_task(DOMAIN, TL)
            if resp is not None:
                box.frontend.respond_activity_task_completed(resp.token)
            resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
            if resp is not None:
                box.frontend.respond_decision_task_completed(resp.token,
                                                             [complete])
            if box.pump_once() == 0 and box.matching.backlog() == 0:
                break
        for rec in box.frontend.list_open_workflow_executions(DOMAIN):
            pytest.fail(f"{rec.workflow_id} still open after recovery drive")


class TestCrashpoints:
    """Named injection sites: the in-process kill-anywhere loop."""

    SITES = (crashpoints.SITE_BEFORE_WRITE, crashpoints.SITE_MID_RECORD,
             crashpoints.SITE_AFTER_WRITE, crashpoints.SITE_AFTER_FSYNC)

    def _workload_until_crash(self, wal):
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        crashed = False
        try:
            box.frontend.register_domain(DOMAIN)
            for i in range(8):
                box.frontend.start_workflow_execution(DOMAIN, f"cp-{i}",
                                                      "t", TL)
                box.frontend.signal_workflow_execution(DOMAIN, f"cp-{i}",
                                                       "go")
        except SimulatedCrash:
            crashed = True
        return crashed

    def test_crash_at_every_wal_site_recovers_clean(self, wal):
        """Arm each WAL site at several hit depths; every crash must leave
        a WAL that recovers with zero fsck findings."""
        for site in self.SITES:
            for hit in (2, 5, 9):
                if os.path.exists(wal):
                    os.remove(wal)
                crashpoints.install(CrashPoint(site, hit=hit))
                crashed = self._workload_until_crash(wal)
                crashpoints.uninstall()
                assert crashed, f"{site} hit={hit} never fired"
                stores, report = recover_stores(wal,
                                                verify_on_device=False,
                                                rebuild_on_device=False)
                assert report.ok, (site, hit, report.divergent)
                _zero_findings(wal, stores)

    def test_crash_between_history_and_pointer_record(self, wal):
        """A store-level site kills between the two WAL records of one
        start transaction (history logged, current pointer not): the run
        is quarantined, never surfaced open, and the id is startable."""
        crashpoints.install(CrashPoint("store.execution.create_workflow",
                                       hit=2))
        crashed = self._workload_until_crash(wal)
        crashpoints.uninstall()
        assert crashed
        stores, report = recover_stores(wal, verify_on_device=False,
                                        rebuild_on_device=False)
        assert report.ok
        assert len(report.quarantined) == 1
        _zero_findings(wal, stores)
        box = Onebox(num_hosts=1, num_shards=2, stores=stores)
        quarantined_wf = report.quarantined[0][1]
        assert quarantined_wf not in [
            r.workflow_id for r in
            box.frontend.list_open_workflow_executions(DOMAIN)]
        # the torn start's workflow id is startable again
        box.frontend.start_workflow_execution(DOMAIN, quarantined_wf, "t",
                                              TL)

    def test_jsonl_torn_tail_really_on_disk(self, tmp_path):
        """The mid-record site leaves a genuine partial line (fsynced), and
        reopening the log heals it instead of welding onto garbage."""
        from cadence_tpu.engine.durability import DurableLog
        wal = str(tmp_path / "torn.jsonl")
        log = DurableLog(wal)
        log.append({"t": "ver", "v": 2})
        crashpoints.install(CrashPoint(crashpoints.SITE_MID_RECORD,
                                       torn_fraction=0.4))
        with pytest.raises(SimulatedCrash):
            log.append({"t": "cfg", "k": "crash-here", "v": 1, "dom": None})
        crashpoints.uninstall()
        log.close()
        raw = open(wal, "rb").read()
        assert not raw.endswith(b"\n")  # the tear is real
        assert read_log(wal) == [{"t": "ver", "v": 2}]
        log = DurableLog(wal)  # reopen: heals the tail before appending
        log.append({"t": "cfg", "k": "after", "v": 2, "dom": None})
        log.close()
        assert [r.get("k") for r in read_log(wal)] == [None, "after"]

    def test_sqlite_mid_record_is_invisible(self, tmp_path):
        """SQLite's torn-write story: a crash between INSERT and COMMIT
        loses the row entirely — recovery never sees a partial record."""
        from cadence_tpu.engine.durability import SqliteLog
        wal = str(tmp_path / "torn.db")
        log = SqliteLog(wal)
        log.append({"t": "ver", "v": 2})
        crashpoints.install(CrashPoint(crashpoints.SITE_MID_RECORD))
        with pytest.raises(SimulatedCrash):
            log.append({"t": "cfg", "k": "never", "v": 1, "dom": None})
        crashpoints.uninstall()
        log.close()
        assert read_log(wal) == [{"t": "ver", "v": 2}]

    def test_spec_parsing(self):
        point = crashpoints.parse_spec(
            "site=wal.append.after-write,hit=3,mode=kill,type=h,torn=0.25")
        assert (point.site, point.hit, point.mode, point.record_type,
                point.torn_fraction) == ("wal.append.after-write", 3,
                                         "kill", "h", 0.25)
        with pytest.raises(ValueError):
            crashpoints.parse_spec("hit=3")  # site is mandatory
        with pytest.raises(ValueError):
            crashpoints.parse_spec("site=x,bogus=1")

    def test_record_type_filter(self, tmp_path):
        """type=h arms the site for history records only — domain and
        pointer records pass through untouched."""
        from cadence_tpu.engine.durability import DurableLog
        wal = str(tmp_path / "typed.jsonl")
        log = DurableLog(wal)
        crashpoints.install(CrashPoint(crashpoints.SITE_BEFORE_WRITE,
                                       record_type="h"))
        log.append({"t": "ver", "v": 2})
        log.append({"t": "cfg", "k": "x", "v": 1, "dom": None})
        with pytest.raises(SimulatedCrash):
            log.append({"t": "h", "d": "d", "w": "w", "r": "r", "b": 0,
                        "blob": ""})
        crashpoints.uninstall()
        log.close()
        assert len(read_log(wal)) == 2


class TestSigkillAtCrashpoint:
    """Subprocess mode over the rpc/cluster launch seam: the store server
    process is SIGKILLed by its own armed crashpoint mid-append; the WAL
    it leaves behind recovers clean."""

    def test_store_sigkilled_mid_append_recovers(self, tmp_path):
        from cadence_tpu.rpc.cluster import launch
        wal = str(tmp_path / "kill.jsonl")
        cluster = launch(
            num_hosts=1, num_shards=4, wal=wal,
            env_extra={"CADENCE_TPU_CRASHPOINT":
                       "site=wal.append.after-write,hit=14,mode=kill"})
        try:
            fe = cluster.frontend(0)
            fe.register_domain(DOMAIN)
            with pytest.raises(Exception):
                for i in range(60):
                    fe.start_workflow_execution(DOMAIN, f"kk-{i}", "t", TL)
            deadline = __import__("time").monotonic() + 10
            while __import__("time").monotonic() < deadline:
                if cluster.store_proc.poll() is not None:
                    break
                __import__("time").sleep(0.1)
            assert cluster.store_proc.poll() is not None, \
                "store server survived its kill crashpoint"
        finally:
            cluster.stop()
        stores, report = recover_stores(wal, verify_on_device=False,
                                        rebuild_on_device=False)
        assert report.ok
        assert report.executions_rebuilt >= 1
        _zero_findings(wal, stores)


class TestFsck:
    def test_clean_wal_has_zero_findings(self, seeded_wal):
        report = walcheck.fsck(seeded_wal)
        assert report.ok, report.as_dict()

    def test_findings_surface_on_metrics(self, tmp_path):
        from cadence_tpu.utils.metrics import MetricsRegistry
        wal = str(tmp_path / "bad.jsonl")
        with open(wal, "w") as fh:
            fh.write(json.dumps({"t": "ver", "v": 2}) + "\n")
            fh.write(json.dumps({"t": "qa", "q": "q1", "c": "c1",
                                 "i": 7}) + "\n")
        registry = MetricsRegistry()
        report = walcheck.fsck(wal, metrics=registry)
        assert [f.code for f in report.findings] == ["orphaned-ack"]
        assert registry.counter("walcheck", "finding-orphaned-ack") == 1
        assert "walcheck" in registry.to_prometheus()

    def test_each_corruption_class_reports_typed_finding(self, tmp_path):
        """stale migration label / dangling current pointer / orphaned
        ack: one doctored log per class, one typed finding per log."""
        cases = {
            "stale-migration-label": [
                {"t": "ver", "v": 2},
                # v1-format domain record under a v2 header
                {"t": "d", "id": "x", "name": "n", "ret": 1, "act": True,
                 "ac": "primary", "cl": ["primary"], "fv": 0, "nv": 0}],
            "dangling-current-pointer": [
                {"t": "ver", "v": 2},
                {"t": "cur", "d": "dd", "w": "ghost", "r": "r1", "st": 1,
                 "cs": 0}],
            "orphaned-ack": [
                {"t": "ver", "v": 2},
                {"t": "qa", "q": "q1", "c": "c1", "i": 5}],
        }
        for code, records in cases.items():
            wal = str(tmp_path / f"{code}.jsonl")
            with open(wal, "w") as fh:
                for rec in records:
                    fh.write(json.dumps(rec) + "\n")
            report = walcheck.fsck(wal)
            assert code in [f.code for f in report.findings], \
                (code, report.as_dict())

    def test_wal_clean_migrates_v1_prefix(self, tmp_path, capsys):
        """The fixed `wal clean`: a v1 prefix under a current-version
        header is MIGRATED, not re-labeled — fsck reports zero findings on
        the cleaned log (the acceptance gate for ADVICE r5)."""
        from cadence_tpu.cli import main as cli_main
        wal = str(tmp_path / "v1.jsonl")
        with open(wal, "w") as fh:
            # pre-header v1 log (no version record, no v2 domain fields)
            fh.write(json.dumps({"t": "d", "id": "d1", "name": "old",
                                 "ret": 2, "act": True, "ac": "primary",
                                 "cl": ["primary"], "fv": 0,
                                 "nv": 0}) + "\n")
        rc = cli_main(["--wal", wal, "wal", "clean"])
        capsys.readouterr()
        assert rc == 0
        from cadence_tpu.engine.durability import WAL_VERSION
        records = read_log(wal)
        assert records[0] == {"t": "ver", "v": WAL_VERSION}
        domain_rec = records[1]
        assert {"st", "desc", "arc"} <= set(domain_rec)  # migrated body
        report = walcheck.fsck(wal)
        assert report.ok, report.as_dict()
        assert report.stores.domain.by_name("old").retention_days == 2

    def test_cli_fsck_verb(self, tmp_path, capsys):
        from cadence_tpu.cli import main as cli_main
        wal = str(tmp_path / "cli.jsonl")
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "fsck-wf", "t", TL)
        box.stores.wal.close()
        rc = cli_main(["--wal", wal, "wal", "fsck"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["ok"] and out["findings"] == []
        # doctor an orphaned ack in: the verb now fails with the finding
        with open(wal, "a") as fh:
            fh.write(json.dumps({"t": "qa", "q": "q", "c": "c",
                                 "i": 9}) + "\n")
        rc = cli_main(["--wal", wal, "wal", "fsck"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert "orphaned-ack" in [f["code"] for f in out["findings"]]


class TestSignalDedupRecovery:
    def test_redelivered_request_id_noops_after_recovery(self, wal):
        """A cross-cluster/client signal redelivered AFTER crash recovery
        must not append a duplicate event: the request id rides the
        WorkflowExecutionSignaled event and replay repopulates the dedup
        set (ADVICE r5)."""
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "sig-wf", "t", TL)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        box.frontend.signal_workflow_execution(DOMAIN, "sig-wf", "go",
                                               request_id="rid-1")
        # same-process duplicate already no-ops
        box.frontend.signal_workflow_execution(DOMAIN, "sig-wf", "go",
                                               request_id="rid-1")
        run_id = box.stores.execution.get_current_run_id(domain_id,
                                                         "sig-wf")
        live = box.stores.execution.get_workflow(domain_id, "sig-wf",
                                                 run_id)
        assert live.execution_info.signal_count == 1
        box.stores.wal.close()
        del box

        stores, report = recover_stores(wal, verify_on_device=False,
                                        rebuild_on_device=False)
        assert report.ok
        rebuilt = stores.execution.get_workflow(domain_id, "sig-wf",
                                                run_id)
        assert "rid-1" in rebuilt.signal_requested_ids
        box2 = Onebox(num_hosts=1, num_shards=2, stores=stores)
        box2.frontend.signal_workflow_execution(DOMAIN, "sig-wf", "go",
                                                request_id="rid-1")
        after = stores.execution.get_workflow(domain_id, "sig-wf", run_id)
        assert after.execution_info.signal_count == 1  # still deduped
        box2.frontend.signal_workflow_execution(DOMAIN, "sig-wf", "go",
                                                request_id="rid-2")
        after = stores.execution.get_workflow(domain_id, "sig-wf", run_id)
        assert after.execution_info.signal_count == 2  # fresh ids apply

    def test_dedup_set_replicates_to_standby(self):
        """The request id crosses the replication stream too: a standby's
        rebuilt state carries the dedup set, so promotion + redelivery
        stays a no-op."""
        from cadence_tpu.engine.multicluster import ReplicatedClusters
        clusters = ReplicatedClusters(num_hosts=1, num_shards=2)
        clusters.register_global_domain(DOMAIN)
        clusters.active.frontend.start_workflow_execution(
            DOMAIN, "rep-wf", "t", TL)
        clusters.active.frontend.signal_workflow_execution(
            DOMAIN, "rep-wf", "go", request_id="xdc-1")
        clusters.replicate()
        domain_id = clusters.standby.stores.domain.by_name(
            DOMAIN).domain_id
        run_id = clusters.standby.stores.execution.get_current_run_id(
            domain_id, "rep-wf")
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, "rep-wf", run_id)
        assert "xdc-1" in standby_ms.signal_requested_ids


class TestHistorySizeRecovery:
    def test_history_size_rebuilt_from_blob_sizes(self, wal):
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "hs-wf", "t", TL)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        for name in ("a", "b", "c"):
            box.frontend.signal_workflow_execution(DOMAIN, "hs-wf", name)
        run_id = box.stores.execution.get_current_run_id(domain_id,
                                                         "hs-wf")
        live = box.stores.execution.get_workflow(domain_id, "hs-wf",
                                                 run_id)
        assert live.history_size > 0
        box.stores.wal.close()
        del box
        stores, report = recover_stores(wal, verify_on_device=False,
                                        rebuild_on_device=False)
        assert report.ok
        rebuilt = stores.execution.get_workflow(domain_id, "hs-wf", run_id)
        assert rebuilt.history_size == live.history_size
        _zero_findings(wal, stores)


class TestServingTierCrash:
    """ISSUE 10 satellite: a crashpoint mid-transaction must leave the
    serving tier's resident state either INVALIDATED or parity-clean —
    never serving a state built from a transaction that half-landed."""

    def test_mid_transaction_crash_then_tail_overwrite_stays_clean(self):
        from cadence_tpu.engine import crashpoints
        from cadence_tpu.engine.crashpoints import SimulatedCrash
        from cadence_tpu.engine.onebox import Onebox
        from cadence_tpu.utils import metrics as m

        box = Onebox(num_hosts=1, num_shards=2)
        sched = box.enable_serving()
        fe = box.frontend
        fe.register_domain("svc")
        fe.start_workflow_execution("svc", "wf", "t", "tl")
        fe.signal_workflow_execution("svc", "wf", "s0", request_id="r0")
        assert sched.drain(timeout=300.0)

        # crash between the history append and the execution-row commit
        # point: the orphan-tail shape — history holds a batch the
        # authoritative state never acknowledged. The serving handoff
        # runs only AFTER a successful commit, so the tier must never
        # have seen the phantom batch.
        crashpoints.install(crashpoints.parse_spec(
            "site=store.execution.update_workflow,mode=raise"))
        try:
            with pytest.raises(SimulatedCrash):
                fe.signal_workflow_execution("svc", "wf", "s-crash",
                                             request_id="rc")
        finally:
            crashpoints.uninstall()

        # the next committed transaction OVERWRITES the orphan tail at
        # the same event ids (append_batch node-overwrite semantics);
        # the content address catches any divergence between what the
        # resident state covers and what the store now holds
        fe.signal_workflow_execution("svc", "wf", "s1", request_id="r1")
        assert sched.drain(timeout=300.0)
        assert box.metrics.counter(m.SCOPE_TPU_SERVING,
                                   m.M_SERVING_DIVERGENCE) == 0
        res = box.route("wf").last_serving_ticket.result(timeout=60)
        assert res.ok and res.parity_ok
        r = box.tpu.verify_all()
        assert r.ok, r.divergent
        sched.stop()

    def test_crash_before_history_append_is_nothing_applied(self):
        """The pre-apply crash family (store.history.append_batch fires
        BEFORE the write): the transaction fails whole, the resident
        entry stays a valid prefix, the next transaction serves
        suffix-clean."""
        from cadence_tpu.engine import crashpoints
        from cadence_tpu.engine.crashpoints import SimulatedCrash
        from cadence_tpu.engine.onebox import Onebox
        from cadence_tpu.utils import metrics as m

        box = Onebox(num_hosts=1, num_shards=2)
        sched = box.enable_serving()
        fe = box.frontend
        fe.register_domain("svc")
        fe.start_workflow_execution("svc", "wf2", "t", "tl")
        assert sched.drain(timeout=300.0)
        crashpoints.install(crashpoints.parse_spec(
            "site=store.history.append_batch,mode=raise"))
        try:
            with pytest.raises(SimulatedCrash):
                fe.signal_workflow_execution("svc", "wf2", "sx",
                                             request_id="rx")
        finally:
            crashpoints.uninstall()
        fe.signal_workflow_execution("svc", "wf2", "s1", request_id="r1")
        assert sched.drain(timeout=300.0)
        res = box.route("wf2").last_serving_ticket.result(timeout=60)
        assert res.ok and res.parity_ok and res.path in ("suffix", "cold")
        assert box.metrics.counter(m.SCOPE_TPU_SERVING,
                                   m.M_SERVING_DIVERGENCE) == 0
        assert box.tpu.verify_all().ok
        sched.stop()


class TestPurgeAckRecovery:
    def test_purged_queue_acks_dropped_and_stay_dropped(self, wal):
        """Items re-enqueued after a purge must never be skipped by a
        consumer resuming from a pre-purge ack level — live, and after
        recovery replays the purge record (ADVICE r5)."""
        from cadence_tpu.engine.domainrepl import DomainReplicationTask
        stores = open_durable_stores(wal)
        task = DomainReplicationTask(
            domain_id="d", name="n", retention_days=1,
            active_cluster="primary", clusters=("primary",),
            failover_version=0, notification_version=0, status=0,
            description="", history_archival_uri="")
        stores.queue.enqueue("dlq", task)
        stores.queue.enqueue("dlq", task)
        stores.queue.set_ack("dlq", "worker", 1)
        assert stores.queue.get_ack("dlq", "worker") == 2
        stores.queue.purge("dlq")
        assert stores.queue.get_ack("dlq", "worker") == 0  # live reset
        stores.queue.enqueue("dlq", task)
        assert stores.queue.read(
            "dlq", stores.queue.get_ack("dlq", "worker"))  # visible again
        stores.wal.close()

        recovered, _ = recover_stores(wal, verify_on_device=False,
                                      rebuild_on_device=False)
        assert recovered.queue.size("dlq") == 1
        assert recovered.queue.get_ack("dlq", "worker") == 0
        assert recovered.queue.read(
            "dlq", recovered.queue.get_ack("dlq", "worker"))
        _zero_findings(wal, recovered)


class TestReplicationCrash:
    """ISSUE 17 satellite: the standby apply pump's crash seams
    (repl.apply fires before a task applies, repl.ack after its ack
    advances) — a death at either point must never double-apply a batch
    on redelivery and never lose a durably-acked position."""

    def _clusters(self, standby_stores=None):
        from cadence_tpu.engine.multicluster import ReplicatedClusters
        clusters = ReplicatedClusters(num_hosts=1, num_shards=2,
                                      standby_stores=standby_stores)
        clusters.register_global_domain(DOMAIN)
        clusters.active.frontend.start_workflow_execution(
            DOMAIN, "rc-wf", "t", TL)
        for name in ("a", "b", "c"):
            clusters.active.frontend.signal_workflow_execution(
                DOMAIN, "rc-wf", name, request_id=f"rc-{name}")
        return clusters

    @staticmethod
    def _events(box, domain_id, run_id):
        return [(e.id, e.event_type, e.version)
                for e in box.stores.history.read_events(
                    domain_id, "rc-wf", run_id)]

    def test_crash_before_apply_then_retry_applies_once(self):
        from cadence_tpu.core.checksum import payload_row
        from cadence_tpu.engine.replication import SITE_REPL_APPLY

        clusters = self._clusters()
        crashpoints.install(CrashPoint(site=SITE_REPL_APPLY, hit=2,
                                       mode="raise"))
        try:
            with pytest.raises(SimulatedCrash):
                clusters.replicate()
        finally:
            crashpoints.uninstall()
        clusters.replicate()  # the restarted pump resumes from its ack
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, "rc-wf")
        a = self._events(clusters.active, domain_id, run_id)
        s = self._events(clusters.standby, domain_id, run_id)
        assert a == s  # once each — no duplicate, no hole
        active_ms = clusters.active.stores.execution.get_workflow(
            domain_id, "rc-wf", run_id)
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, "rc-wf", run_id)
        assert (payload_row(active_ms) == payload_row(standby_ms)).all()

    def test_crash_at_ack_then_full_redelivery_dedups(self):
        """Death AFTER applies but before the ack persisted: the
        restarted pump re-reads from the stale ack and redelivers — the
        replicator's first_event_id dedup must swallow every duplicate
        without touching history."""
        from cadence_tpu.core.checksum import payload_row
        from cadence_tpu.engine.replication import (
            SITE_REPL_ACK,
            ReplicationTaskProcessor,
        )

        clusters = self._clusters()
        crashpoints.install(CrashPoint(site=SITE_REPL_ACK, hit=3,
                                       mode="raise"))
        try:
            with pytest.raises(SimulatedCrash):
                clusters.replicate()
        finally:
            crashpoints.uninstall()
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, "rc-wf")
        before = self._events(clusters.standby, domain_id, run_id)
        assert before  # some prefix really applied before the death
        # restarted pump: fresh processor whose ack position is the
        # PRE-CRASH level (the in-memory ack died with the process)
        restarted = ReplicationTaskProcessor(
            clusters.replicator, clusters.publisher,
            clusters.standby.stores,
            source_history_reader=clusters._read_source_history,
            tpu=clusters.standby.tpu)
        restarted.metrics = clusters.standby.metrics
        while restarted.process_once():
            pass
        assert restarted.deduped > 0  # the redelivered prefix
        a = self._events(clusters.active, domain_id, run_id)
        s = self._events(clusters.standby, domain_id, run_id)
        assert a == s
        active_ms = clusters.active.stores.execution.get_workflow(
            domain_id, "rc-wf", run_id)
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, "rc-wf", run_id)
        assert (payload_row(active_ms) == payload_row(standby_ms)).all()

    def test_standby_wal_restores_ack_and_state(self, wal):
        """The durable seat: a standby on a WAL persists its applied
        state AND its consumer ack ('qa' records); recovery restores
        both, so the resumed pump neither re-applies nor skips."""
        clusters = self._clusters(standby_stores=open_durable_stores(wal))
        clusters.replicate()
        ack = clusters.processor.ack_index
        assert ack > 0
        # the wire pump's ack persistence (rpc/server._pump_xdc shape):
        # set_ack takes the LAST processed index; get_ack hands back the
        # next-to-read position
        clusters.standby.stores.queue.set_ack("repl-from:primary",
                                              "standby", ack - 1)
        domain_id = clusters.active.stores.domain.by_name(DOMAIN).domain_id
        run_id = clusters.active.stores.execution.get_current_run_id(
            domain_id, "rc-wf")
        live = self._events(clusters.standby, domain_id, run_id)
        clusters.standby.stores.wal.close()

        recovered, report = recover_stores(wal, verify_on_device=False,
                                           rebuild_on_device=False)
        assert report.ok
        assert recovered.queue.get_ack("repl-from:primary",
                                       "standby") == ack
        rec_events = [(e.id, e.event_type, e.version)
                      for e in recovered.history.read_events(
                          domain_id, "rc-wf", run_id)]
        assert rec_events == live
        _zero_findings(wal, recovered)

    def test_dlq_and_shipped_snapshot_survive_recovery(self, wal):
        """Queue payload durability for the two ISSUE 17 record kinds:
        a quarantined DLQEntry and a shipped SnapshotRecord ('snapship')
        round-trip the WAL byte-intact on both backends."""
        import numpy as np

        from cadence_tpu.engine.replication import (
            REPLICATION_DLQ,
            DLQEntry,
            ReplicationPublisher,
            ReplicationTask,
        )
        from cadence_tpu.engine.snapshot import SnapshotRecord

        stores = open_durable_stores(wal)
        poison = ReplicationTask(
            domain_id="d1", workflow_id="w1", run_id="r1",
            first_event_id=5, next_event_id=7, version=3,
            events_blob=b"\x00corrupt\xff")
        stores.queue.enqueue(REPLICATION_DLQ,
                             DLQEntry(task=poison, error="missing activity"))
        rec = SnapshotRecord(
            key=("d1", "w1", "r1"), batch_count=2, last_batch_crc=1234,
            events=9, history_size=512, branch=0,
            payload=np.arange(6, dtype=np.int64),
            state_blob=b"state-bytes",
            blob_crc=__import__("zlib").crc32(b"state-bytes"),
            interner={"sig": 4}, layout=(1, 2, 3))
        ReplicationPublisher(stores).publish_snapshot(rec, "primary")
        stores.wal.close()

        recovered, report = recover_stores(wal, verify_on_device=False,
                                           rebuild_on_device=False)
        assert report.ok
        dlq = [e for _, e in recovered.queue.read(REPLICATION_DLQ, 0, 10)]
        assert len(dlq) == 1 and dlq[0].error == "missing activity"
        assert dlq[0].task.events_blob == poison.events_blob
        assert dlq[0].task.first_event_id == 5
        shipped = [t for _, t in recovered.queue.read("replication", 0, 10)]
        assert len(shipped) == 1
        got = shipped[0].record
        assert got.key == rec.key and got.batch_count == 2
        assert got.blob_crc == rec.blob_crc
        assert got.state_blob == rec.state_blob
        assert (np.asarray(got.payload) == rec.payload).all()
        assert got.interner == {"sig": 4}
        assert tuple(got.layout) == (1, 2, 3)
        assert shipped[0].source_cluster == "primary"
        _zero_findings(wal, recovered)
