"""SignalWithStart + UpdateDomain/DeprecateDomain (VERDICT r3 ask #4).

Reference: workflowHandler.go:2489-2496 (SignalWithStart),
:386 (UpdateDomain), common/domain/attrValidator.go.
"""
import pytest

from cadence_tpu.core.enums import CloseStatus, EventType, WorkflowState
from cadence_tpu.engine.domain import DomainValidationError
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import EchoDecider, SignalDecider
from tests.taskpoller import TaskPoller

DOMAIN = "dapi-domain"
TL = "dapi-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


def _history_types(box, wf):
    return [e.event_type
            for e in box.frontend.get_workflow_execution_history(DOMAIN, wf)]


class TestSignalWithStart:
    def test_starts_with_signal_in_first_transaction(self, box):
        run = box.frontend.signal_with_start_workflow_execution(
            DOMAIN, "wf-sws", "sig-wait", "go", TL)
        types = _history_types(box, "wf-sws")
        assert types[:3] == [EventType.WorkflowExecutionStarted,
                             EventType.WorkflowExecutionSignaled,
                             EventType.DecisionTaskScheduled]
        # the signal is visible to the first decision: a decider expecting
        # one signal completes immediately
        poller = TaskPoller(box, DOMAIN, TL,
                            {"wf-sws": SignalDecider(expected_signals=1)})
        poller.drain()
        ms = box.frontend.describe_workflow_execution(DOMAIN, "wf-sws")
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert ms.execution_info.run_id == run

    def test_signals_running_execution_without_new_run(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-run", "sig", TL)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run0 = box.stores.execution.get_current_run_id(domain_id, "wf-run")
        run = box.frontend.signal_with_start_workflow_execution(
            DOMAIN, "wf-run", "ping", "sig", TL)
        assert run == run0
        types = _history_types(box, "wf-run")
        assert EventType.WorkflowExecutionSignaled in types

    def test_signal_buffered_during_inflight_decision(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-buf", "sig", TL)
        box.pump_once()  # transfer task → matching
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        assert resp is not None and resp.token is not None
        # decision in flight: the signal must buffer, not mutate history
        run = box.frontend.signal_with_start_workflow_execution(
            DOMAIN, "wf-buf", "mid-decision", "sig", TL)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        assert run == box.stores.execution.get_current_run_id(domain_id,
                                                              "wf-buf")
        box.frontend.respond_decision_task_completed(resp.token, [])
        types = _history_types(box, "wf-buf")
        assert EventType.WorkflowExecutionSignaled in types

    def test_close_race_falls_through_to_start(self, box):
        """A run that closes between the read and the signal commit flips
        the call to the start arm (the signal-during-close race,
        workflowHandler.go:2489-2496)."""
        from cadence_tpu.engine.persistence import EntityNotExistsError

        box.frontend.start_workflow_execution(DOMAIN, "wf-race", "echo", TL)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run0 = box.stores.execution.get_current_run_id(domain_id, "wf-race")
        engine = box.route("wf-race")
        real_signal = engine.signal_workflow
        calls = {"n": 0}

        def closing_signal(*args, **kwargs):
            if calls["n"] == 0:
                calls["n"] += 1
                # simulate the close landing first: complete the run, then
                # fail this signal the way _require_running would
                TaskPoller(box, DOMAIN, TL,
                           {"wf-race": EchoDecider(TL)}).drain()
                raise EntityNotExistsError("workflow execution already completed")
            return real_signal(*args, **kwargs)

        engine.signal_workflow = closing_signal
        try:
            run = box.frontend.signal_with_start_workflow_execution(
                DOMAIN, "wf-race", "late", "echo", TL)
        finally:
            engine.signal_workflow = real_signal
        assert run != run0  # a NEW run started, carrying the signal
        types = [e.event_type for e in box.route("wf-race").get_history(
            domain_id, "wf-race", run)]
        assert types[1] == EventType.WorkflowExecutionSignaled


class TestDomainUpdate:
    def test_update_retention_and_description(self, box):
        before = box.frontend.describe_domain(DOMAIN)
        after = box.frontend.update_domain(DOMAIN, retention_days=7,
                                           description="prod domain")
        assert after.retention_days == 7
        assert after.description == "prod domain"
        assert after.notification_version == before.notification_version + 1
        assert box.frontend.describe_domain(DOMAIN).retention_days == 7

    def test_validation_rejects_bad_attrs(self, box):
        with pytest.raises(DomainValidationError):
            box.frontend.update_domain(DOMAIN, retention_days=0)
        box.frontend.update_domain(DOMAIN, clusters=("primary", "standby"))
        with pytest.raises(DomainValidationError):
            # clusters can only be added, never removed
            box.frontend.update_domain(DOMAIN, clusters=("primary",))
        with pytest.raises(DomainValidationError):
            box.frontend.update_domain(DOMAIN, active_cluster="nowhere")

    def test_active_cluster_move_is_a_failover(self, box):
        from cadence_tpu.engine.cluster import ClusterMetadata

        box.frontend.update_domain(DOMAIN, clusters=("primary", "standby"))
        before = box.frontend.describe_domain(DOMAIN)
        after = box.frontend.update_domain(DOMAIN, active_cluster="standby")
        meta = ClusterMetadata()
        assert after.active_cluster == "standby"
        assert after.failover_version == meta.next_failover_version(
            "standby", before.failover_version)
        assert not after.is_active  # this box is the primary cluster
        # events written after the failover stamp the new version
        box.frontend.update_domain(DOMAIN, active_cluster="primary")
        box.frontend.start_workflow_execution(DOMAIN, "wf-ver", "echo", TL)
        history = box.frontend.get_workflow_execution_history(DOMAIN, "wf-ver")
        assert history[0].version == box.frontend.describe_domain(
            DOMAIN).failover_version

    def test_deprecate_rejects_new_starts_running_finish(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-old", "echo", TL)
        box.frontend.deprecate_domain(DOMAIN)
        with pytest.raises(DomainValidationError):
            box.frontend.start_workflow_execution(DOMAIN, "wf-new", "echo", TL)
        with pytest.raises(DomainValidationError):
            box.frontend.signal_with_start_workflow_execution(
                DOMAIN, "wf-new", "s", "echo", TL)
        with pytest.raises(DomainValidationError):
            box.frontend.update_domain(DOMAIN, retention_days=3)
        # the running workflow still signals and completes
        box.frontend.signal_workflow_execution(DOMAIN, "wf-old", "bye")
        TaskPoller(box, DOMAIN, TL, {"wf-old": EchoDecider(TL)}).drain()
        ms = box.frontend.describe_workflow_execution(DOMAIN, "wf-old")
        assert ms.execution_info.state == WorkflowState.Completed

    def test_domain_status_survives_crash(self, tmp_path):
        from cadence_tpu.engine.durability import (
            open_durable_stores,
            recover_stores,
        )

        wal = str(tmp_path / "wal.jsonl")
        b = Onebox(num_hosts=1, num_shards=4,
                   stores=open_durable_stores(wal))
        b.frontend.register_domain(DOMAIN)
        b.frontend.update_domain(DOMAIN, retention_days=9)
        b.frontend.deprecate_domain(DOMAIN)
        stores, _ = recover_stores(wal, verify_on_device=False,
                                   rebuild_on_device=False)
        from cadence_tpu.engine.persistence import DOMAIN_STATUS_DEPRECATED
        info = stores.domain.by_name(DOMAIN)
        assert info.status == DOMAIN_STATUS_DEPRECATED
        assert info.retention_days == 9
