"""Chaos soak: a real multi-host wire cluster under combined store +
wire fault injection must converge to EXACTLY the state of a fault-free
run — zero divergence is the acceptance bar.

The fault matrix (all seeded, reproducible):
- wire chaos in EVERY process (CADENCE_TPU_CHAOS env → subprocess hosts;
  programmatic install → this client process): requests dropped before
  send, severed mid-frame, and delayed on the wire (rpc/chaos.py);
- store faults in the store-server process (CADENCE_TPU_STORE_FAULTS →
  engine/faults.FaultInjector): writes raise TransientStoreError before
  they apply.

Both injector families fire BEFORE state changes, so the retry tier
(`rpc/client._Pool` + FrontendClient) can heal every fault without
double-applying — which is what makes byte-identical mutable-state
checksums achievable, and what this test proves. Retry/breaker/deadline
metrics must be observable on the hosts' /metrics scrape surface.
"""
import json
import time
import urllib.request

import pytest

from cadence_tpu.core.checksum import crc32_of_row, payload_row
from cadence_tpu.core.enums import CloseStatus, DecisionType
from cadence_tpu.engine.history_engine import Decision
from cadence_tpu.rpc import chaos as chaos_mod
from cadence_tpu.rpc.client import RemoteStores
from cadence_tpu.rpc.cluster import launch
from cadence_tpu.rpc.wire import call as wire_call

DOMAIN = "chaos-domain"
TL = "chaos-tl"
NUM_WF = 6

#: seeded chaos for the host/store subprocesses AND this client process
CHAOS_SPEC = "drop=0.06,sever=0.04,delay=0.15,delay_ms=8,seed=11"
STORE_FAULT_SPEC = "rate=0.05,seed=13"


def _drive_workload(cluster):
    """Start NUM_WF workflows and complete each via the first decision
    (host/taskpoller.go shape). Returns {workflow_id: payload checksum}
    read from the authoritative store."""
    fe = cluster.frontend(0)
    fe.register_domain(DOMAIN)
    for i in range(NUM_WF):
        fe.start_workflow_execution(DOMAIN, f"cwf-{i}", "chaostype", TL)
    pending = {f"cwf-{i}" for i in range(NUM_WF)}
    deadline = time.monotonic() + 120
    while pending and time.monotonic() < deadline:
        resp = fe.poll_for_decision_task(DOMAIN, TL, wait_seconds=0.5)
        if resp is None or resp.token is None:
            continue
        fe.respond_decision_task_completed(resp.token, [
            Decision(DecisionType.CompleteWorkflowExecution,
                     {"result": b"done"})])
        pending.discard(resp.token.workflow_id)
    assert not pending, f"workflows never completed: {sorted(pending)}"

    stores = RemoteStores(("127.0.0.1", cluster.store_port))
    domain_id = fe.describe_domain(DOMAIN).domain_id
    checksums = {}
    for i in range(NUM_WF):
        wf = f"cwf-{i}"
        run_id = stores.execution.get_current_run_id(domain_id, wf)
        ms = stores.execution.get_workflow(domain_id, wf, run_id)
        assert ms.execution_info.close_status == CloseStatus.Completed
        checksums[wf] = int(crc32_of_row(payload_row(ms)))
    return checksums


def _run_cluster(env_extra=None, client_chaos: str = ""):
    cluster = launch(num_hosts=2, num_shards=8, env_extra=env_extra)
    try:
        if client_chaos:
            chaos_mod.install(chaos_mod.parse_spec(client_chaos))
        checksums = _drive_workload(cluster)
        # metrics collection is verification plumbing, not workload: turn
        # THIS process's chaos off so the one-shot admin/scrape calls
        # (which have no retry tier) read cleanly; host-side chaos stays on
        chaos_mod.uninstall()
        metrics = _collect_metrics(cluster)
        return checksums, metrics
    finally:
        chaos_mod.uninstall()
        cluster.stop()


def _collect_metrics(cluster):
    """Host metric snapshots over the admin wire op + one raw /metrics
    scrape body (the operator-facing surface)."""
    snapshots = []
    for name, port in cluster.hosts.items():
        snapshots.append(wire_call(("127.0.0.1", port),
                                   ("admin_metrics",), timeout=10))
    scrape_port = sorted(cluster.http_ports.values())[0]
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{scrape_port}/metrics", timeout=10
    ).read().decode("utf-8")
    return {"snapshots": snapshots, "prometheus": body}


@pytest.mark.chaos
class TestChaosSoak:
    def test_zero_divergence_under_combined_faults(self):
        """The acceptance bar: seeded wire chaos (drops, delays, severed
        connections) + injected store errors, and the cluster's final
        mutable-state checksums are byte-identical to a fault-free run."""
        baseline, _ = _run_cluster()
        chaotic, metrics = _run_cluster(
            env_extra={"CADENCE_TPU_CHAOS": CHAOS_SPEC,
                       "CADENCE_TPU_STORE_FAULTS": STORE_FAULT_SPEC},
            client_chaos=CHAOS_SPEC)

        assert chaotic == baseline, (
            "state diverged under chaos:\n"
            f"  baseline: {json.dumps(baseline, sort_keys=True)}\n"
            f"  chaotic:  {json.dumps(chaotic, sort_keys=True)}")

        # the run exercised real faults and the resilience tier healed
        # them: retries visible on the hosts' registries...
        retries = sum(s["snapshot"].get("rpc.client", {}).get("retries", 0)
                      for s in metrics["snapshots"])
        assert retries > 0, "chaos run never retried — injectors inert?"
        # ...and the operator scrape exposes every resilience family
        for needle in ("cadence_retries_total",
                       "cadence_breaker_state",
                       "cadence_deadline_expired_rejections_total",
                       "cadence_breaker_rejected_total"):
            assert needle in metrics["prometheus"], f"missing {needle}"

    def test_serving_tier_parity_clean_under_chaos(self):
        """ISSUE 10 satellite: the device-serving transaction tier
        (CADENCE_TPU_SERVING=1 in every host process) under the same
        combined wire+store fault matrix — every committed transaction
        the tier served must have matched the oracle byte for byte
        (parity-divergence == 0 on every host), the tier must actually
        have taken traffic, and the pre-registered tpu.serving series
        must be scrapeable."""
        chaotic, metrics = _run_cluster(
            env_extra={"CADENCE_TPU_CHAOS": CHAOS_SPEC,
                       "CADENCE_TPU_STORE_FAULTS": STORE_FAULT_SPEC,
                       "CADENCE_TPU_SERVING": "1"},
            client_chaos=CHAOS_SPEC)
        baseline, _ = _run_cluster()
        assert chaotic == baseline, (
            "serving-tier chaos run diverged from the fault-free run")
        served = divergence = 0
        for s in metrics["snapshots"]:
            scope = s["snapshot"].get("tpu.serving", {})
            served += scope.get("transactions", 0)
            divergence += scope.get("parity-divergence", 0)
        assert served > 0, "serving tier never took a transaction"
        assert divergence == 0, \
            "device state diverged from the oracle under chaos"
        assert "cadence_parity_divergence_total" in metrics["prometheus"]

    def test_fault_free_soak_is_reproducible(self):
        """Two fault-free runs agree with each other (the baseline itself
        is deterministic — otherwise the zero-divergence assertion above
        would be vacuous)."""
        first, _ = _run_cluster()
        second, _ = _run_cluster()
        assert first == second
