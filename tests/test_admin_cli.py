"""Admin handler + operator CLI (VERDICT missing #8).

Reference: service/frontend/adminHandler.go + tools/cli/app.go.
"""
import json

import pytest

from cadence_tpu.cli import main as cli_main
from cadence_tpu.engine.admin import AdminHandler
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import CompleteDecider, SignalDecider
from tests.taskpoller import TaskPoller

DOMAIN = "admin-domain"
TL = "admin-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=2, num_shards=8)
    b.frontend.register_domain(DOMAIN)
    return b


class TestAdminHandler:
    def test_describe_workflow_execution_raw_state(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "a-1", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"a-1": SignalDecider(expected_signals=2)})
        poller.drain()
        desc = AdminHandler(box).describe_workflow_execution(DOMAIN, "a-1")
        assert desc["state"] == 1  # Running
        assert desc["next_event_id"] >= 5
        assert desc["checksum"].startswith("0x")
        assert desc["version_histories"]["current_index"] == 0
        assert desc["history_length"] == desc["next_event_id"] - 1

    def test_describe_history_host_and_cluster(self, box):
        admin = AdminHandler(box)
        total = sum(admin.describe_history_host(h)["shard_count"]
                    for h in box.hosts)
        assert total == box.num_shards
        cluster = admin.describe_cluster()
        assert cluster["num_shards"] == 8
        assert set(cluster["hosts"]) == set(box.hosts)

    def test_describe_queue_and_close_shard(self, box):
        admin = AdminHandler(box)
        q = admin.describe_queue(0)
        assert q["shard_id"] == 0 and q["range_id"] >= 1
        assert admin.close_shard(0)

    def test_dynamic_config_crud(self, box):
        from cadence_tpu.utils.dynamicconfig import KEY_FRONTEND_RPS
        admin = AdminHandler(box)
        assert admin.get_dynamic_config(KEY_FRONTEND_RPS) == 0
        admin.update_dynamic_config(KEY_FRONTEND_RPS, 50)
        assert box.config.get(KEY_FRONTEND_RPS) == 50


class TestCLI:
    def _run(self, capsys, *argv):
        rc = cli_main(list(argv))
        out = capsys.readouterr().out
        return rc, json.loads(out)

    def test_cli_end_to_end(self, tmp_path, capsys):
        wal = str(tmp_path / "cluster.wal")
        rc, out = self._run(capsys, "--wal", wal, "domain", "register",
                            "--name", "dev")
        assert rc == 0 and out["registered"] == "dev"

        rc, out = self._run(capsys, "--wal", wal, "workflow", "start",
                            "--domain", "dev", "--workflow-id", "wf-1",
                            "--type", "t", "--task-list", TL)
        assert rc == 0 and "run_id" in out

        # state survived across CLI invocations (WAL round-trip)
        rc, out = self._run(capsys, "--wal", wal, "workflow", "show",
                            "--domain", "dev", "--workflow-id", "wf-1")
        assert rc == 0
        assert out[0]["type"] == "WorkflowExecutionStarted"

        rc, out = self._run(capsys, "--wal", wal, "workflow", "describe",
                            "--domain", "dev", "--workflow-id", "wf-1")
        assert rc == 0 and out["state"] == 1

        rc, out = self._run(capsys, "--wal", wal, "workflow", "list",
                            "--domain", "dev")
        assert rc == 0 and out[0]["workflow_id"] == "wf-1"

        rc, out = self._run(capsys, "--wal", wal, "admin", "verify")
        assert rc == 0 and out["ok"] is True

        rc, out = self._run(capsys, "--wal", wal, "admin", "scan")
        assert rc == 0 and out["ok"] is True

        rc, out = self._run(capsys, "--wal", wal, "workflow", "terminate",
                            "--domain", "dev", "--workflow-id", "wf-1")
        assert rc == 0

        rc, out = self._run(capsys, "--wal", wal, "workflow", "list",
                            "--domain", "dev", "--closed")
        assert rc == 0 and out[0]["workflow_id"] == "wf-1"

    def test_cli_config_roundtrip(self, tmp_path, capsys):
        wal = str(tmp_path / "cluster.wal")
        rc, out = self._run(capsys, "--wal", wal, "admin", "config-set",
                            "--key", "frontend.rps", "--value", "25")
        assert rc == 0 and out["frontend.rps"] == 25
        # the WAL-persisted config survives to the next CLI invocation
        # (the configstore analog)
        rc, out = self._run(capsys, "--wal", wal, "admin", "config-get",
                            "--key", "frontend.rps")
        assert rc == 0 and out["frontend.rps"] == 25

    def test_cli_describe_cluster(self, tmp_path, capsys):
        wal = str(tmp_path / "cluster.wal")
        rc, out = self._run(capsys, "--wal", wal, "admin",
                            "describe-cluster")
        assert rc == 0 and out["num_shards"] == 4
