"""Admin handler + operator CLI (VERDICT missing #8).

Reference: service/frontend/adminHandler.go + tools/cli/app.go.
"""
import json

import pytest

from cadence_tpu.cli import main as cli_main
from cadence_tpu.engine.admin import AdminHandler
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import CompleteDecider, SignalDecider
from tests.taskpoller import TaskPoller

DOMAIN = "admin-domain"
TL = "admin-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=2, num_shards=8)
    b.frontend.register_domain(DOMAIN)
    return b


class TestAdminHandler:
    def test_describe_workflow_execution_raw_state(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "a-1", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"a-1": SignalDecider(expected_signals=2)})
        poller.drain()
        desc = AdminHandler(box).describe_workflow_execution(DOMAIN, "a-1")
        assert desc["state"] == 1  # Running
        assert desc["next_event_id"] >= 5
        assert desc["checksum"].startswith("0x")
        assert desc["version_histories"]["current_index"] == 0
        assert desc["history_length"] == desc["next_event_id"] - 1

    def test_describe_history_host_and_cluster(self, box):
        admin = AdminHandler(box)
        total = sum(admin.describe_history_host(h)["shard_count"]
                    for h in box.hosts)
        assert total == box.num_shards
        cluster = admin.describe_cluster()
        assert cluster["num_shards"] == 8
        assert set(cluster["hosts"]) == set(box.hosts)

    def test_cluster_rollup(self, box):
        """`admin cluster` (in-process arm): per-host shard ownership +
        resident/snapshot/migration counters in one doc."""
        doc = AdminHandler(box).cluster()
        assert set(doc["hosts"]) == set(box.hosts)
        owned = [s for h in doc["hosts"].values()
                 for s in h["assigned_shards"]]
        assert sorted(owned) == list(range(box.num_shards))
        assert "entries" in doc["resident"]
        assert "entries" in doc["snapshots"]
        assert doc["migration"]["parity_divergence"] == 0

    def test_describe_queue_and_close_shard(self, box):
        admin = AdminHandler(box)
        q = admin.describe_queue(0)
        assert q["shard_id"] == 0 and q["range_id"] >= 1
        assert admin.close_shard(0)

    def test_dynamic_config_crud(self, box):
        from cadence_tpu.utils.dynamicconfig import KEY_FRONTEND_RPS
        admin = AdminHandler(box)
        assert admin.get_dynamic_config(KEY_FRONTEND_RPS) == 0
        admin.update_dynamic_config(KEY_FRONTEND_RPS, 50)
        assert box.config.get(KEY_FRONTEND_RPS) == 50


class TestCLI:
    def _run(self, capsys, *argv):
        rc = cli_main(list(argv))
        out = capsys.readouterr().out
        return rc, json.loads(out)

    def test_cli_end_to_end(self, tmp_path, capsys):
        wal = str(tmp_path / "cluster.wal")
        rc, out = self._run(capsys, "--wal", wal, "domain", "register",
                            "--name", "dev")
        assert rc == 0 and out["registered"] == "dev"

        rc, out = self._run(capsys, "--wal", wal, "workflow", "start",
                            "--domain", "dev", "--workflow-id", "wf-1",
                            "--type", "t", "--task-list", TL)
        assert rc == 0 and "run_id" in out

        # state survived across CLI invocations (WAL round-trip)
        rc, out = self._run(capsys, "--wal", wal, "workflow", "show",
                            "--domain", "dev", "--workflow-id", "wf-1")
        assert rc == 0
        assert out[0]["type"] == "WorkflowExecutionStarted"

        rc, out = self._run(capsys, "--wal", wal, "workflow", "describe",
                            "--domain", "dev", "--workflow-id", "wf-1")
        assert rc == 0 and out["state"] == 1

        rc, out = self._run(capsys, "--wal", wal, "workflow", "list",
                            "--domain", "dev")
        assert rc == 0 and out[0]["workflow_id"] == "wf-1"

        rc, out = self._run(capsys, "--wal", wal, "admin", "verify")
        assert rc == 0 and out["ok"] is True

        rc, out = self._run(capsys, "--wal", wal, "admin", "scan")
        assert rc == 0 and out["ok"] is True

        rc, out = self._run(capsys, "--wal", wal, "workflow", "terminate",
                            "--domain", "dev", "--workflow-id", "wf-1")
        assert rc == 0

        rc, out = self._run(capsys, "--wal", wal, "workflow", "list",
                            "--domain", "dev", "--closed")
        assert rc == 0 and out[0]["workflow_id"] == "wf-1"

    def test_cli_config_roundtrip(self, tmp_path, capsys):
        wal = str(tmp_path / "cluster.wal")
        rc, out = self._run(capsys, "--wal", wal, "admin", "config-set",
                            "--key", "frontend.rps", "--value", "25")
        assert rc == 0 and out["frontend.rps"] == 25
        # the WAL-persisted config survives to the next CLI invocation
        # (the configstore analog)
        rc, out = self._run(capsys, "--wal", wal, "admin", "config-get",
                            "--key", "frontend.rps")
        assert rc == 0 and out["frontend.rps"] == 25

    def test_cli_describe_cluster(self, tmp_path, capsys):
        wal = str(tmp_path / "cluster.wal")
        rc, out = self._run(capsys, "--wal", wal, "admin",
                            "describe-cluster")
        assert rc == 0 and out["num_shards"] == 4


class TestOpsVerbs:
    """DLQ, failover, WAL scan/clean, canary CLI verbs (VERDICT r4
    missing #5/#6; tools/cli adminFailoverCommands, adminDBScan,
    dlq read/purge/merge, canary/cron.go)."""

    def _run(self, capsys, *argv):
        rc = cli_main(list(argv))
        out = capsys.readouterr().out
        return rc, json.loads(out)

    def _seed_dlq(self, wal):
        """Plant a poison replication task in the WAL-backed DLQ."""
        from cadence_tpu.core.codec import serialize_history
        from cadence_tpu.core.events import HistoryBatch, HistoryEvent
        from cadence_tpu.core.enums import EventType
        from cadence_tpu.engine.durability import (
            open_durable_stores,
            recover_stores,
        )
        from cadence_tpu.engine.replication import (
            REPLICATION_DLQ,
            DLQEntry,
            ReplicationTask,
        )
        import os as _os
        if _os.path.exists(wal):
            stores, _ = recover_stores(wal, verify_on_device=False,
                                       rebuild_on_device=False)
        else:
            stores = open_durable_stores(wal)
        batch = HistoryBatch(
            domain_id="dlq-dom", workflow_id="dlq-wf", run_id="dlq-run",
            events=[HistoryEvent(
                id=5, event_type=EventType.WorkflowExecutionSignaled,
                version=0, timestamp=1, attrs={"signal_name": "x"})])
        stores.queue.enqueue(REPLICATION_DLQ, DLQEntry(
            task=ReplicationTask(
                domain_id="dlq-dom", workflow_id="dlq-wf",
                run_id="dlq-run", first_event_id=5, next_event_id=6,
                version=0, events_blob=serialize_history([batch]),
                version_history_items=((6, 0),)),
            error="planted"))
        stores.wal.close()

    def test_dlq_read_merge_purge(self, tmp_path, capsys):
        wal = str(tmp_path / "dlq.wal")
        self._seed_dlq(wal)
        rc, out = self._run(capsys, "--wal", wal, "admin", "dlq-read")
        assert rc == 0 and len(out) == 1
        assert out[0]["workflow_id"] == "dlq-wf"
        assert out[0]["error"] == "planted"
        # merge: the mid-history task still gaps (no run) → stays failed
        rc, out = self._run(capsys, "--wal", wal, "admin", "dlq-merge")
        assert rc == 0
        assert out["applied"] + out["still_failed"] == 1
        rc, out = self._run(capsys, "--wal", wal, "admin", "dlq-purge")
        assert rc == 0
        # purge persisted across CLI invocations (WAL purge record)
        rc, out = self._run(capsys, "--wal", wal, "admin", "dlq-read")
        assert rc == 0 and out == []

    def test_failover_verb(self, tmp_path, capsys):
        wal = str(tmp_path / "fo.wal")
        rc, _ = self._run(capsys, "--wal", wal, "domain", "register",
                          "--name", "fo-dom")
        assert rc == 0
        rc, _ = self._run(capsys, "--wal", wal, "domain", "update",
                          "--name", "fo-dom",
                          "--clusters", "primary,standby")
        assert rc == 0
        rc, out = self._run(capsys, "--wal", wal, "admin", "failover",
                            "--domain", "fo-dom", "--to", "standby")
        assert rc == 0
        assert out["active_cluster"] == "standby"
        assert out["failover_version"] > 0
        rc, out = self._run(capsys, "--wal", wal, "domain", "list")
        assert rc == 0

    def test_wal_scan_and_clean(self, tmp_path, capsys):
        wal = str(tmp_path / "scan.wal")
        rc, _ = self._run(capsys, "--wal", wal, "domain", "register",
                          "--name", "w-dom")
        rc, _ = self._run(capsys, "--wal", wal, "workflow", "start",
                          "--domain", "w-dom", "--workflow-id", "wf-s",
                          "--type", "t", "--task-list", TL)
        rc, out = self._run(capsys, "--wal", wal, "wal", "scan")
        assert rc == 0 and out["bad_lines"] == 0
        assert out["by_type"]["d"] >= 1 and out["by_type"]["h"] >= 1
        # corrupt a line + plant a tombstoned run, then clean
        with open(wal, "a") as fh:
            fh.write("NOT JSON\n")
            fh.write(json.dumps({"t": "delw", "d": "gone-dom",
                                 "w": "gone-wf", "r": "gone-run"}) + "\n")
            fh.write(json.dumps({"t": "cur", "d": "gone-dom",
                                 "w": "gone-wf", "r": "gone-run",
                                 "st": 2, "cs": 1}) + "\n")
        rc, out = self._run(capsys, "--wal", wal, "wal", "scan")
        assert rc == 1 and out["bad_lines"] == 1
        rc, out = self._run(capsys, "--wal", wal, "wal", "clean")
        assert rc == 0 and out["dropped_bad_lines"] == 1
        rc, out = self._run(capsys, "--wal", wal, "wal", "scan")
        assert rc == 0 and out["bad_lines"] == 0
        assert out["tombstoned_runs"] == 0
        # the cleaned cluster still recovers with its workflow intact
        rc, out = self._run(capsys, "--wal", wal, "workflow", "describe",
                            "--domain", "w-dom", "--workflow-id", "wf-s")
        assert rc == 0

    def test_canary_verb(self, tmp_path, capsys):
        wal = str(tmp_path / "canary.wal")
        rc, out = self._run(capsys, "--wal", wal, "canary", "run",
                            "--cycles", "1")
        assert rc == 0, out
        assert out["green"] == 1 and out["ok"] is True


class TestProfileVerb:
    def _run(self, capsys, *argv):
        rc = cli_main(list(argv))
        out = capsys.readouterr().out
        return rc, json.loads(out)

    def test_profile_captures_trace(self, tmp_path, capsys):
        """The pprof analog (SURVEY §5): `admin profile` captures a JAX
        profiler trace of a representative replay to a directory."""
        import os as _os
        wal = str(tmp_path / "prof.wal")
        out_dir = str(tmp_path / "trace")
        rc, out = self._run(capsys, "--wal", wal, "admin", "profile",
                            "--out", out_dir, "--workflows", "16",
                            "--events", "40")
        assert rc == 0
        assert out["events_per_sec"] > 0
        assert out["trace_dir"] == out_dir
        found = []
        for root, _dirs, files in _os.walk(out_dir):
            found.extend(files)
        assert found, "no trace files captured"
