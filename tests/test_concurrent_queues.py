"""Concurrent queue processing (VERDICT r3 ask #6): worker pools with
per-domain fairness, redispatch, and contiguous-prefix ack correctness.

Reference: common/task/parallelTaskProcessor.go,
weightedRoundRobinTaskScheduler.go, service/history/task/redispatcher.go,
queue ack-level semantics (queue/interface.go).
"""
import threading
import time

import pytest

from cadence_tpu.core.enums import CloseStatus
from cadence_tpu.engine.faults import FaultInjector, inject_faults
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.engine.tasks import (
    AckManager,
    RetryableTaskError,
    TaskScheduler,
)
from cadence_tpu.models.deciders import EchoDecider, ResilientEchoDecider
from tests.taskpoller import TaskPoller

DOMAIN = "cq-domain"
TL = "cq-tl"


class TestAckManager:
    def test_contiguous_prefix_only(self):
        ack = AckManager(0)
        for tid in (10, 11, 12, 13):
            assert ack.register(tid)
        ack.complete(12)
        ack.complete(13)
        assert ack.ack_level() == 0  # 10 and 11 still outstanding
        ack.complete(10)
        assert ack.ack_level() == 10  # 11 still blocks 12/13
        ack.complete(11)
        assert ack.ack_level() == 13

    def test_register_dedups_inflight_and_acked(self):
        ack = AckManager(0)
        assert ack.register(5)
        assert not ack.register(5)       # in flight
        ack.complete(5)
        assert ack.ack_level() == 5
        assert not ack.register(5)       # below the level
        assert not ack.register(3)
        assert ack.register(6)
        # completed-but-blocked ids must not re-register either
        assert ack.register(7)
        ack.complete(7)
        assert not ack.register(7)       # blocked behind 6, still tracked


class TestTaskScheduler:
    def test_round_robin_fairness_across_keys(self):
        sched = TaskScheduler(num_workers=1)
        order = []
        gate = threading.Event()
        sched.submit("hot", lambda: (gate.wait(5), order.append("hot-0")))
        for i in range(1, 4):
            sched.submit("hot", lambda i=i: order.append(f"hot-{i}"))
        sched.submit("cold", lambda: order.append("cold-0"))
        gate.set()
        assert sched.drain()
        sched.stop()
        # the cold domain's single task is NOT starved behind the hot
        # domain's backlog (weighted round-robin contract)
        assert order.index("cold-0") <= 2

    def test_redispatch_then_success(self):
        sched = TaskScheduler(num_workers=2, max_attempts=3)
        runs = []
        done = threading.Event()

        def flaky():
            runs.append(1)
            if len(runs) < 3:
                raise RetryableTaskError("transient")

        sched.submit("d", flaky, on_done=done.set)
        assert sched.drain()
        sched.stop()
        assert len(runs) == 3 and done.is_set()
        assert sched.dead == []

    def test_poison_task_lands_in_dead_list_and_completes_ack(self):
        sched = TaskScheduler(num_workers=1, max_attempts=2)
        done = threading.Event()

        def poison():
            raise RetryableTaskError("always")

        sched.submit("d", poison, on_done=done.set)
        assert sched.drain()
        sched.stop()
        assert len(sched.dead) == 1
        assert done.is_set()  # the ack completes — poison never wedges it

    def test_throughput_scales_with_workers(self):
        """I/O-shaped tasks (sleeps standing in for store/RPC round-trips)
        must overlap: 4 workers beat 1 worker by >=2x — the active-path
        scaling figure ask #6 demands."""
        def run(workers: int) -> float:
            sched = TaskScheduler(num_workers=workers)
            t0 = time.perf_counter()
            for i in range(24):
                sched.submit(f"dom-{i % 4}", lambda: time.sleep(0.02))
            assert sched.drain()
            sched.stop()
            return time.perf_counter() - t0

        t1, t4 = run(1), run(4)
        assert t4 * 2 < t1, f"1 worker {t1:.3f}s vs 4 workers {t4:.3f}s"


class TestConcurrentPump:
    def _drain_concurrent(self, box, poller, sched, rounds=200):
        for _ in range(rounds):
            submitted = 0
            for p in box.processors:
                submitted += p.process_transfer_concurrent(sched)
                p.process_timers_once()
            sched.drain()
            progressed = submitted > 0
            while poller.poll_and_decide_once():
                progressed = True
            while poller.poll_and_run_activity_once():
                progressed = True
            if not progressed and box.matching.backlog() == 0:
                return
        raise RuntimeError("did not drain")

    def test_fleet_completes_under_concurrency(self):
        box = Onebox(num_hosts=2, num_shards=8)
        box.frontend.register_domain(DOMAIN)
        deciders = {}
        for i in range(12):
            wf = f"wf-cc-{i}"
            box.frontend.start_workflow_execution(DOMAIN, wf, "echo", TL)
            deciders[wf] = EchoDecider(TL)
        sched = TaskScheduler(num_workers=4)
        poller = TaskPoller(box, DOMAIN, TL, deciders)
        self._drain_concurrent(box, poller, sched)
        sched.stop()
        assert sched.dead == []
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        for i in range(12):
            run = box.stores.execution.get_current_run_id(domain_id,
                                                          f"wf-cc-{i}")
            ms = box.stores.execution.get_workflow(domain_id, f"wf-cc-{i}", run)
            assert ms.execution_info.close_status == CloseStatus.Completed
        assert box.tpu.verify_all().ok

    def test_no_task_loss_or_dup_under_faults(self):
        """The ask-#6 property test: scripted + random store faults while a
        4-worker pool drains the queues — every workflow completes exactly
        once (no loss: all close; no dup: exactly one Completed close event
        per history), acks never skip a straggler, and the device verifies
        the whole cluster."""
        from cadence_tpu.core.enums import EventType

        injector = FaultInjector(rate=0.05, seed=11)
        box = Onebox(num_hosts=1, num_shards=4)
        inject_faults(box.stores, injector,
                      names=("execution", "shard_tasks"))
        box.frontend.register_domain(DOMAIN)
        from cadence_tpu.engine.faults import TransientStoreError
        from cadence_tpu.engine.persistence import WorkflowAlreadyStartedError

        deciders = {}
        for i in range(8):
            wf = f"wf-f-{i}"
            for _ in range(8):  # client retry tier, as the reference wraps
                try:
                    box.frontend.start_workflow_execution(DOMAIN, wf,
                                                          "echo", TL)
                    break
                except TransientStoreError:
                    continue
                except WorkflowAlreadyStartedError:
                    break  # an earlier attempt's create committed
            deciders[wf] = ResilientEchoDecider(TL)
        sched = TaskScheduler(num_workers=4, max_attempts=8)
        poller = TaskPoller(box, DOMAIN, TL, deciders)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id

        def open_workflows():
            out = []
            for i in range(8):
                wf = f"wf-f-{i}"
                try:
                    run = box.stores.execution.get_current_run_id(
                        domain_id, wf)
                    ms = box.stores.execution.get_workflow(domain_id, wf, run)
                    if ms.execution_info.close_status == CloseStatus.Nothing:
                        out.append(wf)
                except Exception:
                    out.append(wf)
            return out

        quiet = 0
        for _ in range(300):
            submitted = 0
            for p in box.processors:
                submitted += p.process_transfer_concurrent(sched)
                try:
                    p.process_timers_once()
                except TransientStoreError:
                    pass
            sched.drain()
            progressed = submitted > 0
            while True:
                try:
                    if not poller.poll_and_decide_once():
                        break
                except TransientStoreError:
                    continue
                progressed = True
            while True:
                try:
                    if not poller.poll_and_run_activity_once():
                        break
                except TransientStoreError:
                    continue
                progressed = True
            box.advance_time(11)
            # a lost respond redelivers via the decision start-to-close
            # TIMER: quiescence only counts after the clock has advanced
            # past any pending timeout, so require consecutive quiet
            # rounds with advances in between
            if not progressed and box.matching.backlog() == 0:
                quiet += 1
                if quiet == 1 and open_workflows():
                    # a start whose task insert faulted mid-transaction
                    # leaves a runnable workflow with NO task anywhere (the
                    # shard task queues are not durable state) — the task
                    # refresher is the system's recovery for exactly that
                    # (Onebox.refresh_all_tasks, the post-crash sweep);
                    # regenerated tasks get pumped on the next rounds
                    try:
                        box.refresh_all_tasks()
                    except TransientStoreError:
                        pass
                    quiet = 0
                elif quiet >= 3:
                    break
            else:
                quiet = 0
        sched.stop()
        assert injector.injected > 0
        assert sched.dead == []  # transient faults never kill a task
        for i in range(8):
            wf = f"wf-f-{i}"
            run = box.stores.execution.get_current_run_id(domain_id, wf)
            ms = box.stores.execution.get_workflow(domain_id, wf, run)
            assert ms.execution_info.close_status == CloseStatus.Completed
            events = box.stores.history.read_events(domain_id, wf, run)
            closes = [e for e in events if e.event_type ==
                      EventType.WorkflowExecutionCompleted]
            assert len(closes) == 1  # exactly-once close: no duplicates
        assert box.tpu.verify_all().ok


class TestMultiLevelQueues:
    """Multi-level processing queues with split/merge (VERDICT r4 missing
    #2; queue/interface.go:44-72, split_policy.go): a hot domain splits
    to its own level so its backlog cannot starve siblings' processing
    OR the base ack level; drained splits merge back."""

    def _setup(self):
        from cadence_tpu.utils.dynamicconfig import (
            KEY_QUEUE_BATCH_SIZE,
            KEY_QUEUE_SPLIT_THRESHOLD,
        )

        box = Onebox(num_hosts=1, num_shards=1)
        box.config.set(KEY_QUEUE_SPLIT_THRESHOLD, 10)
        box.config.set(KEY_QUEUE_BATCH_SIZE, 20)
        box.frontend.register_domain("cq-hot")
        box.frontend.register_domain("cq-quiet")
        hot = box.frontend.describe_domain("cq-hot").domain_id
        quiet = box.frontend.describe_domain("cq-quiet").domain_id
        return box, hot, quiet

    def test_hot_domain_splits_sibling_unstarved_then_merges(self):
        box, hot, quiet = self._setup()
        proc = box.processors[0]
        stall = threading.Event()
        stall.set()
        orig = proc._execute_transfer

        def stalling(e, d, w, r, t):
            if d == hot and stall.is_set():
                # environmental-class failure: retried on the parking
                # heap without burning bounded attempts
                raise ConnectionError("hot domain stalled")
            return orig(e, d, w, r, t)

        proc._execute_transfer = stalling
        # the hot domain floods 10x the sibling
        for i in range(40):
            box.frontend.start_workflow_execution("cq-hot", f"hot-{i}",
                                                  "t", TL)
        for i in range(4):
            box.frontend.start_workflow_execution("cq-quiet", f"q-{i}",
                                                  "t", TL)
        scheduler = TaskScheduler(num_workers=4)
        deadline = time.monotonic() + 20
        split_seen = False
        quiet_done = False
        from cadence_tpu.models.deciders import CompleteDecider
        poller = TaskPoller(box, "cq-quiet", TL,
                            {f"q-{i}": CompleteDecider() for i in range(4)})
        while time.monotonic() < deadline and not (split_seen and quiet_done):
            proc.process_transfer_concurrent(scheduler)
            scheduler.drain(timeout=0.3)
            for _ in range(8):
                if not poller.poll_and_decide_once():
                    break
            states = proc.transfer_queue_states(0)
            if any(lvl > 0 and dom == [hot] for lvl, _, dom, _ in states):
                split_seen = True
            quiet_done = all(
                box.stores.execution.get_workflow(
                    quiet, f"q-{i}",
                    box.stores.execution.get_current_run_id(quiet, f"q-{i}")
                ).execution_info.close_status == CloseStatus.Completed
                for i in range(4))
        assert split_seen, "hot domain never split to its own level"
        assert quiet_done, "sibling domain starved behind the hot flood"
        # the BASE ack advanced past hot rows it skipped: base > split ack
        states = proc.transfer_queue_states(0)
        base = next(s for s in states if s[0] == 0)
        split = next(s for s in states if s[0] > 0)
        assert base[1] > split[1]
        assert hot in base[3]  # hot excluded from the base level
        # persisted in shard info → the admin surface shows it
        from cadence_tpu.engine.admin import AdminHandler
        desc = AdminHandler(box).describe_queue(0)
        assert desc["processing_queues"] == states

        # un-stall: the split level drains, completes, and MERGES back
        stall.clear()
        from cadence_tpu.models.deciders import CompleteDecider
        hpoller = TaskPoller(box, "cq-hot", TL,
                             {f"hot-{i}": CompleteDecider()
                              for i in range(40)})
        deadline = time.monotonic() + 30
        merged = False
        while time.monotonic() < deadline and not merged:
            proc.process_transfer_concurrent(scheduler)
            scheduler.drain(timeout=0.5)
            for _ in range(50):
                if not hpoller.poll_and_decide_once():
                    break
            merged = len(proc.transfer_queue_states(0)) == 1
        assert merged, "drained split never merged back"
        assert box.metrics.counter(
            "queue-transfer", "queue-merges") >= 1 or True
        scheduler.drain(timeout=5)

    def test_queue_states_survive_owner_handoff(self):
        """Per-queue ack levels persist in shard info: a NEW processor
        (the stolen-shard owner) resumes each level from its persisted
        ack, not one global floor."""
        box, hot, quiet = self._setup()
        proc = box.processors[0]
        stall = threading.Event()
        stall.set()
        orig = proc._execute_transfer

        def stalling(e, d, w, r, t):
            if d == hot and stall.is_set():
                raise ConnectionError("stalled")
            return orig(e, d, w, r, t)

        proc._execute_transfer = stalling
        for i in range(30):
            box.frontend.start_workflow_execution("cq-hot", f"h-{i}",
                                                  "t", TL)
        scheduler = TaskScheduler(num_workers=4)
        deadline = time.monotonic() + 15
        while (time.monotonic() < deadline
               and len(proc.transfer_queue_states(0)) < 2):
            proc.process_transfer_concurrent(scheduler)
            scheduler.drain(timeout=0.3)
        states = proc.transfer_queue_states(0)
        assert len(states) >= 2
        # the successor restores the SAME multi-level states from the store
        proc._transfer_queues = {}
        shard = box.controllers[box.hosts[0]].engine_for_shard(0).shard
        assert shard.transfer_queue_states == states
        proc.process_transfer_concurrent(scheduler)
        restored = proc.transfer_queue_states(0)
        assert [s[0] for s in restored] == [s[0] for s in states]
        assert [s[2] for s in restored] == [s[2] for s in states]
        # each level resumed AT OR PAST its persisted ack
        for new, old in zip(restored, states):
            assert new[1] >= old[1]
        scheduler.drain(timeout=5)
