"""Batcher worker + structured logging + authorization seam
(VERDICT r3 asks #8/#9; service/worker/batcher/batcher.go,
common/log/loggerimpl/logger.go:29, common/authorization/authorizer.go:88).
"""
import logging

import pytest

from cadence_tpu.core.enums import CloseStatus, WorkflowState
from cadence_tpu.engine.authorization import (
    AuthAttributes,
    NoopAuthorizer,
    RoleAuthorizer,
    UnauthorizedError,
)
from cadence_tpu.engine.batcher import Batcher
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import SignalDecider
from cadence_tpu.utils.log import TaggedLogger
from tests.taskpoller import TaskPoller

DOMAIN = "bla-domain"
TL = "bla-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


class TestBatcher:
    def test_batch_terminate_over_query(self, box):
        for i in range(3):
            box.frontend.start_workflow_execution(DOMAIN, f"wf-t-{i}",
                                                  "ordertype", TL)
        box.frontend.start_workflow_execution(DOMAIN, "wf-keep", "other", TL)
        box.pump_once()
        report = Batcher(box.frontend, rps=100).run(
            DOMAIN, "WorkflowType = 'ordertype'", "terminate",
            reason="cleanup")
        assert report.total == 3 and report.succeeded == 3
        assert report.failed == 0
        box.pump_once()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        for i in range(3):
            run = box.stores.execution.get_current_run_id(domain_id,
                                                          f"wf-t-{i}")
            ms = box.stores.execution.get_workflow(domain_id, f"wf-t-{i}", run)
            assert ms.execution_info.close_status == CloseStatus.Terminated
        keep = box.stores.execution.get_workflow(
            domain_id, "wf-keep",
            box.stores.execution.get_current_run_id(domain_id, "wf-keep"))
        assert keep.execution_info.state == WorkflowState.Running

    def test_batch_signal_and_failure_isolation(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-s", "sig", TL)
        box.frontend.start_workflow_execution(DOMAIN, "wf-s2", "sig", TL)
        box.pump_once()
        # make one target un-signalable: terminate it after listing starts
        box.frontend.terminate_workflow_execution(DOMAIN, "wf-s2")
        # the visibility record still shows open (close task not pumped) —
        # exactly the staleness the per-execution isolation exists for
        report = Batcher(box.frontend, rps=100).run(
            DOMAIN, "WorkflowType = 'sig'", "signal", signal_name="go")
        assert report.succeeded >= 1
        assert report.total == report.succeeded + report.failed
        # the live workflow got its signal and completes
        TaskPoller(box, DOMAIN, TL,
                   {"wf-s": SignalDecider(expected_signals=1)}).drain()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run = box.stores.execution.get_current_run_id(domain_id, "wf-s")
        ms = box.stores.execution.get_workflow(domain_id, "wf-s", run)
        assert ms.execution_info.close_status == CloseStatus.Completed

    def test_unknown_op_refused(self, box):
        with pytest.raises(ValueError):
            Batcher(box.frontend).run(DOMAIN, "", "explode")
        with pytest.raises(ValueError):
            Batcher(box.frontend).run(DOMAIN, "", "signal")

    def test_quota_sheds_are_retried_not_failed(self):
        """A ServiceBusyError from the admission door is backpressure:
        the batcher must honor the retry-after hint and re-apply the
        SAME record, not log it as a permanent per-record failure — and
        a quota that never admits must eventually fail the record
        instead of hanging the batch."""
        from types import SimpleNamespace

        from cadence_tpu.utils.quotas import ServiceBusyError

        class _QuotaFrontend:
            def __init__(self, sheds_before_admit):
                self.sheds_before_admit = sheds_before_admit
                self.attempts = {}
                self.terminated = []

            def list_workflow_executions(self, domain, query):
                return [SimpleNamespace(workflow_id=f"wf-{i}", run_id="r",
                                        close_status=-1) for i in range(4)]

            def terminate_workflow_execution(self, domain, workflow_id,
                                             run_id=None, reason=""):
                n = self.attempts.get(workflow_id, 0)
                self.attempts[workflow_id] = n + 1
                if n < self.sheds_before_admit:
                    raise ServiceBusyError("over limit",
                                           retry_after_s=0.005,
                                           domain=domain)
                self.terminated.append(workflow_id)

        fe = _QuotaFrontend(sheds_before_admit=2)
        report = Batcher(fe, rps=1000).run(DOMAIN, "", "terminate")
        assert report.succeeded == 4 and report.failed == 0
        assert sorted(fe.terminated) == [f"wf-{i}" for i in range(4)]
        # every record took the shed → retry → admit path
        assert all(n == 3 for n in fe.attempts.values())
        # a quota that NEVER admits: bounded retries, then failure
        fe2 = _QuotaFrontend(sheds_before_admit=10_000)
        report2 = Batcher(fe2, rps=1000).run(DOMAIN, "", "terminate")
        assert report2.succeeded == 0 and report2.failed == 4
        assert all(n == Batcher.SHED_RETRIES
                   for n in fe2.attempts.values())


class TestStructuredLogging:
    def test_tagged_lines_on_transaction_paths(self, box, caplog):
        with caplog.at_level(logging.DEBUG, logger="cadence_tpu"):
            box.frontend.start_workflow_execution(DOMAIN, "wf-log", "t", TL)
            box.frontend.signal_workflow_execution(DOMAIN, "wf-log", "ping")
        text = caplog.text
        # the signal transaction logs with workflow identity tags
        assert "transaction committed" in text
        assert "workflow_id=wf-log" in text
        # shard acquisition logs ownership movement
        assert "shard acquired" in text and "owner=host-0" in text

    def test_with_tags_composition(self):
        logger = TaggedLogger().with_tags(a=1).with_tags(b=2)
        assert logger._render("msg", {"c": 3}) == "msg a=1 b=2 c=3"


class TestAuthorization:
    def test_noop_allows_everything(self):
        assert NoopAuthorizer().authorize(
            AuthAttributes(api="x", permission="admin")) == 1

    def test_admin_api_denied_for_reader(self, box):
        from cadence_tpu.engine.admin import AdminHandler

        box.authorizer = RoleAuthorizer({"ops": "admin", "dev": "read"})
        admin = AdminHandler(box, actor="dev")
        with pytest.raises(UnauthorizedError):
            admin.describe_cluster()
        ops = AdminHandler(box, actor="ops")
        assert ops.describe_cluster()  # admin role passes

    def test_frontend_domain_mutation_needs_admin(self, box):
        box.frontend.authorizer = RoleAuthorizer({"dev": "write"},
                                                 default_role=None)
        box.frontend.actor = "dev"
        # writes allowed...
        box.frontend.start_workflow_execution(DOMAIN, "wf-authz", "t", TL)
        # ...domain management denied
        with pytest.raises(UnauthorizedError):
            box.frontend.update_domain(DOMAIN, retention_days=5)
        with pytest.raises(UnauthorizedError):
            box.frontend.deprecate_domain(DOMAIN)
        # anonymous (unknown actor, no default role) denied outright
        box.frontend.actor = "stranger"
        with pytest.raises(UnauthorizedError):
            box.frontend.start_workflow_execution(DOMAIN, "wf-no", "t", TL)


class TestVisibilityOutOfOrder:
    def test_close_before_start_never_leaves_phantom_open(self, box):
        """Under the concurrent pump a retried start task can land AFTER
        the close task; the close must stick (code-review r4: a late start
        wrote a fresh open record over the close)."""
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        # close arrives first (start task delayed by redispatch)
        box.stores.visibility.record_closed(
            domain_id, "wf-ooo", "r1", close_time=123, close_status=1,
            workflow_type="t", start_time=100)
        # the start retry lands late
        from cadence_tpu.engine.persistence import VisibilityRecord
        box.stores.visibility.record_started(VisibilityRecord(
            domain_id=domain_id, workflow_id="wf-ooo", run_id="r1",
            workflow_type="t", start_time=100,
            search_attrs={"Tier": b"gold"}))
        open_recs = box.stores.visibility.list_open(domain_id)
        assert "wf-ooo" not in [r.workflow_id for r in open_recs]
        closed = box.stores.visibility.list_closed(domain_id)
        rec = next(r for r in closed if r.workflow_id == "wf-ooo")
        assert rec.close_status == 1 and rec.search_attrs["Tier"] == b"gold"

    def test_signal_with_start_checks_authorization(self, box):
        box.frontend.authorizer = RoleAuthorizer({}, default_role=None)
        box.frontend.actor = "stranger"
        with pytest.raises(UnauthorizedError):
            box.frontend.signal_with_start_workflow_execution(
                DOMAIN, "wf-x", "s", "t", TL)


class TestOAuthAuthorizer:
    """JWT claims-based authorizer (authorization/oauthAuthorizer.go):
    HS256 tokens carry sub/permission/domain/admin/exp claims."""

    def _attrs(self, permission, domain="", actor=""):
        from cadence_tpu.engine.authorization import AuthAttributes
        return AuthAttributes(api="x", permission=permission,
                              domain=domain, actor=actor)

    def test_valid_token_permission_mapping(self):
        from cadence_tpu.engine.authorization import (
            DECISION_ALLOW,
            DECISION_DENY,
            PERMISSION_ADMIN,
            PERMISSION_READ,
            PERMISSION_WRITE,
            OAuthAuthorizer,
            make_token,
        )
        auth = OAuthAuthorizer(b"secret")
        tok = make_token(b"secret", "alice", PERMISSION_WRITE)
        assert auth.authorize(self._attrs(PERMISSION_READ, actor=tok)) \
            == DECISION_ALLOW
        assert auth.authorize(self._attrs(PERMISSION_WRITE, actor=tok)) \
            == DECISION_ALLOW
        assert auth.authorize(self._attrs(PERMISSION_ADMIN, actor=tok)) \
            == DECISION_DENY

    def test_bad_signature_and_garbage_denied(self):
        from cadence_tpu.engine.authorization import (
            DECISION_DENY,
            PERMISSION_READ,
            OAuthAuthorizer,
            make_token,
        )
        auth = OAuthAuthorizer(b"secret")
        forged = make_token(b"WRONG", "mallory", "admin", admin=True)
        assert auth.authorize(self._attrs(PERMISSION_READ, actor=forged)) \
            == DECISION_DENY
        assert auth.authorize(self._attrs(PERMISSION_READ,
                                          actor="not-a-jwt")) \
            == DECISION_DENY

    def test_expiry_and_domain_binding(self):
        from cadence_tpu.engine.authorization import (
            DECISION_ALLOW,
            DECISION_DENY,
            PERMISSION_WRITE,
            OAuthAuthorizer,
            make_token,
        )
        now = [1000.0]
        auth = OAuthAuthorizer(b"s", clock=lambda: now[0])
        tok = make_token(b"s", "bob", PERMISSION_WRITE, domain="orders",
                         ttl_seconds=60, now=now[0])
        ok = self._attrs(PERMISSION_WRITE, domain="orders", actor=tok)
        assert auth.authorize(ok) == DECISION_ALLOW
        # bound to 'orders': another domain is denied
        other = self._attrs(PERMISSION_WRITE, domain="billing", actor=tok)
        assert auth.authorize(other) == DECISION_DENY
        now[0] += 120  # past exp
        assert auth.authorize(ok) == DECISION_DENY

    def test_admin_claim_overrides(self):
        from cadence_tpu.engine.authorization import (
            DECISION_ALLOW,
            PERMISSION_ADMIN,
            OAuthAuthorizer,
            make_token,
        )
        auth = OAuthAuthorizer(b"s")
        tok = make_token(b"s", "root", admin=True)
        assert auth.authorize(self._attrs(PERMISSION_ADMIN, actor=tok)) \
            == DECISION_ALLOW

    def test_frontend_gated_by_oauth(self):
        """Wired into a live frontend: a read token cannot write."""
        from cadence_tpu.engine.authorization import (
            PERMISSION_READ,
            PERMISSION_WRITE,
            OAuthAuthorizer,
            UnauthorizedError,
            make_token,
        )
        from cadence_tpu.engine.onebox import Onebox

        box = Onebox(num_hosts=1, num_shards=2)
        box.frontend.authorizer = OAuthAuthorizer(b"cluster-secret")
        writer = make_token(b"cluster-secret", "w", admin=True)
        reader = make_token(b"cluster-secret", "r", PERMISSION_READ)
        box.frontend.actor = writer
        box.frontend.register_domain("oauth-dom")
        box.frontend.start_workflow_execution("oauth-dom", "wf", "t", "tl")
        box.frontend.actor = reader
        import pytest as _pytest
        with _pytest.raises(UnauthorizedError):
            box.frontend.signal_workflow_execution("oauth-dom", "wf", "s")
