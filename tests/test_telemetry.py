"""Cluster telemetry plane (ISSUE 16): time-series ring-buffer window
math (rates, retention, counter-reset tolerance, leg/saturation
derivation), host-runtime attribution on named threads, flight-recorder
ring bounds + dump-on-signal + dump-on-crash via subprocess kill, SLO
burn rates over seeded synthetic series, the scrape surface's new
routes, and the `admin top` fleet rollup over a live wire cluster.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from cadence_tpu.engine.admin import (
    AdminHandler,
    _cluster_rollup,
    fleet_top,
    scrape_timeseries,
    summarize_windows,
)
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.loadgen.slo import BurnRateEvaluator, BurnTarget
from cadence_tpu.models.deciders import CompleteDecider
from cadence_tpu.utils import flightrecorder
from cadence_tpu.utils import metrics as m
from cadence_tpu.utils.flightrecorder import MAX_STR, FlightRecorder
from cadence_tpu.utils.hostprof import HostProfiler, subsystem_for
from cadence_tpu.utils.metrics import MetricsRegistry
from cadence_tpu.utils.timeseries import TimeSeriesSampler
from tests.taskpoller import TaskPoller

DOMAIN = "telemetry-domain"
TL = "telemetry-tl"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def box():
    b = Onebox(num_hosts=2, num_shards=8)
    b.frontend.register_domain(DOMAIN)
    return b


def _run_one_workflow(b: Onebox, workflow_id: str = "tel-wf") -> None:
    b.frontend.start_workflow_execution(DOMAIN, workflow_id, "t", TL)
    TaskPoller(b, DOMAIN, TL, {workflow_id: CompleteDecider()}).drain()


# ---------------------------------------------------------------------------
# time-series ring buffers
# ---------------------------------------------------------------------------

class TestTimeSeriesSampler:
    def test_first_sample_anchors_no_window(self):
        sampler = TimeSeriesSampler(MetricsRegistry(), period_s=1.0)
        assert sampler.sample_once(now=0.0) is None
        assert sampler.samples_total == 1
        assert sampler.windows() == []

    def test_counter_deltas_rates_and_gauges(self):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        sampler.sample_once(now=0.0)
        reg.inc("a", "commits", 10)
        reg.gauge("a", "depth", 7.0)
        window = sampler.sample_once(now=2.0)
        assert window.dur_s == pytest.approx(2.0)
        assert window.deltas[("a", "commits")] == 10
        assert window.rates[("a", "commits")] == pytest.approx(5.0)
        assert window.gauges[("a", "depth")] == 7.0
        # second window sees only the NEW increments
        reg.inc("a", "commits", 4)
        window = sampler.sample_once(now=3.0)
        assert window.deltas[("a", "commits")] == 4
        assert window.rates[("a", "commits")] == pytest.approx(4.0)

    def test_counter_reset_reads_as_fresh_epoch(self):
        """An in-place registry reset() moves cumulatives BACKWARD; the
        window must report the new cumulative as the delta, never a
        negative rate."""
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        reg.inc("a", "commits", 10)
        sampler.sample_once(now=0.0)
        reg.reset()
        reg.inc("a", "commits", 3)
        window = sampler.sample_once(now=1.0)
        assert window.deltas[("a", "commits")] == 3
        assert all(r >= 0 for r in window.rates.values())

    def test_histogram_count_total_deltas(self):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        sampler.sample_once(now=0.0)
        reg.record("s", "lat", 0.2)
        reg.record("s", "lat", 0.3)
        window = sampler.sample_once(now=1.0)
        count, total = window.hist_deltas[("s", "lat")]
        assert count == 2
        assert total == pytest.approx(0.5)
        assert window.rates[("s", "lat")] == pytest.approx(2.0)

    def test_retention_evicts_oldest(self):
        sampler = TimeSeriesSampler(MetricsRegistry(), period_s=1.0,
                                    retention=3)
        for t in range(6):
            sampler.sample_once(now=float(t))
        windows = sampler.windows()
        assert len(windows) == 3
        assert [w.t for w in windows] == [3.0, 4.0, 5.0]
        # horizon read clips to the trailing span
        assert [w.t for w in sampler.windows(horizon_s=2.0, now=5.0)] == \
            [4.0, 5.0]

    def test_leg_decomposition_binding_and_utilization(self):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        sampler.sample_once(now=0.0)
        reg.record(m.SCOPE_TPU_REPLAY, m.M_PROFILE_KERNEL, 0.6)
        reg.record(m.SCOPE_REBUILD, m.M_PROFILE_KERNEL, 0.2)
        reg.record(m.SCOPE_TPU_REPLAY, m.M_PROFILE_PACK, 0.1)
        window = sampler.sample_once(now=1.0)
        assert window.legs[m.M_PROFILE_KERNEL] == pytest.approx(0.8)
        assert window.legs[m.M_PROFILE_PACK] == pytest.approx(0.1)
        assert window.binding_resource == m.M_PROFILE_KERNEL
        assert window.utilization == pytest.approx(0.9)
        # idle window: nothing ran
        window = sampler.sample_once(now=2.0)
        assert window.binding_resource == "idle"
        assert window.utilization == 0.0

    def test_saturation_queue_fill_and_device_busy(self):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        sampler.set_capacity(m.SCOPE_TPU_SERVING, m.M_SERVING_QUEUE_DEPTH,
                             lambda: 8)
        sampler.sample_once(now=0.0)
        reg.gauge(m.SCOPE_TPU_SERVING, m.M_SERVING_QUEUE_DEPTH, 6.0)
        reg.gauge(m.SCOPE_TPU_EXECUTOR, m.M_EXEC_DEVICE_BUSY, 0.5)
        reg.record(m.SCOPE_TPU_REPLAY, m.M_PROFILE_PACK_WAIT, 0.3)
        reg.record(m.SCOPE_TPU_REPLAY, m.M_PROFILE_KERNEL, 0.1)
        window = sampler.sample_once(now=1.0)
        sat = window.saturation
        assert sat["queue_depth"] == 6.0
        assert sat["queue_capacity"] == 8.0
        assert sat["queue_fill"] == pytest.approx(0.75)
        assert sat["device_busy"] == 0.5
        assert sat["queue_wait_share"] == pytest.approx(0.75)

    def test_fraction_over_bucket_boundary_semantics(self):
        """Bucket-granular over-counting: a bucket bounded exactly AT
        the threshold counts under (le semantics make those observations
        provably <= the ceiling); between bounds the violation rounds UP
        to the enclosing bucket (conservative)."""
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        sampler.track_histogram("s", "lat")
        sampler.sample_once(now=0.0)
        reg.observe("s", "lat", 0.3)   # le=0.5 bucket
        reg.observe("s", "lat", 0.7)   # le=1.0 bucket
        reg.observe("s", "lat", 2.0)   # le=2.5 bucket
        sampler.sample_once(now=1.0)
        # 0.5 is a DEFAULT_BUCKETS bound: the le=0.5 bucket is under
        assert sampler.fraction_over("s", "lat", 0.5, 10.0, now=1.0) == (2, 3)
        # 0.6 is between bounds: the 0.7 (le=1.0 bucket) still counts over
        assert sampler.fraction_over("s", "lat", 0.6, 10.0, now=1.0) == (2, 3)
        # horizon excludes the window entirely
        assert sampler.fraction_over("s", "lat", 0.5, 10.0, now=99.0) == (0, 0)

    def test_untracked_histograms_keep_no_buckets(self):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        sampler.sample_once(now=0.0)
        reg.observe("s", "lat", 0.3)
        window = sampler.sample_once(now=1.0)
        assert ("s", "lat") in window.hist_deltas
        assert window.bucket_deltas == {}

    def test_publishes_own_health_gauges(self):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        sampler.sample_once(now=0.0)
        sampler.sample_once(now=1.0)
        assert reg.gauge_value(m.SCOPE_TIMESERIES, "windows") == 1.0
        assert reg.gauge_value(m.SCOPE_TIMESERIES, "samples") == 2.0

    def test_on_sample_hook_sees_window_and_cannot_break_sampler(self):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        seen = []
        sampler.on_sample = lambda w: seen.append(w.t)
        sampler.sample_once(now=0.0)
        sampler.sample_once(now=1.0)
        assert seen == [1.0]
        sampler.on_sample = lambda w: 1 / 0
        assert sampler.sample_once(now=2.0) is not None  # hook swallowed

    def test_doc_shape(self):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0, retention=10)
        sampler.sample_once(now=0.0)
        reg.inc("a", "b")
        sampler.sample_once(now=1.0)
        doc = sampler.doc(last_n=5)
        assert doc["retention"] == 10
        assert doc["samples"] == 2
        (window,) = doc["windows"]
        assert window["t"] == 1.0
        assert window["rates"]["a/b"] == pytest.approx(1.0)
        assert window["binding_resource"] == "idle"

    def test_thread_lifecycle(self):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=0.02)
        sampler.start()
        try:
            deadline = time.monotonic() + 5
            while sampler.samples_total < 3 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert sampler.samples_total >= 3
            assert any(t.name == "cadence-timeseries"
                       for t in threading.enumerate())
        finally:
            sampler.stop()
        assert not any(t.name == "cadence-timeseries"
                       for t in threading.enumerate())


# ---------------------------------------------------------------------------
# SLO burn rate
# ---------------------------------------------------------------------------

class TestBurnRate:
    def _rig(self, ceiling_s=0.5):
        reg = MetricsRegistry()
        sampler = TimeSeriesSampler(reg, period_s=1.0)
        burn = BurnRateEvaluator(
            sampler, [BurnTarget("start", "s", "lat", ceiling_s)],
            horizons=(5.0, 60.0), registry=reg)
        return reg, sampler, burn

    def test_construction_preregisters_gauges(self):
        reg, _, _ = self._rig()
        assert reg.gauge_value(m.SCOPE_SLO, "burn-rate-start-5s") == 0.0
        assert reg.gauge_value(m.SCOPE_SLO, "burn-rate-start-60s") == 0.0
        assert reg.gauge_value(m.SCOPE_SLO, "alerting-start") == 0.0

    def test_sustained_violation_burns_and_alerts(self):
        reg, sampler, burn = self._rig()
        sampler.sample_once(now=0.0)
        for _ in range(100):
            reg.observe("s", "lat", 2.0)  # all over the 0.5s ceiling
        sampler.sample_once(now=2.0)
        doc = burn.evaluate(now=2.0)
        (row,) = doc["targets"]
        # fraction 1.0 against the p99 budget of 0.01 → burn rate 100
        assert row["windows"]["5s"] == {"over": 100, "total": 100,
                                        "fraction": 1.0, "burn_rate": 100.0}
        assert row["alerting"] and not doc["ok"]
        assert reg.gauge_value(m.SCOPE_SLO, "burn-rate-start-5s") == 100.0
        assert reg.gauge_value(m.SCOPE_SLO, "alerting-start") == 1.0
        assert reg.gauge_value(m.SCOPE_SLO, "alerting") == 1.0

    def test_under_ceiling_traffic_burns_nothing(self):
        reg, sampler, burn = self._rig()
        sampler.sample_once(now=0.0)
        for _ in range(100):
            reg.observe("s", "lat", 0.1)
        sampler.sample_once(now=2.0)
        doc = burn.evaluate(now=2.0)
        (row,) = doc["targets"]
        assert row["windows"]["5s"]["burn_rate"] == 0.0
        assert doc["ok"] and not row["alerting"]
        assert reg.gauge_value(m.SCOPE_SLO, "alerting") == 0.0

    def test_observations_at_ceiling_are_under(self):
        """0.5s is a DEFAULT_BUCKETS bound, so 'p99 <= 500ms' is exact at
        the ceiling: observations landing in the le=0.5 bucket are
        provably within budget."""
        reg, sampler, burn = self._rig(ceiling_s=0.5)
        sampler.sample_once(now=0.0)
        for _ in range(50):
            reg.observe("s", "lat", 0.5)
        sampler.sample_once(now=1.0)
        doc = burn.evaluate(now=1.0)
        assert doc["targets"][0]["windows"]["5s"]["over"] == 0

    def test_multi_window_blip_does_not_page(self):
        """A burst that has LEFT the short horizon: the long window still
        burns but the short one is quiet — multi-window alerting stays
        down (a blip can't page; only a sustained burn trips both)."""
        reg, sampler, burn = self._rig()
        sampler.sample_once(now=0.0)
        for _ in range(100):
            reg.observe("s", "lat", 2.0)
        sampler.sample_once(now=2.0)   # the burst window, t=2
        sampler.sample_once(now=30.0)  # quiet window, t=30
        doc = burn.evaluate(now=30.0)
        (row,) = doc["targets"]
        assert row["windows"]["5s"]["total"] == 0
        assert row["windows"]["60s"]["burn_rate"] == 100.0
        assert not row["alerting"] and doc["ok"]

    def test_proportional_burn_math(self):
        """2% of requests over a p99 ceiling = burn rate 2.0."""
        reg, sampler, burn = self._rig()
        sampler.sample_once(now=0.0)
        for _ in range(98):
            reg.observe("s", "lat", 0.1)
        for _ in range(2):
            reg.observe("s", "lat", 2.0)
        sampler.sample_once(now=1.0)
        doc = burn.evaluate(now=1.0)
        window = doc["targets"][0]["windows"]["5s"]
        assert window == {"over": 2, "total": 100, "fraction": 0.02,
                          "burn_rate": 2.0}


# ---------------------------------------------------------------------------
# host-runtime attribution
# ---------------------------------------------------------------------------

class TestHostProfiler:
    def test_subsystem_prefix_table(self):
        assert subsystem_for("cadence-pack-3") == "feeder-pack"
        assert subsystem_for("wirec-pack-0") == "feeder-pack"
        assert subsystem_for("cadence-serving-drain") == "serving-drain"
        assert subsystem_for("cadence-rpc-dispatch") == "rpc-dispatch"
        assert subsystem_for("cadence-task-worker-2") == "task-workers"
        assert subsystem_for("cadence-timeseries") == "telemetry"
        assert subsystem_for("MainThread") == "main"
        assert subsystem_for("Thread-17") == "other"

    def _spin_threads(self):
        """One runnable spinner + one parked waiter, both framework-named
        (the shapes the profiler must tell apart)."""
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(range(2000))

        spinner = threading.Thread(target=spin, daemon=True,
                                   name="cadence-pack-0")
        waiter = threading.Thread(target=stop.wait, daemon=True,
                                  name="cadence-serving-drain")
        spinner.start()
        waiter.start()
        return stop, spinner, waiter

    def test_attribution_gate_on_named_threads(self):
        """The ISSUE acceptance gate: >= 90% of sampled wall time lands
        on named subsystems when the process's threads are named."""
        reg = MetricsRegistry()
        prof = HostProfiler(reg, period_s=0.01)
        stop, spinner, waiter = self._spin_threads()
        try:
            for _ in range(40):
                prof.sample_once()
                time.sleep(0.005)
        finally:
            stop.set()
            spinner.join(timeout=2)
            waiter.join(timeout=2)
        assert prof.attributed_share() >= 0.9
        rollup = prof.rollup()
        assert rollup["samples"] == 40
        # >= not ==: other suites may leave parked framework threads
        # behind (executor pack pools are process-lived daemons), and
        # those share the spinner's/waiter's subsystems by design
        assert rollup["subsystems"]["feeder-pack"]["samples"] >= 40
        assert rollup["subsystems"]["serving-drain"]["samples"] >= 40
        assert 0.0 <= rollup["gil_contention"] <= 1.0
        # the spinner burned real CPU; the parked waiter did not
        assert rollup["subsystems"]["feeder-pack"]["cpu_s"] > 0.01
        assert rollup["subsystems"]["serving-drain"]["cpu_s"] < \
            rollup["subsystems"]["feeder-pack"]["cpu_s"]
        # the top-of-stack table points into the spinner's hot frame
        assert any(row["subsystem"] == "feeder-pack"
                   for row in rollup["top"])

    def test_waiting_threads_are_not_runnable(self):
        reg = MetricsRegistry()
        prof = HostProfiler(reg, period_s=0.01)
        stop = threading.Event()
        waiter = threading.Thread(target=stop.wait, daemon=True,
                                  name="cadence-serving-drain")
        waiter.start()
        try:
            runnable_before = prof.rollup()["runnable_samples"]
            for _ in range(10):
                prof.sample_once()
                time.sleep(0.002)
            # a parked Event.wait thread contributes wall samples but no
            # runnable ones; the pytest main thread may or may not be
            # mid-wait, so only assert the waiter's subsystem landed
            assert prof.rollup()["subsystems"]["serving-drain"][
                "samples"] >= 10
            assert runnable_before == 0
        finally:
            stop.set()
            waiter.join(timeout=2)

    def test_publishes_hostprof_gauges(self):
        reg = MetricsRegistry()
        prof = HostProfiler(reg, period_s=0.01)
        prof.sample_once()
        assert reg.gauge_value(m.SCOPE_HOSTPROF, "samples") == 1.0
        assert reg.gauge_value(m.SCOPE_HOSTPROF, "threads") >= 1.0
        assert 0.0 <= reg.gauge_value(
            m.SCOPE_HOSTPROF, "attributed-share") <= 1.0

    def test_thread_lifecycle(self):
        prof = HostProfiler(MetricsRegistry(), period_s=0.005)
        prof.start()
        try:
            deadline = time.monotonic() + 5
            while prof.samples < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert prof.samples >= 3
        finally:
            prof.stop()
        assert not any(t.name == "cadence-hostprof"
                       for t in threading.enumerate())


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_bounds_and_dropped_accounting(self):
        rec = FlightRecorder(capacity=16)
        for i in range(40):
            rec.emit("tick", i=i)
        stats = rec.stats()
        assert stats == {"capacity": 16, "ring": 16, "events": 40,
                         "dropped": 24, "dumps": 0}
        events = rec.snapshot()
        assert [e["i"] for e in events] == list(range(24, 40))
        assert rec.snapshot(last_n=3)[0]["i"] == 37
        # seq is a stable total order across drops
        assert [e["seq"] for e in events] == list(range(25, 41))

    def test_payload_clamping(self):
        rec = FlightRecorder(capacity=8)
        rec.emit("wide", s="x" * 1000, lst=list(range(100)),
                 d={f"k{i}": i for i in range(30)},
                 obj=object())
        (event,) = rec.snapshot()
        assert len(event["s"]) == MAX_STR + 1 and event["s"].endswith("…")
        assert len(event["lst"]) == 32
        assert len(event["d"]) == 16
        assert isinstance(event["obj"], str)
        rec.emit("too-many", **{f"f{i}": i for i in range(40)})
        event = rec.snapshot()[-1]
        # kind/t/seq + at most MAX_FIELDS payload fields
        assert len(event) <= flightrecorder.MAX_FIELDS + 3

    def test_dump_writes_jsonl_with_header(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        rec.emit("a", n=1)
        rec.emit("b", n=2)
        path = rec.dump(str(tmp_path / "flight.jsonl"), reason="test")
        lines = [json.loads(l) for l in
                 open(path, encoding="utf-8").read().splitlines()]
        header = lines[0]
        assert header["schema"] == flightrecorder.SCHEMA
        assert header["reason"] == "test"
        assert header["events"] == 2 and header["dropped"] == 0
        assert [e["kind"] for e in lines[1:]] == ["a", "b"]
        assert rec.stats()["dumps"] == 1
        # atomic write: no temp litter next to the dump
        assert os.listdir(tmp_path) == ["flight.jsonl"]

    def test_metrics_attach_counts_events_and_dumps(self, tmp_path):
        reg = MetricsRegistry()
        rec = FlightRecorder(capacity=8)
        rec.metrics = reg
        rec.emit("a")
        rec.emit("b")
        rec.dump(str(tmp_path / "f.jsonl"))
        assert reg.counter("flightrec", "events") == 2
        assert reg.counter("flightrec", "dumps") == 1

    def test_env_knob_disables_emit(self, monkeypatch):
        monkeypatch.setenv(flightrecorder.ENV_ENABLED, "0")
        rec = FlightRecorder(capacity=8)
        rec.emit("a")
        assert rec.stats()["events"] == 0

    def test_default_recorder_reset_isolates(self):
        flightrecorder.emit("leak-check", x=1)
        assert flightrecorder.DEFAULT_RECORDER.stats()["events"] >= 1
        flightrecorder.reset_all()
        assert flightrecorder.DEFAULT_RECORDER.stats()["events"] == 0

    def test_sigterm_dumps_flight_record(self, tmp_path):
        """A SIGTERM'd process leaves its black box behind: the handler
        dumps, then the default disposition still kills the process."""
        dump = tmp_path / "term.jsonl"
        script = (
            "import os, signal, time\n"
            "from cadence_tpu.utils import flightrecorder as fr\n"
            "assert fr.install_dump_handlers()\n"
            "fr.emit('boot-event', step=1)\n"
            "os.kill(os.getpid(), signal.SIGTERM)\n"
            "time.sleep(30)\n"  # never reached: the re-raise kills us
        )
        proc = subprocess.run(
            [sys.executable, "-c", script], cwd=REPO, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": REPO,
                 "CADENCE_TPU_FLIGHTREC_DUMP": str(dump)})
        assert proc.returncode == -signal.SIGTERM
        lines = [json.loads(l) for l in dump.read_text().splitlines()]
        assert lines[0]["schema"] == flightrecorder.SCHEMA
        assert lines[0]["reason"] == "sigterm"
        kinds = [e["kind"] for e in lines[1:]]
        assert "boot-event" in kinds and "sigterm" in kinds

    def test_kill_mode_crashpoint_dumps_before_sigkill(self, tmp_path):
        """SIGKILL runs no handler — the black box must write out at the
        crashpoint trigger itself, so the post-mortem keeps the dead
        process's timeline (arm + fire events included)."""
        dump = tmp_path / "crash.jsonl"
        script = (
            "from cadence_tpu.engine import crashpoints\n"
            "from cadence_tpu.utils import flightrecorder as fr\n"
            "fr.emit('pre-crash', step=1)\n"
            "crashpoints.install(crashpoints.CrashPoint(\n"
            "    site=crashpoints.SITE_AFTER_WRITE, mode='kill'))\n"
            "crashpoints.fire(crashpoints.SITE_AFTER_WRITE)\n"
            "raise SystemExit('crashpoint did not fire')\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", script], cwd=REPO, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": REPO,
                 "CADENCE_TPU_FLIGHTREC_DUMP": str(dump)})
        assert proc.returncode == -signal.SIGKILL
        lines = [json.loads(l) for l in dump.read_text().splitlines()]
        assert lines[0]["reason"] == "crash"
        kinds = [e["kind"] for e in lines[1:]]
        assert kinds == ["pre-crash", "crashpoint-arm", "crashpoint-fire"]


# ---------------------------------------------------------------------------
# scrape-handler consistency under concurrent reset
# ---------------------------------------------------------------------------

class TestScrapeConsistency:
    def test_prometheus_rendering_vs_concurrent_reset(self):
        """Regression for the shallow-copy race: to_prometheus() now
        renders from raw_series()'s single-lock snapshot, so a reset (or
        observe) landing mid-render can never produce an exposition whose
        +Inf bucket disagrees with its own _count line."""
        reg = MetricsRegistry()
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                for _ in range(5):
                    reg.observe("s", "lat", 0.01)
                    reg.inc("s", "reqs")
                reg.reset()

        thread = threading.Thread(target=writer, daemon=True)
        thread.start()
        try:
            for _ in range(300):
                text = reg.to_prometheus()
                inf = count = None
                for line in text.splitlines():
                    if line.startswith("cadence_lat_bucket") and \
                            'le="+Inf"' in line:
                        inf = float(line.rsplit(" ", 1)[1])
                    elif line.startswith("cadence_lat_count"):
                        count = float(line.rsplit(" ", 1)[1])
                if inf is not None or count is not None:
                    assert inf == count, text
        finally:
            stop.set()
            thread.join(timeout=5)


# ---------------------------------------------------------------------------
# admin verbs + fleet rollup math
# ---------------------------------------------------------------------------

class TestAdminTelemetry:
    def test_top_onebox(self, box):
        _run_one_workflow(box)
        doc = AdminHandler(box).top()
        summary = doc["hosts"]["onebox"]
        # the box's sampler anchored at construction: the admin sample
        # folds the whole build→now span into one window
        assert summary["windows"] >= 1
        assert summary["utilization"] >= 0.0
        assert "hostprof" in summary
        assert doc["cluster"]["hosts"] == 1
        assert doc["cluster"]["spread"]["hot_host"] == "onebox"

    def test_timeseries_verb_sees_workflow_traffic(self, box):
        _run_one_workflow(box)
        doc = AdminHandler(box).timeseries()
        rates = doc["windows"][-1]["rates"]
        assert any(key.startswith(m.SCOPE_FRONTEND_START)
                   for key in rates), rates

    def test_hostprof_verb_burst_samples(self, box):
        rollup = AdminHandler(box).hostprof(duration_s=0.05)
        assert rollup["samples"] >= 1
        assert "attributed_share" in rollup and "subsystems" in rollup

    def test_flightrec_verb_snapshot_and_dump(self, box, tmp_path):
        _run_one_workflow(box)
        doc = AdminHandler(box).flightrec(
            last_n=50, dump=str(tmp_path / "adm.jsonl"))
        kinds = {e["kind"] for e in doc["events"]}
        assert "txn-commit" in kinds  # the commit path's wide event
        assert doc["stats"]["events"] >= 1
        lines = (tmp_path / "adm.jsonl").read_text().splitlines()
        assert json.loads(lines[0])["reason"] == "admin"

    def test_summarize_windows_rollup_math(self):
        doc = {"windows": [
            {"utilization": 0.2, "binding_resource": "kernel",
             "legs": {"kernel": 0.2}, "saturation": {"queue_fill": 0.1},
             "gauges": {}},
            {"utilization": 0.6, "binding_resource": "pack",
             "legs": {"kernel": 0.1, "pack": 0.5},
             "saturation": {"queue_fill": 0.9},
             "gauges": {"slo/alerting": 1.0,
                        "slo/burn-rate-start-5s": 14.0,
                        "timeseries/windows": 2.0}},
        ]}
        summary = summarize_windows(doc)
        assert summary["windows"] == 2
        assert summary["utilization"] == pytest.approx(0.4)
        assert summary["legs"]["kernel"] == pytest.approx(0.3)
        assert summary["saturation"] == {"queue_fill": 0.9}  # latest wins
        # slo/* gauges surface with the prefix stripped; others don't leak
        assert summary["burn"] == {"alerting": 1.0,
                                   "burn-rate-start-5s": 14.0}
        assert summary["alerting"] is True
        empty = summarize_windows({"windows": []})
        assert empty["windows"] == 0 and empty["binding_resource"] == "idle"

    def test_cluster_rollup_spread_and_error_rows(self):
        hosts = {
            "host-0": {"utilization": 0.8, "legs": {"kernel": 3.0},
                       "alerting": False},
            "host-1": {"utilization": 0.1, "legs": {"pack": 1.0},
                       "alerting": True},
            "host-2": {"error": "URLError: refused"},
        }
        rollup = _cluster_rollup(hosts)
        assert rollup["hosts"] == 2  # the error row is excluded
        assert rollup["binding_resource"] == "kernel"  # summed-legs argmax
        assert rollup["alerting"] is True
        assert rollup["spread"] == {
            "hot_host": "host-0", "hot_utilization": 0.8,
            "cold_host": "host-1", "cold_utilization": 0.1,
            "utilization_delta": 0.7}
        assert _cluster_rollup({"h": {"error": "x"}})["hosts"] == 0

    def test_fleet_top_tolerates_dead_endpoint(self):
        doc = fleet_top({"dead": "127.0.0.1:1"}, timeout=0.5)
        assert "error" in doc["hosts"]["dead"]
        assert doc["cluster"]["hosts"] == 0


# ---------------------------------------------------------------------------
# scrape surface routes (onebox HTTP)
# ---------------------------------------------------------------------------

def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.status == 200
        return resp.read()


@pytest.mark.smoke
class TestTelemetryScrapeSurface:
    def test_http_telemetry_routes(self, box):
        _run_one_workflow(box, "scrape-tel-wf")
        server = box.scrape_server().start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            ts = json.loads(_get(f"{base}/timeseries"))
            assert ts["samples"] >= 2 and ts["windows"]
            hp = json.loads(_get(f"{base}/hostprof"))
            assert "attributed_share" in hp and "subsystems" in hp
            fr = json.loads(_get(f"{base}/flightrec"))
            assert {e["kind"] for e in fr["events"]} >= {"txn-commit"}
            # the flat /metrics scrape carries the plane's own health
            body = _get(f"{base}/metrics").decode()
            assert 'cadence_windows{scope="timeseries"}' in body
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# fleet `admin top` over a live wire cluster
# ---------------------------------------------------------------------------

@pytest.mark.smoke
class TestFleetTelemetryWire:
    def test_admin_top_over_live_cluster(self):
        """Two service hosts under real traffic: every host's /timeseries
        serves windows with burn-rate gauges, fleet_top aggregates them,
        and the wire admin ops answer."""
        from cadence_tpu.rpc.cluster import launch
        cluster = launch(num_hosts=2, num_shards=4,
                         env_extra={"CADENCE_TPU_TS_PERIOD_S": "0.2"})
        try:
            fe = cluster.frontend(0)
            fe.register_domain(DOMAIN)
            for i in range(6):
                fe.start_workflow_execution(DOMAIN, f"top-wf-{i}", "t", TL)
            time.sleep(1.2)  # >= 4 sampler ticks at 0.2s
            endpoints = {name: f"127.0.0.1:{port}"
                         for name, port in cluster.http_ports.items()}
            raw = scrape_timeseries(next(iter(endpoints.values())))
            assert raw["windows"] and raw["samples"] >= 2
            assert raw["slo"]["targets"]  # burn verdict rides the doc
            doc = fleet_top(endpoints)
            assert doc["cluster"]["hosts"] == 2
            for name, row in doc["hosts"].items():
                assert "error" not in row, row
                assert row["windows"] >= 2
                # the evaluator's gauges landed in the windows (one-tick
                # lag): every host reports its burn keys
                assert any(key.startswith("burn-rate-")
                           for key in row["burn"]), row["burn"]
            assert doc["cluster"]["spread"]["hot_host"] in doc["hosts"]

            name = sorted(cluster.hosts)[0]
            ts = cluster.admin(name, "admin_timeseries", 50)
            assert ts["windows"] and ts["host"] == name
            hp = cluster.admin(name, "admin_hostprof", 0.0)
            assert hp["samples"] >= 1
            assert hp["attributed_share"] >= 0.9  # every host thread named
            fr = cluster.admin(name, "admin_flightrec", 100, None)
            assert "host-boot" in {e["kind"] for e in fr["events"]}
        finally:
            cluster.stop()

    def test_cli_admin_top_wire_arm(self, capsys):
        """`cadence-tpu admin top --http` against a live host exits 0 and
        prints the fleet rollup JSON."""
        from cadence_tpu import cli
        from cadence_tpu.rpc.cluster import launch
        cluster = launch(num_hosts=1, num_shards=4,
                         env_extra={"CADENCE_TPU_TS_PERIOD_S": "0.2"})
        try:
            time.sleep(0.6)
            (name, port), = cluster.http_ports.items()
            rc = cli.main(["admin", "top", "--http",
                           f"{name}=127.0.0.1:{port}"])
            assert rc == 0
            doc = json.loads(capsys.readouterr().out)
            assert doc["cluster"]["hosts"] == 1
            assert name in doc["hosts"]
            # a dead endpoint in the fleet flips the exit code
            rc = cli.main(["admin", "top", "--http",
                           f"{name}=127.0.0.1:{port}",
                           "--http", "dead=127.0.0.1:1"])
            assert rc == 1
            doc = json.loads(capsys.readouterr().out)
            assert "error" in doc["hosts"]["dead"]
        finally:
            cluster.stop()

    def test_sigterm_host_dumps_own_flight_record(self, tmp_path):
        """The acceptance scenario: a SIGTERM'd host dumps its own flight
        record; a SIGKILL'd host's last interactions survive in its
        peers' rings (their events name the dead host's lifecycle)."""
        from cadence_tpu.rpc.cluster import launch
        dump = tmp_path / "host0-flight.jsonl"
        cluster = launch(
            num_hosts=2, num_shards=4,
            env_per_role={"host-0": {
                "CADENCE_TPU_FLIGHTREC_DUMP": str(dump)}})
        try:
            victim = sorted(cluster.hosts)[0]
            cluster.kill_host(victim, sig=signal.SIGTERM)
            deadline = time.monotonic() + 15
            while not dump.exists() and time.monotonic() < deadline:
                time.sleep(0.1)
            assert dump.exists(), "SIGTERM'd host left no flight record"
            # the dump may still be mid-replace; poll until it parses
            lines = []
            while time.monotonic() < deadline:
                try:
                    lines = [json.loads(l)
                             for l in dump.read_text().splitlines()]
                    break
                except ValueError:
                    time.sleep(0.1)
            assert lines[0]["schema"] == flightrecorder.SCHEMA
            assert lines[0]["reason"] == "sigterm"
            kinds = {e["kind"] for e in lines[1:]}
            assert "host-boot" in kinds and "sigterm" in kinds
            # the survivor's ring still answers and holds its own boot
            survivor = sorted(cluster.hosts)[1]
            fr = cluster.admin(survivor, "admin_flightrec", 200, None)
            assert "host-boot" in {e["kind"] for e in fr["events"]}
        finally:
            cluster.stop()
