"""Continuous canary (VERDICT r4 missing #6; canary/cron.go:41,
sanity.go:28-46): the self-verifying feature suite run as a loop —
green over >=100 cycles in-process, and against a LIVE wire cluster."""
import pytest

from cadence_tpu.engine.canary import Canary
from cadence_tpu.engine.onebox import Onebox


class TestCanaryLoop:
    def test_hundred_cycles_green(self):
        """The cron-loop contract: 100 consecutive cycles, every feature
        (echo/signal/timer/query/visibility/batch/reset) green."""
        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain("canary")

        def pump():
            box.pump_once()
            box.advance_time(1.5)

        canary = Canary(box.frontend, "canary", pump=pump, poll_wait=0.02)
        report = canary.run(100)
        assert report.green_cycles == 100, report.summary()
        assert report.ok
        # the cluster the canary hammered still verifies on device
        assert box.tpu.verify_all().ok

    def test_feature_isolation(self):
        """One broken feature fails ITS slot, not the cycle's siblings
        (sanity.go per-child isolation)."""
        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain("canary")

        def pump():
            box.pump_once()
            box.advance_time(1.5)

        canary = Canary(box.frontend, "canary", pump=pump, poll_wait=0.02)

        def broken(tag):
            raise RuntimeError("injected canary failure")

        canary._timer = broken
        result = canary.run_cycle(0)
        assert "timer" in result.failed
        assert "injected" in result.failed["timer"]
        for feat in ("echo", "signal", "query", "visibility", "batch",
                     "reset"):
            assert feat in result.passed, result.failed


class TestCanaryAgainstWireCluster:
    def test_cycles_green_over_sockets(self):
        """The canary against REAL processes: every feature end-to-end
        through a FrontendClient, hosts pumping themselves."""
        from cadence_tpu.rpc.cluster import launch

        cluster = launch(num_hosts=2, num_shards=4, hb_interval=0.1,
                         ttl=2.0)
        try:
            fe = cluster.frontend(0)
            fe.register_domain("canary")
            canary = Canary(fe, "canary", deadline_s=30.0)
            report = canary.run(3)
            assert report.ok, report.summary()
            assert report.green_cycles == 3
        finally:
            cluster.stop()
