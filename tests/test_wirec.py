"""wirec compressed transfer format: exact round-trip, replay parity,
streaming profile pin/refit.

The host link is the product bottleneck (SURVEY §7 hard part 6); wirec
ships ~10-18 B/event instead of wire32's 80 by GCD-scaled columnar
delta/abs/const coding chosen per lane from the measured corpus, decoded
exactly on device (ops/wirec.py). These tests pin the exactness contract:
decode(pack(x)) == x bit-for-bit, and the replay CRCs match the wire32
path on every suite.
"""
import numpy as np
import pytest

from cadence_tpu.core.checksum import DEFAULT_LAYOUT
from cadence_tpu.gen.corpus import SUITES, generate_corpus
from cadence_tpu.ops.encode import NUM_LANES, encode_corpus, to_wire32
from cadence_tpu.ops.wirec import (
    KIND_CONST,
    KIND_DELTA,
    ProfileMisfit,
    decode_wirec,
    pack_wirec,
)


def _corpus(suite, n=16, seed=9, target_events=80):
    return encode_corpus(generate_corpus(suite, num_workflows=n, seed=seed,
                                         target_events=target_events))


class TestWirecRoundTrip:
    @pytest.mark.parametrize("suite", SUITES)
    def test_decode_is_exact(self, suite):
        ev = _corpus(suite)
        c = pack_wirec(ev)
        back = np.asarray(decode_wirec(c.slab, c.bases, c.n_events,
                                       c.profile))
        assert back.shape == ev.shape
        assert (back == ev).all()

    @pytest.mark.parametrize("suite", SUITES)
    def test_density_beats_wire32(self, suite):
        """The whole point: ≤20 B/event vs wire32's 80 (VERDICT r4 #2)."""
        ev = _corpus(suite, n=64)
        c = pack_wirec(ev)
        assert c.bytes_per_event() <= 20.0
        assert c.wire_bytes < to_wire32(ev).nbytes / 3

    def test_adversarial_values_still_exact(self):
        """Pathological lanes (wide random values, negatives, 64-bit
        magnitudes) degrade toward raw width-8 columns, never corrupt."""
        rng = np.random.default_rng(3)
        W, E = 8, 32
        ev = np.zeros((W, E, NUM_LANES), dtype=np.int64)
        n = rng.integers(5, E, size=W)
        for w in range(W):
            ev[w, :n[w], 0] = np.arange(1, n[w] + 1)          # event ids
            ev[w, :n[w], 1] = rng.integers(0, 40, n[w])       # types
            ev[w, :n[w], 3] = rng.integers(-2**62, 2**62, n[w])  # wild ts
            ev[w, :n[w], 7] = rng.integers(-2**31, 2**31, n[w])
            ev[w, n[w]:, 1] = -1
        c = pack_wirec(ev)
        back = np.asarray(decode_wirec(c.slab, c.bases, c.n_events,
                                       c.profile))
        assert (back == ev).all()

    def test_empty_workflows_roundtrip(self):
        """All-padding rows (the feeder's tail-chunk filler blobs)."""
        ev = np.zeros((4, 16, NUM_LANES), dtype=np.int64)
        ev[:, :, 1] = -1  # event-type pad value
        ev[0, :3, 0] = [1, 2, 3]
        ev[0, :3, 1] = [0, 2, 3]
        c = pack_wirec(ev)
        assert (np.asarray(decode_wirec(c.slab, c.bases, c.n_events,
                                        c.profile)) == ev).all()


class TestWirecReplayParity:
    @pytest.mark.parametrize("suite", SUITES)
    def test_crc_matches_wire32_path(self, suite):
        import jax.numpy as jnp

        from cadence_tpu.ops.replay import replay_to_crc32, replay_wirec_to_crc

        ev = _corpus(suite)
        crc32_, err32 = replay_to_crc32(jnp.asarray(to_wire32(ev)),
                                        DEFAULT_LAYOUT)
        c = pack_wirec(ev)
        crcw, errw = replay_wirec_to_crc(jnp.asarray(c.slab),
                                         jnp.asarray(c.bases),
                                         jnp.asarray(c.n_events),
                                         c.profile, DEFAULT_LAYOUT)
        assert (np.asarray(crcw) == np.asarray(crc32_)).all()
        assert (np.asarray(errw) == np.asarray(err32)).all()

    def test_sharded_crc_matches(self):
        """SPMD wirec replay over the 8-device CPU mesh: compressed in,
        identical CRCs out."""
        from cadence_tpu.parallel.mesh import (
            make_mesh,
            replay_sharded_crc,
            replay_wirec_sharded_crc,
            shard_events32,
        )

        ev = _corpus("ndc", n=32)
        mesh = make_mesh()
        crc32_, _, _ = replay_sharded_crc(
            shard_events32(np.ascontiguousarray(to_wire32(ev)), mesh),
            mesh, DEFAULT_LAYOUT)
        c = pack_wirec(ev)
        crcw, _, _ = replay_wirec_sharded_crc(c, mesh, DEFAULT_LAYOUT)
        assert (np.asarray(crcw) == np.asarray(crc32_)).all()


class TestWirecStreaming:
    def test_pinned_profile_packs_identically(self):
        ev = _corpus("basic")
        c = pack_wirec(ev)
        c2 = pack_wirec(ev, profile=c.profile)
        assert (c2.slab == c.slab).all()
        assert (c2.bases == c.bases).all()

    def test_profile_misfit_raises_not_corrupts(self):
        """A chunk outside the pinned widths/scales must REFUSE, so the
        feeder refits + recompiles instead of shipping wrong bytes."""
        ev = _corpus("basic")
        c = pack_wirec(ev)
        wild = ev.copy()
        wild[:, 1::2, 3] += 7  # ±7ns jitter breaks the delta GCD scale
        with pytest.raises(ProfileMisfit):
            pack_wirec(wild, profile=c.profile)

    def test_feeder_wirec_matches_wire32(self):
        """End-to-end ingest parity: serialized blobs → C++ packer →
        wirec → device decode+replay vs the wire32 pipeline."""
        from cadence_tpu.native import packing
        from cadence_tpu.native.feeder import feed_corpus32, feed_corpus_wirec

        if not packing.native_available():
            pytest.skip("native packer not built")
        histories = generate_corpus("echo_signal", num_workflows=48, seed=5,
                                    target_events=60)
        crcw, errw, report = feed_corpus_wirec(histories, chunk_workflows=16)
        crc3, err3, _ = feed_corpus32(histories, chunk_workflows=16)
        assert (crcw == crc3).all()
        assert (errw == err3).all()
        assert report.profile_refits == 0
        assert report.bytes_per_event <= 25  # tiny chunks amortize worse

    def test_profile_kinds_are_sensible(self):
        """The plan the packer discovers on a real corpus: sequential ids
        delta/abs at width 1, constant lanes at width 0."""
        ev = _corpus("basic", n=64)
        c = pack_wirec(ev)
        by_lane = {e.lane: e for e in c.profile}
        assert by_lane[0].width <= 2            # event ids
        assert by_lane[3].kind == KIND_DELTA    # timestamps delta-coded
        assert by_lane[3].width <= 2
        assert any(e.kind == KIND_CONST for e in c.profile)
        total = sum(e.width for e in c.profile)
        assert total <= 20
