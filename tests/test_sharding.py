"""Sharded replay on a virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8): parity must hold under SPMD
partitioning of the workflow axis."""
import jax
import numpy as np
import pytest

from cadence_tpu.core.checksum import payload_row
from cadence_tpu.gen.corpus import generate_corpus
from cadence_tpu.oracle.state_builder import StateBuilder
from cadence_tpu.ops.encode import encode_corpus
from cadence_tpu.parallel.mesh import make_mesh, replay_sharded


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {len(jax.devices())}"
    )
    return make_mesh()


def test_sharded_parity(mesh):
    histories = generate_corpus("basic", num_workflows=16, seed=13,
                                target_events=60)
    events = encode_corpus(histories)
    rows, errors, stats = replay_sharded(jax.numpy.asarray(events), mesh)
    rows, errors, stats = map(np.asarray, (rows, errors, stats))
    assert (errors == 0).all()
    assert stats[0] == 0  # global error count via collective
    assert stats[1] == 16  # all workflows closed
    expected = np.stack([
        payload_row(StateBuilder().replay_history(h)) for h in histories
    ])
    assert (rows == expected).all()


def test_sharded_matches_single_device(mesh):
    from cadence_tpu.ops.replay import replay_to_payload
    histories = generate_corpus("timer_retry", num_workflows=8, seed=4,
                                target_events=60)
    events = jax.numpy.asarray(encode_corpus(histories))
    rows_sharded, _, _ = replay_sharded(events, mesh)
    rows_single, _ = replay_to_payload(events)
    assert (np.asarray(rows_sharded) == np.asarray(rows_single)).all()
