"""Pipelined feeder + resharding (VERDICT ask #9).

wire bytes → C++ packer → device replay chunks, double-buffered; and
shard-movement invariance: the same corpus on differently-shaped meshes
yields identical payloads.
"""
import numpy as np
import pytest

from cadence_tpu.core.checksum import crc32_of_rows
from cadence_tpu.gen.corpus import SUITES, generate_corpus
from cadence_tpu.native import packing
from cadence_tpu.native.feeder import feed_corpus, feed_serialized
from cadence_tpu.ops.encode import encode_corpus, history_length
from cadence_tpu.ops.replay import replay_corpus

needs_native = pytest.mark.skipif(not packing.native_available(),
                                  reason="native packer unavailable")


@needs_native
class TestFeeder:
    def test_feeder_matches_direct_replay(self):
        """Chunked pipelined feed == one-shot replay, bit for bit."""
        histories = []
        for suite in SUITES:
            histories.extend(generate_corpus(suite, num_workflows=6, seed=5,
                                             target_events=40))
        rows_direct, crcs_direct, errors_direct = replay_corpus(histories)

        rows, errors, report = feed_corpus(histories, chunk_workflows=8)
        assert (errors == errors_direct).all()
        assert (rows == rows_direct).all()
        assert (crc32_of_rows(rows) == crcs_direct).all()
        assert report.workflows == len(histories)
        assert report.chunks == -(-len(histories) // 8)
        assert report.events_per_sec > 0
        assert report.pack_events_per_sec >= report.events_per_sec

    def test_feeder_pads_tail_chunk(self):
        histories = generate_corpus("basic", num_workflows=5, seed=3,
                                    target_events=30)
        rows, errors, report = feed_corpus(histories, chunk_workflows=4)
        assert rows.shape[0] == 5 and errors.shape[0] == 5
        assert (errors == 0).all()
        assert report.chunks == 2

    def test_feeder_event_count_is_real(self):
        histories = generate_corpus("basic", num_workflows=4, seed=9,
                                    target_events=30)
        total = sum(history_length(h) for h in histories)
        _, _, report = feed_corpus(histories, chunk_workflows=4)
        assert report.events == total


class TestResharding:
    def test_mesh_shapes_agree(self):
        """Replay on an 8-device mesh, then a 2-device mesh, then a single
        device: identical payload rows (shard movement never changes
        state — the P1 axis is pure data parallelism)."""
        import jax
        import jax.numpy as jnp

        from cadence_tpu.parallel.mesh import make_mesh, replay_sharded

        histories = []
        for suite in SUITES[:3]:
            histories.extend(generate_corpus(suite, num_workflows=8, seed=11,
                                             target_events=24))
        events = jnp.asarray(encode_corpus(histories))
        devices = jax.devices()
        assert len(devices) >= 8  # conftest forces the 8-device CPU mesh

        rows8, err8, _ = replay_sharded(events, make_mesh(devices[:8]))
        rows2, err2, _ = replay_sharded(events, make_mesh(devices[:2]))
        rows1, err1, _ = replay_sharded(events, make_mesh(devices[:1]))
        rows8, rows2, rows1 = map(np.asarray, (rows8, rows2, rows1))
        assert (np.asarray(err8) == 0).all()
        assert (rows8 == rows2).all()
        assert (rows8 == rows1).all()

    def test_resharded_array_replays_identically(self):
        """Move an ALREADY-SHARDED corpus to a different mesh (the
        shard-steal path: device_put with a new sharding) and replay —
        payloads unchanged."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from cadence_tpu.parallel.mesh import SHARD_AXIS, make_mesh, replay_sharded

        histories = generate_corpus("echo_signal", num_workflows=16, seed=2,
                                    target_events=24)
        events = jnp.asarray(encode_corpus(histories))
        devices = jax.devices()
        mesh_a = make_mesh(devices[:8])
        mesh_b = make_mesh(devices[4:8])  # different device set + shape

        rows_a, _, _ = replay_sharded(events, mesh_a)
        moved = jax.device_put(
            events, NamedSharding(mesh_b, P(SHARD_AXIS, None, None)))
        rows_b, _, _ = replay_sharded(moved, mesh_b)
        assert (np.asarray(rows_a) == np.asarray(rows_b)).all()


class TestFeeder32:
    def test_feed32_matches_direct_crc(self):
        """The wire32 ingest pipeline produces the same per-workflow CRCs
        as a direct single-launch replay of the same corpus."""
        import jax.numpy as jnp
        import numpy as np
        import pytest

        from cadence_tpu.core.checksum import DEFAULT_LAYOUT, crc32_of_rows
        from cadence_tpu.gen.corpus import generate_corpus
        from cadence_tpu.native import packing
        from cadence_tpu.native.feeder import feed_corpus32
        from cadence_tpu.ops.encode import encode_corpus, history_length
        from cadence_tpu.ops.replay import replay_to_payload

        if not packing.native_available():
            pytest.skip("no C++ toolchain")
        hists = generate_corpus("basic", num_workflows=96, seed=13,
                                target_events=60)
        max_events = max(history_length(h) for h in hists)
        crcs, errors, report = feed_corpus32(hists, chunk_workflows=32,
                                             max_events=max_events)
        assert report.chunks == 3 and report.workflows == 96
        assert (errors == 0).all()
        rows, _ = replay_to_payload(
            jnp.asarray(encode_corpus(hists, max_events)), DEFAULT_LAYOUT)
        assert (crcs == crc32_of_rows(np.asarray(rows))).all()


@needs_native
class TestFeederNativeWirec:
    """The ISSUE 9 ingest path: the wirec feeder routed through the
    native fused encoder must be CRC-identical to the pure-Python
    fallback (CADENCE_TPU_NATIVE_WIREC=0), with the report saying which
    encoder served and the profile pin surviving the whole stream."""

    def _hists(self):
        return generate_corpus("basic", num_workflows=48, seed=21,
                               target_events=40)

    def test_native_and_python_paths_crc_identical(self, monkeypatch):
        from cadence_tpu.native import wirec as nwirec
        from cadence_tpu.native.feeder import feed_corpus_wirec

        hists = self._hists()
        monkeypatch.delenv(nwirec.NATIVE_WIREC_ENV, raising=False)
        crc_n, err_n, rep_n = feed_corpus_wirec(hists, chunk_workflows=16)
        monkeypatch.setenv(nwirec.NATIVE_WIREC_ENV, "0")
        crc_p, err_p, rep_p = feed_corpus_wirec(hists, chunk_workflows=16)
        if nwirec.native_wirec_available():
            assert rep_n.native_wirec
        assert not rep_p.native_wirec
        assert (crc_n == crc_p).all()
        assert (err_n == err_p).all()
        assert rep_n.events == rep_p.events
        assert rep_n.chunks == rep_p.chunks == 3

    def test_native_feed_matches_direct_replay_crc(self):
        """Native-fed CRCs == a one-shot replay of the same corpus."""
        import jax.numpy as jnp

        from cadence_tpu.core.checksum import DEFAULT_LAYOUT
        from cadence_tpu.native.feeder import feed_corpus_wirec
        from cadence_tpu.ops.replay import replay_to_payload

        hists = self._hists()
        max_events = max(history_length(h) for h in hists)
        crcs, errors, report = feed_corpus_wirec(hists, chunk_workflows=16,
                                                 max_events=max_events)
        assert (errors == 0).all()
        assert report.profile_refits == 0
        assert report.h2d_s >= 0.0
        rows, _ = replay_to_payload(
            jnp.asarray(encode_corpus(hists, max_events)), DEFAULT_LAYOUT)
        assert (crcs == crc32_of_rows(np.asarray(rows))).all()

    def test_feed_appends_o_new_events_and_payload_parity(self):
        """The suffix-append feeder leg: PackCache.encode_suffix +
        resident from-state replay — launched chunk shapes are sized by
        the SUFFIX event axis (O(new events)), payloads equal a full
        replay, and a second pass serves exact hits with zero device
        events."""
        import jax.numpy as jnp

        from cadence_tpu.core.checksum import DEFAULT_LAYOUT
        from cadence_tpu.engine.cache import PackCache, content_address
        from cadence_tpu.engine.ladder import EscalationLadder
        from cadence_tpu.engine.resident import ResidentStateCache
        from cadence_tpu.native.feeder import feed_appends
        from cadence_tpu.ops.encode import assemble_corpus
        from cadence_tpu.ops.payload import payload_rows
        from cadence_tpu.ops.replay import replay_events

        layout = DEFAULT_LAYOUT
        hists = generate_corpus("basic", num_workflows=16, seed=33,
                                target_events=60)
        keys = [("d", f"wf-{i}", "r") for i in range(len(hists))]
        pack_cache = PackCache(max_size=64)
        cache = ResidentStateCache(layout, ladder=EscalationLadder(layout))
        prefix_rows = [pack_cache.encode(k, h[:-1])
                       for k, h in zip(keys, hists)]
        corpus = assemble_corpus(prefix_rows,
                                 max(r.shape[0] for r in prefix_rows))
        s = replay_events(jnp.asarray(corpus), layout)
        rows = np.asarray(payload_rows(s, layout))
        branch = np.asarray(s.current_branch)
        for i, k in enumerate(keys):
            assert cache.admit(k, content_address(hists[i][:-1]),
                               cache.extract_row(s, i), rows[i],
                               int(branch[i]))

        items = [(k, h) for k, h in zip(keys, hists)]
        results, report = feed_appends(items, cache, pack_cache)
        assert all(r.ok for r in results)
        assert report.events > 0 and report.chunks >= 1
        # O(new events): every launched suffix axis is far below the
        # (bucketed) history axis
        history_e = corpus.shape[1]
        for _w, e in cache.last_append.chunk_shapes:
            assert e <= max(16, history_e // 2), (e, history_e)
        # payload parity vs full replay
        full_rows = [pack_cache.encode(k, h) for k, h in zip(keys, hists)]
        full = assemble_corpus(full_rows,
                               max(r.shape[0] for r in full_rows))
        s2 = replay_events(jnp.asarray(full), layout)
        expect = np.asarray(payload_rows(s2, layout))
        got = np.stack([np.asarray(r.payload) for r in results])
        assert (got == expect).all()
        # exact-hit pass: served from resident payloads, no device work
        results2, report2 = feed_appends(items, cache, pack_cache)
        assert all(r.ok for r in results2)
        assert report2.events == 0 and report2.chunks == 0
        got2 = np.stack([np.asarray(r.payload) for r in results2])
        assert (got2 == expect).all()

    def test_heterogeneous_stream_refits_identically(self, monkeypatch):
        """A stream whose later chunks fall outside chunk 0's pinned
        profile must REFIT (counted, never silent) on both encoders and
        still land on identical CRCs — the refit contract is
        path-independent, including the native fast path that re-emits
        from the already-decoded lanes scratch."""
        from cadence_tpu.native import wirec as nwirec
        from cadence_tpu.native.feeder import feed_corpus_wirec

        hists = generate_corpus("basic", num_workflows=16, seed=3,
                                target_events=30)
        hists += generate_corpus("timer_retry", num_workflows=16, seed=3,
                                 target_events=30)
        monkeypatch.delenv(nwirec.NATIVE_WIREC_ENV, raising=False)
        crc_n, err_n, rep_n = feed_corpus_wirec(hists, chunk_workflows=16)
        monkeypatch.setenv(nwirec.NATIVE_WIREC_ENV, "0")
        crc_p, err_p, rep_p = feed_corpus_wirec(hists, chunk_workflows=16)
        assert rep_n.profile_refits == rep_p.profile_refits >= 1, \
            "the heterogeneous stream no longer exercises the refit path"
        assert (crc_n == crc_p).all()
        assert (err_n == err_p).all()
