"""Capacity-escalation ladder (ISSUE 5).

Covers: flagged-row gather round-trip parity; the narrow payload
projection (widened state → base width, elementwise identical at equal
layouts); rung-1 resolution byte-identical to the oracle; rows that
overflow EVERY rung still arbitrating through the oracle byte-identically
(engine verify path included); rung/compile/residual counters visible on
/metrics; escalation under the pipelined executor at depth ≥ 2; the
rebuild path hydrating from widened rung states; the wirec ladder's CRC
parity; and the kernel-variant cache proving warm escalations recompile
nothing.
"""
import random

import numpy as np
import pytest

import jax.numpy as jnp

from cadence_tpu.core.checksum import (
    DEFAULT_LAYOUT,
    STICKY_ROW_INDEX,
    crc32_of_row,
    payload_row,
)
from cadence_tpu.core.enums import EventType
from cadence_tpu.engine.ladder import EscalationLadder
from cadence_tpu.engine.persistence import Stores
from cadence_tpu.engine.tpu_engine import TPUReplayEngine
from cadence_tpu.gen.corpus import (
    HistoryWriter,
    OVERFLOW_FRACTION,
    gen_overflow,
    generate_corpus,
)
from cadence_tpu.ops.encode import (
    LANE_EVENT_ID,
    encode_corpus,
    gather_subcorpus,
)
from cadence_tpu.ops.payload import payload_rows, payload_rows_narrow
from cadence_tpu.ops.replay import replay_events
from cadence_tpu.ops.state import CAPACITY_ERRORS, ErrorCode, widen_layout
from cadence_tpu.oracle.state_builder import StateBuilder
from cadence_tpu.utils import metrics as m
from cadence_tpu.utils.compile_cache import KernelVariantCache

SEED = 20260730


def _flood_seed() -> int:
    """A seed whose first random() lands in the flood branch."""
    s = 0
    while random.Random(s).random() >= OVERFLOW_FRACTION:
        s += 1
    return s


def _flood_history(capacity_hint: int, wf: str = "flood"):
    """One history holding capacity_hint + 8 concurrently-pending
    activities mid-replay (drained before close, so the ORACLE's final
    payload is representable at the base layout)."""
    rng = random.Random(_flood_seed())
    w = HistoryWriter(workflow_id=wf, run_id=f"run-{wf}")
    gen_overflow(rng, w, target_events=40, capacity_hint=capacity_hint)
    assert w._open is None
    return w.batches


def _overflow_setup(n=256, target=80):
    hists = generate_corpus("overflow", num_workflows=n, seed=SEED,
                            target_events=target)
    events = encode_corpus(hists)
    state = replay_events(jnp.asarray(events))
    errors = np.asarray(state.error)
    return hists, events, state, errors


def _oracle_row(history):
    row = payload_row(StateBuilder().replay_history(history))
    row[STICKY_ROW_INDEX] = 0
    return row


class TestGatherAndNarrow:
    def test_gather_subcorpus_roundtrip(self):
        """Gathered rows replay to EXACTLY the outputs the same rows had
        inside the full corpus — the gather loses nothing."""
        hists, events, state, errors = _overflow_setup(n=128)
        full_rows = np.asarray(payload_rows(state))
        idx = np.asarray([0, 5, 17, 99])
        sub = gather_subcorpus(events, idx, pad_workflows=8, pad_events=0)
        assert sub.shape[0] == 8
        # the event axis trims to the gathered rows' longest real history
        assert sub.shape[1] == int(
            (events[idx][:, :, LANE_EVENT_ID] > 0).sum(axis=1).max())
        s2 = replay_events(jnp.asarray(sub))
        assert (np.asarray(s2.error)[:4] == errors[idx]).all()
        rows2 = np.asarray(payload_rows(s2))
        healthy = errors[idx] == 0
        assert (rows2[:4][healthy] == full_rows[idx][healthy]).all()
        # padding rows replay as no-ops: no error, untouched fresh state
        assert (np.asarray(s2.error)[4:] == 0).all()

    def test_narrow_equals_payload_rows_at_same_layout(self):
        _, _, state, errors = _overflow_setup(n=64)
        rows = np.asarray(payload_rows(state))
        rows_n, ovf = payload_rows_narrow(state, DEFAULT_LAYOUT)
        assert (np.asarray(rows_n) == rows).all()
        assert not np.asarray(ovf).any()

    def test_narrow_from_widened_state_matches_oracle(self):
        """Replay at 2K, project to base width: byte-identical to the
        oracle's base-layout payload for rows that fit."""
        hists, events, _, errors = _overflow_setup(n=128)
        flagged = np.nonzero(errors)[0]
        assert len(flagged) > 0
        wide = widen_layout(DEFAULT_LAYOUT, 2)
        sub = gather_subcorpus(events, flagged)
        s = replay_events(jnp.asarray(sub), wide)
        assert (np.asarray(s.error) == 0).all()
        rows_n, ovf = payload_rows_narrow(s, DEFAULT_LAYOUT)
        assert not np.asarray(ovf).any()
        for k, i in enumerate(flagged):
            assert (np.asarray(rows_n)[k] == _oracle_row(hists[i])).all()

    def test_narrow_overflow_flags_unrepresentable_final_state(self):
        """A FINAL state wider than the base payload can never narrow —
        the overflow mask says so instead of truncating silently."""
        w = HistoryWriter(workflow_id="wide-final", run_id="r")
        w.begin_batch()
        w.add(EventType.WorkflowExecutionStarted,
              execution_start_to_close_timeout_seconds=600,
              task_start_to_close_timeout_seconds=10)
        w.end_batch()
        w.begin_batch()
        w.add(EventType.DecisionTaskScheduled,
              start_to_close_timeout_seconds=10)
        w.end_batch()
        started = w.single(EventType.DecisionTaskStarted,
                           scheduled_event_id=2)
        w.begin_batch()
        completed = w.add(EventType.DecisionTaskCompleted,
                          scheduled_event_id=2, started_event_id=started.id)
        for i in range(DEFAULT_LAYOUT.max_activities + 4):
            w.add(EventType.ActivityTaskScheduled, activity_id=f"a-{i}",
                  task_list="tl", schedule_to_start_timeout_seconds=60,
                  schedule_to_close_timeout_seconds=120,
                  start_to_close_timeout_seconds=60,
                  heartbeat_timeout_seconds=0)
        w.end_batch()
        events = encode_corpus([w.batches])
        wide = widen_layout(DEFAULT_LAYOUT, 2)
        s = replay_events(jnp.asarray(events), wide)
        assert int(np.asarray(s.error)[0]) == 0  # fits at 2K
        _, ovf = payload_rows_narrow(s, DEFAULT_LAYOUT)
        assert bool(np.asarray(ovf)[0])


class TestLadderCore:
    def test_rung1_resolves_default_overflow_suite(self):
        hists, events, _, errors = _overflow_setup(n=256)
        flagged = np.nonzero(errors)[0]
        assert len(flagged) >= 4
        assert set(errors[flagged]) == {ErrorCode.TABLE_OVERFLOW}
        ladder = EscalationLadder(DEFAULT_LAYOUT)
        outcome = ladder.escalate(gather_subcorpus(events, flagged))
        assert outcome.resolved.all()
        assert [r["rung"] for r in outcome.rungs] == [1]
        for k, i in enumerate(flagged):
            assert (outcome.rows[k] == _oracle_row(hists[i])).all()

    def test_rung2_resolves_what_rung1_cannot(self):
        """A flood past 2K but under 4K climbs to rung 2 and resolves."""
        hint = DEFAULT_LAYOUT.max_activities * 2  # flood = 2K + 8 > 2K
        hists = [_flood_history(hint)]
        events = encode_corpus(hists)
        errors = np.asarray(replay_events(jnp.asarray(events)).error)
        assert errors[0] == ErrorCode.TABLE_OVERFLOW
        ladder = EscalationLadder(DEFAULT_LAYOUT, max_rungs=2)
        outcome = ladder.escalate(gather_subcorpus(events, [0]))
        assert outcome.resolved[0]
        assert [r["rung"] for r in outcome.rungs] == [1, 2]
        assert (outcome.rows[0] == _oracle_row(hists[0])).all()

    def test_top_rung_overflow_stays_residual(self):
        """A flood past the TOP rung never resolves on device — the
        outcome says so and the caller's oracle arbitration still
        produces the byte-identical payload."""
        hint = DEFAULT_LAYOUT.max_activities * 4  # flood > top rung (4K)
        hists = [_flood_history(hint)]
        events = encode_corpus(hists)
        ladder = EscalationLadder(DEFAULT_LAYOUT, max_rungs=2)
        outcome = ladder.escalate(gather_subcorpus(events, [0]))
        assert not outcome.resolved[0]
        assert outcome.errors[0] == ErrorCode.TABLE_OVERFLOW
        # oracle arbitration of the residue: drained before close, so the
        # final payload IS representable at base width
        row = _oracle_row(hists[0])
        assert row.shape[0] == DEFAULT_LAYOUT.width

    def test_counters_reach_metrics_scrape(self):
        hists, events, _, errors = _overflow_setup(n=128)
        flagged = np.nonzero(errors)[0]
        registry = m.MetricsRegistry()
        ladder = EscalationLadder(DEFAULT_LAYOUT, registry=registry,
                                  variants=KernelVariantCache())
        ladder.variants.metrics = registry
        ladder.escalate(gather_subcorpus(events, flagged))
        snap = registry.snapshot()[m.SCOPE_TPU_FALLBACK]
        assert snap[m.M_LADDER_FLAGGED] == len(flagged)
        assert snap[m.ladder_rung_rows(1)] == len(flagged)
        assert snap[m.M_LADDER_RESOLVED] == len(flagged)
        assert snap[m.M_LADDER_RESIDUAL] == 0
        assert snap[m.M_LADDER_COMPILES] >= 1
        prom = registry.to_prometheus()
        assert 'cadence_rows_rung1_total{scope="tpu.fallback"}' in prom
        assert 'cadence_rung_compiles_total{scope="tpu.fallback"}' in prom
        assert ('cadence_residual_oracle_rows_total{scope="tpu.fallback"}'
                in prom)

    def test_warm_escalation_pays_zero_recompiles(self):
        _, events, _, errors = _overflow_setup(n=128)
        flagged = np.nonzero(errors)[0]
        registry = m.MetricsRegistry()
        ladder = EscalationLadder(DEFAULT_LAYOUT, registry=registry,
                                  variants=KernelVariantCache(registry))
        ladder.escalate(gather_subcorpus(events, flagged))
        cold = registry.counter(m.SCOPE_TPU_FALLBACK, m.M_LADDER_COMPILES)
        assert cold >= 1
        # same shapes (pow2-bucketed) → pure cache hits, zero compiles
        ladder.escalate(gather_subcorpus(events, flagged))
        ladder.escalate(gather_subcorpus(events, flagged[:-1]))
        assert registry.counter(m.SCOPE_TPU_FALLBACK,
                                m.M_LADDER_COMPILES) == cold
        assert registry.counter(m.SCOPE_TPU_FALLBACK,
                                m.M_LADDER_CACHE_HITS) >= 2

    def test_wirec_ladder_crc_parity(self):
        from cadence_tpu.ops.wirec import gather_corpus, pack_wirec

        hists, events, _, errors = _overflow_setup(n=128)
        flagged = np.nonzero(errors)[0]
        corpus = pack_wirec(events)
        # gather keeps the profile and the rows' exact bytes
        sub = gather_corpus(corpus, flagged)
        assert sub.profile == corpus.profile
        assert (sub.n_events[:len(flagged)]
                == corpus.n_events[flagged]).all()
        ladder = EscalationLadder(DEFAULT_LAYOUT)
        crcs, resolved, _ = ladder.escalate_wirec(corpus, flagged)
        assert resolved.all()
        for k, i in enumerate(flagged):
            assert crcs[k] == np.uint32(crc32_of_row(_oracle_row(hists[i])))


def _stores_with(hists):
    stores = Stores()
    keys = []
    for h in hists:
        key = (h[0].domain_id, h[0].workflow_id, h[0].run_id)
        for b in h:
            stores.history.append_batch(*key, list(b.events))
        stores.execution.upsert_workflow(StateBuilder().replay_history(h))
        keys.append(key)
    return stores, keys


class TestEngineEscalation:
    def test_verify_all_escalates_under_pipelined_executor(self):
        """Overflow corpus through the chunked, depth-≥2 pipelined
        executor: capacity-flagged rows across MULTIPLE chunks resolve on
        device (escalated, not oracle fallback), zero divergence."""
        hists = generate_corpus("overflow", num_workflows=192, seed=SEED,
                                target_events=60)
        stores, keys = _stores_with(hists)
        engine = TPUReplayEngine(stores, chunk_workflows=48,
                                 pipeline_depth=2)
        result = engine.verify_all(keys)
        assert result.ok
        assert result.verified_on_device == result.total == len(keys)
        assert len(result.escalated) >= 2
        assert result.fallback == []  # the oracle never ran
        assert len(engine.last_run_chunk_shapes) == 4
        # ladder accounting reached the engine's registry
        reg = engine.metrics
        assert reg.counter(m.SCOPE_TPU_FALLBACK, m.M_LADDER_RESOLVED) \
            == len(result.escalated)

    def test_verify_all_residual_still_arbitrates_through_oracle(self):
        """A workflow overflowing EVERY rung verifies byte-identically
        through the oracle path — the ladder narrows the oracle's job,
        never changes its answer."""
        hint = DEFAULT_LAYOUT.max_activities * 4
        hists = generate_corpus("overflow", num_workflows=31, seed=SEED,
                                target_events=60) + [_flood_history(hint)]
        stores, keys = _stores_with(hists)
        engine = TPUReplayEngine(stores, chunk_workflows=16,
                                 pipeline_depth=2)
        result = engine.verify_all(keys)
        assert result.ok
        assert keys[-1] in result.fallback
        assert keys[-1] not in result.escalated
        assert result.verified_on_device == result.total - 1

    def test_verify_all_detects_divergence_in_escalated_rows(self):
        """An escalated row whose LIVE state diverges must still be
        caught — escalation is not a verification bypass."""
        hists = generate_corpus("overflow", num_workflows=64, seed=SEED,
                                target_events=60)
        stores, keys = _stores_with(hists)
        errors = np.asarray(replay_events(
            jnp.asarray(encode_corpus(hists))).error)
        bad = int(np.nonzero(errors)[0][0])
        live = stores.execution.get_workflow(*keys[bad])
        live.execution_info.signal_count += 7  # corrupt the live state
        stores.execution.upsert_workflow(live, set_current=False)
        result = TPUReplayEngine(stores, chunk_workflows=32,
                                 pipeline_depth=2).verify_all(keys)
        assert keys[bad] in result.divergent
        assert not result.ok

    def test_rebuild_hydrates_from_widened_rung_state(self):
        from cadence_tpu.engine.rebuild import DeviceRebuilder

        hists = generate_corpus("overflow", num_workflows=96, seed=SEED,
                                target_events=60)
        flagged = np.asarray(replay_events(
            jnp.asarray(encode_corpus(hists))).error)
        n_flagged = int((flagged != 0).sum())
        assert n_flagged >= 1
        rb = DeviceRebuilder(chunk_jobs=32)
        states = rb.rebuild([(h, None) for h in hists])
        assert rb.stats.ladder == n_flagged
        assert rb.stats.oracle_fallback == 0
        assert rb.stats.device == len(hists)
        for ms, h in zip(states, hists):
            got = payload_row(ms)
            got[STICKY_ROW_INDEX] = 0
            assert (got == _oracle_row(h)).all()
