"""LRU cache tier: execution context cache + domain cache (inventory rows
5/50; execution/cache.go:48, common/cache/lru.go, domainCache.go).
"""
import pytest

from cadence_tpu.core.enums import CloseStatus, EventType
from cadence_tpu.engine.cache import DomainCache, ExecutionCache, LRUCache
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import EchoDecider
from tests.taskpoller import TaskPoller

DOMAIN = "cache-domain"
TL = "cache-tl"


class TestLRU:
    def test_bounded_eviction_lru_order(self):
        lru = LRUCache(max_size=2)
        lru.put("a", 1)
        lru.put("b", 2)
        assert lru.get("a") == 1  # refresh recency: b is now LRU
        lru.put("c", 3)
        assert lru.get("b") is None and lru.evictions == 1
        assert lru.get("a") == 1 and lru.get("c") == 3

    def test_delete_and_clear(self):
        lru = LRUCache(4)
        lru.put("x", 1)
        lru.delete("x")
        assert lru.get("x") is None
        lru.put("y", 2)
        lru.clear()
        assert len(lru) == 0


class TestExecutionCache:
    def test_foreign_writer_invalidates(self):
        """A write that bypasses the engine (replication passive apply,
        admin rebuild) must never be served stale: the store version
        revalidation detects it."""
        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "wf-c", "t", TL)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run = box.stores.execution.get_current_run_id(domain_id, "wf-c")
        engine = box.route("wf-c")
        # prime the cache through a real transaction
        box.frontend.signal_workflow_execution(DOMAIN, "wf-c", "s1")
        assert engine.execution_cache.load(box.stores, domain_id, "wf-c",
                                           run) is not None
        # a FOREIGN writer upserts the snapshot directly (passive path)
        import copy
        foreign = copy.deepcopy(box.stores.execution.get_workflow(
            domain_id, "wf-c", run))
        foreign.execution_info.signal_count = 99
        box.stores.execution.upsert_workflow(foreign)
        # the cache detects the version bump and refuses the stale entry
        assert engine.execution_cache.load(box.stores, domain_id, "wf-c",
                                           run) is None
        # and the next transaction sees the foreign write
        box.frontend.signal_workflow_execution(DOMAIN, "wf-c", "s2")
        ms = box.stores.execution.get_workflow(domain_id, "wf-c", run)
        assert ms.execution_info.signal_count == 100

    def test_hot_path_hits_and_workflows_stay_correct(self):
        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain(DOMAIN)
        for i in range(4):
            box.frontend.start_workflow_execution(DOMAIN, f"wf-h-{i}", "t", TL)
        TaskPoller(box, DOMAIN, TL,
                   {f"wf-h-{i}": EchoDecider(TL) for i in range(4)}).drain()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        for i in range(4):
            run = box.stores.execution.get_current_run_id(domain_id,
                                                          f"wf-h-{i}")
            ms = box.stores.execution.get_workflow(domain_id, f"wf-h-{i}", run)
            assert ms.execution_info.close_status == CloseStatus.Completed
        hits = sum(c.execution_cache.lru.hits
                   for ctrl in box.controllers.values()
                   for c in ctrl._engines.values())
        assert hits > 0  # the hot path actually used the cache
        assert box.tpu.verify_all().ok


class TestDomainCache:
    def test_update_visible_on_next_read(self):
        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain(DOMAIN, retention_days=1)
        box.frontend.start_workflow_execution(DOMAIN, "wf-d", "t", TL)
        engine = box.route("wf-d")
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        assert engine._domain_entry(domain_id).retention_days == 1
        box.frontend.update_domain(DOMAIN, retention_days=7)
        # mutation-counter revalidation: no TTL staleness window
        assert engine._domain_entry(domain_id).retention_days == 7

    def test_failover_version_flows_through_cache(self):
        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain(DOMAIN,
                                     clusters=("primary", "standby"))
        box.frontend.update_domain(DOMAIN, active_cluster="standby")
        box.frontend.update_domain(DOMAIN, active_cluster="primary")
        ver = box.frontend.describe_domain(DOMAIN).failover_version
        box.frontend.start_workflow_execution(DOMAIN, "wf-v", "t", TL)
        events = box.frontend.get_workflow_execution_history(DOMAIN, "wf-v")
        assert events[0].version == ver
