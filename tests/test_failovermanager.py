"""Managed failover workflow (inventory row 36;
service/worker/failovermanager/workflow.go): batched domain failover
with drain → flip → replicate → refresh → verify, plus rebalance.
"""
import pytest

from cadence_tpu.core.enums import CloseStatus
from cadence_tpu.engine.failovermanager import (
    STATUS_FAILED,
    STATUS_SKIPPED,
    STATUS_SUCCESS,
    FailoverManager,
)
from cadence_tpu.engine.multicluster import ReplicatedClusters
from cadence_tpu.models.deciders import SignalDecider
from tests.taskpoller import TaskPoller

TL = "fm-tl"


@pytest.fixture()
def clusters():
    return ReplicatedClusters(num_hosts=1, num_shards=4)


class TestManagedFailover:
    def test_batched_failover_with_inflight_workflows(self, clusters):
        for name in ("fm-a", "fm-b", "fm-c"):
            clusters.register_global_domain(name)
        # an in-flight workflow on fm-a: one signal received, one to go
        clusters.active.frontend.start_workflow_execution(
            "fm-a", "wf-live", "sig", TL)
        apoller = TaskPoller(clusters.active, "fm-a", TL,
                             {"wf-live": SignalDecider(expected_signals=2)})
        clusters.active.frontend.signal_workflow_execution("fm-a", "wf-live",
                                                           "s1")
        apoller.drain()

        report = FailoverManager(clusters).managed_failover(
            ["fm-a", "fm-b", "fm-c"], to_cluster="standby", batch_size=2)
        assert report.ok and report.succeeded == 3
        for box in (clusters.active, clusters.standby):
            for name in ("fm-a", "fm-b", "fm-c"):
                assert box.stores.domain.by_name(
                    name).active_cluster == "standby"

        # the in-flight workflow CONTINUES on the new active side
        domain_id = clusters.standby.frontend.describe_domain("fm-a").domain_id
        spoller = TaskPoller(clusters.standby, "fm-a", TL,
                             {"wf-live": SignalDecider(expected_signals=2)})
        clusters.standby.frontend.signal_workflow_execution("fm-a", "wf-live",
                                                            "s2")
        spoller.drain()
        run = clusters.standby.stores.execution.get_current_run_id(
            domain_id, "wf-live")
        ms = clusters.standby.stores.execution.get_workflow(
            domain_id, "wf-live", run)
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert clusters.standby.tpu.verify_all().ok

    def test_skips_local_and_already_active(self, clusters):
        clusters.register_global_domain("fm-g")
        clusters.active.frontend.register_domain("fm-local")
        fm = FailoverManager(clusters)
        first = fm.managed_failover(["fm-g", "fm-local"], "standby")
        statuses = {r.domain: r.status for r in first.results}
        assert statuses == {"fm-g": STATUS_SUCCESS,
                            "fm-local": STATUS_SKIPPED}
        again = fm.managed_failover(["fm-g"], "standby")
        assert again.results[0].status == STATUS_SKIPPED

    def test_rebalance_brings_domains_home(self, clusters):
        for name in ("fm-x", "fm-y"):
            clusters.register_global_domain(name)
        fm = FailoverManager(clusters)
        fm.managed_failover(["fm-x", "fm-y"], "standby")
        report = fm.rebalance(home_cluster="primary")
        assert report.ok and report.succeeded == 2
        for name in ("fm-x", "fm-y"):
            assert clusters.active.stores.domain.by_name(
                name).active_cluster == "primary"

    def test_unknown_domain_isolated(self, clusters):
        clusters.register_global_domain("fm-ok")
        report = FailoverManager(clusters).managed_failover(
            ["no-such-domain", "fm-ok"], "standby")
        statuses = {r.domain: r.status for r in report.results}
        assert statuses["no-such-domain"] == STATUS_FAILED
        assert statuses["fm-ok"] == STATUS_SUCCESS
        assert not report.ok
