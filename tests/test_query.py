"""Consistent query (query/registry.go + query/query.go analog).

VERDICT ask #5: query a workflow mid-flight; the answer arrives with the
next decision completion. Plus the direct path: an idle workflow answers
through a query-only task dispatched via matching.
"""
import pytest

from cadence_tpu.core.enums import DecisionType, EventType
from cadence_tpu.engine.history_engine import Decision, InvalidRequestError
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.engine.query import QueryState
from cadence_tpu.models.deciders import SignalDecider
from tests.taskpoller import TaskPoller

DOMAIN = "query-domain"
TL = "query-tl"


class QueryableSignalDecider(SignalDecider):
    """SignalDecider + a 'signal-count' query answered from history."""

    def query(self, query_type: str, history) -> bytes:
        if query_type == "signal-count":
            n = sum(1 for e in history
                    if e.event_type == EventType.WorkflowExecutionSignaled)
            return str(n).encode()
        return b"unknown-query"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


class TestConsistentQuery:
    def test_query_answered_at_decision_completion(self, box):
        """Query arriving mid-decision: buffered, then attached to the next
        decision task (here forced by a signal) and answered by the
        worker's query_results."""
        box.frontend.start_workflow_execution(DOMAIN, "q-1", "signal", TL)
        decider = QueryableSignalDecider(expected_signals=2)
        box.pump_once()
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        assert resp is not None  # decision 1 in flight

        # signal buffers behind the decision, guaranteeing a follow-up
        # decision at close; the query buffers too
        box.frontend.signal_workflow_execution(DOMAIN, "q-1", "s1")
        qid = box.frontend.query_workflow(DOMAIN, "q-1", "signal-count")
        state, result, _ = box.frontend.get_query_result(DOMAIN, "q-1", qid)
        assert state == QueryState.BUFFERED  # parked until decision close

        box.frontend.respond_decision_task_completed(resp.token, [])
        box.pump_once()
        resp2 = box.frontend.poll_for_decision_task(DOMAIN, TL)
        assert resp2 is not None and not resp2.query_only
        # the buffered query is attached to this decision task
        assert [q[0] for q in resp2.queries] == [qid]
        box.frontend.respond_decision_task_completed(
            resp2.token, decider.decide(resp2.history),
            query_results={q[0]: decider.query(q[1], resp2.history)
                           for q in resp2.queries})
        state, result, _ = box.frontend.get_query_result(DOMAIN, "q-1", qid)
        assert state == QueryState.COMPLETED
        assert result == b"1"

    def test_query_mid_decision_no_followup_still_answers(self, box):
        """Liveness: a query buffered while a decision is in flight must
        not hang when that decision completes without scheduling another —
        the frontend re-dispatches leftover buffered queries directly."""
        box.frontend.start_workflow_execution(DOMAIN, "q-6", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"q-6": QueryableSignalDecider(expected_signals=2)})
        box.pump_once()
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        qid = box.frontend.query_workflow(DOMAIN, "q-6", "signal-count")
        # decision completes with no decisions, no buffered events → no
        # follow-up decision; the query re-dispatches as a query-only task
        box.frontend.respond_decision_task_completed(resp.token, [])
        assert poller.poll_and_decide_once()
        state, result, _ = box.frontend.get_query_result(DOMAIN, "q-6", qid)
        assert state == QueryState.COMPLETED
        assert result == b"0"

    def test_idle_workflow_query_direct_path(self, box):
        """No decision pending: the query dispatches as a query-only task
        through matching and the worker answers without history mutation."""
        box.frontend.start_workflow_execution(DOMAIN, "q-2", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"q-2": QueryableSignalDecider(expected_signals=2)})
        poller.drain()  # first decision done; workflow idle awaiting signals
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "q-2")
        events_before = len(box.stores.history.read_events(domain_id, "q-2",
                                                           run_id))

        qid = box.frontend.query_workflow(DOMAIN, "q-2", "signal-count")
        # the poller services the query-only task
        assert poller.poll_and_decide_once()
        state, result, _ = box.frontend.get_query_result(DOMAIN, "q-2", qid)
        assert state == QueryState.COMPLETED
        assert result == b"0"
        # no history was written for the query
        events_after = len(box.stores.history.read_events(domain_id, "q-2",
                                                          run_id))
        assert events_after == events_before

    def test_query_via_drain_loop(self, box):
        """The standard drain loop answers queries as part of worker
        simulation (host/taskpoller.go parity)."""
        box.frontend.start_workflow_execution(DOMAIN, "q-3", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"q-3": QueryableSignalDecider(expected_signals=1)})
        poller.drain()
        box.frontend.signal_workflow_execution(DOMAIN, "q-3", "s1")
        qid = box.frontend.query_workflow(DOMAIN, "q-3", "signal-count")
        poller.drain()
        state, result, _ = box.frontend.get_query_result(DOMAIN, "q-3", qid)
        assert state == QueryState.COMPLETED
        assert result == b"1"

    def test_query_fails_on_workflow_close(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "q-4", "signal", TL)
        box.pump_once()
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        qid = box.frontend.query_workflow(DOMAIN, "q-4", "signal-count")
        box.frontend.respond_decision_task_completed(
            resp.token, [Decision(DecisionType.CompleteWorkflowExecution, {})])
        state, _, failure = box.frontend.get_query_result(DOMAIN, "q-4", qid)
        assert state == QueryState.FAILED
        assert "closed" in failure

    def test_query_completed_workflow_rejected(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "q-5", "t", TL)
        box.frontend.terminate_workflow_execution(DOMAIN, "q-5")
        with pytest.raises(InvalidRequestError):
            box.frontend.query_workflow(DOMAIN, "q-5", "signal-count")
