"""System workers: retention deletion + parent close policy + scanner
(VERDICT ask #8, missing #5).

Reference: service/worker/scanner (history scavenger, executions
scanner/fixer), service/worker/parentclosepolicy/processor.go, and the
DeleteHistoryEvent timer arm of the timer queue executor.
"""
import pytest

from cadence_tpu.core.enums import (
    CloseStatus,
    DecisionType,
    ParentClosePolicy,
    WorkflowState,
)
from cadence_tpu.engine.history_engine import Decision
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.engine.persistence import EntityNotExistsError
from cadence_tpu.models.deciders import CompleteDecider
from tests.taskpoller import TaskPoller

DOMAIN = "worker-domain"
TL = "worker-tl"
DAY = 86_400


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


def _run_to_completion(box, wf):
    box.frontend.start_workflow_execution(DOMAIN, wf, "t", TL)
    TaskPoller(box, DOMAIN, TL, {wf: CompleteDecider()}).drain()
    domain_id = box.frontend.describe_domain(DOMAIN).domain_id
    run_id = box.stores.execution.get_current_run_id(domain_id, wf)
    return domain_id, run_id


class TestRetention:
    def test_delete_timer_removes_closed_run(self, box):
        domain_id, run_id = _run_to_completion(box, "ret-1")
        assert box.stores.history.branch_count(domain_id, "ret-1", run_id) == 1

        box.advance_time(DAY + 60)  # default domain retention: 1 day
        box.pump_once()             # DeleteHistoryEvent timer fires

        assert box.stores.history.branch_count(domain_id, "ret-1", run_id) == 0
        with pytest.raises(EntityNotExistsError):
            box.stores.execution.get_workflow(domain_id, "ret-1", run_id)
        # visibility gone, workflow id startable again
        assert all(r.run_id != run_id
                   for r in box.stores.visibility.list_closed(DOMAIN))
        box.frontend.start_workflow_execution(DOMAIN, "ret-1", "t", TL)

    def test_retention_never_deletes_open_run(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "ret-2", "signal", TL)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "ret-2")
        engine = box.route("ret-2")
        assert not engine.delete_workflow_execution(domain_id, "ret-2", run_id)
        assert box.stores.history.branch_count(domain_id, "ret-2", run_id) == 1

    def test_tombstone_survives_recovery(self, tmp_path):
        """A deleted run must NOT be resurrected by WAL replay."""
        from cadence_tpu.engine.durability import (
            open_durable_stores,
            recover_stores,
        )

        path = str(tmp_path / "wal.log")
        box = Onebox(num_hosts=1, num_shards=4,
                     stores=open_durable_stores(path))
        box.frontend.register_domain(DOMAIN)
        domain_id, run_id = _run_to_completion(box, "ret-3")
        box.advance_time(DAY + 60)
        box.pump_once()
        assert box.stores.history.branch_count(domain_id, "ret-3", run_id) == 0

        stores, report = recover_stores(path)
        assert (domain_id, "ret-3", run_id) not in stores.history.list_runs()
        assert report.ok

    def test_scavenger_backstop_sweeps_lost_timer(self, box):
        """The scavenger deletes expired runs even when the deletion timer
        was lost (crash between close and timer fire)."""
        domain_id, run_id = _run_to_completion(box, "ret-4")
        box.advance_time(DAY + 60)
        # DON'T pump (simulates the lost timer): sweep directly
        deleted = box.scavenger.run_once()
        assert deleted == 1
        assert box.stores.history.branch_count(domain_id, "ret-4", run_id) == 0

    def test_scavenger_respects_retention_window(self, box):
        domain_id, run_id = _run_to_completion(box, "ret-5")
        box.advance_time(3600)  # one hour < 1 day retention
        assert box.scavenger.run_once() == 0
        assert box.stores.history.branch_count(domain_id, "ret-5", run_id) == 1


def _start_parent_with_child(box, wf, policy: ParentClosePolicy):
    box.frontend.start_workflow_execution(DOMAIN, wf, "parent", TL)
    box.pump_once()
    resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
    box.frontend.respond_decision_task_completed(
        resp.token, [Decision(DecisionType.StartChildWorkflowExecution,
                              dict(workflow_id=f"{wf}-child",
                                   workflow_type="child",
                                   task_list=TL,
                                   parent_close_policy=int(policy)))])
    box.pump_once()  # start the child, deliver ChildWorkflowExecutionStarted
    domain_id = box.frontend.describe_domain(DOMAIN).domain_id
    child_run = box.stores.execution.get_current_run_id(domain_id, f"{wf}-child")
    return domain_id, child_run


class TestParentClosePolicy:
    def test_terminate_policy_stops_child(self, box):
        domain_id, child_run = _start_parent_with_child(
            box, "pcp-t", ParentClosePolicy.Terminate)
        box.frontend.terminate_workflow_execution(DOMAIN, "pcp-t")
        box.pump_once()  # close fan-out
        child = box.stores.execution.get_workflow(domain_id, "pcp-t-child",
                                                  child_run)
        assert child.execution_info.close_status == CloseStatus.Terminated

    def test_cancel_policy_requests_cancel(self, box):
        domain_id, child_run = _start_parent_with_child(
            box, "pcp-c", ParentClosePolicy.RequestCancel)
        box.frontend.terminate_workflow_execution(DOMAIN, "pcp-c")
        box.pump_once()
        child = box.stores.execution.get_workflow(domain_id, "pcp-c-child",
                                                  child_run)
        assert child.execution_info.cancel_requested
        assert child.execution_info.state == WorkflowState.Running

    def test_abandon_policy_leaves_child_running(self, box):
        domain_id, child_run = _start_parent_with_child(
            box, "pcp-a", ParentClosePolicy.Abandon)
        box.frontend.terminate_workflow_execution(DOMAIN, "pcp-a")
        box.pump_once()
        child = box.stores.execution.get_workflow(domain_id, "pcp-a-child",
                                                  child_run)
        assert child.execution_info.state == WorkflowState.Running
        assert not child.execution_info.cancel_requested


class TestScanner:
    def test_healthy_cluster_scans_clean(self, box):
        _run_to_completion(box, "scan-1")
        report = box.scanner.run_once()
        assert report.ok
        assert report.executions >= 1

    def test_orphan_pointer_detected_and_fixed(self, box):
        from cadence_tpu.engine.persistence import CurrentExecution
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        box.stores.execution.restore_current(
            domain_id, "ghost", CurrentExecution(run_id="no-such-run",
                                                 state=WorkflowState.Running,
                                                 close_status=0))
        report = box.scanner.run_once(fix=True)
        assert (domain_id, "ghost", "no-such-run") in report.orphan_pointers
        assert report.fixed == 1
        # fixed: pointer dropped, id startable
        report2 = box.scanner.run_once()
        assert report2.ok


class TestNewInvariantsAndWatchdog:
    def test_open_without_pointer_reported(self):
        """Zombie/orphan open runs surface in the scan (invariant/
        openCurrentExecution.go) without failing it — they are expected
        on standbys — while invalid pending items DO fail it."""
        import copy

        from cadence_tpu.engine.onebox import Onebox
        from tests.taskpoller import TaskPoller
        from cadence_tpu.models.deciders import EchoDecider

        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain("wd-dom")
        box.frontend.start_workflow_execution("wd-dom", "wf-z", "t", "wd-tl")
        domain_id = box.frontend.describe_domain("wd-dom").domain_id
        run = box.stores.execution.get_current_run_id(domain_id, "wf-z")
        # forge a zombie: a second OPEN run without the current pointer
        zombie = copy.deepcopy(box.stores.execution.get_workflow(
            domain_id, "wf-z", run))
        zombie.execution_info.run_id = "zombie-run"
        box.stores.history.append_batch(
            domain_id, "wf-z", "zombie-run",
            box.stores.history.read_events(domain_id, "wf-z", run))
        box.stores.execution.upsert_workflow(zombie, set_current=False)
        report = box.scanner.run_once()
        assert (domain_id, "wf-z", "zombie-run") in report.open_without_pointer
        assert report.ok  # zombies don't fail the scan; corruption does

    def test_invalid_pending_fails_scan(self):
        import copy

        from cadence_tpu.engine.onebox import Onebox

        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain("wd-dom")
        box.frontend.start_workflow_execution("wd-dom", "wf-bad", "t", "wd-tl")
        domain_id = box.frontend.describe_domain("wd-dom").domain_id
        run = box.stores.execution.get_current_run_id(domain_id, "wf-bad")
        broken = copy.deepcopy(box.stores.execution.get_workflow(
            domain_id, "wf-bad", run))
        # a pending activity whose schedule id is beyond the history tail
        import dataclasses
        from cadence_tpu.oracle.mutable_state import ActivityInfo
        fields = {f.name: 0 for f in dataclasses.fields(ActivityInfo)}
        fields.update(schedule_id=999, activity_id="ghost", domain_id="",
                      task_list="", started_id=-23)
        for f in dataclasses.fields(ActivityInfo):
            if f.type == "str":
                fields.setdefault(f.name, "")
                if not isinstance(fields[f.name], str):
                    fields[f.name] = ""
        broken.pending_activity_info_ids[999] = ActivityInfo(**fields)
        box.stores.execution.upsert_workflow(broken)
        report = box.scanner.run_once()
        assert (domain_id, "wf-bad", run) in report.invalid_pending
        assert not report.ok

    def test_watchdog_rolls_up_health(self):
        from cadence_tpu.engine.onebox import Onebox
        from cadence_tpu.engine.workers import Watchdog
        from cadence_tpu.models.deciders import EchoDecider
        from tests.taskpoller import TaskPoller

        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain("wd-dom")
        box.frontend.start_workflow_execution("wd-dom", "wf-w", "echo", "wd-tl")
        TaskPoller(box, "wd-dom", "wd-tl", {"wf-w": EchoDecider("wd-tl")}).drain()
        report = Watchdog(box).run_once()
        assert report["ok"]
        assert report["executions"] >= 1
        assert report["verified_on_device"] >= 1
