"""Persisted mutable-state snapshots (ISSUE 11).

Covers: checksum-gated snapshot writes (a diverged resident payload is
never persisted); warm vs cold restart byte-parity across the workload
suites on both WAL backends; stale/torn/foreign snapshots detected and
ignored with full-replay fallback; derived invalidation on tail
overwrite / NDC branch switch / run deletion; the batch-range history
read's parity with the full read; the serving chain-break fallback
hydrating from a snapshot WITHOUT reading the full history (the
raising-prefix-read seam); the wal fsck stale-/orphaned-snapshot
findings; and the crashsim cut-point sweep over snapshot records.
"""
import json

import numpy as np
import pytest

from cadence_tpu.core.checksum import (
    DEFAULT_LAYOUT,
    STICKY_ROW_INDEX,
    Checksum,
    payload_row,
)
from cadence_tpu.engine.cache import batch_crc, content_address
from cadence_tpu.engine.persistence import Stores
from cadence_tpu.engine.tpu_engine import TPUReplayEngine
from cadence_tpu.gen.corpus import generate_corpus
from cadence_tpu.oracle.state_builder import StateBuilder
from cadence_tpu.utils import metrics as m

SUITES = ("basic", "timer_retry", "concurrent_child", "ndc")


def _seed_stores(stores, suite="basic", n=3, target_events=24, seed=7):
    """Append generated histories + oracle-rebuilt mutable states (the
    store shape verify_all expects); returns the run keys."""
    hists = generate_corpus(suite, num_workflows=n, seed=seed,
                            target_events=target_events)
    keys = []
    for h in hists:
        b0 = h[0]
        key = (b0.domain_id, b0.workflow_id, b0.run_id)
        for b in h:
            stores.history.append_batch(*key, list(b.events))
        ms = StateBuilder().replay_history(
            stores.history.as_history_batches(*key))
        info = ms.execution_info
        info.domain_id, info.workflow_id, info.run_id = key
        stores.execution.upsert_workflow(ms)
        keys.append(key)
    return keys


def _oracle_row(batches, layout=DEFAULT_LAYOUT):
    row = payload_row(StateBuilder().replay_history(batches), layout)
    row[STICKY_ROW_INDEX] = 0
    return row


# ---------------------------------------------------------------------------
# store mechanics: batch-range reads + derived invalidation
# ---------------------------------------------------------------------------


class TestBatchRangeRead:
    def test_range_read_parity_with_full_read(self):
        stores = Stores()
        (key,) = _seed_stores(stores, n=1, target_events=30)
        full = stores.history.read_batches(*key)
        total = stores.history.batch_count(*key)
        assert total == len(full) > 3
        for c in (0, 1, total // 2, total - 1, total):
            part = stores.history.read_batches_range(*key, from_batch=c)
            assert part == full[c:]
        hb = stores.history.as_history_batches_range(*key,
                                                     from_batch=total - 1)
        assert len(hb) == 1 and hb[0].events == full[-1]
        assert stores.history.batch_count("x", "y", "z") == 0

    def test_snapshot_survives_overwrite_beyond_its_point(self):
        """A tail overwrite strictly past the snapshot point keeps the
        snapshot (still a valid prefix); one at/before it drops it."""
        stores = Stores()
        (key,) = _seed_stores(stores, n=1, target_events=30)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        full = stores.history.read_batches(*key)
        # snapshot everything (force bypasses the policy gates)
        assert tpu.snapshot_sweep(force=True).written == 1
        snap = stores.snapshot.get(key)
        assert snap is not None and snap.batch_count == len(full)
        # rewrite ONLY the final batch: overwrite lands at index n-1,
        # which the tip snapshot covers -> dropped
        stores.history.append_batch(*key, list(full[-1]))
        assert stores.snapshot.get(key) is None

    def test_mid_batch_truncating_overwrite_drops_tip_snapshot(self):
        """An overwrite landing MID-batch truncates the last kept batch
        — its bytes change, so a snapshot covering it must drop (the
        boundary is one batch earlier than a clean-boundary rewrite)."""
        stores = Stores()
        (key,) = _seed_stores(stores, n=1, target_events=30)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == 1
        full = stores.history.read_batches(*key)
        last = full[-1]
        if len(last) < 2:
            pytest.skip("corpus tail batch too short to split")
        # rewrite from the SECOND event of the final batch: the kept
        # half of that batch is itself rewritten bytes
        stores.history.append_batch(*key, list(last[1:]))
        assert stores.snapshot.get(key) is None

    def test_snapshot_dropped_on_branch_switch_and_delete(self):
        stores = Stores()
        keys = _seed_stores(stores, n=2, target_events=24)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == 2
        # NDC branch switch
        k0 = keys[0]
        stores.history.fork_branch(*k0, source_branch=0, fork_event_id=2)
        stores.history.set_current_branch(*k0, branch=1)
        assert stores.snapshot.get(k0) is None
        # run deletion
        k1 = keys[1]
        stores.history.delete_run(*k1)
        assert stores.snapshot.get(k1) is None

    def test_prefix_snapshot_survives_pure_append(self):
        """Appending new batches never invalidates (the whole point:
        the snapshot remains a valid prefix the suffix replays from)."""
        stores = Stores()
        (key,) = _seed_stores(stores, n=1, target_events=30)
        full = stores.history.read_batches(*key)
        # rebuild the store holding only the prefix, snapshot it there
        pre = Stores()
        for b in full[:-1]:
            pre.history.append_batch(*key, list(b))
        ms = StateBuilder().replay_history(
            pre.history.as_history_batches(*key))
        info = ms.execution_info
        info.domain_id, info.workflow_id, info.run_id = key
        pre.execution.upsert_workflow(ms)
        tpu = TPUReplayEngine(pre)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == 1
        pre.history.append_batch(*key, list(full[-1]))
        snap = pre.snapshot.get(key)
        assert snap is not None and snap.batch_count == len(full) - 1


# ---------------------------------------------------------------------------
# the checksum gate: a diverged payload is never persisted
# ---------------------------------------------------------------------------


class TestChecksumGate:
    def test_diverged_resident_payload_refused(self):
        stores = Stores()
        (key,) = _seed_stores(stores, n=1, target_events=24)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        # corrupt the LIVE oracle state after the resident pin: the
        # write gate compares resident payload vs oracle row and must
        # refuse (a snapshot of either side would persist a lie)
        ms = stores.execution.get_workflow(*key)
        ms.execution_info.signal_count += 1
        reg = tpu.metrics
        pre = reg.counter(m.SCOPE_TPU_SNAPSHOT, m.M_SNAP_CHECKSUM_SKIPS)
        sweep = tpu.snapshot_sweep(force=True)
        assert sweep.written == 0
        assert sweep.skipped_checksum == 1
        assert reg.counter(m.SCOPE_TPU_SNAPSHOT,
                           m.M_SNAP_CHECKSUM_SKIPS) == pre + 1
        assert len(stores.snapshot) == 0

    def test_policy_gates_due_and_min_events(self, monkeypatch):
        from cadence_tpu.engine.snapshot import Snapshotter
        stores = Stores()
        (key,) = _seed_stores(stores, n=1, target_events=24)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        snapper = Snapshotter(stores, tpu.resident, tpu.pack_cache,
                              tpu.layout, registry=tpu.metrics,
                              min_events=10_000, every_events=4)
        # min-events floor: no snapshot yet -> due, but the history is
        # far too small for the floor
        assert snapper.due(key)
        assert not snapper.snapshot_key(key)
        snapper.min_events = 1
        assert snapper.snapshot_key(key)
        # freshly written: not due until every_events accumulate
        assert not snapper.due(key)
        snapper.note_append(key, 3)
        assert not snapper.due(key)
        snapper.note_append(key, 1)
        assert snapper.due(key)


# ---------------------------------------------------------------------------
# warm vs cold restart byte-parity, every suite, both WAL backends
# ---------------------------------------------------------------------------


class TestWarmRestartParity:
    def test_warm_equals_cold_across_suites(self, wal, monkeypatch):
        """The acceptance core: recover the same WAL twice — snapshots
        disabled (cold: full-history replay storm) and enabled (warm:
        hydrate + suffix) — and require byte-identical mutable states
        for every run of every workload suite, zero divergence both
        ways, and the warm pass actually hydrating."""
        from cadence_tpu.engine import snapshot as snapshot_mod
        from cadence_tpu.engine.durability import (
            open_durable_stores,
            recover_stores,
        )

        stores = open_durable_stores(wal)
        keys = []
        for i, suite in enumerate(SUITES):
            keys += _seed_stores(stores, suite=suite, n=2,
                                 target_events=20, seed=20 + i)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        sweep = tpu.snapshot_sweep(force=True)
        assert sweep.written == len(keys)
        stores.wal.close()

        monkeypatch.setenv(snapshot_mod.ENABLE_ENV, "0")
        cold, rep_cold = recover_stores(wal, verify_on_device=True,
                                        rebuild_on_device=True)
        assert rep_cold.ok and rep_cold.snapshot_hydrated == 0
        cold.wal.close()

        monkeypatch.setenv(snapshot_mod.ENABLE_ENV, "1")
        warm, rep_warm = recover_stores(wal, verify_on_device=True,
                                        rebuild_on_device=True)
        assert rep_warm.ok
        assert rep_warm.snapshot_hydrated == len(keys)
        for key in keys:
            assert Checksum.of(cold.execution.get_workflow(*key)).value \
                == Checksum.of(warm.execution.get_workflow(*key)).value
        warm.wal.close()

    def test_warm_restart_after_post_snapshot_appends(self, tmp_path):
        """Snapshots taken mid-history: appends land after the sweep, so
        recovery must hydrate + replay ONLY the suffix and still land on
        the oracle's bytes."""
        from cadence_tpu.engine.durability import (
            open_durable_stores,
            recover_stores,
        )

        wal = str(tmp_path / "midsnap.jsonl")
        stores = open_durable_stores(wal)
        hists = generate_corpus("basic", num_workflows=3, seed=31,
                                target_events=28)
        keys = []
        for h in hists:
            b0 = h[0]
            key = (b0.domain_id, b0.workflow_id, b0.run_id)
            for b in h[:-2]:
                stores.history.append_batch(*key, list(b.events))
            ms = StateBuilder().replay_history(
                stores.history.as_history_batches(*key))
            info = ms.execution_info
            info.domain_id, info.workflow_id, info.run_id = key
            stores.execution.upsert_workflow(ms)
            keys.append(key)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == len(keys)
        # two more batches commit AFTER the snapshot
        for h, key in zip(hists, keys):
            for b in h[-2:]:
                stores.history.append_batch(*key, list(b.events))
            ms = StateBuilder().replay_history(
                stores.history.as_history_batches(*key))
            info = ms.execution_info
            info.domain_id, info.workflow_id, info.run_id = key
            stores.execution.upsert_workflow(ms)
        stores.wal.close()

        warm, rep = recover_stores(wal, verify_on_device=True,
                                   rebuild_on_device=True)
        assert rep.ok and rep.snapshot_hydrated == len(keys)
        for h, key in zip(hists, keys):
            expected = StateBuilder().replay_history(
                warm.history.as_history_batches(*key))
            assert Checksum.of(warm.execution.get_workflow(*key)).value \
                == Checksum.of(expected).value
        warm.wal.close()


# ---------------------------------------------------------------------------
# stale / torn snapshots: detected, counted, ignored — never served
# ---------------------------------------------------------------------------


class TestTornAndStaleRejection:
    def _engine_with_snapshot(self):
        stores = Stores()
        (key,) = _seed_stores(stores, n=1, target_events=24)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == 1
        tpu.resident.clear()
        tpu.pack_cache.clear()
        return stores, tpu, key

    def test_torn_blob_falls_back_to_full_replay(self):
        stores, tpu, key = self._engine_with_snapshot()
        rec = stores.snapshot.get(key)
        rec.state_blob = rec.state_blob[:-7] + b"\x7f" * 7  # torn bytes
        reg = tpu.metrics
        result = tpu.verify_all()
        assert result.ok and not result.snapshot
        assert reg.counter(m.SCOPE_TPU_SNAPSHOT, m.M_SNAP_IGNORED_TORN) >= 1
        assert reg.counter(m.SCOPE_TPU_SNAPSHOT, m.M_SNAP_HYDRATES) == 0

    def test_stale_address_falls_back_to_full_replay(self):
        stores, tpu, key = self._engine_with_snapshot()
        rec = stores.snapshot.get(key)
        rec.last_batch_crc ^= 0xDEAD  # bytes under the address changed
        reg = tpu.metrics
        result = tpu.verify_all()
        assert result.ok and not result.snapshot
        assert reg.counter(m.SCOPE_TPU_SNAPSHOT,
                           m.M_SNAP_IGNORED_STALE) >= 1

    def test_foreign_layout_falls_back_to_full_replay(self):
        stores, tpu, key = self._engine_with_snapshot()
        rec = stores.snapshot.get(key)
        rec.layout = tuple(v * 2 for v in rec.layout)
        result = tpu.verify_all()
        assert result.ok and not result.snapshot
        assert tpu.metrics.counter(m.SCOPE_TPU_SNAPSHOT,
                                   m.M_SNAP_IGNORED_STALE) >= 1

    def test_kill_switch_disables_hydration(self, monkeypatch):
        from cadence_tpu.engine import snapshot as snapshot_mod
        stores, tpu, key = self._engine_with_snapshot()
        monkeypatch.setenv(snapshot_mod.ENABLE_ENV, "0")
        result = tpu.verify_all()
        assert result.ok and not result.snapshot
        assert tpu.metrics.counter(m.SCOPE_TPU_SNAPSHOT,
                                   m.M_SNAP_HYDRATES) == 0


# ---------------------------------------------------------------------------
# wal fsck: the two new typed findings
# ---------------------------------------------------------------------------


class TestFsckFindings:
    def _doctored_wal(self, wal, doctor):
        from cadence_tpu.engine.durability import (
            open_durable_stores,
            read_log,
        )
        stores = open_durable_stores(wal)
        (key,) = _seed_stores(stores, n=1, target_events=24)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == 1
        stores.wal.close()
        # doctor the snap record in place (both backends)
        from cadence_tpu.engine.durability import (
            SqliteLog,
            is_sqlite_path,
        )
        records = read_log(wal)
        for rec in records:
            if rec.get("t") == "snap":
                doctor(rec)
        if is_sqlite_path(wal):
            SqliteLog.rewrite(wal, records)
        else:
            with open(wal, "w") as f:
                for rec in records:
                    f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        return key

    def test_stale_snapshot_finding(self, wal):
        from cadence_tpu.engine.walcheck import fsck
        from cadence_tpu.utils.metrics import DEFAULT_REGISTRY

        self._doctored_wal(wal, lambda rec: rec.update(n=rec["n"] + 5))
        report = fsck(wal)
        assert [f.code for f in report.findings] == ["stale-snapshot"]
        assert DEFAULT_REGISTRY.counter(
            "walcheck", "finding-stale-snapshot") == 1

    def test_orphaned_snapshot_finding(self, wal):
        from cadence_tpu.engine.walcheck import fsck
        from cadence_tpu.utils.metrics import DEFAULT_REGISTRY

        self._doctored_wal(wal, lambda rec: rec.update(w="no-such-wf"))
        report = fsck(wal)
        assert [f.code for f in report.findings] == ["orphaned-snapshot"]
        assert DEFAULT_REGISTRY.counter(
            "walcheck", "finding-orphaned-snapshot") == 1

    def test_clean_wal_has_no_snapshot_findings(self, wal):
        from cadence_tpu.engine.durability import open_durable_stores
        from cadence_tpu.engine.walcheck import fsck

        stores = open_durable_stores(wal)
        _seed_stores(stores, n=2, target_events=24)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == 2
        stores.wal.close()
        assert fsck(wal).ok


# ---------------------------------------------------------------------------
# crashsim: the cut-point matrix sweeps snapshot records too
# ---------------------------------------------------------------------------


class TestCrashsimOverSnapshots:
    def test_cut_matrix_with_snapshot_records(self, wal):
        """Kill-anywhere over a WAL that interleaves history, snapshot,
        and post-snapshot history records: every prefix (and torn tail,
        on JSONL) must recover to a legal state with zero fsck findings
        — a half-written snapshot can cost a warm start, never
        correctness."""
        from cadence_tpu.engine.crashsim import CrashSim, seed_workload
        from cadence_tpu.engine.durability import recover_stores
        from cadence_tpu.engine.walcheck import read_raw_lines

        seed_workload(wal, num_workflows=2)
        stores, _ = recover_stores(wal, verify_on_device=False,
                                   rebuild_on_device=True)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written >= 1
        # one more committed batch AFTER the snapshots, so cuts land on
        # snapshot-then-history interleavings too
        key = tpu.snapshotter().stores.snapshot.keys()[0]
        batches = stores.history.read_batches(*key)
        stores.history.append_batch(*key, list(batches[-1]))
        stores.wal.close()

        raw = read_raw_lines(wal)
        assert any('"snap"' in l or "'snap'" in l or '"t": "snap"' in l
                   or '"t":"snap"' in l for l in raw), \
            "workload WAL carries no snapshot records to cut through"
        report = CrashSim(wal).run(torn=True, stride=5)
        assert report.ok, report.summary()


# ---------------------------------------------------------------------------
# serving chain-break fallback: snapshot hydrate + batch-range read ONLY
# ---------------------------------------------------------------------------


class TestServingChainBreakRanged:
    def test_chain_break_never_reads_full_history(self, monkeypatch):
        """The acceptance seam: after a restart (resident + pack caches
        empty, snapshot persisted), a committed transaction whose chain
        is broken must serve through snapshot hydrate + batch-range read
        — with the FULL-history read path booby-trapped to raise."""
        stores = Stores()
        hists = generate_corpus("basic", num_workflows=1, seed=13,
                                target_events=28)
        h = hists[0]
        b0 = h[0]
        key = (b0.domain_id, b0.workflow_id, b0.run_id)
        for b in h[:-1]:
            stores.history.append_batch(*key, list(b.events))
        ms = StateBuilder().replay_history(
            stores.history.as_history_batches(*key))
        info = ms.execution_info
        info.domain_id, info.workflow_id, info.run_id = key
        stores.execution.upsert_workflow(ms)

        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == 1
        # restart: HBM and host caches are gone; the snapshot is not
        tpu.resident.clear()
        tpu.pack_cache.clear()

        # commit one more batch + the oracle's post-state
        stores.history.append_batch(*key, list(h[-1].events))
        full = stores.history.as_history_batches(*key)
        ms2 = StateBuilder().replay_history(full)
        info2 = ms2.execution_info
        info2.domain_id, info2.workflow_id, info2.run_id = key
        stores.execution.upsert_workflow(ms2)
        expected = _oracle_row(full)
        tail_crc = batch_crc(full[-1])

        # booby-trap every prefix-reading seam
        def boom(*a, **k):
            raise AssertionError("full-history read on the chain-break "
                                 "fallback path")
        monkeypatch.setattr(stores.history, "read_batches", boom)

        sched = tpu.serving_scheduler()
        try:
            ticket = sched.submit(
                key, expected,
                int(ms2.version_histories.current_index), tail_crc)
            res = ticket.result(timeout=120.0)
        finally:
            sched.stop()
        assert res.ok and res.parity_ok, res
        assert res.path == "suffix"
        assert tpu.metrics.counter(m.SCOPE_TPU_SNAPSHOT,
                                   m.M_SNAP_HYDRATES) == 1

    def test_exact_chain_break_served_from_snapshot(self, monkeypatch):
        """Tip snapshot + chain break: zero device work, zero prefix
        reads — the persisted payload answers the parity check."""
        stores = Stores()
        (key,) = _seed_stores(stores, n=1, target_events=24)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == 1
        tpu.resident.clear()
        tpu.pack_cache.clear()

        full = stores.history.as_history_batches(*key)
        ms = stores.execution.get_workflow(*key)
        expected = payload_row(ms, tpu.layout)
        expected[STICKY_ROW_INDEX] = 0

        def boom(*a, **k):
            raise AssertionError("full-history read on the exact path")
        monkeypatch.setattr(stores.history, "read_batches", boom)

        sched = tpu.serving_scheduler()
        try:
            ticket = sched.submit(
                key, expected, int(ms.version_histories.current_index),
                batch_crc(full[-1]))
            res = ticket.result(timeout=120.0)
        finally:
            sched.stop()
        assert res.ok and res.path == "exact", res


# ---------------------------------------------------------------------------
# rebuild: reset-prefix path stops re-encoding the prefix (satellite 1)
# ---------------------------------------------------------------------------


class TestRebuildSuffixOnly:
    def test_snapshotted_rebuild_never_packs_the_prefix(self):
        """A standalone DeviceRebuilder (the reset/recovery shape) with
        a snapshot wired must hydrate + suffix-encode through its OWN
        pack cache: zero full-pack misses — the prefix is never
        re-encoded on the host."""
        from cadence_tpu.engine.rebuild import DeviceRebuilder

        stores = Stores()
        hists = generate_corpus("basic", num_workflows=2, seed=17,
                                target_events=26)
        keys = []
        for h in hists:
            b0 = h[0]
            key = (b0.domain_id, b0.workflow_id, b0.run_id)
            for b in h[:-1]:
                stores.history.append_batch(*key, list(b.events))
            ms = StateBuilder().replay_history(
                stores.history.as_history_batches(*key))
            info = ms.execution_info
            info.domain_id, info.workflow_id, info.run_id = key
            stores.execution.upsert_workflow(ms)
            keys.append(key)
        tpu = TPUReplayEngine(stores)
        assert tpu.verify_all().ok
        assert tpu.snapshot_sweep(force=True).written == 2
        for h, key in zip(hists, keys):
            stores.history.append_batch(*key, list(h[-1].events))

        rebuilder = DeviceRebuilder(tpu.layout)
        assert rebuilder.pack_cache is not None  # owned by default now
        rebuilder.snapshots = stores.snapshot
        reg = rebuilder.metrics
        pre_miss = reg.counter(m.SCOPE_PACK_CACHE, m.M_CACHE_MISSES)
        jobs = [(stores.history.as_history_batches(*key), None)
                for key in keys]
        states = rebuilder.rebuild(jobs)
        assert rebuilder.stats.snapshot_seeded == 2
        assert rebuilder.stats.resident == 2
        assert reg.counter(m.SCOPE_PACK_CACHE, m.M_CACHE_MISSES) \
            == pre_miss, "a snapshotted rebuild paid a full pack"
        for key, ms in zip(keys, states):
            expected = StateBuilder().replay_history(
                stores.history.as_history_batches(*key))
            assert Checksum.of(ms).value == Checksum.of(expected).value


# ---------------------------------------------------------------------------
# admin surface
# ---------------------------------------------------------------------------


class TestAdminSnapshot:
    def test_admin_snapshot_cli_sweep_and_rollup(self, tmp_path, capsys):
        from cadence_tpu.cli import main as cli_main

        wal = str(tmp_path / "snapcli.jsonl")

        def run(*argv):
            rc = cli_main(list(argv))
            return rc, json.loads(capsys.readouterr().out)

        rc, _ = run("--wal", wal, "domain", "register", "--name", "sd")
        assert rc == 0
        rc, _ = run("--wal", wal, "workflow", "start", "--domain", "sd",
                    "--workflow-id", "w1", "--type", "t",
                    "--task-list", "tl")
        assert rc == 0
        rc, out = run("--wal", wal, "admin", "snapshot", "--sweep")
        assert rc == 0
        assert out["sweep"]["written"] >= 1
        assert out["entries"] >= 1 and out["bytes"] > 0
        assert out["writes"] >= 1
        assert "staleness_batches" in out
        # rollup-only invocation over the recovered WAL sees the records
        rc, out = run("--wal", wal, "admin", "snapshot")
        assert rc == 0 and out["entries"] >= 1
