"""wire32 int32 transfer format: exact round-trip + replay equivalence.

H2D bytes are the scarce resource on tunneled TPU hosts; the wire format
ships 20 int32 lanes instead of 18 int64 with the two 64-bit values
(timestamp nanos, start-event expiration nanos) split lo/hi and
reconstructed exactly on device.
"""
import numpy as np
import pytest

from cadence_tpu.core.checksum import DEFAULT_LAYOUT, crc32_of_rows
from cadence_tpu.gen.corpus import SUITES, generate_corpus
from cadence_tpu.ops.encode import NUM_LANES, NUM_LANES32, encode_corpus, to_wire32


def _corpus(suite, n=16, seed=9):
    return encode_corpus(generate_corpus(suite, num_workflows=n, seed=seed,
                                         target_events=80))


class TestWire32:
    def test_round_trip_exact(self):
        import jax.numpy as jnp

        from cadence_tpu.ops.replay import widen_wire32

        ev = _corpus("timer_retry")
        w32 = to_wire32(ev)
        assert w32.dtype == np.int32 and w32.shape[-1] == NUM_LANES32
        back = np.asarray(widen_wire32(jnp.asarray(w32)))
        assert back.shape == ev.shape and (back == ev).all()

    @pytest.mark.parametrize("suite", SUITES)
    def test_replay32_matches_replay64(self, suite):
        import jax.numpy as jnp

        from cadence_tpu.ops.replay import replay_to_crc32, replay_to_payload

        ev = _corpus(suite)
        rows, errors = replay_to_payload(jnp.asarray(ev), DEFAULT_LAYOUT)
        want = crc32_of_rows(np.asarray(rows))
        crc, errors32 = replay_to_crc32(jnp.asarray(to_wire32(ev)),
                                        DEFAULT_LAYOUT)
        assert (np.asarray(crc) == want).all()
        assert (np.asarray(errors32) == np.asarray(errors)).all()

    def test_sharded_crc_matches(self):
        import jax

        from cadence_tpu.parallel.mesh import make_mesh, replay_sharded_crc

        ev = _corpus("concurrent_child", n=32)
        mesh = make_mesh()
        crc, errors, stats = replay_sharded_crc(to_wire32(ev), mesh,
                                                DEFAULT_LAYOUT)
        from cadence_tpu.ops.replay import replay_to_payload
        import jax.numpy as jnp
        rows, _ = replay_to_payload(jnp.asarray(ev), DEFAULT_LAYOUT)
        assert (np.asarray(crc) == crc32_of_rows(np.asarray(rows))).all()
        assert int(stats[0]) == 0

    def test_overflow_refuses(self):
        ev = _corpus("basic", n=2)
        ev[0, 0, 4] = 1 << 40  # task_id lane beyond int32
        with pytest.raises(OverflowError):
            to_wire32(ev)

    def test_fused_generator_crc_matches_rows(self):
        from cadence_tpu.ops.genkernel import (
            generate_and_replay,
            generate_and_replay_crc,
        )

        rows, errors = generate_and_replay(11, 0, 64, 120, DEFAULT_LAYOUT)
        crc, errors2 = generate_and_replay_crc(11, 0, 64, 120, DEFAULT_LAYOUT)
        assert (np.asarray(crc) == crc32_of_rows(np.asarray(rows))).all()
        assert (np.asarray(errors2) == np.asarray(errors)).all()
