"""Size/count limits + pagination (VERDICT r4 missing #3/#4).

Reference: host/size_limit_test.go (history growth TERMINATES the run;
oversized blobs are refused), workflowHandler.go:3745-3811 (paginated
history with nextPageToken), the ES search_after tokens for List/Scan.
"""
import pytest

from cadence_tpu.core.enums import CloseStatus, DecisionType, EventType
from cadence_tpu.engine.history_engine import Decision
from cadence_tpu.engine.limits import TERMINATE_REASON, LimitExceededError
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import EchoDecider
from cadence_tpu.utils.dynamicconfig import (
    KEY_BLOB_SIZE_LIMIT_ERROR,
    KEY_BLOB_SIZE_LIMIT_WARN,
    KEY_HISTORY_COUNT_LIMIT_ERROR,
    KEY_HISTORY_COUNT_LIMIT_WARN,
)
from tests.taskpoller import TaskPoller

DOMAIN = "lim-domain"
TL = "lim-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


class TestBlobSizeLimits:
    def test_oversized_start_payload_refused(self, box):
        box.config.set(KEY_BLOB_SIZE_LIMIT_ERROR, 1024)
        with pytest.raises(LimitExceededError):
            box.frontend.start_workflow_execution(
                DOMAIN, "wf-blob", "t", TL, input_payload=b"x" * 2048)
        # under the limit: accepted
        box.frontend.start_workflow_execution(
            DOMAIN, "wf-blob", "t", TL, input_payload=b"x" * 512)

    def test_warn_threshold_counts_not_refuses(self, box):
        box.config.set(KEY_BLOB_SIZE_LIMIT_WARN, 64)
        box.config.set(KEY_BLOB_SIZE_LIMIT_ERROR, 10_000)
        box.frontend.start_workflow_execution(
            DOMAIN, "wf-warn", "t", TL, input_payload=b"x" * 128)
        assert box.frontend.metrics.counter("limits", "blob-size-warnings") >= 1

    def test_oversized_decision_result_fails_decision(self, box):
        """A decision carrying a blob past the limit fails the DECISION
        (BAD_BINARY cause), not the transaction — the worker re-decides
        (decision/checker.go blob arm)."""
        box.config.set(KEY_BLOB_SIZE_LIMIT_ERROR, 256)
        box.frontend.start_workflow_execution(DOMAIN, "wf-dec", "t", TL)

        class OversizedDecider:
            def __init__(self):
                self.attempts = 0

            def decide(self, history):
                self.attempts += 1
                if self.attempts == 1:
                    return [Decision(DecisionType.CompleteWorkflowExecution,
                                     {"result": b"x" * 1024})]
                return [Decision(DecisionType.CompleteWorkflowExecution,
                                 {"result": b"ok"})]

        decider = OversizedDecider()
        TaskPoller(box, DOMAIN, TL, {"wf-dec": decider}).drain()
        did = box.frontend.describe_domain(DOMAIN).domain_id
        run = box.stores.execution.get_current_run_id(did, "wf-dec")
        events = box.stores.history.read_events(did, "wf-dec", run)
        causes = [e.get("cause") for e in events
                  if e.event_type == EventType.DecisionTaskFailed]
        assert "BAD_BINARY" in causes
        ms = box.stores.execution.get_workflow(did, "wf-dec", run)
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert decider.attempts >= 2


class TestHistoryGrowthLimit:
    def test_history_count_limit_terminates_run(self, box):
        """The size_limit_test contract: a run whose history outgrows the
        error threshold is TERMINATED by the engine, not left growing."""
        box.config.set(KEY_HISTORY_COUNT_LIMIT_WARN, 10)
        box.config.set(KEY_HISTORY_COUNT_LIMIT_ERROR, 16)
        box.frontend.start_workflow_execution(DOMAIN, "wf-grow", "t", TL)
        did = box.frontend.describe_domain(DOMAIN).domain_id
        # signals append events with no decision progress (buffered-free
        # path: no inflight decision) until the limit trips
        for i in range(30):
            try:
                box.frontend.signal_workflow_execution(DOMAIN, "wf-grow",
                                                       f"s{i}")
            except Exception:
                break
        run = box.stores.execution.get_current_run_id(did, "wf-grow")
        ms = box.stores.execution.get_workflow(did, "wf-grow", run)
        assert ms.execution_info.close_status == CloseStatus.Terminated
        events = box.stores.history.read_events(did, "wf-grow", run)
        term = [e for e in events
                if e.event_type == EventType.WorkflowExecutionTerminated]
        assert term and term[0].get("reason") == TERMINATE_REASON
        assert box.metrics.counter("limits", "history-limit-terminations") >= 1
        # the warn threshold fired on the way up
        assert box.metrics.counter("limits", "history-limit-warnings") >= 1


class TestHistoryPagination:
    def test_pages_concatenate_to_full_history(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-page", "echo", TL)
        for i in range(8):
            box.frontend.signal_workflow_execution(DOMAIN, "wf-page", f"s{i}")
        did = box.frontend.describe_domain(DOMAIN).domain_id
        run = box.stores.execution.get_current_run_id(did, "wf-page")
        full = box.stores.history.read_events(did, "wf-page", run)
        assert len(full) > 6
        paged = []
        token = None
        pages = 0
        while True:
            page = box.frontend.get_workflow_execution_history_page(
                DOMAIN, "wf-page", page_size=3, next_page_token=token)
            paged.extend(page.events)
            pages += 1
            assert len(page.events) <= 3
            if page.next_page_token is None:
                break
            token = page.next_page_token
        assert pages >= 3
        assert [e.id for e in paged] == [e.id for e in full]

    def test_page_cap_bounds_default_reads(self, box):
        from cadence_tpu.utils.dynamicconfig import KEY_HISTORY_PAGE_SIZE
        box.config.set(KEY_HISTORY_PAGE_SIZE, 4)
        box.frontend.start_workflow_execution(DOMAIN, "wf-cap", "t", TL)
        for i in range(6):
            box.frontend.signal_workflow_execution(DOMAIN, "wf-cap", f"s{i}")
        page = box.frontend.get_workflow_execution_history_page(
            DOMAIN, "wf-cap", page_size=9999)
        assert len(page.events) == 4  # the configured cap wins
        assert page.next_page_token is not None


class TestVisibilityPaginationAndIndex:
    def _seed(self, box, n=12):
        did = box.frontend.describe_domain(DOMAIN).domain_id
        for i in range(n):
            wf = f"wf-v{i}"
            wtype = "orders" if i % 2 == 0 else "billing"
            box.frontend.start_workflow_execution(DOMAIN, wf, wtype, TL)
            TaskPoller(box, DOMAIN, TL, {wf: EchoDecider(TL)}).drain()
        box.pump_until_quiet()
        return did

    def test_list_pages_are_disjoint_and_complete(self, box):
        self._seed(box)
        seen = []
        token = None
        while True:
            page = box.frontend.list_workflow_executions_page(
                DOMAIN, "WorkflowType = 'orders'", page_size=2,
                next_page_token=token)
            assert len(page.records) <= 2
            seen.extend(r.workflow_id for r in page.records)
            if page.next_page_token is None:
                break
            token = page.next_page_token
        assert sorted(seen) == sorted(f"wf-v{i}" for i in range(0, 12, 2))
        assert len(seen) == len(set(seen))  # disjoint pages

    def test_index_prunes_candidates(self, box):
        """The (type, status) indexes actually plan the query: a selective
        type filter evaluates the predicate on the type's records only."""
        did = self._seed(box)
        store = box.stores.visibility
        evaluated = []
        from cadence_tpu.engine import visibility_query as vq
        orig = vq.compile_query_with_hints

        def spy(query):
            pred, hints = orig(query)

            def counting(rec):
                evaluated.append(rec.workflow_id)
                return pred(rec)
            return counting, hints

        vq.compile_query_with_hints, token = spy, None
        try:
            hits = store.query(did, "WorkflowType = 'billing'")
        finally:
            vq.compile_query_with_hints = orig
        assert len(hits) == 6
        assert len(evaluated) == 6  # only the billing index set, not all 12