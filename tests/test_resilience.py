"""Resilient RPC tier: deadlines, retry policies, circuit breakers,
stale-connection healing, and wire chaos.

Reference: common/backoff (ExponentialRetryPolicy), hystrix-style
outbound breakers, gRPC deadline propagation, and
persistenceErrorInjectionClients.go-style injection moved down to the
transport (rpc/chaos.py).
"""
import threading
import time

import pytest

from cadence_tpu.engine.persistence import Stores
from cadence_tpu.rpc import chaos as chaos_mod
from cadence_tpu.rpc.chaos import ChaosError, WireChaos
from cadence_tpu.rpc.client import _Pool, _is_idempotent, RemoteStores
from cadence_tpu.rpc.storeserver import StoreServer, _parse_fault_spec
from cadence_tpu.rpc.wire import call as wire_call
from cadence_tpu.utils import deadline as deadline_mod
from cadence_tpu.utils.backoff import NO_BACKOFF, RetryPolicy
from cadence_tpu.utils.circuitbreaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerRegistry,
    CircuitBreaker,
    CircuitOpenError,
    ServiceBusy,
)
from cadence_tpu.utils.deadline import Deadline, DeadlineExceeded
from cadence_tpu.utils.metrics import MetricsRegistry


def start_store_server(port: int = 0, stores=None):
    server = StoreServer(("127.0.0.1", port), stores or Stores())
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, server.server_address[1]


# ---------------------------------------------------------------------------
# RetryPolicy (common/backoff retrypolicy.go edge cases)
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_exponential_growth_capped_and_jittered(self):
        policy = RetryPolicy(init_interval_s=0.1, max_interval_s=0.4,
                             backoff_coefficient=2.0, max_attempts=0,
                             seed=7)
        # full jitter: every sample in [0, min(init*2^i, cap)]
        for attempt, ceiling in ((0, 0.1), (1, 0.2), (2, 0.4), (9, 0.4)):
            for _ in range(20):
                s = policy.next_interval(attempt, 0.0)
                assert 0.0 <= s <= ceiling

    def test_max_attempts_counts_the_initial_try(self):
        policy = RetryPolicy(max_attempts=3, seed=1)
        assert policy.next_interval(0, 0.0) != NO_BACKOFF
        assert policy.next_interval(1, 0.0) != NO_BACKOFF
        # attempt index 2 would be the 4th try: stop (retry.go:38 shape)
        assert policy.next_interval(2, 0.0) == NO_BACKOFF

    def test_expiration_cuts_off(self):
        policy = RetryPolicy(init_interval_s=0.5, max_interval_s=0.5,
                             backoff_coefficient=1.0, max_attempts=0,
                             expiration_s=2.0, seed=3)
        assert policy.next_interval(0, 0.0) != NO_BACKOFF
        # elapsed + next interval would land past expiration: stop
        assert policy.next_interval(0, 1.9) == NO_BACKOFF
        assert policy.next_interval(0, 5.0) == NO_BACKOFF

    def test_coefficient_overflow_falls_to_cap(self):
        policy = RetryPolicy(init_interval_s=1.0, max_interval_s=2.0,
                             backoff_coefficient=1e308, max_attempts=0,
                             seed=5)
        # pow overflows to inf on a late attempt; the cap absorbs it
        s = policy.next_interval(500, 0.0)
        assert 0.0 <= s <= 2.0

    def test_overflow_without_cap_stops(self):
        policy = RetryPolicy(init_interval_s=1.0, max_interval_s=0.0,
                             backoff_coefficient=1e308, max_attempts=0,
                             seed=5)
        assert policy.next_interval(500, 0.0) == NO_BACKOFF

    def test_bad_parameters_rejected(self):
        with pytest.raises(ValueError):
            RetryPolicy(init_interval_s=0.0)
        with pytest.raises(ValueError):
            RetryPolicy(backoff_coefficient=0.5)

    def test_non_retriable_classification(self):
        """The _Pool classifier: chaos + injected store faults always
        retry; transport faults retry only for idempotent requests; typed
        service errors never retry."""
        from cadence_tpu.engine.faults import TransientStoreError
        from cadence_tpu.engine.persistence import ConditionFailedError

        classify = _Pool._classify
        assert classify(ChaosError("x"), False) is True
        assert classify(TransientStoreError("x"), False) is True
        assert classify(ConnectionResetError("x"), True) is True
        assert classify(ConnectionResetError("x"), False) is False
        assert classify(CircuitOpenError("x"), True) is False
        assert classify(ConditionFailedError("x"), True) is False
        assert classify(ValueError("x"), True) is False

    def test_request_idempotency_classification(self):
        assert _is_idempotent(("store", "execution", "get_workflow",
                               (), {}))
        assert _is_idempotent(("store", "queue", "size", (), {}))
        assert not _is_idempotent(("store", "execution", "update_workflow",
                                   (), {}))
        assert _is_idempotent(("peers", 3.0))
        assert _is_idempotent(("ping",))
        assert _is_idempotent(("matching", "poll_for_decision_task",
                               (), {}))
        assert not _is_idempotent(("matching", "add_decision_task",
                                   (), {}))
        assert not _is_idempotent(("frontend", "signal_workflow_execution",
                                   (), {}))
        assert not _is_idempotent("garbage")


# ---------------------------------------------------------------------------
# Circuit breaker state machine
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_consecutive_failures_open(self):
        b = CircuitBreaker(failure_threshold=3, reset_timeout_s=60.0)
        for _ in range(2):
            b.on_failure()
        assert b.state() == CLOSED and b.allow()
        b.on_failure()
        assert b.state() == OPEN
        assert not b.allow()

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker(failure_threshold=3)
        b.on_failure()
        b.on_failure()
        b.on_success()
        b.on_failure()
        b.on_failure()
        assert b.state() == CLOSED

    def test_failure_rate_opens_over_min_throughput(self):
        b = CircuitBreaker(failure_threshold=100, failure_rate=0.5,
                           min_throughput=10)
        # 5 failures / 9 calls: above rate but below throughput → closed
        for _ in range(4):
            b.on_success()
        for _ in range(5):
            b.on_failure()
        assert b.state() == CLOSED
        b.on_success()  # 10th call; next failure tips 6/11 > 0.5
        b.on_failure()
        assert b.state() == OPEN

    def test_half_open_probe_success_closes(self):
        b = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05)
        b.on_failure()
        assert b.state() == OPEN and not b.allow()
        time.sleep(0.06)
        assert b.allow()          # the single half-open probe
        assert b.state() == HALF_OPEN
        assert not b.allow()      # second concurrent probe is shed
        b.on_success()
        assert b.state() == CLOSED
        assert b.allow()

    def test_half_open_probe_failure_reopens(self):
        b = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05)
        b.on_failure()
        time.sleep(0.06)
        assert b.allow()
        b.on_failure()
        assert b.state() == OPEN
        assert not b.allow()      # reset clock restarted
        time.sleep(0.06)
        assert b.allow()          # probes again after another window

    def test_abandoned_probe_releases_the_slot(self):
        """A probe whose caller's DEADLINE expired produced no evidence:
        the slot must free, or the breaker wedges HALF_OPEN forever and
        sheds a recovered peer until process restart."""
        b = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05)
        b.on_failure()
        time.sleep(0.06)
        assert b.allow()              # this caller holds the probe
        b.on_probe_abandoned()
        assert b.state() == HALF_OPEN
        assert b.allow()              # the next caller can still probe
        b.on_success()
        assert b.state() == CLOSED

    def test_relayed_connection_error_not_charged_to_breaker(self):
        """A ConnectionError the PEER reports as an op error (its own
        outbound hop died) arrives as a well-formed ("err", exc) response:
        the peer is healthy, so its breaker must stay closed and the
        pooled socket must survive."""
        stores_bundle = Stores()

        def refuse(*args, **kwargs):
            raise ConnectionRefusedError("downstream of the peer is dead")

        stores_bundle.queue.enqueue = refuse
        server, port = start_store_server(stores=stores_bundle)
        try:
            registry = MetricsRegistry()
            breakers = BreakerRegistry(metrics=registry, failure_threshold=1)
            remote = RemoteStores(("127.0.0.1", port), metrics=registry,
                                  breakers=breakers)
            with pytest.raises(ConnectionRefusedError):
                remote.queue.enqueue("q", b"x")
            assert breakers.for_target(("127.0.0.1", port)).state() == CLOSED
            assert remote.ping() == "pong"
        finally:
            server.shutdown()

    def test_local_encode_failure_not_charged_to_breaker(self, monkeypatch):
        """A failure raised BEFORE any byte leaves this process (oversize
        frame, unpicklable argument) says nothing about the peer: the
        breaker stays closed and the healthy pooled socket survives."""
        from cadence_tpu.rpc import wire

        server, port = start_store_server()
        try:
            registry = MetricsRegistry()
            breakers = BreakerRegistry(metrics=registry, failure_threshold=1)
            remote = RemoteStores(("127.0.0.1", port), metrics=registry,
                                  breakers=breakers)
            assert remote.ping() == "pong"
            monkeypatch.setattr(wire, "MAX_FRAME", 64)
            with pytest.raises(wire.WireError):
                remote.queue.enqueue("q", b"x" * 4096)
            monkeypatch.setattr(wire, "MAX_FRAME", 256 * 1024 * 1024)
            with pytest.raises(Exception):
                remote.queue.enqueue("q", lambda: None)  # unpicklable
            assert breakers.for_target(("127.0.0.1", port)).state() == CLOSED
            assert remote.ping() == "pong"
        finally:
            server.shutdown()

    def test_budget_exhausted_timeout_not_charged_to_breaker(self):
        """A socket timeout caused by the CALLER's nearly-spent deadline
        (wire.effective_timeout clamps the socket timeout to the remaining
        budget) is the caller's problem, not the peer's: a healthy target
        at normal latency must not have its breaker opened by a few
        tight-deadline callers."""
        import time as _time

        stores_bundle = Stores()

        def slowish(*args, **kwargs):
            _time.sleep(0.2)  # normal latency, far beyond a 1ms budget
            return 0

        stores_bundle.queue.size = slowish
        server, port = start_store_server(stores=stores_bundle)
        try:
            registry = MetricsRegistry()
            breakers = BreakerRegistry(metrics=registry, failure_threshold=1)
            remote = RemoteStores(("127.0.0.1", port), metrics=registry,
                                  breakers=breakers)
            assert remote.ping() == "pong"
            with deadline_mod.bind(Deadline.after(0.05)):
                with pytest.raises((OSError, DeadlineExceeded)):
                    remote.queue.size("q")
            assert breakers.for_target(("127.0.0.1", port)).state() == CLOSED
            assert remote.ping() == "pong"  # still served, not shed
        finally:
            server.shutdown()

    def test_registry_emits_state_gauge_and_transitions(self):
        registry = MetricsRegistry()
        breakers = BreakerRegistry(metrics=registry, failure_threshold=1,
                                   reset_timeout_s=60.0)
        b = breakers.for_target(("10.0.0.1", 7000))
        assert registry.gauge_value("rpc.circuitbreaker.10.0.0.1:7000",
                                    "breaker-state") == float(CLOSED)
        b.on_failure()
        assert registry.gauge_value("rpc.circuitbreaker.10.0.0.1:7000",
                                    "breaker-state") == float(OPEN)
        assert registry.counter("rpc.circuitbreaker", "transitions") == 1
        assert registry.counter("rpc.circuitbreaker", "opened") == 1
        assert breakers.snapshot() == {"10.0.0.1:7000": "open"}

    def test_pool_sheds_when_breaker_open(self):
        registry = MetricsRegistry()
        breakers = BreakerRegistry(metrics=registry, failure_threshold=1,
                                   reset_timeout_s=60.0)
        pool = _Pool(("127.0.0.1", 1), metrics=registry, breakers=breakers)
        breakers.for_target(("127.0.0.1", 1)).on_failure()
        t0 = time.perf_counter()
        with pytest.raises(CircuitOpenError):
            pool.call(("ping",))
        assert time.perf_counter() - t0 < 0.1  # shed, not a connect timeout
        assert registry.counter("rpc.client", "breaker-rejected") == 1


# ---------------------------------------------------------------------------
# Deadline propagation
# ---------------------------------------------------------------------------


class TestDeadline:
    def test_bind_and_current_nest(self):
        assert deadline_mod.current() is None
        with deadline_mod.bind(Deadline.after(5.0)) as outer:
            assert deadline_mod.current() is outer
            with deadline_mod.bind(Deadline.after(1.0)) as inner:
                assert deadline_mod.current() is inner
            assert deadline_mod.current() is outer
        assert deadline_mod.current() is None
        # bind(None) is a pass-through
        with deadline_mod.bind(None):
            assert deadline_mod.current() is None

    def test_inject_peek_roundtrip(self):
        with deadline_mod.bind(Deadline.after(5.0)):
            wrapped = deadline_mod.inject(("ping",))
        peeked = deadline_mod.peek(wrapped)
        assert peeked is not None
        assert 4.0 < peeked.remaining() <= 5.0
        # coexists with a trace carrier on the same envelope
        from cadence_tpu.utils import tracing
        with tracing.DEFAULT_TRACER.start_span("op"):
            with deadline_mod.bind(Deadline.after(5.0)):
                wrapped = deadline_mod.inject(tracing.inject(("ping",)))
        ctx, inner = tracing.extract(wrapped)
        assert ctx is not None and inner == ("ping",)
        assert deadline_mod.peek(wrapped) is not None
        # pass-through without a bound deadline; tolerant peek
        assert deadline_mod.inject(("ping",)) == ("ping",)
        assert deadline_mod.peek(("ping",)) is None
        assert deadline_mod.peek(("traced", {"deadline_s": "bogus"},
                                  ("ping",))) is None

    def test_expired_budget_fails_before_dialing(self):
        # no listener needed: the call must not even attempt a connect
        pool = _Pool(("127.0.0.1", 1))
        with deadline_mod.bind(Deadline.after(-1.0)):
            with pytest.raises(DeadlineExceeded):
                pool.call(("ping",))

    def test_server_rejects_expired_envelope(self):
        server, port = start_store_server()
        try:
            # an honest call works
            assert wire_call(("127.0.0.1", port), ("ping",)) == "pong"
            # forge an envelope that arrives already expired
            with pytest.raises(DeadlineExceeded):
                wire_call(("127.0.0.1", port),
                          ("traced", {"deadline_s": -0.5}, ("ping",)))
        finally:
            server.shutdown()

    def test_budget_rides_the_wire(self):
        """A generous client budget reaches the server shrunk by transit,
        and the served call still succeeds."""
        server, port = start_store_server()
        try:
            stores = RemoteStores(("127.0.0.1", port))
            with deadline_mod.bind(Deadline.after(10.0)):
                assert stores.ping() == "pong"
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# Stale-connection poisoning (satellite regression)
# ---------------------------------------------------------------------------


class TestStaleConnections:
    @staticmethod
    def _one_shot_peer(respond: bool):
        """A fake peer that serves at most one frame on one connection,
        then hangs up — the peer-restarted-between-calls FIN. Returns
        (port, thread, listener)."""
        import socket as socketlib

        from cadence_tpu.rpc.wire import (
            recv_frame,
            send_frame,
            verify_hello,
        )

        listener = socketlib.socket()
        listener.setsockopt(socketlib.SOL_SOCKET,
                            socketlib.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        port = listener.getsockname()[1]

        def serve():
            conn, _ = listener.accept()
            try:
                verify_hello(conn)
                recv_frame(conn)
                if respond:
                    send_frame(conn, ("ok", "pong"))
            finally:
                conn.close()
                listener.close()

        thread = threading.Thread(target=serve, daemon=True)
        thread.start()
        return port, thread

    def test_peer_restart_between_calls_does_not_wedge_the_thread(self):
        # leg 1: the peer answers one ping, then closes (restart FIN);
        # the pool caches the now-stale connection
        port, thread = self._one_shot_peer(respond=True)
        stores = RemoteStores(("127.0.0.1", port))
        assert stores.ping() == "pong"
        thread.join(timeout=5)
        # leg 2: the peer comes back on the SAME port; the pool must drop
        # the poisoned per-thread slot and dial fresh — transparently,
        # because ping is idempotent and the retry tier owns the resend
        server2, _ = start_store_server(port=port)
        try:
            assert stores.ping() == "pong"
        finally:
            server2.shutdown()

    def test_receive_failure_drops_the_pooled_connection(self):
        """After a receive-side failure on a NON-idempotent op the error
        surfaces (no blind resend) and the per-thread Connection object is
        discarded — the next call dials fresh instead of reusing a corpse."""
        port, thread = self._one_shot_peer(respond=False)
        stores = RemoteStores(("127.0.0.1", port))
        pool = stores._pool
        with pytest.raises((ConnectionError, OSError)):
            stores.execution.update_workflow("d", "w", "r", None)
        thread.join(timeout=5)
        assert getattr(pool._local, "conn", None) is None


# ---------------------------------------------------------------------------
# Wire chaos
# ---------------------------------------------------------------------------


class TestWireChaos:
    def test_parse_spec(self):
        chaos = chaos_mod.parse_spec("drop=0.2,sever=0.1,delay=0.5,"
                                     "delay_ms=5,seed=9")
        assert (chaos.drop, chaos.sever, chaos.delay) == (0.2, 0.1, 0.5)
        assert chaos.delay_ms == 5 and isinstance(chaos.counts(), dict)
        with pytest.raises(ValueError):
            chaos_mod.parse_spec("dorp=0.2")

    def test_store_fault_spec_parses(self):
        injector = _parse_fault_spec("rate=0.25,seed=3,writes_only=0")
        assert injector.rate == 0.25 and injector.writes_only is False
        with pytest.raises(ValueError):
            _parse_fault_spec("rat=0.25")

    def test_retry_tier_heals_chaos(self):
        """Seeded drop+sever+delay chaos on every request leg: the _Pool
        retry tier pushes every call through, and the injector actually
        fired (the run exercised real faults, not a lucky seed)."""
        server, port = start_store_server()
        chaos = WireChaos(drop=0.25, sever=0.15, delay=0.3, delay_ms=2,
                          seed=11)
        chaos_mod.install(chaos)
        try:
            stores = RemoteStores(("127.0.0.1", port))
            for _ in range(40):
                assert stores.ping() == "pong"
            counts = chaos.counts()
            assert counts["drops"] > 0 and counts["severs"] > 0
            assert counts["delays"] > 0
        finally:
            chaos_mod.uninstall()
            server.shutdown()

    def test_torn_frame_never_dispatches(self):
        """A severed request is discarded whole by the server: the op it
        carried must NOT have been applied (the nothing-was-applied
        guarantee that makes ChaosError universally retryable)."""
        stores_bundle = Stores()
        server, port = start_store_server(stores=stores_bundle)
        chaos = WireChaos(sever=1.0, seed=1)
        chaos_mod.install(chaos)
        try:
            remote = RemoteStores(("127.0.0.1", port))
            with pytest.raises((ChaosError, ConnectionError)):
                remote.queue.enqueue("q", b"payload")
            assert chaos.counts()["severs"] > 0
        finally:
            chaos_mod.uninstall()
        try:
            assert stores_bundle.queue.size("q") == 0
        finally:
            server.shutdown()

    def test_breaker_open_surfaces_as_service_busy(self):
        """FrontendClient translates its own breaker shedding into the
        typed ServiceBusy after retries exhaust (degrade, don't hang)."""
        from cadence_tpu.rpc.cluster import FrontendClient
        from cadence_tpu.utils.circuitbreaker import DEFAULT_BREAKERS

        client = FrontendClient(("127.0.0.1", 1))
        breaker = DEFAULT_BREAKERS.for_target(("127.0.0.1", 1))
        breaker.reset_timeout_s = 60.0
        for _ in range(breaker.failure_threshold):
            breaker.on_failure()
        assert breaker.state() == OPEN
        client.RETRIES = 2
        client.BACKOFF_S = 0.01
        with pytest.raises(ServiceBusy):
            client.describe_domain("d")
