"""WAL schema versioning + migration (layer-1/row-66: the
cadence-cassandra-tool/sql-tool analog — versioned schema with an
upgrade chain and a newer-writer refusal gate)."""
import json

import pytest

from cadence_tpu.core.enums import CloseStatus
from cadence_tpu.engine.durability import (
    WAL_VERSION,
    DurableLog,
    SchemaVersionError,
    migrate_wal_file,
    open_durable_stores,
    recover_stores,
    wal_version,
)
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import EchoDecider
from tests.taskpoller import TaskPoller

DOMAIN = "sv-domain"
TL = "sv-tl"


class TestSchemaVersion:
    def test_fresh_log_carries_current_header(self, tmp_path):
        wal = str(tmp_path / "wal.jsonl")
        stores = open_durable_stores(wal)
        stores.wal.close()
        records = DurableLog.read_all(wal)
        assert records[0] == {"t": "ver", "v": WAL_VERSION}
        assert wal_version(records) == WAL_VERSION

    def test_v1_log_recovers_via_migration(self, tmp_path):
        """A pre-header (v1) log — domain records without the v2 fields —
        recovers transparently with defaults lifted in memory."""
        wal = str(tmp_path / "v1.jsonl")
        with open(wal, "w") as f:
            f.write(json.dumps({"t": "d", "id": "d-1", "name": DOMAIN,
                                "ret": 3, "act": True, "ac": "primary",
                                "cl": ["primary"], "fv": 0, "nv": 0}) + "\n")
        stores, report = recover_stores(wal, verify_on_device=False,
                                        rebuild_on_device=False)
        info = stores.domain.by_name(DOMAIN)
        assert info.retention_days == 3
        assert info.status == 0 and info.history_archival_uri == ""

    def test_newer_writer_is_refused(self, tmp_path):
        wal = str(tmp_path / "future.jsonl")
        with open(wal, "w") as f:
            f.write(json.dumps({"t": "ver", "v": WAL_VERSION + 1}) + "\n")
        with pytest.raises(SchemaVersionError):
            recover_stores(wal, verify_on_device=False,
                           rebuild_on_device=False)

    def test_migrate_tool_rewrites_and_preserves_state(self, tmp_path):
        wal = str(tmp_path / "migrate.jsonl")
        # build a REAL v2 cluster, then strip it back to v1 on disk
        box = Onebox(num_hosts=1, num_shards=4,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "wf-m", "echo", TL)
        TaskPoller(box, DOMAIN, TL, {"wf-m": EchoDecider(TL)}).drain()
        box.stores.wal.close()
        records = DurableLog.read_all(wal)
        with open(wal, "w") as f:
            for rec in records:
                if rec.get("t") == "ver":
                    continue  # drop the header
                if rec.get("t") == "d":
                    rec = {k: v for k, v in rec.items()
                           if k not in ("st", "desc", "arc")}
                f.write(json.dumps(rec) + "\n")
        assert wal_version(DurableLog.read_all(wal)) == 1
        before, after = migrate_wal_file(wal)
        assert (before, after) == (1, WAL_VERSION)
        assert wal_version(DurableLog.read_all(wal)) == WAL_VERSION
        # the migrated cluster recovers with its workflow intact
        stores, report = recover_stores(wal, verify_on_device=False,
                                        rebuild_on_device=False)
        domain_id = stores.domain.by_name(DOMAIN).domain_id
        run = stores.execution.get_current_run_id(domain_id, "wf-m")
        ms = stores.execution.get_workflow(domain_id, "wf-m", run)
        assert ms.execution_info.close_status == CloseStatus.Completed

    def test_recovery_stamps_midfile_header_no_remigration(self, tmp_path):
        """Recovering a v1 log stamps a CURRENT version header so records
        appended afterwards are not re-migrated; positional migration
        lifts only the pre-header prefix (advisor r4)."""
        import cadence_tpu.engine.durability as dur

        wal = str(tmp_path / "mid.jsonl")
        with open(wal, "w") as f:
            f.write(json.dumps({"t": "d", "id": "d-1", "name": DOMAIN,
                                "ret": 3, "act": True, "ac": "primary",
                                "cl": ["primary"], "fv": 0, "nv": 0}) + "\n")
        stores, _ = recover_stores(wal, verify_on_device=False,
                                   rebuild_on_device=False)
        stores.wal.close()
        records = DurableLog.read_all(wal)
        # mid-file header appended by recovery; file now reads as current
        assert records[-1] == {"t": "ver", "v": WAL_VERSION}
        assert wal_version(records) == WAL_VERSION
        # positional migration: prefix lifts, post-header records pass
        # through untouched even with a destructive migration registered
        calls = []
        orig = dict(dur._MIGRATIONS)

        def _spy(rec):
            calls.append(rec.get("t"))
            return orig[1](rec)

        dur._MIGRATIONS[1] = _spy
        try:
            body, original = dur.migrate_records(
                records + [{"t": "d", "id": "d-2", "name": "post", "ret": 1,
                            "act": True, "ac": "primary", "cl": ["primary"],
                            "fv": 0, "nv": 0, "st": 0, "desc": "",
                            "arc": ""}])
        finally:
            dur._MIGRATIONS.update(orig)
        assert original == WAL_VERSION
        assert calls == ["d"]  # ONLY the v1 prefix record was migrated
        # second recovery still sees the domain exactly once
        stores2, _ = recover_stores(wal, verify_on_device=False,
                                    rebuild_on_device=False)
        assert stores2.domain.by_name(DOMAIN).retention_days == 3


class TestSqliteBackend:
    """The second storage backend (the sql persistence plugin next to
    nosql): a SQLite WAL selected by path extension, same record
    contract, crash-recovery and migration included."""

    def _run_workflow(self, wal):
        from cadence_tpu.engine.durability import open_durable_stores
        box = Onebox(num_hosts=1, num_shards=4,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "wf-sq", "echo", TL)
        TaskPoller(box, DOMAIN, TL, {"wf-sq": EchoDecider(TL)}).drain()
        box.stores.wal.close()

    def test_workflow_survives_recovery(self, tmp_path):
        wal = str(tmp_path / "cluster.db")
        self._run_workflow(wal)
        stores, report = recover_stores(wal, verify_on_device=False,
                                        rebuild_on_device=False)
        domain_id = stores.domain.by_name(DOMAIN).domain_id
        run = stores.execution.get_current_run_id(domain_id, "wf-sq")
        ms = stores.execution.get_workflow(domain_id, "wf-sq", run)
        assert ms.execution_info.close_status == CloseStatus.Completed
        stores.wal.close()
        # records round-trip identically through both backends
        from cadence_tpu.engine.durability import read_log
        assert read_log(wal)[0] == {"t": "ver", "v": WAL_VERSION}

    def test_migration_over_sqlite(self, tmp_path):
        """migrate_wal_file rewrites a v1 SQLite log atomically."""
        from cadence_tpu.engine.durability import SqliteLog, read_log
        wal = str(tmp_path / "old.db")
        SqliteLog.rewrite(wal, [
            {"t": "d", "id": "d-1", "name": DOMAIN, "ret": 3, "act": True,
             "ac": "primary", "cl": ["primary"], "fv": 0, "nv": 0}])
        assert wal_version(read_log(wal)) == 1
        before, after = migrate_wal_file(wal)
        assert (before, after) == (1, WAL_VERSION)
        assert wal_version(read_log(wal)) == WAL_VERSION
        stores, _ = recover_stores(wal, verify_on_device=False,
                                   rebuild_on_device=False)
        assert stores.domain.by_name(DOMAIN).retention_days == 3

    def test_cli_drives_sqlite_wal(self, tmp_path, capsys):
        """The CLI's --wal picks the backend by extension; scan/clean
        work over SQLite rows."""
        import json as _json

        from cadence_tpu.cli import main as cli_main
        wal = str(tmp_path / "cli.db")

        def run(*argv):
            rc = cli_main(list(argv))
            return rc, _json.loads(capsys.readouterr().out)

        rc, out = run("--wal", wal, "domain", "register", "--name", "sq-d")
        assert rc == 0
        rc, out = run("--wal", wal, "workflow", "start", "--domain", "sq-d",
                      "--workflow-id", "w", "--type", "t",
                      "--task-list", TL)
        assert rc == 0
        rc, out = run("--wal", wal, "wal", "scan")
        assert rc == 0 and out["bad_lines"] == 0 and out["records"] > 3
        rc, out = run("--wal", wal, "wal", "clean")
        assert rc == 0
        rc, out = run("--wal", wal, "workflow", "describe",
                      "--domain", "sq-d", "--workflow-id", "w")
        assert rc == 0
