"""Multi-host device serving over the wire (ISSUE 13's acceptance gates).

Marked slow+load: each test boots a real wire cluster with the serving
tier enabled in every host process (JAX init + kernel warm-up per
host), so they run through deploy/smoke_multihost.sh — not tier-1.

- `test_kill_host_mid_traffic_migration_gate`: the production proof —
  SIGKILL a host mid-window; victim-domain p99 holds, zero parity
  divergence anywhere, survivors' stolen-shard admits are
  snapshot-hydrated above the floor, and events/s/cluster is recorded
  next to events/s/pod.
- `test_planned_rebalance_byte_parity`: grow the cluster by one host;
  the losing hosts snapshot their moving resident rows out through the
  shared store, the gaining host hydrates, and every migrated row's
  canonical payload CRC equals the oracle's — byte-identical
  losing-host → gaining-host → oracle.
"""
import os
import time

import numpy as np
import pytest

from cadence_tpu.core.checksum import (
    DEFAULT_LAYOUT,
    STICKY_ROW_INDEX,
    crc32_of_row,
    payload_row,
)

pytestmark = [pytest.mark.slow, pytest.mark.load]

DOMAIN = "cs-domain"


class TestKillHostMigration:
    def test_kill_host_mid_traffic_migration_gate(self):
        from cadence_tpu.loadgen.scenarios import cluster_serving_scenario

        duration = float(os.environ.get("CLUSTER_DURATION_S", "10"))
        doc = cluster_serving_scenario(duration_s=duration, rps=14.0,
                                       workers=16, verify=True)
        fo = doc["failover"]
        assert fo["victim_shards_taken"], fo
        steals = fo["migrated_in"] + fo["cold_steals"] \
            + fo["stale_snapshots"]
        assert steals > 0, fo
        assert fo["hydration_ratio"] >= 0.8, fo
        assert doc["parity"]["serving_divergence"] == 0
        assert doc["parity"]["migration_divergence"] == 0
        assert doc["slo"]["ok"], doc["slo"]
        assert doc["verify"]["divergent"] == 0, doc["verify"]
        ns = doc["north_star"]
        assert ns["events_per_sec_cluster"] > 0
        assert ns["events_per_sec_pod"] > 0
        assert doc["ok"], {k: doc[k] for k in ("failover", "parity",
                                               "verify")}


class TestPlannedRebalance:
    def _wait(self, predicate, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return
            time.sleep(0.5)
        raise TimeoutError(what)

    def test_planned_rebalance_byte_parity(self):
        from cadence_tpu.rpc.client import RemoteStores
        from cadence_tpu.rpc.cluster import launch

        env = {"CADENCE_TPU_SERVING": "1",
               "CADENCE_TPU_SNAPSHOT_MIN_EVENTS": "1",
               "CADENCE_TPU_SNAPSHOT_EVERY_EVENTS": "1",
               "CADENCE_TPU_SERVING_BATCH": "8",
               "CADENCE_TPU_SERVING_WARM_EVENTS": "16,32"}
        cluster = launch(num_hosts=2, num_shards=8, env_extra=env)
        try:
            self._wait(lambda: all(
                cluster.admin(n, "admin_cluster").get("serving_warmed")
                for n in sorted(cluster.hosts)), 600,
                "serving never warmed")
            fe = cluster.frontend(0)
            fe.register_domain(DOMAIN)
            # a long-lived pool: start + one completed decision each,
            # then a couple of signal rounds — committed transactions
            # the serving tier pins as resident rows (and snapshots,
            # policy floor 1)
            pool = [f"cs-wf-{i}" for i in range(12)]
            for wf in pool:
                fe.start_workflow_execution(DOMAIN, wf, "t", "cs-tl",
                                            execution_timeout=3600)
            pending = set(pool)
            deadline = time.monotonic() + 60
            while pending and time.monotonic() < deadline:
                resp = fe.poll_for_decision_task(DOMAIN, "cs-tl",
                                                 wait_seconds=0.3)
                if resp is None or resp.token is None:
                    continue
                fe.respond_decision_task_completed(resp.token, [])
                pending.discard(resp.token.workflow_id)
            assert not pending, f"pool never seeded: {sorted(pending)}"
            for rnd in range(2):
                for wf in pool:
                    fe.signal_workflow_execution(
                        DOMAIN, wf, f"cs-sig-{rnd}",
                        request_id=f"cs-req-{rnd}-{wf}")
            # complete the decisions the signals scheduled: pending
            # decisions would TIME OUT mid-test on the real clock and
            # keep committing transactions under the comparisons below
            quiet_deadline = time.monotonic() + 60
            idle = 0
            while idle < 4 and time.monotonic() < quiet_deadline:
                resp = fe.poll_for_decision_task(DOMAIN, "cs-tl",
                                                 wait_seconds=0.3)
                if resp is None or resp.token is None:
                    idle += 1
                    continue
                idle = 0
                fe.respond_decision_task_completed(resp.token, [])

            # quiesce: every host's serving queue drained and resident
            # rows pinned (the state the rebalance must carry)
            def drained():
                docs = [cluster.admin(n, "admin_cluster")
                        for n in sorted(cluster.hosts)]
                entries = sum((d["resident"] or {}).get("entries", 0)
                              for d in docs)
                depth = sum((d["serving"] or {}).get("queue_depth", 1)
                            for d in docs)
                return entries >= len(pool) and depth == 0
            self._wait(drained, 120, "serving tier never quiesced")

            before = {n: cluster.admin(n, "admin_cluster", True)
                      for n in sorted(cluster.hosts)}
            moved_rows = {}
            for doc in before.values():
                moved_rows.update(doc.get("resident_rows", {}))
            assert len(moved_rows) >= len(pool)

            # the planned rebalance: one more host joins the ring
            new_host = cluster.add_host()
            # the losers' release hooks snapshot + evict the moving
            # rows; the gainer hydrates in the background
            self._wait(lambda: (cluster.admin(new_host, "admin_cluster")
                                .get("resident", {}) or {})
                       .get("entries", 0) > 0, 300,
                       f"{new_host} never hydrated any resident rows")
            gained = cluster.admin(new_host, "admin_cluster", True)
            mig = gained["migration"]
            assert mig["migrated_in"] > 0, mig
            assert mig["parity_divergence"] == 0, mig
            losers_out = sum(
                cluster.admin(n, "admin_cluster")["migration"]
                ["migrated_out"] for n in sorted(cluster.hosts)
                if n != new_host)
            assert losers_out > 0

            # byte parity AT THE ROW'S CONTENT ADDRESS: replay exactly
            # the batches the pinned state covers through the oracle
            # StateBuilder and compare CRCs — immune to any transaction
            # that commits after the hydration pass (content addressing
            # already guarantees such a row is never served stale)
            from cadence_tpu.engine.cache import batch_crc
            from cadence_tpu.oracle.state_builder import StateBuilder

            stores = RemoteStores(("127.0.0.1", cluster.store_port))
            rows = gained.get("resident_rows", {})
            assert rows, gained
            checked = 0
            for key, (crc, branch, addr) in rows.items():
                batch_count, tail_crc = addr
                batches = stores.history.as_history_batches(*key)
                assert batch_count <= len(batches), key
                prefix = batches[:batch_count]
                assert int(batch_crc(prefix[-1])) == tail_crc, key
                ms = StateBuilder().replay_history(prefix)
                oracle = payload_row(ms, DEFAULT_LAYOUT)
                oracle[STICKY_ROW_INDEX] = 0
                assert crc == int(crc32_of_row(
                    np.asarray(oracle, dtype=np.int64))), key
                assert branch == int(ms.version_histories.current_index)
                checked += 1
            assert checked > 0
            # and the moved keys' pre-migration CRCs (read on the losing
            # hosts) match what the gainer now serves, wherever the row
            # still sits at the same content address
            for key, (crc, _branch, addr) in rows.items():
                if key in moved_rows and moved_rows[key][2] == tuple(addr):
                    assert moved_rows[key][0] == crc, key
        finally:
            cluster.stop()
