"""Open-loop load harness (ISSUE 8 / ROADMAP item 4).

Three layers, cheapest first:

- schedule construction (`loadgen/mixes.py`): seeded reproducibility —
  identical seeds must produce byte-identical traffic traces — and
  per-domain stream independence;
- the open-loop property itself (`loadgen/generator.py`), pinned with a
  deliberately STALLED fake server: latency is clocked from each op's
  INTENDED send time, so a backlogged server shows growing user-facing
  latency while its service latency stays flat — the generator can
  never degrade to closed-loop measurement (coordinated omission);
- the wire-cluster overload gate (`loadgen/scenarios.py`, marker
  `load`, deploy/smoke_load.sh): one domain driven at 2x its quota
  under seeded wire chaos — the victim domain's p99 holds its SLO,
  >= 90% of the aggressor's overflow sheds as typed ServiceBusy
  (counters on /metrics), and every completed workflow verifies
  oracle<->device with zero checksum divergence.
"""
import os
import threading
import time

import pytest

from cadence_tpu.loadgen import report as report_mod
from cadence_tpu.loadgen import scenarios
from cadence_tpu.loadgen.generator import (
    DecisionCompleters,
    LoadGenerator,
)
from cadence_tpu.loadgen.mixes import (
    ALL_OPS,
    OP_QUERY,
    OP_SIGNAL,
    OP_START,
    POOL_OPS,
    STANDARD_MIX,
    START_ONLY_MIX,
    DomainPlan,
    TrafficMix,
    build_schedule,
    pool_workflow_ids,
    trace_digest,
)
from cadence_tpu.loadgen.slo import SLO, evaluate_slos
from cadence_tpu.utils.quotas import ServiceBusyError

START_ONLY = TrafficMix("start-only", {OP_START: 1.0})


# -- schedules: seeded reproducibility --------------------------------------

class TestSchedules:
    def test_same_seed_reproduces_identical_trace(self):
        plans = [DomainPlan("d-a", 40, mix=STANDARD_MIX),
                 DomainPlan("d-b", 25, mix=START_ONLY)]
        s1 = build_schedule(plans, duration_s=3.0, seed=7)
        s2 = build_schedule(plans, duration_s=3.0, seed=7)
        assert s1 == s2
        assert trace_digest(s1) == trace_digest(s2)
        assert len(s1) > 100

    def test_different_seed_different_trace(self):
        plans = [DomainPlan("d-a", 40)]
        a = build_schedule(plans, duration_s=2.0, seed=1)
        b = build_schedule(plans, duration_s=2.0, seed=2)
        assert trace_digest(a) != trace_digest(b)

    def test_domain_streams_independent(self):
        """Adding a domain must not perturb another domain's trace (each
        stream is seeded by (seed, domain))."""
        alone = build_schedule([DomainPlan("d-a", 30)], 2.0, seed=9)
        merged = build_schedule([DomainPlan("d-a", 30),
                                 DomainPlan("d-b", 50)], 2.0, seed=9)
        a_ops = [(op.at_s, op.kind, op.workflow_id, op.arg)
                 for op in merged if op.domain == "d-a"]
        assert a_ops == [(op.at_s, op.kind, op.workflow_id, op.arg)
                        for op in alone]

    def test_schedule_is_open_loop_and_sorted(self):
        plans = [DomainPlan("d-u", 50, arrival="uniform", mix=START_ONLY)]
        sched = build_schedule(plans, duration_s=2.0, seed=3)
        # uniform lattice: exactly rps*duration - 1 arrivals strictly
        # inside (0, duration)
        assert len(sched) == 99
        ats = [op.at_s for op in sched]
        assert ats == sorted(ats)
        assert all(0 < t < 2.0 for t in ats)
        assert [op.index for op in sched] == list(range(len(sched)))

    def test_nonpositive_rps_rejected(self):
        # the CLI's --rps is an unvalidated float; rps <= 0 would divide
        # by zero or walk scheduled time backwards forever
        with pytest.raises(ValueError, match="rps must be > 0"):
            DomainPlan("d-bad", 0.0)
        with pytest.raises(ValueError, match="rps must be > 0"):
            DomainPlan("d-bad", -1.0)

    def test_population_targeting(self):
        plans = [DomainPlan("d-p", 80, pool_size=4)]
        sched = build_schedule(plans, duration_s=2.0, seed=11)
        pool = set(pool_workflow_ids(plans[0]))
        start_ids = [op.workflow_id for op in sched
                     if op.kind not in POOL_OPS
                     and op.kind != "signal-with-start"]
        assert len(start_ids) == len(set(start_ids))  # churn ids unique
        for op in sched:
            if op.kind in POOL_OPS:
                assert op.workflow_id in pool
        assert {op.kind for op in sched} <= set(ALL_OPS)


# -- the open-loop property -------------------------------------------------

class _StalledClient:
    """Fake frontend whose every op takes `stall` seconds of service
    time: a closed-loop driver would report `stall` per op; the open
    loop must report the GROWING backlog."""

    def __init__(self, stall: float) -> None:
        self.stall = stall
        self.calls = 0
        self._lock = threading.Lock()

    def start_workflow_execution(self, *a, **k):
        with self._lock:
            self.calls += 1
        time.sleep(self.stall)


class _SheddingClient:
    """Fake frontend shedding every other request with the typed quota
    rejection (retry-after riding along, like the real frontend)."""

    def __init__(self) -> None:
        self.calls = 0

    def start_workflow_execution(self, *a, **k):
        self.calls += 1
        if self.calls % 2 == 0:
            raise ServiceBusyError("over request limit",
                                   retry_after_s=0.125, domain="d-s")


class TestOpenLoop:
    def test_stalled_server_latency_clocks_from_intended_time(self):
        """THE open-loop pin: one worker, 0.1s service stall, arrivals
        scheduled every 12.5ms. A closed-loop driver would report ~0.1s
        per op; the open loop must report the backlog — the last op's
        user-facing latency is ~n*stall while its SERVICE latency stays
        ~stall. Coordinated omission is structurally impossible."""
        stall = 0.1
        plan = DomainPlan("d-o", 80, mix=START_ONLY, arrival="uniform")
        sched = build_schedule([plan], duration_s=0.2, seed=1)  # 15 ops
        n = len(sched)
        client = _StalledClient(stall)
        gen = LoadGenerator([client], sched, [plan], workers=1)
        rep = gen.run()
        assert client.calls == n
        lat = rep.percentiles(OP_START, metric="latency")
        svc = rep.percentiles(OP_START, metric="service-latency")
        # service latency: every op ~0.1s — p99 within one bucket of it
        assert svc["p99"] <= 0.25
        # user-facing latency: the backlog (~n*stall at the tail).
        # p50 alone proves the divergence: half the ops waited > 3x the
        # service time, which a closed-loop measurement cannot show.
        assert lat["p50"] >= 3 * stall
        assert lat["p99"] >= 0.5 * n * stall
        assert rep.duration_s >= n * stall * 0.9

    def test_sheds_are_counted_not_errors(self):
        plan = DomainPlan("d-s", 100, mix=START_ONLY, arrival="uniform")
        sched = build_schedule([plan], duration_s=0.1, seed=2)
        client = _SheddingClient()
        gen = LoadGenerator([client], sched, [plan], workers=2)
        rep = gen.run()
        t = rep.totals()
        assert t.sent == len(sched)
        assert t.shed == len(sched) // 2
        assert t.errors == 0
        assert rep.max_retry_after_s == pytest.approx(0.125)
        # shed series mirror the server-side quotas counters
        scope = "loadgen.start"
        assert rep.registry.counter(scope, "shed") == t.shed
        assert rep.registry.counter(scope,
                                    "shed-domain-d-s") == t.shed

    def test_breaker_sheds_kept_apart_from_quota_sheds(self):
        """A client-side breaker ServiceBusy never reached a host, so it
        must NOT count into `shed` (which the overload gate compares
        one-for-one against the server's quotas/shed counters) — it gets
        its own `shed_busy` bucket."""
        from cadence_tpu.utils.circuitbreaker import ServiceBusy

        class _BreakerClient:
            calls = 0

            def start_workflow_execution(self, *a, **k):
                _BreakerClient.calls += 1
                if _BreakerClient.calls % 2 == 0:
                    raise ServiceBusy("circuit open")

        plan = DomainPlan("d-b", 100, mix=START_ONLY, arrival="uniform")
        sched = build_schedule([plan], duration_s=0.1, seed=2)
        gen = LoadGenerator([_BreakerClient()], sched, [plan], workers=2)
        rep = gen.run()
        t = rep.totals()
        assert t.shed_busy == len(sched) // 2
        assert t.shed == 0 and t.errors == 0
        assert rep.registry.counter("loadgen.start",
                                    "shed-busy") == t.shed_busy
        assert rep.registry.counter("loadgen.start", "shed") == 0

    def test_unknown_exception_counted_by_type(self):
        class _Boom:
            def start_workflow_execution(self, *a, **k):
                raise RuntimeError("boom")
        plan = DomainPlan("d-e", 50, mix=START_ONLY, arrival="uniform")
        sched = build_schedule([plan], duration_s=0.1, seed=3)
        gen = LoadGenerator([_Boom()], sched, [plan], workers=2)
        rep = gen.run()
        t = rep.totals()
        assert t.errors == t.sent > 0
        assert rep.stats[(OP_START, "d-e")].error_types == {
            "RuntimeError": t.sent}


# -- SLO evaluation ---------------------------------------------------------

class TestSLO:
    def _report(self):
        plan = DomainPlan("d-slo", 100, mix=START_ONLY, arrival="uniform")
        sched = build_schedule([plan], duration_s=0.1, seed=4)
        gen = LoadGenerator([_StalledClient(0.0)], sched, [plan], workers=4)
        return gen.run()

    def test_slo_pass_and_violation(self):
        rep = self._report()
        ok = evaluate_slos(rep, [SLO(domain="d-slo", p99_ms=5000.0)])
        assert ok.ok and ok.checks and not ok.violations
        bad = evaluate_slos(rep, [SLO(domain="d-slo", p99_ms=0.0001)])
        assert not bad.ok
        assert [c.metric for c in bad.violations] == ["p99_ms"]
        assert bad.as_dict()["violations"] == 1

    def test_error_rate_excludes_sheds(self):
        plan = DomainPlan("d-s", 100, mix=START_ONLY, arrival="uniform")
        sched = build_schedule([plan], duration_s=0.1, seed=5)
        gen = LoadGenerator([_SheddingClient()], sched, [plan], workers=1)
        rep = gen.run()
        # half the traffic shed, ZERO errors: a 1% error SLO still holds
        out = evaluate_slos(rep, [SLO(max_error_rate=0.01)])
        assert out.ok

    def test_slo_slice_matching(self):
        s = SLO(op=OP_SIGNAL, domain="d-x", p50_ms=1)
        assert s.matches(OP_SIGNAL, "d-x")
        assert not s.matches(OP_SIGNAL, "d-y")
        assert not s.matches(OP_QUERY, "d-x")
        assert SLO().matches(OP_QUERY, "anything")


# -- trajectory files -------------------------------------------------------

class TestTrajectory:
    def test_numbering_and_schema(self, tmp_path):
        root = str(tmp_path)
        assert report_mod.latest_trajectory_path(root) is None
        p1 = report_mod.write_trajectory({"ok": True}, root=root)
        assert p1.endswith("LOADGEN_r01.json")
        p2 = report_mod.write_trajectory({"ok": True}, root=root)
        assert p2.endswith("LOADGEN_r02.json")
        assert report_mod.latest_trajectory_path(root) == p2
        import json
        doc = json.load(open(p1))
        assert doc["schema"] == report_mod.SCHEMA


# -- in-process integration (Onebox) ---------------------------------------

class TestOneboxIntegration:
    def test_mixed_traffic_runs_and_verifies(self):
        """The full generator loop against an in-process cluster: seeded
        pools, every op kind executing, latency percentiles recorded per
        domain, oracle<->device verify green over the traffic's
        output."""
        from cadence_tpu.engine.onebox import Onebox
        box = Onebox(num_hosts=1, num_shards=4)
        plans = [DomainPlan("lg-ob-a", 12, pool_size=3),
                 DomainPlan("lg-ob-b", 12, pool_size=3)]
        sched = build_schedule(plans, duration_s=1.5, seed=6)
        gen = LoadGenerator([box.frontend], sched, plans, workers=8,
                            pump=box.pump_once)
        gen.prepare(setup_deadline_s=30.0)
        completers = DecisionCompleters(
            lambda: box.frontend, [p.domain for p in plans],
            per_domain=1, poll_wait=0.05)
        completers.start()
        try:
            rep = gen.run()
        finally:
            completers.stop()
        # bounded pump: cron churn re-schedules forever and unpolled
        # signal-with-start decisions park in matching, so the box never
        # fully quiesces — verify does not need it to
        for _ in range(50):
            box.pump_once()
        t = rep.totals()
        assert t.sent == len(sched) > 20
        # nothing sheds (no quotas configured) and errors stay rare
        # (signal/reset races on pool workflows are tolerated noise)
        assert t.shed == 0
        assert t.errors <= 0.1 * t.sent
        for plan in plans:
            pct = rep.percentiles(OP_START, domain=plan.domain)
            assert 0 <= pct["p50"] <= pct["p999"] < 60
        assert rep.trace_digest == trace_digest(sched)
        assert box.tpu.verify_all().ok

    def test_quota_sheds_surface_on_both_sides(self):
        """Client-observed sheds == server quotas/shed counters, and the
        victim domain stays un-shed (per-domain stage isolation)."""
        from cadence_tpu.engine.onebox import Onebox
        from cadence_tpu.utils import metrics as m
        from cadence_tpu.utils.dynamicconfig import (
            KEY_FRONTEND_DOMAIN_RPS,
            DynamicConfig,
        )
        cfg = DynamicConfig()
        cfg.set(KEY_FRONTEND_DOMAIN_RPS, 2, domain="lg-hot")
        box = Onebox(num_hosts=1, num_shards=4, config=cfg)
        plans = [DomainPlan("lg-hot", 40, mix=START_ONLY,
                            arrival="uniform", pool_size=1),
                 DomainPlan("lg-cool", 5, mix=START_ONLY,
                            arrival="uniform", pool_size=1)]
        sched = build_schedule(plans, duration_s=1.0, seed=7)
        gen = LoadGenerator([box.frontend], sched, plans, workers=8,
                            pump=box.pump_once)
        gen.prepare(setup_deadline_s=30.0)
        rep = gen.run()
        hot, cool = rep.totals("lg-hot"), rep.totals("lg-cool")
        assert hot.shed > 0 and hot.errors == 0
        assert cool.shed == 0 and cool.ok == cool.sent
        shed_srv = box.metrics.counter(m.SCOPE_QUOTAS, m.M_QUOTA_SHED)
        # prepare()'s seed starts can also shed; the generator's view is
        # a lower bound, the per-domain split pins the victim at zero
        assert shed_srv >= hot.shed
        assert box.metrics.counter(
            m.SCOPE_QUOTAS,
            m.domain_metric(m.M_QUOTA_SHED, "lg-cool")) == 0
        assert box.metrics.counter(
            m.SCOPE_QUOTAS,
            m.domain_metric(m.M_QUOTA_ADMITTED, "lg-cool")) >= cool.ok


class TestScenarioValidation:
    def test_subtoken_per_host_quota_rejected_before_launch(self):
        """aggressor_quota_rps / num_hosts < 1 makes every per-host
        bucket's capacity (burst=rps alias) smaller than one token —
        permanently unadmittable. The scenario must refuse loudly up
        front instead of hanging through prepare()'s setup deadline."""
        with pytest.raises(ValueError, match="below one token"):
            scenarios.overload_scenario(aggressor_quota_rps=1.0,
                                        num_hosts=2)


# -- the wire-cluster overload gate ----------------------------------------

@pytest.mark.load
class TestOverloadGate:
    def test_overload_sheds_aggressor_victim_p99_holds(self):
        """The acceptance bar (deploy/smoke_load.sh): 2-host wire
        cluster, aggressor at 2x quota, victim on the standard mix,
        seeded wire chaos in every process AND seeded store faults in
        the store-server process (the ROADMAP item 4 headroom: chaos was
        wire-level only). Pass iff the victim's p99 holds its SLO,
        >= 90% of aggressor overflow sheds as typed ServiceBusy visible
        on /metrics, and every completed workflow verifies
        oracle<->device with zero divergence."""
        duration = float(os.environ.get("LOADGEN_DURATION_S", "8"))
        seed = int(os.environ.get("LOADGEN_SEED", "20260803"))
        doc = scenarios.overload_scenario(
            duration_s=duration, seed=seed,
            chaos_spec=scenarios.DEFAULT_CHAOS_SPEC,
            store_fault_spec=scenarios.DEFAULT_STORE_FAULT_SPEC)
        adm = doc["admission"]
        agg = adm["aggressor"]
        assert agg["shed"] > 0, doc
        assert agg["shed_ratio_of_overflow"] >= 0.9, adm
        # server-side counters agree with the client-observed sheds —
        # over the measured window only (prepare-time sheds are retried
        # client-side and excluded via the post-prepare baseline)
        assert adm["scrape"]["shed_total_run"] == agg["shed"], adm
        assert adm["scrape"]["prometheus_has_shed"]
        # every shed carried a usable backoff hint
        assert adm["max_retry_after_s"] > 0
        # victim untouched by the aggressor's quota
        assert adm["victim"]["shed"] == 0
        assert doc["slo"]["ok"], doc["slo"]
        assert doc["verify"]["divergent"] == 0, doc["verify"]
        assert doc["verify"]["completed_workflows"] > 0
        assert doc["ok"], doc
        # the recorded trace is reproducible from (plans, duration, seed)
        plans = [
            DomainPlan(scenarios.VICTIM_DOMAIN, 4.0, mix=STANDARD_MIX,
                       pool_size=6),
            DomainPlan(scenarios.AGGRESSOR_DOMAIN, 8.0,
                       mix=START_ONLY_MIX, pool_size=1),
        ]
        rebuilt = build_schedule(plans, duration, seed)
        assert doc["traffic"]["trace_digest"] == trace_digest(rebuilt)
