"""Decision attribute validation (VERDICT r2 weak #9; decision/checker.go).

Malformed decisions fail the decision task with a typed cause and the
worker re-decides; valid-but-sparse activity timeouts get the reference's
deduction/defaulting.
"""
import pytest

from cadence_tpu.core.enums import CloseStatus, DecisionType, EventType
from cadence_tpu.engine.checker import BadDecisionAttributes, validate_decision
from cadence_tpu.engine.history_engine import Decision
from cadence_tpu.engine.onebox import Onebox
from tests.taskpoller import TaskPoller

DOMAIN = "check-domain"
TL = "check-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


def _poll(box, wf):
    box.pump_once()
    resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
    assert resp is not None and resp.token.workflow_id == wf
    return resp


class TestValidator:
    def test_activity_requires_id(self):
        d = Decision(DecisionType.ScheduleActivityTask,
                     dict(schedule_to_close_timeout_seconds=30))
        with pytest.raises(BadDecisionAttributes) as err:
            validate_decision(d, 3600)
        assert err.value.cause == "BAD_SCHEDULE_ACTIVITY_ATTRIBUTES"

    def test_activity_negative_timeout_rejected(self):
        d = Decision(DecisionType.ScheduleActivityTask,
                     dict(activity_id="a",
                          schedule_to_close_timeout_seconds=-5))
        with pytest.raises(BadDecisionAttributes):
            validate_decision(d, 3600)

    def test_activity_no_deducible_timeout_rejected(self):
        d = Decision(DecisionType.ScheduleActivityTask,
                     dict(activity_id="a",
                          schedule_to_start_timeout_seconds=10))
        with pytest.raises(BadDecisionAttributes):
            validate_decision(d, 3600)

    def test_activity_timeout_deduction_from_s2c(self):
        """checker.go:287-293 — schedule-to-close fills the missing pair."""
        d = Decision(DecisionType.ScheduleActivityTask,
                     dict(activity_id="a",
                          schedule_to_close_timeout_seconds=30))
        validate_decision(d, 3600)
        assert d.attrs["schedule_to_start_timeout_seconds"] == 30
        assert d.attrs["start_to_close_timeout_seconds"] == 30

    def test_activity_timeout_deduction_sum_and_cap(self):
        """checker.go:294-299 — s2c = s2s + stc, capped at wf timeout."""
        d = Decision(DecisionType.ScheduleActivityTask,
                     dict(activity_id="a",
                          schedule_to_start_timeout_seconds=40,
                          start_to_close_timeout_seconds=50))
        validate_decision(d, 60)
        assert d.attrs["schedule_to_close_timeout_seconds"] == 60  # capped
        assert d.attrs["schedule_to_start_timeout_seconds"] == 40
        assert d.attrs["start_to_close_timeout_seconds"] == 50

    def test_timer_requires_positive_fire_timeout(self):
        d = Decision(DecisionType.StartTimer,
                     dict(timer_id="t", start_to_fire_timeout_seconds=0))
        with pytest.raises(BadDecisionAttributes) as err:
            validate_decision(d, 3600)
        assert err.value.cause == "BAD_START_TIMER_ATTRIBUTES"

    def test_child_and_signal_requirements(self):
        with pytest.raises(BadDecisionAttributes):
            validate_decision(Decision(
                DecisionType.StartChildWorkflowExecution,
                dict(workflow_type="t")), 3600)
        with pytest.raises(BadDecisionAttributes):
            validate_decision(Decision(
                DecisionType.SignalExternalWorkflowExecution,
                dict(workflow_id="w")), 3600)


class TestEngineIntegration:
    def test_bad_decision_fails_task_and_worker_retries(self, box):
        """A malformed decision produces DecisionTaskFailed with the typed
        cause (no transaction crash, no partial state); the retried
        decision completes the workflow."""
        box.frontend.start_workflow_execution(DOMAIN, "c-1", "t", TL)
        resp = _poll(box, "c-1")
        box.frontend.respond_decision_task_completed(
            resp.token,
            [Decision(DecisionType.ScheduleActivityTask,
                      dict(schedule_to_close_timeout_seconds=30))])
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "c-1")
        events = box.stores.history.read_events(domain_id, "c-1", run_id)
        failed = [e for e in events
                  if e.event_type == EventType.DecisionTaskFailed]
        assert len(failed) == 1
        assert failed[0].get("cause") == "BAD_SCHEDULE_ACTIVITY_ATTRIBUTES"
        # no activity was scheduled
        ms = box.stores.execution.get_workflow(domain_id, "c-1", run_id)
        assert not ms.pending_activity_info_ids

        # the transient retry dispatches; a good decision completes
        box.pump_once()
        resp2 = box.frontend.poll_for_decision_task(DOMAIN, TL)
        assert resp2 is not None
        box.frontend.respond_decision_task_completed(
            resp2.token, [Decision(DecisionType.CompleteWorkflowExecution, {})])
        ms = box.stores.execution.get_workflow(domain_id, "c-1", run_id)
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert box.tpu.verify_all().ok

    def test_deduced_timeouts_reach_the_scheduled_event(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "c-2", "t", TL,
                                              execution_timeout=120)
        resp = _poll(box, "c-2")
        box.frontend.respond_decision_task_completed(
            resp.token,
            [Decision(DecisionType.ScheduleActivityTask,
                      dict(activity_id="a", task_list=TL,
                           schedule_to_close_timeout_seconds=30))])
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "c-2")
        ms = box.stores.execution.get_workflow(domain_id, "c-2", run_id)
        ai = next(iter(ms.pending_activity_info_ids.values()))
        assert ai.schedule_to_start_timeout == 30
        assert ai.start_to_close_timeout == 30
        assert ai.schedule_to_close_timeout == 30
