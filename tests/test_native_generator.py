"""Native (C++) corpus generator parity (native/generator.cc).

The host-side bulk generator for tooling and CPU-cluster runs (the bench's
north star uses the DEVICE generator in ops/genkernel.py). Same contract:
distinct, reproducible, oracle-valid histories in the packed lane schema.
"""
import numpy as np
import pytest

from cadence_tpu.core.checksum import STICKY_ROW_INDEX, payload_row
from cadence_tpu.core.enums import EventType, WorkflowState
from cadence_tpu.native.gen_native import (
    generate_corpus_native,
    generator_available,
)
from cadence_tpu.ops.encode import decode_lanes
from cadence_tpu.oracle.state_builder import StateBuilder

pytestmark = pytest.mark.skipif(not generator_available(),
                                reason="no C++ toolchain")

W, E = 48, 200


@pytest.fixture(scope="module")
def corpus():
    lanes, total = generate_corpus_native(seed=5, first_index=0,
                                          num_workflows=W, max_events=E)
    return lanes, total


class TestNativeGenerator:
    def test_distinct_and_reproducible(self, corpus):
        lanes, total = corpus
        assert total > W * E // 2
        assert len({lanes[i].tobytes() for i in range(W)}) == W
        again, total2 = generate_corpus_native(5, 0, W, E)
        assert total2 == total and (again == lanes).all()

    def test_first_index_is_seamless(self, corpus):
        lanes, _ = corpus
        tail, _ = generate_corpus_native(5, 24, W - 24, E)
        assert (tail == lanes[24:]).all()

    def test_oracle_valid_and_device_parity(self, corpus):
        import jax.numpy as jnp

        from cadence_tpu.ops.replay import replay_to_payload

        lanes, _ = corpus
        rows, errors = map(np.asarray,
                           replay_to_payload(jnp.asarray(lanes)))
        assert (errors == 0).all()
        for i in range(0, W, 6):
            ms = StateBuilder().replay_history(decode_lanes(lanes[i]))
            expected = payload_row(ms)
            expected[STICKY_ROW_INDEX] = 0
            assert (rows[i] == expected).all(), f"workflow {i} diverged"
            assert ms.execution_info.state == WorkflowState.Completed
            assert not ms.pending_activity_info_ids
            assert not ms.pending_timer_info_ids

    def test_histories_close_cleanly(self, corpus):
        lanes, _ = corpus
        for i in range(W):
            real = lanes[i][lanes[i][:, 0] > 0]
            assert real[0][1] == int(EventType.WorkflowExecutionStarted)
            assert real[-1][1] == int(EventType.WorkflowExecutionCompleted)
