"""Device-side corpus generator (ops/genkernel.py).

The north-star bench's data source: distinct histories generated inside
the same scan that replays them. Contracts tested here:
- reproducible + distinct per (seed, workflow_index);
- the fused generate_and_replay path equals materialize-then-replay;
- generated histories are ORACLE-valid (decode → StateBuilder replay →
  payload parity with the device);
- chunking by first_index is seamless (chunked == one-shot).
"""
import numpy as np
import pytest

from cadence_tpu.core.checksum import STICKY_ROW_INDEX, payload_row
from cadence_tpu.core.enums import EventType, WorkflowState
from cadence_tpu.ops.encode import decode_lanes
from cadence_tpu.ops.genkernel import generate_and_replay, generate_lanes
from cadence_tpu.ops.replay import replay_to_payload
from cadence_tpu.oracle.state_builder import StateBuilder

W, E = 32, 120


@pytest.fixture(scope="module")
def lanes():
    return np.asarray(generate_lanes(42, 0, W, E))


class TestGenerator:
    def test_reproducible_and_distinct(self, lanes):
        again = np.asarray(generate_lanes(42, 0, W, E))
        assert (lanes == again).all()
        assert len({lanes[i].tobytes() for i in range(W)}) == W
        other_seed = np.asarray(generate_lanes(43, 0, W, E))
        assert not (lanes == other_seed).all()

    def test_every_slot_is_a_real_event(self, lanes):
        assert (lanes[:, :, 0] > 0).all()
        # ids are 1..E in order
        assert (lanes[:, :, 0] == np.arange(1, E + 1)[None, :]).all()

    def test_histories_start_and_close(self, lanes):
        assert (lanes[:, 0, 1] == int(EventType.WorkflowExecutionStarted)).all()
        assert (lanes[:, 1, 1] == int(EventType.DecisionTaskScheduled)).all()
        assert (lanes[:, -1, 1]
                == int(EventType.WorkflowExecutionCompleted)).all()

    def test_fused_equals_materialized(self, lanes):
        import jax.numpy as jnp

        rows_m, err_m = map(np.asarray,
                            replay_to_payload(jnp.asarray(lanes)))
        rows_f, err_f = map(np.asarray, generate_and_replay(42, 0, W, E))
        assert (err_m == 0).all() and (err_f == err_m).all()
        assert (rows_f == rows_m).all()

    def test_oracle_parity(self, lanes):
        rows, errors = map(np.asarray, generate_and_replay(42, 0, W, E))
        assert (errors == 0).all()
        for i in range(W):
            ms = StateBuilder().replay_history(decode_lanes(lanes[i]))
            expected = payload_row(ms)
            expected[STICKY_ROW_INDEX] = 0
            assert (rows[i] == expected).all(), f"workflow {i} diverged"
            assert ms.execution_info.state == WorkflowState.Completed
            # every pending entity resolved before the close
            assert not ms.pending_activity_info_ids
            assert not ms.pending_timer_info_ids
            assert not ms.pending_child_execution_info_ids

    def test_chunked_indices_are_seamless(self):
        """first_index chunking reproduces the one-shot stream: workflow w
        depends only on (seed, w), never on chunk boundaries."""
        whole, _ = map(np.asarray, generate_and_replay(7, 0, 16, E))
        lo, _ = map(np.asarray, generate_and_replay(7, 0, 8, E))
        hi, _ = map(np.asarray, generate_and_replay(7, 8, 8, E))
        assert (whole == np.concatenate([lo, hi])).all()

    def test_sharded_equals_single_device(self):
        """The bench's multi-chip path: shard_map over the 8-device mesh
        produces the identical rows/errors as the one-device kernel."""
        import jax

        from cadence_tpu.ops.genkernel import generate_and_replay_sharded
        from cadence_tpu.parallel.mesh import make_mesh

        devices = jax.devices()
        assert len(devices) >= 8  # conftest forces the CPU 8-device mesh
        mesh = make_mesh(devices[:8])
        rows_s, err_s = map(np.asarray,
                            generate_and_replay_sharded(11, 0, 64, E, mesh))
        rows_1, err_1 = map(np.asarray, generate_and_replay(11, 0, 64, E))
        assert (err_s == err_1).all()
        assert (rows_s == rows_1).all()

        with pytest.raises(ValueError):
            generate_and_replay_sharded(11, 0, 65, E, mesh)


def test_persistent_compile_cache_config_applied(tmp_path):
    """enable() must set the post-import jax config — the env var alone
    is frozen unread on hosts whose site bootstrap imports jax first
    (VERDICT r4 #7: every process paid the ~50s compile)."""
    import jax

    from cadence_tpu.utils import compile_cache

    before = jax.config.jax_compilation_cache_dir
    try:
        used = compile_cache.enable(str(tmp_path / "cache"))
        assert jax.config.jax_compilation_cache_dir == used
        assert (tmp_path / "cache").is_dir()
    finally:
        jax.config.update("jax_compilation_cache_dir", before)
