"""Device-resident incremental replay (ISSUE 6).

Covers: the from-state kernel family (ops/replay.replay_from_state*,
dense + wirec) replaying suffixes byte-identically to full-history
replay; ResidentStateCache content-address semantics (exact / suffix /
stale), LRU eviction under the HBM budget, and invalidation on tail
overwrite / reset / NDC branch switch through verify_all; the
capacity-escalation ladder widening a resident state on an overflowing
append and re-narrowing it once the load drains; the pipelined executor
packing only suffix batches at depth >= 2; the rebuilder's resident
consult; and the tpu.resident/* metrics surface.
"""
import random

import numpy as np
import pytest

from cadence_tpu.core.checksum import (
    DEFAULT_LAYOUT,
    STICKY_ROW_INDEX,
    crc32_of_rows,
    payload_row,
)
from cadence_tpu.core.enums import EventType
from cadence_tpu.engine.cache import (
    ContentAddress,
    address_relation,
    content_address,
)
from cadence_tpu.engine.ladder import EscalationLadder
from cadence_tpu.engine.resident import ResidentStateCache
from cadence_tpu.gen.corpus import generate_corpus
from cadence_tpu.ops.encode import assemble_corpus, encode_batches_resumable
from cadence_tpu.oracle.state_builder import StateBuilder
from cadence_tpu.utils import metrics as m

DOMAIN = "res-domain"
TL = "res-tl"


def _oracle_row(batches, layout=DEFAULT_LAYOUT):
    ms = StateBuilder().replay_history(batches)
    row = payload_row(ms, layout)
    row[STICKY_ROW_INDEX] = 0
    return row


def _replay_full(hists):
    """Full-history device replay -> (state, payload rows np)."""
    import jax.numpy as jnp

    from cadence_tpu.ops.payload import payload_rows
    from cadence_tpu.ops.replay import replay_events

    rows_list = [encode_batches_resumable(h)[0] for h in hists]
    corpus = assemble_corpus(rows_list,
                             max(r.shape[0] for r in rows_list))
    s = replay_events(jnp.asarray(corpus))
    return s, np.asarray(payload_rows(s))


def _seed_cache(cache, keys, prefix_hists):
    """Pin every workflow's prefix state (the cold-path admission the
    engine does from verify_all, done directly)."""
    s, rows = _replay_full(prefix_hists)
    branch = np.asarray(s.current_branch)
    assert (np.asarray(s.error) == 0).all()
    for i, key in enumerate(keys):
        assert cache.admit(key, content_address(prefix_hists[i]),
                           cache.extract_row(s, i), rows[i],
                           int(branch[i]))


# ---------------------------------------------------------------------------
# from-state kernels: suffix replay == full replay, dense and wirec
# ---------------------------------------------------------------------------


class TestFromStateKernels:
    @pytest.mark.parametrize("suite", ["basic", "timer_retry",
                                       "concurrent_child", "ndc"])
    def test_dense_suffix_parity_every_suite(self, suite):
        """replay_from_state over the appended batches must land on the
        exact payload bytes of a full-history replay — the correctness
        gate of the whole subsystem, per workload suite."""
        import jax.numpy as jnp

        from cadence_tpu.ops.replay import (
            replay_events,
            replay_from_state_to_payload,
        )

        hists = generate_corpus(suite, num_workflows=8, seed=11,
                                target_events=40)
        _, rows_full = _replay_full(hists)

        prefixes = [encode_batches_resumable(h[:-1]) for h in hists]
        pref = assemble_corpus([r for r, _ in prefixes],
                               max(r.shape[0] for r, _ in prefixes))
        s_pref = replay_events(jnp.asarray(pref))
        suffix_rows = [encode_batches_resumable(h[-1:], mp)[0]
                       for h, (_, mp) in zip(hists, prefixes)]
        suf = assemble_corpus(suffix_rows,
                              max(r.shape[0] for r in suffix_rows))
        _s, rows, err, ovf = replay_from_state_to_payload(
            jnp.asarray(suf), s_pref, DEFAULT_LAYOUT)
        assert (np.asarray(err) == 0).all()
        assert not np.asarray(ovf).any()
        assert (np.asarray(rows) == rows_full).all()
        for i, h in enumerate(hists):
            assert (np.asarray(rows)[i] == _oracle_row(h)).all()

    def test_wirec_suffix_crc_parity(self):
        """The compressed-wire variant: suffix packs as its own wirec
        corpus and the from-state CRC matches full replay bit for bit."""
        import jax.numpy as jnp

        from cadence_tpu.ops.replay import (
            replay_events,
            replay_wirec_from_state_to_crc,
        )
        from cadence_tpu.ops.wirec import pack_wirec

        hists = generate_corpus("echo_signal", num_workflows=6, seed=5,
                                target_events=32)
        _, rows_full = _replay_full(hists)
        crc_full = crc32_of_rows(rows_full)

        prefixes = [encode_batches_resumable(h[:-1]) for h in hists]
        pref = assemble_corpus([r for r, _ in prefixes],
                               max(r.shape[0] for r, _ in prefixes))
        s_pref = replay_events(jnp.asarray(pref))
        suffix_rows = [encode_batches_resumable(h[-1:], mp)[0]
                       for h, (_, mp) in zip(hists, prefixes)]
        suf = assemble_corpus(suffix_rows,
                              max(r.shape[0] for r in suffix_rows))
        wc = pack_wirec(suf)
        crc, err, ovf = replay_wirec_from_state_to_crc(
            jnp.asarray(wc.slab), jnp.asarray(wc.bases),
            jnp.asarray(wc.n_events), wc.profile, s_pref, DEFAULT_LAYOUT)
        assert (np.asarray(err) == 0).all()
        assert not np.asarray(ovf).any()
        assert (np.asarray(crc).astype(np.uint32) == crc_full).all()

    def test_wirec_suffix_payload_parity(self):
        """The payload twin of the compressed suffix path
        (replay_wirec_from_state_to_payload — the serving shape): wirec
        suffix from-state replay lands on the exact payload rows of the
        dense from-state replay and of a full-history replay."""
        import jax.numpy as jnp

        from cadence_tpu.ops.replay import (
            replay_events,
            replay_wirec_from_state_to_payload,
        )
        from cadence_tpu.ops.wirec import pack_wirec

        hists = generate_corpus("basic", num_workflows=6, seed=17,
                                target_events=32)
        _, rows_full = _replay_full(hists)
        prefixes = [encode_batches_resumable(h[:-1]) for h in hists]
        pref = assemble_corpus([r for r, _ in prefixes],
                               max(r.shape[0] for r, _ in prefixes))
        s_pref = replay_events(jnp.asarray(pref))
        suffix_rows = [encode_batches_resumable(h[-1:], mp)[0]
                       for h, (_, mp) in zip(hists, prefixes)]
        suf = assemble_corpus(suffix_rows,
                              max(r.shape[0] for r in suffix_rows))
        wc = pack_wirec(suf)
        _s, rows, err, ovf = replay_wirec_from_state_to_payload(
            jnp.asarray(wc.slab), jnp.asarray(wc.bases),
            jnp.asarray(wc.n_events), wc.profile, s_pref, DEFAULT_LAYOUT)
        assert (np.asarray(err) == 0).all()
        assert not np.asarray(ovf).any()
        assert (np.asarray(rows) == rows_full).all()

    def test_widen_then_suffix_replay_then_narrow(self):
        """A base state widened to 2K replays the suffix to the same
        base-width payload, and narrow_state round-trips it back."""
        import jax.numpy as jnp

        from cadence_tpu.ops.payload import payload_rows
        from cadence_tpu.ops.replay import (
            replay_events,
            replay_from_state_to_payload,
        )
        from cadence_tpu.ops.state import (
            layout_of,
            narrow_ok,
            narrow_state,
            widen_layout,
            widen_state,
        )

        hists = generate_corpus("timer_retry", num_workflows=5, seed=7,
                                target_events=36)
        _, rows_full = _replay_full(hists)
        prefixes = [encode_batches_resumable(h[:-1]) for h in hists]
        pref = assemble_corpus([r for r, _ in prefixes],
                               max(r.shape[0] for r, _ in prefixes))
        s_pref = replay_events(jnp.asarray(pref))
        wide = widen_layout(DEFAULT_LAYOUT, 2)
        s_wide = widen_state(s_pref, wide)
        assert layout_of(s_wide) == wide
        suffix_rows = [encode_batches_resumable(h[-1:], mp)[0]
                       for h, (_, mp) in zip(hists, prefixes)]
        suf = assemble_corpus(suffix_rows,
                              max(r.shape[0] for r in suffix_rows))
        s_fin, rows, err, _ovf = replay_from_state_to_payload(
            jnp.asarray(suf), s_wide, DEFAULT_LAYOUT)
        assert (np.asarray(err) == 0).all()
        assert (np.asarray(rows) == rows_full).all()
        assert np.asarray(narrow_ok(s_fin, DEFAULT_LAYOUT)).all()
        s_narrow = narrow_state(s_fin, DEFAULT_LAYOUT)
        assert layout_of(s_narrow) == DEFAULT_LAYOUT
        assert (np.asarray(payload_rows(s_narrow)) == rows_full).all()


# ---------------------------------------------------------------------------
# content-address + cache unit semantics
# ---------------------------------------------------------------------------


class TestContentAddress:
    def test_relations(self):
        hists = generate_corpus("basic", num_workflows=1, seed=3,
                                target_events=24)
        h = hists[0]
        addr = content_address(h[:-1])
        assert addr == ContentAddress(len(h) - 1,
                                      content_address(h[:-1]).last_batch_crc)
        assert address_relation(addr, h[:-1]) == "exact"
        assert address_relation(addr, h) == "prefix"
        # fewer batches than cached: stale
        assert address_relation(content_address(h), h[:-1]) == "stale"
        # overwritten tail at the cached position: stale
        mutated = list(h[:-2]) + [h[-1]]
        assert address_relation(addr, mutated) == "stale"

    def test_packcache_and_resident_share_the_helper(self):
        """The drift guard: both caches must address through the SAME
        functions (no private copies of the tuple logic)."""
        import inspect

        from cadence_tpu.engine import cache as cache_mod
        from cadence_tpu.engine import resident as resident_mod

        src_pack = inspect.getsource(cache_mod.PackCache)
        src_res = inspect.getsource(resident_mod.ResidentStateCache)
        assert "address_relation" in src_pack
        assert "address_relation" in src_res or \
            "address_relation" in inspect.getsource(
                resident_mod.ResidentStateCache.lookup)
        assert "_batch_crc" not in src_pack  # the old private copy is gone


class TestResidentCacheUnit:
    def _cache(self, **kw):
        kw.setdefault("ladder", EscalationLadder(DEFAULT_LAYOUT))
        return ResidentStateCache(DEFAULT_LAYOUT, **kw)

    def test_lookup_exact_suffix_stale(self):
        cache = self._cache()
        hists = generate_corpus("basic", num_workflows=2, seed=13,
                                target_events=24)
        keys = [("d", f"w{i}", "r") for i in range(2)]
        _seed_cache(cache, keys, [h[:-1] for h in hists])
        reg = cache.metrics

        kind, entry = cache.lookup(keys[0], hists[0][:-1])
        assert kind == "exact"
        assert (entry.payload == _oracle_row(hists[0][:-1])).all()
        kind, _ = cache.lookup(keys[0], hists[0])
        assert kind == "suffix"
        assert reg.counter(m.SCOPE_TPU_RESIDENT, m.M_CACHE_HITS) == 1
        assert reg.counter(m.SCOPE_TPU_RESIDENT,
                           m.M_RESIDENT_SUFFIX_HITS) == 1

        # tail overwrite: stale -> entry invalidated, then a clean miss
        mutated = list(hists[1][:-2]) + [hists[1][-1]]
        assert cache.lookup(keys[1], mutated) is None
        assert reg.counter(m.SCOPE_TPU_RESIDENT,
                           m.M_CACHE_INVALIDATIONS) == 1
        assert cache.lookup(keys[1], hists[1][:-1]) is None  # dropped
        assert reg.counter(m.SCOPE_TPU_RESIDENT, m.M_CACHE_MISSES) == 2

        # non-authoritative prefix lookups (rebuild at a reset point)
        # must NOT invalidate the entry
        assert cache.lookup(keys[0], hists[0][:1],
                            authoritative=False) is None
        assert cache.lookup(keys[0], hists[0][:-1])[0] == "exact"

    def test_lru_eviction_at_budget(self):
        probe = self._cache()
        row_bytes = probe._row_nbytes(DEFAULT_LAYOUT)
        cache = self._cache(budget_bytes=3 * row_bytes + 1)
        hists = generate_corpus("basic", num_workflows=5, seed=17,
                                target_events=20)
        keys = [("d", f"w{i}", "r") for i in range(5)]
        _seed_cache(cache, keys, [h[:-1] for h in hists])
        assert len(cache) == 3
        assert cache.resident_bytes <= cache.budget_bytes
        reg = cache.metrics
        assert reg.counter(m.SCOPE_TPU_RESIDENT, m.M_CACHE_EVICTIONS) == 2
        # LRU order: the first two admitted were evicted
        assert cache.lookup(keys[0], hists[0][:-1]) is None
        assert cache.lookup(keys[4], hists[4][:-1])[0] == "exact"
        assert reg.gauge_value(m.SCOPE_TPU_RESIDENT,
                               m.M_RESIDENT_BYTES) == cache.resident_bytes
        assert reg.gauge_value(m.SCOPE_TPU_RESIDENT,
                               m.M_RESIDENT_ENTRIES) == 3

    def test_oversized_budget_rejects_admission(self):
        cache = self._cache(budget_bytes=16)  # smaller than any row
        hists = generate_corpus("basic", num_workflows=1, seed=19,
                                target_events=20)
        s, rows = _replay_full([hists[0][:-1]])
        assert not cache.admit(("d", "w", "r"),
                               content_address(hists[0][:-1]),
                               cache.extract_row(s, 0), rows[0], 0)
        assert len(cache) == 0

    def test_replay_append_parity_and_readdress(self):
        cache = self._cache()
        hists = generate_corpus("concurrent_child", num_workflows=4,
                                seed=23, target_events=40)
        keys = [("d", f"w{i}", "r") for i in range(4)]
        _seed_cache(cache, keys, [h[:-1] for h in hists])
        items = [(k, cache.lookup(k, h)[1], h)
                 for k, h in zip(keys, hists)]
        results = cache.replay_append(items)
        for h, res in zip(hists, results):
            assert res.ok and not res.escalated
            assert (res.payload == _oracle_row(h)).all()
        # entries re-addressed at the full history: exact hits now
        for k, h in zip(keys, hists):
            assert cache.lookup(k, h)[0] == "exact"
        assert cache.last_append.events_appended == sum(
            len(h[-1].events) for h in hists)


# ---------------------------------------------------------------------------
# capacity escalation: widen on overflowing append, stay resident,
# re-narrow once the load drains
# ---------------------------------------------------------------------------


def _overflow_chain():
    """A 3-stage history: prefix pins 12 pending activities (fits the
    base K=16); append-1 schedules 10 more (transient 22 -> TABLE_OVERFLOW
    at base, fits 2K) and completes the 8 OLDEST (final 14 <= 16 but
    high table slots stay occupied -> not narrowable); append-2 completes
    the 6 activities sitting in the widened slots (narrowable again).
    Returns (prefix, after_append1, after_append2) batch lists."""
    from cadence_tpu.gen.corpus import (
        HistoryWriter,
        _begin_decision_completed_batch,
        _run_decision,
        _schedule_decision,
        _start,
    )

    w = HistoryWriter(workflow_id="ovf")
    _start(w, random.Random(0))
    cyc = _run_decision(w, 2)
    completed = _begin_decision_completed_batch(w, cyc)
    prefix_acts = [w.add(
        EventType.ActivityTaskScheduled, activity_id=f"p{i}",
        task_list=TL, schedule_to_start_timeout_seconds=60,
        schedule_to_close_timeout_seconds=120,
        start_to_close_timeout_seconds=60, heartbeat_timeout_seconds=0,
    ) for i in range(12)]
    sched = _schedule_decision(w, in_batch=True)
    w.end_batch()
    prefix = list(w.batches)

    def complete(act_ev):
        started = w.single(EventType.ActivityTaskStarted,
                           scheduled_event_id=act_ev.id,
                           request_id=f"poll-{act_ev.id}")
        w.begin_batch()
        w.add(EventType.ActivityTaskCompleted, scheduled_event_id=act_ev.id,
              started_event_id=started.id)
        w.end_batch()

    cyc = _run_decision(w, sched)
    _begin_decision_completed_batch(w, cyc)
    flood_acts = [w.add(
        EventType.ActivityTaskScheduled, activity_id=f"f{i}",
        task_list=TL, schedule_to_start_timeout_seconds=60,
        schedule_to_close_timeout_seconds=120,
        start_to_close_timeout_seconds=60, heartbeat_timeout_seconds=0,
    ) for i in range(10)]
    _schedule_decision(w, in_batch=True)
    w.end_batch()
    for ev in prefix_acts[:8]:  # oldest slots free; widened slots stay
        complete(ev)
    after_append1 = list(w.batches)

    # the 6 flood activities in widened slots (base indices >= 16 were
    # taken by flood acts 4..9) drain -> the state can re-narrow
    for ev in flood_acts[4:]:
        complete(ev)
    after_append2 = list(w.batches)
    return prefix, after_append1, after_append2


class TestResidentLadder:
    def test_overflowing_append_widens_and_renarrows(self):
        cache = ResidentStateCache(DEFAULT_LAYOUT,
                                   ladder=EscalationLadder(DEFAULT_LAYOUT))
        prefix, append1, append2 = _overflow_chain()
        key = ("d", "ovf", "r")
        _seed_cache(cache, [key], [prefix])
        reg = cache.metrics

        # append-1 overflows the base tables: the ladder widens the
        # RESIDENT state, replays only the suffix, stays resident widened
        items = [(key, cache.lookup(key, append1)[1], append1)]
        res = cache.replay_append(items)[0]
        assert res.ok and res.escalated and res.rung == 1
        assert (res.payload == _oracle_row(append1)).all()
        kind, entry = cache.lookup(key, append1)
        assert kind == "exact" and entry.rung == 1
        assert reg.counter(m.SCOPE_TPU_RESIDENT, m.M_RESIDENT_WIDENED) == 1
        assert reg.counter(m.SCOPE_TPU_FALLBACK, m.M_LADDER_RESOLVED) >= 1
        assert cache.stats()["widened_entries"] == 1

        # append-2 replays against the WIDENED resident state, drains the
        # widened slots, and the state re-narrows to the base footprint
        items = [(key, entry, append2)]
        res = cache.replay_append(items)[0]
        assert res.ok and res.rung == 0
        assert (res.payload == _oracle_row(append2)).all()
        kind, entry = cache.lookup(key, append2)
        assert kind == "exact" and entry.rung == 0
        assert reg.counter(m.SCOPE_TPU_RESIDENT,
                           m.M_RESIDENT_NARROWED) == 1
        assert cache.stats()["widened_entries"] == 0

    def test_no_ladder_falls_back_cleanly(self):
        cache = ResidentStateCache(DEFAULT_LAYOUT, ladder=None)
        prefix, append1, _ = _overflow_chain()
        key = ("d", "ovf", "r")
        _seed_cache(cache, [key], [prefix])
        items = [(key, cache.lookup(key, append1)[1], append1)]
        res = cache.replay_append(items)[0]
        assert not res.ok
        assert len(cache) == 0  # invalidated for oracle arbitration


# ---------------------------------------------------------------------------
# pipelined executor integration: suffix-only packing at depth >= 2
# ---------------------------------------------------------------------------


class TestExecutorIntegration:
    def test_suffix_chunks_through_pipeline_depth3(self):
        cache = ResidentStateCache(
            DEFAULT_LAYOUT, ladder=EscalationLadder(DEFAULT_LAYOUT),
            chunk_workflows=4, pipeline_depth=3)
        hists = generate_corpus("basic", num_workflows=12, seed=29,
                                target_events=48)
        keys = [("d", f"w{i}", "r") for i in range(12)]
        _seed_cache(cache, keys, [h[:-1] for h in hists])
        items = [(k, cache.lookup(k, h)[1], h)
                 for k, h in zip(keys, hists)]
        results = cache.replay_append(items)
        for h, res in zip(hists, results):
            assert res.ok
            assert (res.payload == _oracle_row(h)).all()
        # 12 items / chunk 4 = 3 chunks, each packed to the SUFFIX event
        # axis (pow2 floor 16), not the 48-event history
        shapes = cache.last_append.chunk_shapes
        assert len(shapes) == 3
        assert all(e <= 16 for _, e in shapes)

    def test_append_shapes_independent_of_history_length(self):
        """The O(new events) contract, structurally: appending equal-size
        suffixes to SHORT and LONG histories launches identical suffix
        corpus shapes — history length never enters the append cost."""
        shapes = {}
        for label, target in (("short", 24), ("long", 160)):
            cache = ResidentStateCache(
                DEFAULT_LAYOUT, ladder=EscalationLadder(DEFAULT_LAYOUT))
            hists = generate_corpus("basic", num_workflows=6, seed=31,
                                    target_events=target)
            keys = [("d", f"w{i}-{label}", "r") for i in range(6)]
            _seed_cache(cache, keys, [h[:-1] for h in hists])
            items = [(k, cache.lookup(k, h)[1], h)
                     for k, h in zip(keys, hists)]
            for h, res in zip(hists, cache.replay_append(items)):
                assert res.ok
                assert (res.payload == _oracle_row(h)).all()
            shapes[label] = cache.last_append.chunk_shapes
        assert shapes["short"] == shapes["long"]


# ---------------------------------------------------------------------------
# verify_all integration: invalidation on tail overwrite / reset / NDC
# ---------------------------------------------------------------------------


@pytest.fixture()
def box():
    from cadence_tpu.engine.onebox import Onebox
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


def _current_key(box, wf):
    domain_id = box.stores.domain.by_name(DOMAIN).domain_id
    run_id = box.stores.execution.get_current_run_id(domain_id, wf)
    return (domain_id, wf, run_id)


class TestVerifyAllResident:
    def test_tail_overwrite_invalidates_then_reverifies(self, box):
        """A retried-transaction tail overwrite (same event ids, new
        bytes) changes the last batch's CRC: the pinned entry must drop
        (counted) and the key re-verify through the full path — never
        served from the stale resident state."""
        import copy

        box.frontend.start_workflow_execution(DOMAIN, "wf-ow", "t", TL)
        box.frontend.signal_workflow_execution(DOMAIN, "wf-ow", "first")
        box.pump_once()
        key = _current_key(box, "wf-ow")
        assert box.tpu.verify_all().ok
        assert box.tpu.verify_all().resident  # pinned and serving

        # overwrite the tail batch in place: same ids and event types
        # (the live state's payload is unchanged — only the BYTES moved,
        # exactly what a retried transaction produces)
        batches = box.stores.history.read_batches(*key)
        tail = [copy.deepcopy(e) for e in batches[-1]]
        for e in tail:
            if e.event_type == EventType.WorkflowExecutionSignaled:
                e.attrs = dict(e.attrs, signal_name="rewritten")
        box.stores.history.append_batch(*key, tail)

        reg = box.metrics
        inval0 = reg.counter(m.SCOPE_TPU_RESIDENT, m.M_CACHE_INVALIDATIONS)
        result = box.tpu.verify_all()
        assert result.ok  # payload identical; bytes differ
        assert key not in result.resident
        assert reg.counter(m.SCOPE_TPU_RESIDENT,
                           m.M_CACHE_INVALIDATIONS) == inval0 + 1
        # re-seeded from the full replay: warm again
        assert key in box.tpu.verify_all().resident

    def test_reset_stays_byte_identical(self, box):
        """Reset rewrites the world (new run forked at the decision
        boundary, base run terminated): every key must still verify
        byte-identically — the resident cache may serve only what the
        content address proves unchanged."""
        from cadence_tpu.models.deciders import SignalDecider
        from tests.taskpoller import TaskPoller

        box.frontend.start_workflow_execution(DOMAIN, "wf-rst", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"wf-rst": SignalDecider(expected_signals=3)})
        poller.drain()
        key = _current_key(box, "wf-rst")
        box.frontend.signal_workflow_execution(DOMAIN, "wf-rst", "s-1")
        poller.drain()
        assert box.tpu.verify_all().ok  # pin pre-reset states

        new_run = box.frontend.reset_workflow_execution(
            DOMAIN, "wf-rst", decision_finish_event_id=4, run_id=key[2],
            reason="resident-test")
        result = box.tpu.verify_all()
        assert result.ok
        # the forked new run is a fresh key: it cannot have been served
        # from the cache on its first verify
        new_key = (key[0], "wf-rst", new_run)
        assert new_key not in result.resident
        # base run's termination append and the new run both verified;
        # a second pass serves everything resident
        result2 = box.tpu.verify_all()
        assert result2.ok
        assert len(result2.resident) == result2.total

    def test_ndc_branch_switch_invalidates(self, box):
        """An NDC branch switch (current-branch pointer moves) makes the
        pinned single-lineage state wrong: the entry must invalidate and
        the key route through the full tree path."""
        box.frontend.start_workflow_execution(DOMAIN, "wf-ndc", "t", TL)
        box.pump_once()
        key = _current_key(box, "wf-ndc")
        assert box.tpu.verify_all().ok
        assert key in box.tpu.verify_all().resident

        hs = box.stores.history
        last_id = hs.read_events(*key)[-1].id
        hs.fork_branch(*key, source_branch=0, fork_event_id=last_id)
        hs.set_current_branch(*key, 1)

        reg = box.metrics
        inval0 = reg.counter(m.SCOPE_TPU_RESIDENT, m.M_CACHE_INVALIDATIONS)
        result = box.tpu.verify_all()
        assert key not in result.resident
        assert reg.counter(m.SCOPE_TPU_RESIDENT,
                           m.M_CACHE_INVALIDATIONS) == inval0 + 1
        # the live state still points at branch 0: the device's branch
        # arbitration must surface the disagreement, not the stale cache
        assert key in result.divergent

    def test_disable_env_forces_full_path(self, box, monkeypatch):
        from cadence_tpu.engine import resident as resident_mod

        box.frontend.start_workflow_execution(DOMAIN, "wf-off", "t", TL)
        assert box.tpu.verify_all().ok
        monkeypatch.setenv(resident_mod.ENABLE_ENV, "0")
        result = box.tpu.verify_all()
        assert result.ok and not result.resident


# ---------------------------------------------------------------------------
# rebuilder consult
# ---------------------------------------------------------------------------


class TestRebuilderResident:
    def test_rebuild_exact_then_suffix(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-rb", "t", TL)
        box.pump_once()
        key = _current_key(box, "wf-rb")
        assert box.tpu.verify_all().ok  # pins the state

        batches = box.stores.history.as_history_batches(*key)
        before = box.rebuilder.stats.resident
        ms = box.rebuilder.rebuild_one(batches)
        assert box.rebuilder.stats.resident == before + 1
        expected = payload_row(
            StateBuilder().replay_history(batches), DEFAULT_LAYOUT)
        got = payload_row(ms, DEFAULT_LAYOUT)
        got[STICKY_ROW_INDEX] = expected[STICKY_ROW_INDEX]
        assert (got == expected).all()

        # appended batch: the rebuild replays only the suffix
        box.frontend.signal_workflow_execution(DOMAIN, "wf-rb", "go")
        box.pump_once()
        batches = box.stores.history.as_history_batches(*key)
        ms2 = box.rebuilder.rebuild_one(batches)
        assert box.rebuilder.stats.resident == before + 2
        assert ms2.execution_info.signal_count == 1
        reg = box.metrics
        assert reg.counter(m.SCOPE_TPU_RESIDENT,
                           m.M_RESIDENT_SUFFIX_HITS) >= 1

    def test_rebuild_prefix_does_not_invalidate(self, box):
        """Rebuild at a reset point passes a PREFIX of the stored
        history: the lookup is non-authoritative — the pinned entry must
        survive for the next full verify."""
        box.frontend.start_workflow_execution(DOMAIN, "wf-pre", "t", TL)
        box.frontend.signal_workflow_execution(DOMAIN, "wf-pre", "x")
        box.pump_once()
        key = _current_key(box, "wf-pre")
        assert box.tpu.verify_all().ok
        batches = box.stores.history.as_history_batches(*key)
        box.rebuilder.rebuild_one(batches[:1])  # prefix rebuild
        assert key in box.tpu.verify_all().resident  # still pinned


# ---------------------------------------------------------------------------
# metrics surface
# ---------------------------------------------------------------------------


class TestMetricsSurface:
    def test_prometheus_series(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-m", "t", TL)
        assert box.tpu.verify_all().ok   # cold: miss + seed
        box.frontend.signal_workflow_execution(DOMAIN, "wf-m", "go")
        assert box.tpu.verify_all().ok   # suffix hit
        assert box.tpu.verify_all().ok   # exact hit
        text = box.metrics.to_prometheus()
        for series in (
            'cadence_hits_total{scope="tpu.resident"}',
            'cadence_misses_total{scope="tpu.resident"}',
            'cadence_suffix_hits_total{scope="tpu.resident"}',
            'cadence_events_appended_total{scope="tpu.resident"}',
            'cadence_resident_bytes{scope="tpu.resident"}',
            'cadence_resident_entries{scope="tpu.resident"}',
            'cadence_budget_bytes{scope="tpu.resident"}',
        ):
            assert series in text, series

    def test_servicehost_preregisters_resident_series(self):
        """A fresh host's /metrics must already expose the tpu.resident
        names (scraped as zero before the first verify)."""
        import urllib.request

        from cadence_tpu.rpc.cluster import launch

        cluster = launch(num_hosts=1, num_shards=2)
        try:
            (_name, port), = cluster.http_ports.items()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                text = r.read().decode()
        finally:
            cluster.stop()
        assert 'cadence_invalidations_total{scope="tpu.resident"} 0' in text
        assert 'cadence_suffix_hits_total{scope="tpu.resident"} 0' in text
        assert 'cadence_resident_bytes{scope="tpu.resident"} 0' in text
        assert 'cadence_budget_bytes{scope="tpu.resident"} 0' in text


# ---------------------------------------------------------------------------
# admin surface
# ---------------------------------------------------------------------------


class TestAdminResident:
    def test_admin_resident_rollup(self, box):
        from cadence_tpu.engine.admin import AdminHandler

        box.frontend.start_workflow_execution(DOMAIN, "wf-adm", "t", TL)
        admin = AdminHandler(box)
        assert admin.verify().ok
        assert admin.verify().ok
        info = admin.resident()
        assert info["enabled"] is True
        assert info["entries"] == 1
        assert info["hits"] >= 1
        assert 0.0 < info["hit_rate"] <= 1.0
        assert info["resident_bytes"] > 0
        assert info["budget_bytes"] > 0
