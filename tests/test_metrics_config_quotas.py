"""Metrics + dynamic config + quotas (VERDICT ask #6).

Reference analogs: common/metrics (defs.go scopes), common/dynamicconfig
(~350 knobs consumed as closures), common/quotas/ratelimiter.go:43.
"""
import pytest

from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import CompleteDecider, SignalDecider
from cadence_tpu.utils import metrics as m
from cadence_tpu.utils.clock import ManualTimeSource
from cadence_tpu.utils.dynamicconfig import (
    KEY_FRONTEND_DOMAIN_RPS,
    KEY_FRONTEND_RPS,
    KEY_MAX_ACTIVITIES,
    KEY_MAX_BRANCHES,
    DynamicConfig,
)
from cadence_tpu.utils.quotas import ServiceBusyError, TokenBucket
from tests.taskpoller import TaskPoller

DOMAIN = "metrics-domain"
TL = "metrics-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


class TestMetrics:
    def test_engine_transaction_counters_emit(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "m-1", "t", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"m-1": CompleteDecider()})
        poller.drain()
        assert box.metrics.counter(m.SCOPE_FRONTEND_START, m.M_REQUESTS) == 1
        assert box.metrics.counter(m.SCOPE_HISTORY_START_WORKFLOW,
                                   m.M_REQUESTS) == 1
        assert box.metrics.counter(m.SCOPE_HISTORY_DECISION_COMPLETED,
                                   m.M_REQUESTS) >= 1
        assert box.metrics.counter(m.SCOPE_QUEUE_TRANSFER,
                                   m.M_TASKS_PROCESSED) >= 1

    def test_buffered_flush_counter(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "m-2", "signal", TL)
        box.pump_once()
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        box.frontend.signal_workflow_execution(DOMAIN, "m-2", "s")
        box.frontend.respond_decision_task_completed(resp.token, [])
        assert box.metrics.counter(m.SCOPE_HISTORY_DECISION_COMPLETED,
                                   m.M_BUFFERED_FLUSHED) == 1

    def test_replay_throughput_and_kernel_metrics_emit(self, box):
        """verify_all records kernel launches, events replayed, and a
        replay-throughput gauge (the VERDICT 'Done' criterion)."""
        box.frontend.start_workflow_execution(DOMAIN, "m-3", "t", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"m-3": CompleteDecider()})
        poller.drain()
        assert box.tpu.verify_all().ok
        assert box.metrics.counter(m.SCOPE_TPU_REPLAY, m.M_KERNEL_LAUNCHES) >= 1
        assert box.metrics.counter(m.SCOPE_TPU_REPLAY, m.M_EVENTS_REPLAYED) > 0
        assert box.metrics.gauge_value(m.SCOPE_TPU_REPLAY,
                                       m.M_REPLAY_THROUGHPUT) > 0

    def test_fallback_rate_gauge_emits(self, box):
        """A reset runs the device rebuilder, which publishes the
        fallback-rate gauge (0.0 when everything stayed on device)."""
        box.frontend.start_workflow_execution(DOMAIN, "m-4", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"m-4": SignalDecider(expected_signals=2)})
        poller.drain()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "m-4")
        box.frontend.reset_workflow_execution(
            DOMAIN, "m-4", decision_finish_event_id=4, run_id=run_id)
        assert box.metrics.counter(m.SCOPE_REBUILD, m.M_DEVICE_REBUILDS) >= 1
        assert box.metrics.gauge_value(m.SCOPE_REBUILD, m.M_FALLBACK_RATE,
                                       default=-1.0) == 0.0
        snap = box.metrics.snapshot()
        assert m.SCOPE_REBUILD in snap and m.SCOPE_TPU_REPLAY not in ("",)


class TestDynamicConfig:
    def test_payload_layout_tunable_without_code_edits(self):
        cfg = DynamicConfig({KEY_MAX_ACTIVITIES: 32, KEY_MAX_BRANCHES: 4})
        box = Onebox(num_hosts=1, num_shards=2, config=cfg)
        assert box.tpu.layout.max_activities == 32
        assert box.tpu.layout.max_branches == 4
        assert box.rebuilder.layout.max_activities == 32

    def test_live_update_via_closure(self):
        cfg = DynamicConfig()
        prop = cfg.int_property(KEY_FRONTEND_RPS)
        assert prop() == 0
        cfg.set(KEY_FRONTEND_RPS, 7)
        assert prop() == 7  # consumers see updates without rebuilds

    def test_domain_filter_precedence(self):
        cfg = DynamicConfig({KEY_FRONTEND_DOMAIN_RPS: 10})
        cfg.set(KEY_FRONTEND_DOMAIN_RPS, 3, domain="hot-domain")
        assert cfg.get(KEY_FRONTEND_DOMAIN_RPS, domain="hot-domain") == 3
        assert cfg.get(KEY_FRONTEND_DOMAIN_RPS, domain="other") == 10


class TestQuotas:
    def test_token_bucket_refills_with_clock(self):
        clock = ManualTimeSource()
        tb = TokenBucket(clock, rps=2, burst=2)
        assert tb.allow() and tb.allow()
        assert not tb.allow()  # burst exhausted
        clock.advance(500_000_000)  # 0.5s → one token back
        assert tb.allow()
        assert not tb.allow()

    def test_over_limit_start_rejected_cleanly(self):
        cfg = DynamicConfig({KEY_FRONTEND_RPS: 2})
        box = Onebox(num_hosts=1, num_shards=2, config=cfg)
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "q-1", "t", TL)
        box.frontend.start_workflow_execution(DOMAIN, "q-2", "t", TL)
        with pytest.raises(ServiceBusyError):
            box.frontend.start_workflow_execution(DOMAIN, "q-3", "t", TL)
        assert box.metrics.counter(m.SCOPE_FRONTEND_START,
                                   m.M_RATE_LIMITED) == 1
        # nothing was persisted for the rejected start
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        assert len([k for k in box.stores.execution.list_executions()
                    if k[1] == "q-3"]) == 0
        # tokens refill with time → admitted again
        box.clock.advance(1_000_000_000)
        box.frontend.start_workflow_execution(DOMAIN, "q-3", "t", TL)

    def test_per_domain_limit(self):
        cfg = DynamicConfig()
        cfg.set(KEY_FRONTEND_DOMAIN_RPS, 1, domain="limited")
        box = Onebox(num_hosts=1, num_shards=2, config=cfg)
        box.frontend.register_domain("limited")
        box.frontend.register_domain("free")
        box.frontend.start_workflow_execution("limited", "a", "t", TL)
        with pytest.raises(ServiceBusyError):
            box.frontend.start_workflow_execution("limited", "b", "t", TL)
        # other domains unaffected
        for i in range(5):
            box.frontend.start_workflow_execution("free", f"f-{i}", "t", TL)
