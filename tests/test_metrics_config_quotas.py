"""Metrics + dynamic config + quotas (VERDICT ask #6).

Reference analogs: common/metrics (defs.go scopes), common/dynamicconfig
(~350 knobs consumed as closures), common/quotas/ratelimiter.go:43.
"""
import pytest

from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import CompleteDecider, SignalDecider
from cadence_tpu.utils import metrics as m
from cadence_tpu.utils.clock import ManualTimeSource
from cadence_tpu.utils.dynamicconfig import (
    KEY_FRONTEND_DOMAIN_RPS,
    KEY_FRONTEND_RPS,
    KEY_MAX_ACTIVITIES,
    KEY_MAX_BRANCHES,
    DynamicConfig,
)
from cadence_tpu.utils.quotas import (
    NANOS,
    Collection,
    MultiStageRateLimiter,
    ServiceBusyError,
    TokenBucket,
    parse_quota_spec,
)
from tests.taskpoller import TaskPoller

DOMAIN = "metrics-domain"
TL = "metrics-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


class TestMetrics:
    def test_engine_transaction_counters_emit(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "m-1", "t", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"m-1": CompleteDecider()})
        poller.drain()
        assert box.metrics.counter(m.SCOPE_FRONTEND_START, m.M_REQUESTS) == 1
        assert box.metrics.counter(m.SCOPE_HISTORY_START_WORKFLOW,
                                   m.M_REQUESTS) == 1
        assert box.metrics.counter(m.SCOPE_HISTORY_DECISION_COMPLETED,
                                   m.M_REQUESTS) >= 1
        assert box.metrics.counter(m.SCOPE_QUEUE_TRANSFER,
                                   m.M_TASKS_PROCESSED) >= 1

    def test_buffered_flush_counter(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "m-2", "signal", TL)
        box.pump_once()
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        box.frontend.signal_workflow_execution(DOMAIN, "m-2", "s")
        box.frontend.respond_decision_task_completed(resp.token, [])
        assert box.metrics.counter(m.SCOPE_HISTORY_DECISION_COMPLETED,
                                   m.M_BUFFERED_FLUSHED) == 1

    def test_replay_throughput_and_kernel_metrics_emit(self, box):
        """verify_all records kernel launches, events replayed, and a
        replay-throughput gauge (the VERDICT 'Done' criterion)."""
        box.frontend.start_workflow_execution(DOMAIN, "m-3", "t", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"m-3": CompleteDecider()})
        poller.drain()
        assert box.tpu.verify_all().ok
        assert box.metrics.counter(m.SCOPE_TPU_REPLAY, m.M_KERNEL_LAUNCHES) >= 1
        assert box.metrics.counter(m.SCOPE_TPU_REPLAY, m.M_EVENTS_REPLAYED) > 0
        assert box.metrics.gauge_value(m.SCOPE_TPU_REPLAY,
                                       m.M_REPLAY_THROUGHPUT) > 0

    def test_fallback_rate_gauge_emits(self, box):
        """A reset runs the device rebuilder, which publishes the
        fallback-rate gauge (0.0 when everything stayed on device)."""
        box.frontend.start_workflow_execution(DOMAIN, "m-4", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"m-4": SignalDecider(expected_signals=2)})
        poller.drain()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "m-4")
        box.frontend.reset_workflow_execution(
            DOMAIN, "m-4", decision_finish_event_id=4, run_id=run_id)
        assert box.metrics.counter(m.SCOPE_REBUILD, m.M_DEVICE_REBUILDS) >= 1
        assert box.metrics.gauge_value(m.SCOPE_REBUILD, m.M_FALLBACK_RATE,
                                       default=-1.0) == 0.0
        snap = box.metrics.snapshot()
        assert m.SCOPE_REBUILD in snap and m.SCOPE_TPU_REPLAY not in ("",)


class TestDynamicConfig:
    def test_payload_layout_tunable_without_code_edits(self):
        cfg = DynamicConfig({KEY_MAX_ACTIVITIES: 32, KEY_MAX_BRANCHES: 4})
        box = Onebox(num_hosts=1, num_shards=2, config=cfg)
        assert box.tpu.layout.max_activities == 32
        assert box.tpu.layout.max_branches == 4
        assert box.rebuilder.layout.max_activities == 32

    def test_live_update_via_closure(self):
        cfg = DynamicConfig()
        prop = cfg.int_property(KEY_FRONTEND_RPS)
        assert prop() == 0
        cfg.set(KEY_FRONTEND_RPS, 7)
        assert prop() == 7  # consumers see updates without rebuilds

    def test_domain_filter_precedence(self):
        cfg = DynamicConfig({KEY_FRONTEND_DOMAIN_RPS: 10})
        cfg.set(KEY_FRONTEND_DOMAIN_RPS, 3, domain="hot-domain")
        assert cfg.get(KEY_FRONTEND_DOMAIN_RPS, domain="hot-domain") == 3
        assert cfg.get(KEY_FRONTEND_DOMAIN_RPS, domain="other") == 10


class TestQuotas:
    def test_token_bucket_refills_with_clock(self):
        clock = ManualTimeSource()
        tb = TokenBucket(clock, rps=2, burst=2)
        assert tb.allow() and tb.allow()
        assert not tb.allow()  # burst exhausted
        clock.advance(500_000_000)  # 0.5s → one token back
        assert tb.allow()
        assert not tb.allow()

    def test_over_limit_start_rejected_cleanly(self):
        cfg = DynamicConfig({KEY_FRONTEND_RPS: 2})
        box = Onebox(num_hosts=1, num_shards=2, config=cfg)
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "q-1", "t", TL)
        box.frontend.start_workflow_execution(DOMAIN, "q-2", "t", TL)
        with pytest.raises(ServiceBusyError):
            box.frontend.start_workflow_execution(DOMAIN, "q-3", "t", TL)
        assert box.metrics.counter(m.SCOPE_FRONTEND_START,
                                   m.M_RATE_LIMITED) == 1
        # nothing was persisted for the rejected start
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        assert len([k for k in box.stores.execution.list_executions()
                    if k[1] == "q-3"]) == 0
        # tokens refill with time → admitted again
        box.clock.advance(1_000_000_000)
        box.frontend.start_workflow_execution(DOMAIN, "q-3", "t", TL)

    def test_per_domain_limit(self):
        cfg = DynamicConfig()
        cfg.set(KEY_FRONTEND_DOMAIN_RPS, 1, domain="limited")
        box = Onebox(num_hosts=1, num_shards=2, config=cfg)
        box.frontend.register_domain("limited")
        box.frontend.register_domain("free")
        box.frontend.start_workflow_execution("limited", "a", "t", TL)
        with pytest.raises(ServiceBusyError):
            box.frontend.start_workflow_execution("limited", "b", "t", TL)
        # other domains unaffected
        for i in range(5):
            box.frontend.start_workflow_execution("free", f"f-{i}", "t", TL)

    def test_per_domain_series_capped_against_junk_domains(self):
        """_admit charges BEFORE domain validation, so the domain name in
        the per-domain metric series is request-supplied: a spray of junk
        names must stop growing the registry at the cap (totals keep
        counting) — the metrics side of quotas.Collection's no-leak
        guard."""
        box = Onebox(num_hosts=1, num_shards=2)
        fe = box.frontend
        fe.MAX_DOMAIN_SERIES = 3
        for i in range(10):
            with pytest.raises(Exception):  # EntityNotExist, post-admit
                fe.start_workflow_execution(f"junk-{i}", "w", "t", TL)
        per_domain = [name for name in box.metrics.snapshot()["quotas"]
                      if name.startswith("admitted-domain-")]
        assert len(per_domain) == 3
        assert box.metrics.counter(m.SCOPE_QUOTAS, "admitted") == 10


class TestTokenBucket:
    """Satellite: burst semantics + the non-consuming reserve/wait path
    (common/tokenbucket/tb.go), deterministic under ManualTimeSource."""

    def test_burst_zero_aliases_to_rps(self):
        clock = ManualTimeSource()
        tb = TokenBucket(clock, rps=5, burst=0)
        assert tb.burst == 5.0  # documented alias: one second's tokens
        assert TokenBucket(clock, rps=5, burst=2).burst == 2.0
        for _ in range(5):
            assert tb.try_consume()
        assert not tb.try_consume()

    def test_try_consume_n(self):
        clock = ManualTimeSource()
        tb = TokenBucket(clock, rps=4, burst=4)
        assert tb.try_consume(3)
        assert not tb.try_consume(2)  # only 1 left
        assert tb.try_consume(1)
        clock.advance(NANOS)  # 1s -> 4 tokens back
        assert tb.try_consume(4)

    def test_time_to_is_non_consuming(self):
        clock = ManualTimeSource()
        tb = TokenBucket(clock, rps=2, burst=2)
        assert tb.time_to() == 0.0
        assert tb.time_to() == 0.0  # asking twice consumed nothing
        assert tb.try_consume(2)
        assert tb.time_to(1) == pytest.approx(0.5)
        assert tb.time_to(2) == pytest.approx(1.0)
        # n beyond burst capacity can never be granted in one piece
        assert tb.time_to(3) == float("inf")

    def test_wait_deterministic_on_manual_clock(self):
        clock = ManualTimeSource()
        sleeps = []

        def manual_sleep(s):
            sleeps.append(s)
            clock.advance(int(s * NANOS))

        tb = TokenBucket(clock, rps=2, burst=2, sleep=manual_sleep)
        assert tb.try_consume(2)
        assert tb.wait(1)  # slept exactly the 0.5s deficit, then got it
        assert sleeps == pytest.approx([0.5])
        assert not tb.try_consume()  # wait() consumed the refilled token

    def test_wait_respects_deadline(self):
        clock = ManualTimeSource()
        tb = TokenBucket(clock, rps=1, burst=1,
                         sleep=lambda s: clock.advance(int(s * NANOS)))
        assert tb.try_consume()
        # 1 token needs 1s; deadline only 0.2s out -> refuse WITHOUT
        # sleeping (the clock must not advance)
        before = clock.now()
        assert not tb.wait(1, deadline=before + int(0.2 * NANOS))
        assert clock.now() == before
        # n > burst is unsatisfiable regardless of deadline
        assert not tb.wait(5, deadline=before + 100 * NANOS)

    def test_non_monotonic_clock_grants_nothing(self):
        clock = ManualTimeSource()
        tb = TokenBucket(clock, rps=10, burst=10)
        assert all(tb.try_consume() for _ in range(10))
        clock.advance(-5 * NANOS)  # NTP step-back
        assert not tb.try_consume()  # backwards time granted no tokens
        clock.advance(5 * NANOS)  # catch back up to the old reading
        # re-elapsed time must not be credited: still empty
        assert not tb.try_consume()
        clock.advance(NANOS // 10)  # genuinely new time -> 1 token
        assert tb.try_consume()
        assert not tb.try_consume()

    def test_unlimited_when_rps_zero(self):
        tb = TokenBucket(ManualTimeSource(), rps=0)
        assert all(tb.try_consume(100) for _ in range(50))
        assert tb.time_to(1000) == 0.0


class TestQuotaCollection:
    """Satellite: the per-domain collection under ManualTimeSource —
    deterministic refill, two-domain isolation, live-limit rebuild."""

    def test_deterministic_refill_per_domain(self):
        clock = ManualTimeSource()
        limits = {"hot": 2.0, "cold": 4.0}
        coll = Collection(clock, rps_for=lambda d: limits[d])
        assert [coll.allow("hot") for _ in range(3)] == [True, True, False]
        clock.advance(NANOS // 2)  # 0.5s: hot +1, cold untouched at 4
        assert coll.allow("hot")
        assert not coll.allow("hot")
        assert [coll.allow("cold") for _ in range(5)] == [
            True, True, True, True, False]

    def test_two_domain_isolation(self):
        clock = ManualTimeSource()
        coll = Collection(clock, rps_for=lambda d: 1.0)
        assert coll.allow("a")
        assert not coll.allow("a")  # a exhausted...
        assert coll.allow("b")      # ...b's bucket untouched

    def test_live_limit_change_rebuilds_bucket(self):
        clock = ManualTimeSource()
        limits = {"d": 1.0}
        coll = Collection(clock, rps_for=lambda d: limits[d])
        assert coll.allow("d")
        assert not coll.allow("d")
        limits["d"] = 3.0  # operator raises the limit
        # next request sees a fresh 3-rps bucket, no restart
        assert [coll.allow("d") for _ in range(4)] == [
            True, True, True, False]

    def test_multistage_admit_carries_retry_after(self):
        clock = ManualTimeSource()
        lim = MultiStageRateLimiter(clock, global_rps=lambda: 100,
                                    domain_rps=lambda d: 2,
                                    burst=lambda: 0)
        lim.admit("d")
        lim.admit("d")
        with pytest.raises(ServiceBusyError) as ei:
            lim.admit("d")
        assert ei.value.domain == "d"
        assert ei.value.retry_after_s == pytest.approx(0.5)
        assert "retry after" in str(ei.value)
        clock.advance(NANOS // 2)
        lim.admit("d")  # the hint was accurate

    def test_domain_stage_rejection_spares_global_bucket(self):
        """multistageratelimiter.go ordering: a hot domain's rejections
        must not drain the global stage for everyone else."""
        clock = ManualTimeSource()
        lim = MultiStageRateLimiter(clock, global_rps=lambda: 3,
                                    domain_rps=lambda d:
                                    2 if d == "hot" else 0,
                                    burst=lambda: 0)
        assert lim.allow("hot") and lim.allow("hot")
        for _ in range(10):
            assert not lim.allow("hot")  # hot-stage rejections
        # global stage still has its third token for the cold domain
        assert lim.allow("cold")

    def test_dynamicconfig_hot_update_takes_effect_without_restart(self):
        """Satellite acceptance: an operator config.set on a domain's
        RPS reaches the frontend's limiter mid-flight — the live closure
        rebuilds that domain's bucket on its next request."""
        cfg = DynamicConfig()
        cfg.set(KEY_FRONTEND_DOMAIN_RPS, 1, domain="tuned")
        box = Onebox(num_hosts=1, num_shards=2, config=cfg)
        box.frontend.register_domain("tuned")
        box.frontend.start_workflow_execution("tuned", "h-0", "t", TL)
        with pytest.raises(ServiceBusyError):
            box.frontend.start_workflow_execution("tuned", "h-1", "t", TL)
        cfg.set(KEY_FRONTEND_DOMAIN_RPS, 5, domain="tuned")  # hot update
        # the rebuilt bucket carries a fresh 5-token burst (burst=0
        # aliases to rps): five admits, then the sixth sheds
        for i in range(1, 6):
            box.frontend.start_workflow_execution("tuned", f"h-{i}",
                                                  "t", TL)
        with pytest.raises(ServiceBusyError):
            box.frontend.start_workflow_execution("tuned", "h-9", "t", TL)
        # and back down: the rebuilt bucket applies the new, lower limit
        cfg.set(KEY_FRONTEND_DOMAIN_RPS, 1, domain="tuned")
        box.clock.advance(1_000_000_000)
        box.frontend.start_workflow_execution("tuned", "h-10", "t", TL)
        with pytest.raises(ServiceBusyError):
            box.frontend.start_workflow_execution("tuned", "h-11", "t", TL)


class TestQuotaSpec:
    """Satellite: the CADENCE_TPU_QUOTAS per-host knob format."""

    def test_round_trip(self):
        g, b, d = parse_quota_spec(
            "rps=200, burst=50, domain.hot=20, domain.cold=80")
        assert (g, b) == (200.0, 50.0)
        assert d == {"hot": 20.0, "cold": 80.0}

    def test_empty_and_partial(self):
        assert parse_quota_spec("") == (0.0, 0.0, {})
        assert parse_quota_spec("domain.x=3") == (0.0, 0.0, {"x": 3.0})

    def test_malformed_rejected_loudly(self):
        with pytest.raises(ValueError):
            parse_quota_spec("rps")  # no '='
        with pytest.raises(ValueError):
            parse_quota_spec("domain.=5")  # empty domain
        with pytest.raises(ValueError):
            parse_quota_spec("rsp=5")  # typo'd key must not silently
            #                            admit everything
