"""Workflow shadowing (inventory row 37; service/worker/shadower):
recorded histories replayed against current decider code, nondeterminism
flagged decision-by-decision.
"""
import pytest

from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.engine.shadower import WorkflowShadower, shadow_history
from cadence_tpu.models.deciders import (
    ChainedActivityDecider,
    EchoDecider,
    TimerDecider,
)
from tests.taskpoller import TaskPoller

DOMAIN = "sh-domain"
TL = "sh-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


def run_workflow(box, wf, decider, wtype="echo"):
    box.frontend.start_workflow_execution(DOMAIN, wf, wtype, TL)
    TaskPoller(box, DOMAIN, TL, {wf: decider}).drain()
    domain_id = box.frontend.describe_domain(DOMAIN).domain_id
    return domain_id, box.stores.execution.get_current_run_id(domain_id, wf)


class TestShadower:
    def test_same_decider_shadows_clean(self, box):
        domain_id, run = run_workflow(box, "wf-s", EchoDecider(TL))
        result = WorkflowShadower(box.stores).shadow_workflow(
            domain_id, "wf-s", run, EchoDecider(TL))
        assert result.ok and result.decisions_checked >= 2

    def test_changed_decider_flags_nondeterminism(self, box):
        """Deploying TimerDecider over histories recorded by EchoDecider is
        exactly the break shadowing exists to catch."""
        domain_id, run = run_workflow(box, "wf-nd", EchoDecider(TL))
        result = WorkflowShadower(box.stores).shadow_workflow(
            domain_id, "wf-nd", run, TimerDecider(fire_seconds=5))
        assert not result.ok
        mismatch = result.mismatches[0]
        assert mismatch.decision_index == 0
        assert mismatch.expected != mismatch.recorded

    def test_multi_decision_chain_shadows_clean(self, box):
        decider = ChainedActivityDecider(TL, chain_length=3)
        domain_id, run = run_workflow(box, "wf-chain", decider, "basic")
        result = WorkflowShadower(box.stores).shadow_workflow(
            domain_id, "wf-chain", run,
            ChainedActivityDecider(TL, chain_length=3))
        assert result.ok and result.decisions_checked >= 4

    def test_shadow_query_sweeps_by_type(self, box):
        run_workflow(box, "wf-a", EchoDecider(TL), "echo")
        run_workflow(box, "wf-b", ChainedActivityDecider(TL, 2), "basic")
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        results = WorkflowShadower(box.stores).shadow_query(
            domain_id, "CloseStatus = 'Completed'",
            {"echo": EchoDecider(TL),
             "basic": ChainedActivityDecider(TL, 2)})
        assert len(results) == 2 and all(r.ok for r in results)

    def test_cron_continue_as_new_shadows_clean(self, box):
        """The engine translates a cron run's CompleteWorkflowExecution
        into ContinuedAsNew; shadowing must accept that translation
        (code-review r4)."""
        from cadence_tpu.models.deciders import CompleteDecider

        box.frontend.start_workflow_execution(DOMAIN, "wf-cron", "cron", TL,
                                              cron_schedule="@every 60s")
        TaskPoller(box, DOMAIN, TL, {"wf-cron": CompleteDecider()}).drain()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        # shadow the FIRST (continued-as-new) run
        runs = [r for (d, w, r) in box.stores.history.list_runs()
                if d == domain_id and w == "wf-cron"]
        shadower = WorkflowShadower(box.stores)
        results = [shadower.shadow_workflow(domain_id, "wf-cron", run,
                                            CompleteDecider())
                   for run in runs]
        closed = [r for r in results if r.decisions_checked >= 1]
        assert closed and all(r.ok for r in closed)
