"""Device replay of continue-as-new chains and divergent branch trees.

Round-3 kernel capabilities (VERDICT asks #4):
- a batch carrying new_run_events chains the new run into the same device
  row via FLAG_RUN_RESET (state_builder.go:446-520 newRunHistory analog);
- per-branch version-history tables + device-side fork inheritance and
  current-branch arbitration let a divergent NDC history replay end-to-end
  on device to the winning branch's state (conflict_resolver.go analog).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from cadence_tpu.core.checksum import DEFAULT_LAYOUT, PAD, payload_row
from cadence_tpu.core.enums import CloseStatus, EventType
from cadence_tpu.core.events import HistoryBatch, HistoryEvent
from cadence_tpu.engine.multicluster import ReplicatedClusters
from cadence_tpu.gen.corpus import HistoryWriter
from cadence_tpu.models.deciders import SignalDecider
from cadence_tpu.ops.encode import (
    encode_chain,
    encode_history,
    encode_segment_corpus,
    encode_segments,
)
from cadence_tpu.ops.payload import payload_rows
from cadence_tpu.ops.replay import replay_events
from cadence_tpu.oracle.mutable_state import MutableState
from cadence_tpu.oracle.state_builder import StateBuilder
from tests.taskpoller import TaskPoller

DOMAIN = "chain-domain"
TL = "chain-tl"


def _simple_run_events(w: HistoryWriter, close_type: EventType,
                       **close_attrs):
    """start → decision cycle → close, via the corpus writer."""
    w.begin_batch()
    w.add(EventType.WorkflowExecutionStarted,
          execution_start_to_close_timeout_seconds=60,
          task_start_to_close_timeout_seconds=10)
    w.add(EventType.DecisionTaskScheduled, start_to_close_timeout_seconds=10)
    w.end_batch()
    sched = w.next_id - 1
    w.begin_batch()
    started = w.add(EventType.DecisionTaskStarted, scheduled_event_id=sched)
    w.end_batch()
    w.begin_batch()
    w.add(EventType.DecisionTaskCompleted, scheduled_event_id=sched,
          started_event_id=started.id)
    return w


class TestContinueAsNewChain:
    def _make_chain_batches(self):
        """Run 1 closes ContinuedAsNew with the new run's first batch
        attached as new_run_events (the ApplyEvents input shape)."""
        w = _simple_run_events(HistoryWriter(), EventType.WorkflowExecutionContinuedAsNew)
        w.add(EventType.WorkflowExecutionContinuedAsNew,
              new_execution_run_id="run-2")
        w2 = HistoryWriter()
        w2.begin_batch()
        w2.add(EventType.WorkflowExecutionStarted,
               execution_start_to_close_timeout_seconds=60,
               task_start_to_close_timeout_seconds=10)
        w2.add(EventType.DecisionTaskScheduled, start_to_close_timeout_seconds=10)
        w2.end_batch()
        new_run_events = [e for b in w2.batches for e in b.events]
        w.end_batch(new_run_events=new_run_events)
        return w.batches

    def test_new_run_events_chain_in_one_row(self):
        batches = self._make_chain_batches()
        # oracle: the CAN batch spawns a fresh builder for the new run
        sb = StateBuilder(MutableState())
        for b in batches:
            sb.apply_batch(b)
        assert sb.ms.execution_info.close_status == CloseStatus.ContinuedAsNew
        assert sb.new_run_state is not None
        expected = payload_row(sb.new_run_state)

        events = encode_history(batches, max_events=16)[None]
        state = replay_events(jnp.asarray(events))
        assert int(state.error[0]) == 0
        got = np.asarray(payload_rows(state))[0]
        assert (got == expected).all(), np.nonzero(got != expected)

    def test_encode_chain_multiple_runs(self):
        """encode_chain packs a 3-run chain; final state == last run."""
        runs = []
        for i in range(3):
            w = _simple_run_events(
                HistoryWriter(), EventType.WorkflowExecutionContinuedAsNew)
            if i < 2:
                w.add(EventType.WorkflowExecutionContinuedAsNew,
                      new_execution_run_id=f"run-{i + 1}")
            else:
                w.add(EventType.WorkflowExecutionCompleted)
            w.end_batch()
            runs.append(w.batches)
        expected = payload_row(StateBuilder(MutableState()).replay_history(runs[-1]))
        events = encode_chain(runs, max_events=32)[None]
        state = replay_events(jnp.asarray(events))
        assert int(state.error[0]) == 0
        got = np.asarray(payload_rows(state))[0]
        assert (got == expected).all()

    def test_cron_chain_from_engine(self, ):
        """ENGINE-generated cron chain: every run of the chain encodes as
        one device row; the row's final payload matches the LAST run's live
        mutable state."""
        from cadence_tpu.engine.onebox import Onebox
        from cadence_tpu.models.deciders import CompleteDecider

        box = Onebox(num_hosts=1, num_shards=2)
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(
            DOMAIN, "cron-chain", "cron-type", TL, cron_schedule="* * * * *")
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_ids = []
        poller = TaskPoller(box, DOMAIN, TL, {"cron-chain": CompleteDecider()})
        for _ in range(3):
            run_ids.append(box.stores.execution.get_current_run_id(
                domain_id, "cron-chain"))
            poller.drain()
            box.advance_time(61)
            box.pump_once()
        final_run = box.stores.execution.get_current_run_id(
            domain_id, "cron-chain")
        assert final_run not in run_ids and len(set(run_ids)) == 3

        runs = [
            box.stores.history.as_history_batches(domain_id, "cron-chain", rid)
            for rid in run_ids
        ]
        total = sum(sum(len(b.events) for b in r) for r in runs)
        events = encode_chain(runs, max_events=total)[None]
        state = replay_events(jnp.asarray(events))
        assert int(state.error[0]) == 0
        live = box.stores.execution.get_workflow(
            domain_id, "cron-chain", run_ids[-1])
        got = np.asarray(payload_rows(state))[0]
        assert (got == payload_row(live)).all()


def _diverged_clusters():
    clusters = ReplicatedClusters(num_hosts=1, num_shards=4)
    clusters.register_global_domain(DOMAIN)
    box = clusters.active
    box.frontend.start_workflow_execution(DOMAIN, "split", "signal", TL)
    poller = TaskPoller(box, DOMAIN, TL,
                        {"split": SignalDecider(expected_signals=2)})
    poller.drain()
    clusters.replicate()
    domain_id = box.stores.domain.by_name(DOMAIN).domain_id
    run_id = box.stores.execution.get_current_run_id(domain_id, "split")

    clusters.split_brain_promote(DOMAIN)
    apoller = TaskPoller(clusters.active, DOMAIN, TL,
                         {"split": SignalDecider(expected_signals=2)})
    clusters.active.frontend.signal_workflow_execution(DOMAIN, "split", "a-1")
    apoller.drain()
    spoller = TaskPoller(clusters.standby, DOMAIN, TL,
                         {"split": SignalDecider(expected_signals=2)})
    clusters.standby.frontend.signal_workflow_execution(DOMAIN, "split", "b-1")
    clusters.standby.frontend.signal_workflow_execution(DOMAIN, "split", "b-2")
    spoller.drain()
    clusters.heal(DOMAIN, "standby")
    return clusters, (domain_id, "split", run_id)


class TestBranchTree:
    def test_divergent_tree_replays_on_device(self):
        """The full two-branch tree (winner current, loser retained)
        replays on device: payload parity + arbitration parity on both
        clusters."""
        clusters, key = _diverged_clusters()
        for box in (clusters.active, clusters.standby):
            ms = box.stores.execution.get_workflow(*key)
            assert len(ms.version_histories.histories) == 2
            rows, errors, branch = box.tpu.replay_tree_payloads([key])
            assert errors[0] == 0
            assert branch[0] == ms.version_histories.current_index
            assert (rows[0] == payload_row(ms)).all()

    def test_device_holds_loser_branch_items(self):
        """The device's non-current branch table matches the store's
        retained loser branch."""
        clusters, key = _diverged_clusters()
        box = clusters.active
        ms = box.stores.execution.get_workflow(*key)
        vhs = ms.version_histories
        loser_index = 1 - vhs.current_index

        from cadence_tpu.ops.encode import encode_segment_corpus
        corpus = encode_segment_corpus([box.tpu.tree_segments(key)])
        state = replay_events(jnp.asarray(corpus))
        assert int(state.error[0]) == 0
        loser = vhs.histories[loser_index]
        got_ids = np.asarray(state.vh_event_ids)[0, loser_index]
        got_versions = np.asarray(state.vh_versions)[0, loser_index]
        got_count = int(np.asarray(state.vh_count)[0, loser_index])
        assert got_count == len(loser.items)
        for i, item in enumerate(loser.items):
            assert got_ids[i] == item.event_id
            assert got_versions[i] == item.version

    def test_verify_all_checks_branch_arbitration(self):
        clusters, key = _diverged_clusters()
        for box in (clusters.active, clusters.standby):
            result = box.tpu.verify_all()
            assert result.ok
            assert result.verified_on_device == result.total

    def test_arrival_order_arbitration(self):
        """Device-side arbitration in ARRIVAL order: prefix then losing
        suffix (b0) then winning fork (b1) — the current pointer switches
        exactly when the higher-version suffix lands."""
        w = HistoryWriter()
        w.begin_batch()
        w.add(EventType.WorkflowExecutionStarted,
              execution_start_to_close_timeout_seconds=60,
              task_start_to_close_timeout_seconds=10, version=1)
        w.add(EventType.DecisionTaskScheduled,
              start_to_close_timeout_seconds=10, version=1)
        w.end_batch()
        prefix = w.batches
        for b in prefix:
            for e in b.events:
                e.version = 1

        def suffix(first_id, version, n=2):
            events = []
            for i in range(n):
                events.append(HistoryEvent(
                    id=first_id + i,
                    event_type=EventType.WorkflowExecutionSignaled,
                    version=version, timestamp=1000 + i))
            return [HistoryBatch(domain_id="d", workflow_id="w", run_id="r",
                                 events=events)]

        nid = prefix[-1].events[-1].id + 1
        losing = suffix(nid, version=1)
        winning = suffix(nid, version=12)

        # arrival order: prefix (state), losing suffix persisted VH-only to
        # b0, winning fork state-carrying on b1
        segs = [
            (prefix, 0, 0, False),
            (losing, 0, 0, True),
            (winning, 1, 0, False),
        ]
        events = encode_segments(segs, max_events=16)[None]
        state = replay_events(jnp.asarray(events))
        assert int(state.error[0]) == 0
        assert int(state.current_branch[0]) == 1
        # winner branch: fork item capped at the LCA + the v12 item
        ids = np.asarray(state.vh_event_ids)[0, 1]
        versions = np.asarray(state.vh_versions)[0, 1]
        assert (ids[0], versions[0]) == (nid - 1, 1)
        assert (ids[1], versions[1]) == (nid + 1, 12)
        # loser branch keeps its v1 run to nid+1
        ids0 = np.asarray(state.vh_event_ids)[0, 0]
        assert ids0[0] == nid + 1
        # signals applied: exactly the winning suffix's two
        assert int(state.signal_count[0]) == 2

    def test_lower_version_fork_stays_non_current(self):
        w = HistoryWriter()
        w.begin_batch()
        w.add(EventType.WorkflowExecutionStarted,
              execution_start_to_close_timeout_seconds=60,
              task_start_to_close_timeout_seconds=10)
        w.end_batch()
        prefix = w.batches
        for b in prefix:
            for e in b.events:
                e.version = 5
        nid = prefix[-1].events[-1].id + 1
        lower = [HistoryBatch(domain_id="d", workflow_id="w", run_id="r",
                              events=[HistoryEvent(
                                  id=nid,
                                  event_type=EventType.WorkflowExecutionSignaled,
                                  version=5, timestamp=99)])]
        cont = [HistoryBatch(domain_id="d", workflow_id="w", run_id="r",
                             events=[HistoryEvent(
                                 id=nid,
                                 event_type=EventType.WorkflowExecutionSignaled,
                                 version=6, timestamp=100)])]
        segs = [
            (prefix, 0, 0, False),
            (cont, 0, 0, False),      # local continues at higher version
            (lower, 1, 0, True),      # stale lower-version fork arrives late
        ]
        events = encode_segments(segs, max_events=16)[None]
        state = replay_events(jnp.asarray(events))
        assert int(state.error[0]) == 0
        assert int(state.current_branch[0]) == 0
        assert int(state.signal_count[0]) == 1
