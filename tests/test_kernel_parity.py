"""Differential tests: the JAX replay kernel must produce byte-identical
canonical checksum payloads to the Python oracle on every corpus suite —
the framework's analog of the north-star "zero mutable-state divergence vs
the Go stateBuilder" contract."""
import numpy as np
import pytest

from cadence_tpu.core.checksum import payload_row
from cadence_tpu.gen.corpus import SUITES, generate_corpus
from cadence_tpu.oracle.state_builder import StateBuilder
from cadence_tpu.ops.replay import replay_corpus


def oracle_rows(histories):
    return np.stack([
        payload_row(StateBuilder().replay_history(h)) for h in histories
    ])


@pytest.mark.parametrize("suite", SUITES)
def test_suite_parity(suite):
    histories = generate_corpus(suite, num_workflows=16, seed=11,
                                target_events=100)
    kernel, crcs, errors = replay_corpus(histories)
    assert (errors == 0).all(), f"kernel flagged errors: {errors}"
    expected = oracle_rows(histories)
    mismatch = np.nonzero((kernel != expected).any(axis=1))[0]
    if mismatch.size:
        w = int(mismatch[0])
        cols = np.nonzero(kernel[w] != expected[w])[0]
        raise AssertionError(
            f"suite={suite} workflow {w} diverges at payload cols {cols}: "
            f"kernel={kernel[w][cols]} oracle={expected[w][cols]}"
        )


def test_mixed_suites_one_batch():
    """Different suites padded into one ragged tensor replay correctly."""
    histories = []
    for suite in SUITES:
        histories.extend(generate_corpus(suite, num_workflows=3, seed=5,
                                         target_events=80))
    kernel, crcs, errors = replay_corpus(histories)
    assert (errors == 0).all()
    expected = oracle_rows(histories)
    assert (kernel == expected).all()
    # CRCs are per-row CRC32 of identical payloads
    from cadence_tpu.core.checksum import crc32_of_rows
    assert (crcs == crc32_of_rows(expected)).all()


def test_error_flag_on_corrupt_history():
    """A corrupted history freezes only that workflow; neighbors unaffected."""
    from cadence_tpu.core.enums import EventType
    histories = generate_corpus("basic", num_workflows=3, seed=2,
                                target_events=60)
    # corrupt workflow 1: point an activity completion at a bogus schedule id
    for b in histories[1]:
        for e in b.events:
            if e.event_type == EventType.ActivityTaskCompleted:
                e.attrs["scheduled_event_id"] = 9999
                break
    kernel, _, errors = replay_corpus(histories)
    assert errors[1] != 0
    assert errors[0] == 0 and errors[2] == 0
    expected0 = payload_row(StateBuilder().replay_history(histories[0]))
    assert (kernel[0] == expected0).all()


def test_ragged_lengths():
    """Histories of very different lengths in one padded batch."""
    histories = [
        generate_corpus("basic", 1, seed=s, target_events=n)[0]
        for s, n in [(1, 20), (2, 100), (3, 50), (4, 200)]
    ]
    kernel, _, errors = replay_corpus(histories)
    assert (errors == 0).all()
    expected = oracle_rows(histories)
    assert (kernel == expected).all()


class TestOverflowFallback:
    """The adversarial overflow suite (SURVEY §7 hard part 3): a planted
    fraction of workflows exceed the device pending tables; the device
    must FLAG exactly those (TABLE_OVERFLOW), replay the rest correctly,
    and the oracle leg must agree on every flagged workflow."""

    def test_device_flags_planted_overflows_and_oracle_covers(self):
        import jax.numpy as jnp
        import numpy as np

        from cadence_tpu.core.checksum import (
            DEFAULT_LAYOUT,
            STICKY_ROW_INDEX,
            crc32_of_row,
            payload_row,
        )
        from cadence_tpu.gen.corpus import generate_corpus
        from cadence_tpu.ops.encode import encode_corpus
        from cadence_tpu.ops.wirec import pack_wirec
        from cadence_tpu.ops.replay import replay_wirec_to_crc
        from cadence_tpu.oracle.state_builder import StateBuilder

        histories = generate_corpus("overflow", num_workflows=256, seed=3,
                                    target_events=100)
        ev = encode_corpus(histories)
        c = pack_wirec(ev)
        crc, errors = replay_wirec_to_crc(
            jnp.asarray(c.slab), jnp.asarray(c.bases),
            jnp.asarray(c.n_events), c.profile, DEFAULT_LAYOUT)
        crc, errors = (np.asarray(crc).astype(np.uint32),
                       np.asarray(errors))
        flagged = set(np.nonzero(errors != 0)[0].tolist())
        assert flagged, "no overflow planted — the suite is vacuous"
        assert len(flagged) < 256 // 4, "overflow fraction far too high"
        for i in range(256):
            ms = StateBuilder().replay_history(histories[i])
            row = payload_row(ms, DEFAULT_LAYOUT)
            row[STICKY_ROW_INDEX] = 0
            expect = np.uint32(crc32_of_row(row))
            if i in flagged:
                # flagged: the ORACLE leg is authoritative (and must
                # replay the over-capacity history fine — it has none)
                assert ms.execution_info.close_status != 0
            else:
                assert crc[i] == expect, f"unflagged workflow {i} diverged"
        # the planted shape is what got flagged: >capacity pending
        # activities at peak
        from cadence_tpu.core.enums import EventType
        for i in list(flagged)[:4]:
            pend = peak = 0
            for b in histories[i]:
                for e in b.events:
                    if e.event_type == EventType.ActivityTaskScheduled:
                        pend += 1
                        peak = max(peak, pend)
                    elif e.event_type == EventType.ActivityTaskCompleted:
                        pend -= 1
            assert peak > DEFAULT_LAYOUT.max_activities
