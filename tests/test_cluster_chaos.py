"""Fleet chaos campaigns (gen/cluster_chaos.py) + the asymmetric
partition layer (rpc/chaos.PartitionTable).

Tier-1 carries the campaign grammar/shrinker units, the partition-table
units, and TWO small live campaigns: a steady baseline-vs-chaos run
(1 SIGKILL + 1 asymmetric partition+heal, gated byte-identical) and a
single-launch storm run (reset/cron/retry churn, gated self-consistent:
fsck + parity + verify_all). The full acceptance sweep — 3 hosts, store
kill, membership flap, both profiles — rides behind `slow`
(deploy/smoke_fleetchaos.sh runs the recorded version).

WAL record kinds: the campaign engine introduces NONE. Partitions and
heals are runtime socket state (dialer-side PartitionTable through the
admin_partition wire op), never persisted; kills only truncate WAL
appends mid-record, which is exactly the surface the existing crashsim
cut matrix (tests/test_crashsim.py) already walks. The fsck-clean gates
on every killed store's recovered WAL are the campaign-level witness.
"""
import pytest

from cadence_tpu.gen.cluster_chaos import (
    FAULT_KINDS,
    WORKLOAD_KINDS,
    CampaignOp,
    build_campaign,
    cluster_campaign_scenario,
    injected_regression_predicate,
    pick_poison_wf,
    shrink_campaign,
)
from cadence_tpu.rpc.chaos import (
    ChaosError,
    PartitionTable,
    parse_partition_spec,
)


class TestPartitionTable:
    def test_block_is_asymmetric_and_heals(self):
        t = PartitionTable()
        t.block("10.0.0.1", 7001)
        assert t.is_blocked(("10.0.0.1", 7001))
        # asymmetry: only the exact (host, port) dial is severed
        assert not t.is_blocked(("10.0.0.1", 7002))
        assert not t.is_blocked(("10.0.0.2", 7001))
        t.heal("10.0.0.1", 7001)
        assert not t.is_blocked(("10.0.0.1", 7001))

    def test_wildcard_host_blocks_any_dial_to_port(self):
        t = PartitionTable()
        t.block("*", 7005)
        assert t.is_blocked(("127.0.0.1", 7005))
        assert t.is_blocked(("10.9.9.9", 7005))
        assert not t.is_blocked(("127.0.0.1", 7006))

    def test_check_raises_typed_chaos_error(self):
        t = PartitionTable()
        t.block("127.0.0.1", 7001)
        with pytest.raises(ChaosError, match="partition"):
            t.check(("127.0.0.1", 7001))
        # an unblocked endpoint passes silently
        t.check(("127.0.0.1", 7002))

    def test_heal_all_and_counts(self):
        t = PartitionTable()
        t.block("a", 1)
        t.block("b", 2)
        assert len(t.pairs()) == 2
        t.heal_all()
        assert t.pairs() == []
        assert not t.is_blocked(("a", 1))

    def test_parse_partition_spec(self):
        t = parse_partition_spec("block=127.0.0.1:7001;7002")
        assert t.is_blocked(("127.0.0.1", 7001))
        # bare port means wildcard host
        assert t.is_blocked(("anything", 7002))


class TestCampaignGrammar:
    def test_deterministic_from_seed(self):
        a = build_campaign(11, num_hosts=3, kills=1, store_kills=1,
                           partitions=1, flaps=1)
        b = build_campaign(11, num_hosts=3, kills=1, store_kills=1,
                           partitions=1, flaps=1)
        assert a == b
        assert a != build_campaign(12, num_hosts=3, kills=1,
                                   store_kills=1, partitions=1, flaps=1)

    def test_requested_faults_all_present(self):
        ops = build_campaign(11, num_hosts=3, kills=1, store_kills=1,
                             partitions=1, flaps=1)
        kinds = [op.kind for op in ops]
        for kind in FAULT_KINDS:
            assert kind in kinds, f"missing fault arm {kind}"
        assert all(op.kind in WORKLOAD_KINDS + FAULT_KINDS for op in ops)

    def test_per_workflow_order_preserved(self):
        ops = build_campaign(23, num_workflows=5, signals_per_wf=3,
                             num_hosts=3)
        for w in range(5):
            chain = [op.kind for op in ops if op.wf == w]
            assert chain[0] == "start"
            assert chain[-1] == "complete"
            assert chain[1:-1] == ["signal"] * 3

    def test_fault_banding_and_victim_policy(self):
        """Heals land before the kill band; host 0 (the coordinator) is
        never a victim; flap victims survive every kill."""
        for seed in range(1, 12):
            ops = build_campaign(seed, num_hosts=3, kills=1,
                                 store_kills=1, partitions=1, flaps=1)
            index = {op.kind: i for i, op in enumerate(ops)
                     if op.kind in FAULT_KINDS}
            assert index["partition"] < index["heal_partition"]
            assert index["flap_begin"] < index["flap_end"]
            assert index["heal_partition"] < index["kill_host"]
            victims = {op.host for op in ops if op.kind in
                       ("kill_host", "partition", "flap_begin")}
            assert 0 not in victims
            flap = {op.host for op in ops if op.kind == "flap_begin"}
            killed = {op.host for op in ops if op.kind == "kill_host"}
            assert not (flap & killed)

    def test_storm_profile_adds_churn_verbs(self):
        ops = build_campaign(31, num_workflows=12, profile="storm")
        kinds = {op.kind for op in ops}
        assert kinds & {"reset", "terminate", "sws"} or any(
            op.flag in ("cron", "retry", "fail") for op in ops)

    def test_rejects_bad_arguments(self):
        with pytest.raises(ValueError):
            build_campaign(1, profile="mayhem")
        with pytest.raises(ValueError):
            build_campaign(1, num_hosts=1, kills=1)


class TestCampaignShrink:
    def test_injected_regression_shrinks_to_one_minimal_pair(self):
        """The harness-validation oracle: ddmin over a 30-op campaign
        must land on EXACTLY {the kill, the poisoned signal} — and the
        report must reproduce that slice from coordinates alone."""
        seed = 11
        campaign = build_campaign(seed, num_hosts=3, kills=1,
                                  store_kills=1, partitions=1, flaps=1)
        poison = pick_poison_wf(campaign)
        assert poison is not None
        report = shrink_campaign(
            seed, injected_regression_predicate(poison), num_hosts=3,
            kills=1, store_kills=1, partitions=1, flaps=1)
        assert report.shrunk_ops == 2
        assert report.kept_kinds == ["kill_host", "signal"]
        minimal = report.reproduce()
        assert [op.kind for op in minimal] == ["kill_host", "signal"]
        assert minimal[1].wf == poison
        # 1-minimality: dropping either op un-fails the predicate
        failing = injected_regression_predicate(poison)
        assert failing(minimal)
        assert not failing(minimal[:1])
        assert not failing(minimal[1:])

    def test_reproduce_is_pure_function_of_coordinates(self):
        seed = 11
        campaign = build_campaign(seed, num_hosts=3, kills=1,
                                  store_kills=1, partitions=1, flaps=1)
        poison = pick_poison_wf(campaign)
        report = shrink_campaign(
            seed, injected_regression_predicate(poison), num_hosts=3,
            kills=1, store_kills=1, partitions=1, flaps=1)
        assert report.reproduce() == [campaign[i]
                                      for i in report.kept_indices]

    def test_campaign_op_as_dict_drops_defaults(self):
        assert CampaignOp("kill_store").as_dict() == {"kind": "kill_store"}
        d = CampaignOp("signal", wf=2, seq=0).as_dict()
        assert d == {"kind": "signal", "wf": 2, "seq": 0}


@pytest.mark.chaos
class TestFleetCampaignLive:
    def test_steady_campaign_byte_identical_under_kill_and_partition(self):
        """Tier-1 live gate: a 2-host steady campaign with one real
        SIGKILL and one asymmetric partition+heal converges to checksums
        byte-identical to the fault-free replay of the same seed, fsck
        clean, zero parity divergence, clean closing verify_all."""
        doc = cluster_campaign_scenario(
            seed=101, num_hosts=2, num_shards=4, num_workflows=4,
            signals_per_wf=2, kills=1, store_kills=0, partitions=1,
            flaps=0, profile="steady")
        assert doc["ok"], doc
        assert doc["checksums_identical"]
        assert doc["fsck_clean"]
        assert doc["parity_divergence"] == 0
        assert doc["verify"]["ok"]
        assert doc["executed"]["kills"] == 1
        assert doc["executed"]["partitions_cut"] == 1
        assert doc["executed"]["partitions_healed"] == 1
        # the chaos run actually had to retry through the faults
        assert doc["executed"]["retries"] > 0

    def test_storm_campaign_self_consistent(self):
        """Tier-1 storm arm (single launch, no baseline): reset/cron/
        retry churn under a partition still ends fsck-clean with zero
        parity divergence and a clean verify_all."""
        doc = cluster_campaign_scenario(
            seed=37, num_hosts=2, num_shards=4, num_workflows=4,
            signals_per_wf=1, kills=0, store_kills=0, partitions=1,
            flaps=0, profile="storm")
        assert doc["ok"], doc
        assert doc["fsck_clean"]
        assert doc["parity_divergence"] == 0
        assert doc["verify"]["ok"]
        assert doc["baseline"] is None  # storm gates self-consistency


@pytest.mark.slow
@pytest.mark.chaos
class TestFleetCampaignWide:
    def test_full_acceptance_campaign(self):
        """The ISSUE acceptance sweep: 3 hosts, host SIGKILL + store
        SIGKILL (fsck'd + relaunched) + asymmetric partition + membership
        flap, all mid-traffic, byte-identical vs fault-free. The flap
        arm can very rarely trip a transient CONTAINED serving-parity
        invalidation (SIGSTOP freezes a host mid-pipeline; the entry is
        dropped, state stays correct — see ROADMAP item 5 headroom):
        that exact shape, and only it, earns one retry."""
        run = lambda: cluster_campaign_scenario(
            seed=20260806, num_hosts=3, num_shards=8, num_workflows=6,
            signals_per_wf=2, kills=1, store_kills=1, partitions=1,
            flaps=1, profile="steady")
        doc = run()
        if (not doc["ok"] and doc["parity_divergence"] > 0
                and doc["checksums_identical"] and doc["fsck_clean"]
                and doc["verify"]["ok"]):
            doc = run()
        assert doc["ok"], doc
        executed = doc["executed"]
        assert executed["kills"] >= 1
        assert executed["store_kills"] == 1
        assert executed["partitions_cut"] >= 1
        assert executed["flaps"] == 1
        # every store kill's recovered WAL fsck'd clean
        assert all(r["ok"] for r in doc["chaotic"]["fsck_on_kill"])
        # the flap was witnessed by the membership plane
        assert doc["witnesses"]["membership/ring-drops"] > 0
        assert doc["witnesses"]["membership/ring-joins"] > 0

    def test_two_region_campaign_standby_identical(self):
        """regions=2: the standby's replicated checksums match the
        primary's, and verify_all holds on BOTH regions."""
        doc = cluster_campaign_scenario(
            seed=53, num_hosts=2, num_shards=4, num_workflows=4,
            signals_per_wf=1, kills=1, store_kills=0, partitions=1,
            flaps=0, profile="steady", regions=2)
        assert doc["ok"], doc
        assert doc["verify"]["ok"] and doc["verify_standby"]["ok"]
        chaotic = doc["chaotic"]
        assert chaotic["standby_checksums"] == chaotic["checksums"]
