"""Cross-cluster task executors (inventory row 15;
service/history/task/cross_cluster_*.go): operations targeting a domain
active on ANOTHER cluster park on a per-target queue, execute there, and
the result applies back on the source workflow.
"""
import pytest

from cadence_tpu.core.enums import CloseStatus, DecisionType, EventType
from cadence_tpu.engine.history_engine import Decision
from cadence_tpu.engine.multicluster import ReplicatedClusters
from tests.taskpoller import TaskPoller

TL = "xc-tl"


@pytest.fixture()
def clusters():
    c = ReplicatedClusters(num_hosts=1, num_shards=4)
    # parent domain active on PRIMARY, child/target domain on STANDBY
    c.register_global_domain("xc-parent")
    c.register_global_domain("xc-child")
    c.failover("xc-child", to_cluster="standby")
    return c


def _ids(c):
    return (c.active.frontend.describe_domain("xc-parent").domain_id,
            c.active.frontend.describe_domain("xc-child").domain_id)


class _CrossChildDecider:
    """Starts a child IN ANOTHER DOMAIN, completes when it closes."""

    def __init__(self, child_domain_id, child_wf):
        self.child_domain_id = child_domain_id
        self.child_wf = child_wf

    def decide(self, history):
        closes = [e for e in history if e.event_type in (
            EventType.ChildWorkflowExecutionCompleted,
            EventType.ChildWorkflowExecutionFailed,
            EventType.ChildWorkflowExecutionTerminated)]
        if closes:
            return [Decision(DecisionType.CompleteWorkflowExecution,
                             {"result": b""})]
        if any(e.event_type == EventType.StartChildWorkflowExecutionInitiated
               for e in history):
            return []
        return [Decision(DecisionType.StartChildWorkflowExecution,
                         {"workflow_id": self.child_wf,
                          "workflow_type": "xc-child-type",
                          "domain_id": self.child_domain_id,
                          "task_list": TL})]


class TestCrossClusterChild:
    def test_child_starts_on_other_cluster_and_closes_back(self, clusters):
        from cadence_tpu.models.deciders import CompleteDecider

        parent_id, child_id = _ids(clusters)
        clusters.active.frontend.start_workflow_execution(
            "xc-parent", "wf-par", "par-type", TL)
        apoller = TaskPoller(clusters.active, "xc-parent", TL,
                             {"wf-par": _CrossChildDecider(child_id,
                                                           "wf-chi")})
        spoller = TaskPoller(clusters.standby, "xc-child", TL,
                             {"wf-chi": CompleteDecider()})
        for _ in range(40):
            apoller.drain()
            moved = clusters.process_cross_cluster()
            spoller.drain()
            moved += clusters.process_cross_cluster()
            apoller.drain()
            parent_run = clusters.active.stores.execution.get_current_run_id(
                parent_id, "wf-par")
            ms = clusters.active.stores.execution.get_workflow(
                parent_id, "wf-par", parent_run)
            if ms.execution_info.close_status == CloseStatus.Completed:
                break
        # the child RAN on the standby, with parent linkage to primary
        child_run = clusters.standby.stores.execution.get_current_run_id(
            child_id, "wf-chi")
        child_ms = clusters.standby.stores.execution.get_workflow(
            child_id, "wf-chi", child_run)
        assert child_ms.execution_info.close_status == CloseStatus.Completed
        assert child_ms.execution_info.parent_workflow_id == "wf-par"
        # the parent SAW the start and the close across the cluster boundary
        events = clusters.active.stores.history.read_events(
            parent_id, "wf-par", parent_run)
        types = [e.event_type for e in events]
        assert EventType.ChildWorkflowExecutionStarted in types
        assert EventType.ChildWorkflowExecutionCompleted in types
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert clusters.active.tpu.verify_all().ok
        assert clusters.standby.tpu.verify_all().ok

    def test_cross_cluster_signal_external(self, clusters):
        """A workflow on primary signals an execution living in a domain
        active on the STANDBY; the delivery confirmation comes back."""
        from cadence_tpu.models.deciders import SignalDecider

        parent_id, child_id = _ids(clusters)
        # the target lives on the standby
        clusters.standby.frontend.start_workflow_execution(
            "xc-child", "wf-target", "sig", TL)
        # the source on primary: first decision signals the external target
        clusters.active.frontend.start_workflow_execution(
            "xc-parent", "wf-src", "src", TL)

        class SignalExternalDecider:
            def decide(self, history):
                if any(e.event_type ==
                       EventType.ExternalWorkflowExecutionSignaled
                       for e in history):
                    return [Decision(DecisionType.CompleteWorkflowExecution,
                                     {"result": b""})]
                if any(e.event_type ==
                       EventType.SignalExternalWorkflowExecutionInitiated
                       for e in history):
                    return []
                return [Decision(
                    DecisionType.SignalExternalWorkflowExecution,
                    {"workflow_id": "wf-target", "domain_id": child_id,
                     "signal_name": "cross"})]

        apoller = TaskPoller(clusters.active, "xc-parent", TL,
                             {"wf-src": SignalExternalDecider()})
        spoller = TaskPoller(clusters.standby, "xc-child", TL,
                             {"wf-target": SignalDecider(expected_signals=1)})
        for _ in range(40):
            apoller.drain()
            clusters.process_cross_cluster()
            spoller.drain()
            clusters.process_cross_cluster()
            apoller.drain()
            run = clusters.active.stores.execution.get_current_run_id(
                parent_id, "wf-src")
            ms = clusters.active.stores.execution.get_workflow(
                parent_id, "wf-src", run)
            if ms.execution_info.close_status == CloseStatus.Completed:
                break
        assert ms.execution_info.close_status == CloseStatus.Completed
        # the target on the standby got the signal and completed
        trun = clusters.standby.stores.execution.get_current_run_id(
            child_id, "wf-target")
        tms = clusters.standby.stores.execution.get_workflow(
            child_id, "wf-target", trun)
        assert tms.execution_info.close_status == CloseStatus.Completed


class TestFailoverRaces:
    def test_parked_task_rehomes_after_failover(self, clusters):
        """A task parked for the standby executes on PRIMARY when the
        target domain fails back before processing (code-review r4: never
        execute at a stale failover version)."""
        parent_id, child_id = _ids(clusters)
        clusters.active.frontend.start_workflow_execution(
            "xc-parent", "wf-race", "par-type", TL)
        apoller = TaskPoller(clusters.active, "xc-parent", TL,
                             {"wf-race": _CrossChildDecider(child_id,
                                                            "wf-chi-race")})
        apoller.drain()  # parks the start-child for the standby
        # the child domain fails BACK to primary before processing
        clusters.failover("xc-child", to_cluster="primary")
        moved = clusters.process_cross_cluster()
        assert moved >= 1
        # the child started on the PRIMARY (current active), not standby
        run = clusters.active.stores.execution.get_current_run_id(
            child_id, "wf-chi-race")
        assert run
        from cadence_tpu.engine.persistence import EntityNotExistsError
        with pytest.raises(EntityNotExistsError):
            clusters.standby.stores.execution.get_current_run_id(
                child_id, "wf-chi-race")


class TestRedeliveryDedup:
    """At-least-once result-leg failures must not corrupt the source
    workflow (advisor r4 medium/low: redelivered start-child and signal)."""

    def test_redelivered_start_child_with_same_request_id_reports_started(
            self, clusters):
        """start_workflow committed but on_child_started failed transiently:
        the redelivery must match the existing run's create request id and
        report STARTED (the reference's StartRequestID dedup arm), not
        record StartChildWorkflowExecutionFailed for a child that runs."""
        from cadence_tpu.engine.crosscluster import (
            KIND_START_CHILD, CrossClusterTask)

        parent_id, child_id = _ids(clusters)
        task = CrossClusterTask(
            kind=KIND_START_CHILD, source_domain_id=parent_id,
            source_workflow_id="wf-dd-par", source_run_id="run-dd",
            event_id=5, target_domain_id=child_id,
            target_workflow_id="wf-dd-chi", workflow_type="t",
            task_list=TL, parent_initiated_id=5,
            create_request_id="req-dd-1")

        applied = {}
        proc = clusters.cross_cluster_processor

        class _Source:
            def on_child_started(self, d, w, r, eid, child_run):
                applied["started"] = child_run

            def on_child_start_failed(self, d, w, r, eid):
                applied["failed"] = True

        proc.source_router = lambda wf: _Source()
        proc._execute(task)          # first delivery: child starts
        proc._execute(task)          # redelivery: same create request id
        assert "failed" not in applied
        assert applied["started"] == (
            clusters.standby.stores.execution.get_current_run_id(
                child_id, "wf-dd-chi"))

    def test_redelivered_start_child_different_request_id_reports_failed(
            self, clusters):
        """A DIFFERENT creator holds the workflow id: genuine
        already-started — the parent gets the Failed event."""
        from cadence_tpu.engine.crosscluster import (
            KIND_START_CHILD, CrossClusterTask)

        parent_id, child_id = _ids(clusters)
        clusters.standby.frontend.start_workflow_execution(
            "xc-child", "wf-dd2", "t", TL)
        task = CrossClusterTask(
            kind=KIND_START_CHILD, source_domain_id=parent_id,
            source_workflow_id="wf-dd2-par", source_run_id="run-dd2",
            event_id=5, target_domain_id=child_id,
            target_workflow_id="wf-dd2", workflow_type="t",
            task_list=TL, parent_initiated_id=5,
            create_request_id="req-other")
        applied = {}
        proc = clusters.cross_cluster_processor

        class _Source:
            def on_child_started(self, d, w, r, eid, child_run):
                applied["started"] = child_run

            def on_child_start_failed(self, d, w, r, eid):
                applied["failed"] = True

        proc.source_router = lambda wf: _Source()
        proc._execute(task)
        assert applied == {"failed": True}

    def test_signal_request_id_dedups_redelivery(self, clusters):
        """The same signal request id applied twice appends ONE
        WorkflowExecutionSignaled event."""
        parent_id, child_id = _ids(clusters)
        clusters.standby.frontend.start_workflow_execution(
            "xc-child", "wf-sig-dd", "t", TL)
        eng = clusters.standby.route("wf-sig-dd")
        eng.signal_workflow(child_id, "wf-sig-dd", "ping",
                            request_id="sig-req-1")
        eng.signal_workflow(child_id, "wf-sig-dd", "ping",
                            request_id="sig-req-1")
        run = clusters.standby.stores.execution.get_current_run_id(
            child_id, "wf-sig-dd")
        events = clusters.standby.stores.history.read_events(
            child_id, "wf-sig-dd", run)
        signals = [e for e in events
                   if e.event_type == EventType.WorkflowExecutionSignaled]
        assert len(signals) == 1
