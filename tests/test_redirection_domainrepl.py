"""Cluster redirection + domain-metadata replication (inventory rows
26/49/54; clusterRedirectionHandler.go, common/domain/replication_queue.go,
service/worker/replicator).
"""
import pytest

from cadence_tpu.core.enums import CloseStatus, WorkflowState
from cadence_tpu.engine.domain import DomainNotActiveError
from cadence_tpu.engine.multicluster import ReplicatedClusters
from cadence_tpu.models.deciders import EchoDecider
from tests.taskpoller import TaskPoller

DOMAIN = "rd-domain"
TL = "rd-tl"


@pytest.fixture()
def clusters():
    c = ReplicatedClusters(num_hosts=1, num_shards=4)
    c.register_global_domain(DOMAIN)
    return c


class TestDomainNotActive:
    def test_passive_cluster_rejects_active_apis(self, clusters):
        with pytest.raises(DomainNotActiveError):
            clusters.standby.frontend.start_workflow_execution(
                DOMAIN, "wf-x", "t", TL)
        with pytest.raises(DomainNotActiveError):
            clusters.standby.frontend.signal_workflow_execution(
                DOMAIN, "wf-x", "s")
        # the ACTIVE side serves normally
        clusters.active.frontend.start_workflow_execution(DOMAIN, "wf-x",
                                                          "t", TL)

    def test_local_domains_always_active(self, clusters):
        clusters.standby.frontend.register_domain("local-only")
        clusters.standby.frontend.start_workflow_execution(
            "local-only", "wf-l", "t", TL)


class TestRedirection:
    def test_passive_frontend_forwards_to_active(self, clusters):
        fe = clusters.redirecting_frontend("standby")
        fe.start_workflow_execution(DOMAIN, "wf-fwd", "echo", TL)
        # the workflow LIVES on the active cluster
        domain_id = clusters.active.frontend.describe_domain(DOMAIN).domain_id
        run = clusters.active.stores.execution.get_current_run_id(
            domain_id, "wf-fwd")
        assert run
        fe.signal_workflow_execution(DOMAIN, "wf-fwd", "hello")
        TaskPoller(clusters.active, DOMAIN, TL,
                   {"wf-fwd": EchoDecider(TL)}).drain()
        ms = clusters.active.stores.execution.get_workflow(domain_id,
                                                           "wf-fwd", run)
        assert ms.execution_info.close_status == CloseStatus.Completed
        # reads stay local (served by the wrapper's own cluster)
        assert fe.describe_domain(DOMAIN).name == DOMAIN

    def test_noop_policy_surfaces_not_active(self, clusters):
        fe = clusters.redirecting_frontend("standby", policy="noop")
        with pytest.raises(DomainNotActiveError):
            fe.start_workflow_execution(DOMAIN, "wf-noop", "t", TL)

    def test_forwarding_flips_after_failover(self, clusters):
        clusters.failover(DOMAIN, to_cluster="standby")
        clusters.replicate_domains()
        fe_standby = clusters.redirecting_frontend("standby")
        fe_active = clusters.redirecting_frontend("primary")
        # the standby now serves locally...
        fe_standby.start_workflow_execution(DOMAIN, "wf-after", "t", TL)
        domain_id = clusters.standby.frontend.describe_domain(
            DOMAIN).domain_id
        assert clusters.standby.stores.execution.get_current_run_id(
            domain_id, "wf-after")
        # ...and the old active FORWARDS to it
        fe_active.signal_workflow_execution(DOMAIN, "wf-after", "sig")
        ms = clusters.standby.stores.execution.get_workflow(
            domain_id, "wf-after",
            clusters.standby.stores.execution.get_current_run_id(
                domain_id, "wf-after"))
        assert ms.execution_info.signal_count == 1


class TestDomainReplication:
    def test_update_streams_to_standby(self, clusters):
        clusters.active.frontend.update_domain(
            DOMAIN, retention_days=9, description="replicated")
        assert clusters.replicate_domains() >= 1
        info = clusters.standby.frontend.describe_domain(DOMAIN)
        assert info.retention_days == 9
        assert info.description == "replicated"
        assert not info.is_active  # recomputed locally on the standby

    def test_failover_via_update_replicates_activeness(self, clusters):
        clusters.active.frontend.update_domain(DOMAIN,
                                               active_cluster="standby")
        clusters.replicate_domains()
        standby_info = clusters.standby.frontend.describe_domain(DOMAIN)
        assert standby_info.active_cluster == "standby"
        assert standby_info.is_active  # the standby knows it is active now
        # active-cluster APIs now serve on the standby
        clusters.standby.frontend.start_workflow_execution(
            DOMAIN, "wf-failover", "t", TL)

    def test_stale_replay_is_skipped(self, clusters):
        clusters.active.frontend.update_domain(DOMAIN, retention_days=5)
        assert clusters.replicate_domains() >= 1
        # replaying the SAME queue from scratch must not regress
        from cadence_tpu.engine.domainrepl import DomainReplicationProcessor
        replayer = DomainReplicationProcessor(clusters.active.stores,
                                              clusters.standby.stores,
                                              "standby")
        assert replayer.process_once() == 0  # all stale: notification ver
        assert clusters.standby.frontend.describe_domain(
            DOMAIN).retention_days == 5

    def test_deprecate_streams_to_standby(self, clusters):
        clusters.active.frontend.deprecate_domain(DOMAIN)
        clusters.replicate_domains()
        from cadence_tpu.engine.persistence import DOMAIN_STATUS_DEPRECATED
        assert clusters.standby.frontend.describe_domain(
            DOMAIN).status == DOMAIN_STATUS_DEPRECATED

    def test_global_registration_replicates(self):
        """A global domain registered through the active frontend exists
        on the standby after one drain — no manual dual registration."""
        c = ReplicatedClusters(num_hosts=1, num_shards=4)
        c.active.frontend.register_domain(
            "fresh-global", clusters=("primary", "standby"),
            active_cluster="primary",
            failover_version=c.meta.initial_failover_version("primary"))
        assert c.replicate_domains() >= 1
        info = c.standby.frontend.describe_domain("fresh-global")
        assert info.clusters == ("primary", "standby")
        assert not info.is_active

    def test_update_then_failover_never_reverts(self):
        """A queued pre-failover update must not replay OVER the failover
        on the receiving side (code-review r4 #2)."""
        c = ReplicatedClusters(num_hosts=1, num_shards=4)
        c.register_global_domain(DOMAIN)
        c.active.frontend.update_domain(DOMAIN, description="before")
        # failover WITHOUT draining the queued update first
        c.failover(DOMAIN, to_cluster="standby")
        c.replicate_domains()
        info = c.standby.frontend.describe_domain(DOMAIN)
        assert info.active_cluster == "standby"
        assert info.is_active


class TestDomainArbitration:
    """ISSUE 18 satellite: failover-version-first conflict arbitration
    replacing last-writer-wins — the loser region's update arriving
    after a partition heals must be rejected typed + counted, never
    applied (domain/replicationTaskExecutor.go
    handleDomainUpdateReplicationTask)."""

    def _processor_and_registry(self, clusters):
        from cadence_tpu.engine.domainrepl import DomainReplicationProcessor
        from cadence_tpu.utils.metrics import MetricsRegistry

        proc = DomainReplicationProcessor(clusters.active.stores,
                                          clusters.standby.stores,
                                          "standby")
        proc.metrics = MetricsRegistry()
        return proc, proc.metrics

    def _task(self, info, **overrides):
        from cadence_tpu.engine.domainrepl import DomainReplicationTask

        base = DomainReplicationTask.of(info)
        return DomainReplicationTask(**{**base.__dict__, **overrides})

    def test_lower_failover_version_rejected_typed(self, clusters):
        from cadence_tpu.utils import metrics as cm

        clusters.replicate_domains()
        proc, reg = self._processor_and_registry(clusters)
        local = clusters.standby.stores.domain.by_name(DOMAIN)
        stale = self._task(local,
                           failover_version=local.failover_version - 1,
                           notification_version=local.notification_version
                           + 99, description="split-brain loser")
        assert proc._apply(stale) is False
        # never applied — LWW would have taken the higher notification
        after = clusters.standby.stores.domain.by_name(DOMAIN)
        assert after.description == local.description
        assert after.failover_version == local.failover_version
        # typed + counted + kept for forensics
        assert reg.counter(cm.SCOPE_REPLICATION,
                           cm.M_DOMREPL_STALE_REJECTED) == 1
        rej = proc.stale_rejects[-1]
        assert rej.domain_id == local.domain_id
        assert rej.task_failover_version == local.failover_version - 1
        assert rej.local_failover_version == local.failover_version

    def test_equal_version_stale_notification_is_duplicate(self, clusters):
        from cadence_tpu.utils import metrics as cm

        clusters.replicate_domains()
        proc, reg = self._processor_and_registry(clusters)
        local = clusters.standby.stores.domain.by_name(DOMAIN)
        dup = self._task(local, description="queue redelivery")
        assert proc._apply(dup) is False
        # a duplicate is NOT an arbitration loser: no stale_rejects entry
        assert len(proc.stale_rejects) == 0
        assert reg.counter(cm.SCOPE_REPLICATION,
                           cm.M_DOMREPL_DUPLICATE) == 1
        assert reg.counter(cm.SCOPE_REPLICATION,
                           cm.M_DOMREPL_STALE_REJECTED) == 0

    def test_higher_failover_version_wins_regardless_of_notification(
            self, clusters):
        from cadence_tpu.utils import metrics as cm

        clusters.replicate_domains()
        proc, reg = self._processor_and_registry(clusters)
        local = clusters.standby.stores.domain.by_name(DOMAIN)
        winner = self._task(local,
                            failover_version=local.failover_version + 10,
                            notification_version=0,
                            description="new failover epoch")
        assert proc._apply(winner) is True
        after = clusters.standby.stores.domain.by_name(DOMAIN)
        assert after.failover_version == local.failover_version + 10
        assert after.description == "new failover epoch"
        assert reg.counter(cm.SCOPE_REPLICATION,
                           cm.M_DOMREPL_APPLIED) == 1

    def test_stale_rejects_deque_bounded(self, clusters):
        from cadence_tpu.engine.domainrepl import STALE_KEEP

        clusters.replicate_domains()
        proc, _ = self._processor_and_registry(clusters)
        local = clusters.standby.stores.domain.by_name(DOMAIN)
        stale = self._task(local,
                           failover_version=local.failover_version - 1)
        for _ in range(STALE_KEEP + 5):
            assert proc._apply(stale) is False
        assert len(proc.stale_rejects) == STALE_KEEP

    def test_healed_partition_replay_keeps_winner(self, clusters):
        """End-to-end split-brain: after a failover to standby, the old
        active's queued pre-failover update replays into the standby —
        and must lose arbitration instead of reverting activeness."""
        clusters.active.frontend.update_domain(DOMAIN,
                                               description="pre-failover")
        clusters.failover(DOMAIN, to_cluster="standby")
        clusters.replicate_domains()
        info = clusters.standby.stores.domain.by_name(DOMAIN)
        assert info.active_cluster == "standby"
        # replay the whole queue from scratch (the healed partition's
        # redelivery): the pre-failover update carries the OLD failover
        # version and must be rejected, not LWW-applied
        from cadence_tpu.engine.domainrepl import DomainReplicationProcessor
        replayer = DomainReplicationProcessor(clusters.active.stores,
                                              clusters.standby.stores,
                                              "standby")
        replayer.process_once()
        after = clusters.standby.stores.domain.by_name(DOMAIN)
        assert after.active_cluster == "standby"
        assert after.failover_version == info.failover_version
        assert any(r.task_failover_version < r.local_failover_version
                   for r in replayer.stale_rejects)
