"""Test configuration.

Sharding tests run on a virtual 8-device CPU mesh: the env vars must be set
before jax initializes its backends, so they are set here at conftest import
time (pytest imports conftest before test modules import jax).
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_DEVICE_FLAG = "--xla_force_host_platform_device_count=8"
if _DEVICE_FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _DEVICE_FLAG).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
