"""Test configuration.

Sharding tests run on a virtual 8-device CPU mesh: the env vars must be set
before jax initializes its backends, so they are set here at conftest import
time (pytest imports conftest before test modules import jax).
"""
import os
import sys

# force CPU for tests even when the environment pins a TPU platform
# (e.g. JAX_PLATFORMS=axon); bench.py runs outside pytest and keeps the TPU
os.environ["JAX_PLATFORMS"] = "cpu"
_DEVICE_FLAG = "--xla_force_host_platform_device_count=8"
if _DEVICE_FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _DEVICE_FLAG).strip()

# this environment pre-imports jax via sitecustomize, which snapshots
# JAX_PLATFORMS at import time — override through the config API too
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent compilation cache: the suite is dominated by XLA compiles on this
# single-core host; cache them across processes/runs so CI stays under minutes
_CACHE_DIR = os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.makedirs(_CACHE_DIR, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_default_observability():
    """DEFAULT_REGISTRY / DEFAULT_TRACER are process-global fallbacks that
    components constructed without explicit wiring share; reset them IN
    PLACE (components hold them by reference) before every test so one
    test's counters and spans never leak into another's assertions."""
    from cadence_tpu.utils import circuitbreaker, metrics, tracing
    metrics.DEFAULT_REGISTRY.reset()
    tracing.DEFAULT_TRACER.reset()
    # per-target breaker state is process-global the same way: a breaker
    # opened by one test must not shed the next test's calls to a reused
    # ephemeral port; chaos is per-process too, never leak an injector
    circuitbreaker.DEFAULT_BREAKERS.reset()
    from cadence_tpu.rpc import chaos
    chaos.uninstall()
    # durability crashpoints are process-global the same way: one test's
    # armed kill site must never fire inside another test's WAL append
    from cadence_tpu.engine import crashpoints
    crashpoints.uninstall()
    # resident-state caches pin DEVICE buffers per entry; clear every
    # live cache so one test's HBM residents (and their hit/miss state)
    # never leak into another's assertions or memory budget
    from cadence_tpu.engine import resident
    resident.reset_all()
    # serving schedulers own daemon drain threads + pending tickets the
    # same way: stop them so a leaked drain never flushes into the next
    # test's registry (a stopped scheduler restarts on its next submit)
    from cadence_tpu.engine import serving
    serving.reset_all()
    # quota limiters are held by reference inside frontends the same
    # way: drain one test's consumed tokens so they never shed the next
    # test's first requests
    from cadence_tpu.utils import quotas
    quotas.reset_all()
    # device-visibility views own daemon appender threads the same way
    # as serving schedulers: stop them so a leaked drain never applies
    # into the next test's registry (a stopped view restarts its thread
    # on the next enqueue)
    from cadence_tpu.engine import visibility_device
    visibility_device.reset_all()
    # the telemetry plane is process-global three ways: the flight
    # recorder's ring (emit points hold DEFAULT_RECORDER by reference),
    # and any sampler/profiler threads a test started — stop + clear so
    # one test's events/windows never surface in another's dumps
    from cadence_tpu.utils import flightrecorder, hostprof, timeseries
    flightrecorder.reset_all()
    timeseries.reset_all()
    hostprof.reset_all()
    yield


@pytest.fixture(params=["jsonl", "sqlite"])
def wal(request, tmp_path):
    """One durable-WAL path per open_log backend: every crash/fault/
    recovery test requesting this fixture runs the full matrix over both
    JSONL and SqliteLog (backend selected by extension)."""
    return str(tmp_path /
               ("wal.db" if request.param == "sqlite" else "wal.jsonl"))
