"""Process-boundary integration: real OS processes, real sockets.

VERDICT r3 ask #3: start >=2 server processes, route a workflow over the
wire, kill one host, observe shard steal + range-ID fencing across the
network. Reference: common/rpc/factory.go:27-90 (transport),
cmd/server/cadence/server.go:271-278 (role dispatch), shard fencing
shard/context.go:586-700.

The store server owns the authoritative stores (the DB role): every CAS
and range fence evaluates THERE, which is exactly why fencing holds across
host processes.
"""
import signal
import time

import pytest

from cadence_tpu.core.enums import CloseStatus, WorkflowState
from cadence_tpu.engine.membership import shard_id_for_workflow
from cadence_tpu.rpc.cluster import launch
from cadence_tpu.rpc.wire import call as wire_call

DOMAIN = "mp-domain"
TL = "mp-tl"
NUM_SHARDS = 8


@pytest.fixture(scope="module")
def cluster():
    c = launch(num_hosts=2, num_shards=NUM_SHARDS)
    try:
        c.frontend(0).register_domain(DOMAIN)
        yield c
    finally:
        c.stop()


def drive_workflow(fe, workflow_id: str, deadline_s: float = 30.0) -> None:
    """Hand-rolled worker against the wire frontend (host/taskpoller.go
    analog): poll decision tasks until this workflow's arrives, complete it."""
    from cadence_tpu.core.enums import DecisionType
    from cadence_tpu.engine.history_engine import Decision

    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        resp = fe.poll_for_decision_task(DOMAIN, TL, wait_seconds=0.5)
        if resp is None or resp.token is None:
            continue
        if resp.token.workflow_id != workflow_id:
            continue
        fe.respond_decision_task_completed(resp.token, [
            Decision(DecisionType.CompleteWorkflowExecution,
                     {"result": b"done"})])
        return
    raise TimeoutError(f"no decision task for {workflow_id}")


def wf_on_host(owned, host):
    """A workflow id hashing to a shard the given host owns."""
    for i in range(256):
        wf = f"wf-{host}-{i}"
        if shard_id_for_workflow(wf, NUM_SHARDS) in owned[host]:
            return wf
    raise AssertionError(f"no workflow id hashes onto {host}'s shards")


class TestWireCluster:
    def test_workflow_end_to_end_over_the_wire(self, cluster):
        """Start on one host's frontend, poll/respond through the other's:
        every hop (frontend→history, matching rendezvous, store writes)
        crosses a process boundary."""
        fe0, fe1 = cluster.frontend(0), cluster.frontend(1)
        fe0.start_workflow_execution(DOMAIN, "wf-wire", "wiretype", TL)
        drive_workflow(fe1, "wf-wire")
        ms = fe0.describe_workflow_execution(DOMAIN, "wf-wire")
        assert ms.execution_info.state == WorkflowState.Completed
        assert ms.execution_info.close_status == CloseStatus.Completed

    def test_cross_process_range_fence(self, cluster):
        """A usurper (this test process) acquires a shard through the store
        server; the old owner's CACHED engine then writes through its stale
        context and MUST be fenced — three processes, one authoritative
        range CAS (shard/context.go:586-700 across the network)."""
        from cadence_tpu.engine.persistence import ShardOwnershipLostError
        from cadence_tpu.engine.shard import ShardContext
        from cadence_tpu.rpc.client import RemoteStores

        fe0 = cluster.frontend(0)
        owned = cluster.owned_shards()
        wf = wf_on_host(owned, "host-0")
        fe0.start_workflow_execution(DOMAIN, wf, "wiretype", TL)
        domain_id = fe0.describe_domain(DOMAIN).domain_id

        # usurp the shard from a third process (this one), over the wire
        sid = shard_id_for_workflow(wf, NUM_SHARDS)
        usurper = ShardContext(sid, "usurper",
                               RemoteStores(("127.0.0.1",
                                             cluster.store_port)))
        usurper.acquire()

        # the deposed owner's cached engine writes through its stale range
        with pytest.raises(ShardOwnershipLostError):
            wire_call(("127.0.0.1", cluster.hosts["host-0"]),
                      ("admin_stale_probe", domain_id, wf), timeout=10)

        # self-heal: real traffic re-acquires past the usurper and works
        drive_workflow(fe0, wf)
        ms = fe0.describe_workflow_execution(DOMAIN, wf)
        assert ms.execution_info.close_status == CloseStatus.Completed

    def test_killed_host_shards_are_stolen_and_served(self, cluster):
        """Pause host-1 (it stops heartbeating — the failure detector's
        view of a dead/partitioned host), watch host-0 steal its shards,
        then SIGKILL it and complete a workflow that lived there."""
        fe0 = cluster.frontend(0)
        owned_before = cluster.owned_shards()
        assert set(owned_before) == {"host-0", "host-1"}
        target_wf = wf_on_host(owned_before, "host-1")
        fe0.start_workflow_execution(DOMAIN, target_wf, "wiretype", TL)

        cluster.pause_host("host-1")
        deadline = time.monotonic() + 20
        stolen = False
        while time.monotonic() < deadline:
            owned = cluster.owned_shards().get("host-0", [])
            if set(owned_before["host-1"]).issubset(set(owned)):
                stolen = True
                break
            time.sleep(0.1)
        assert stolen, "host-0 never stole the paused host's shards"

        cluster.kill_host("host-1", signal.SIGKILL)
        # the stolen workflow completes through the survivor, over the wire
        drive_workflow(fe0, target_wf)
        ms = fe0.describe_workflow_execution(DOMAIN, target_wf)
        assert ms.execution_info.close_status == CloseStatus.Completed


class TestWireApiSurface:
    def test_new_apis_work_over_the_wire(self, cluster):
        """SignalWithStart, query visibility, count, domain update, and
        batch all cross the process boundary (pickled args/results over
        real sockets)."""
        fe = cluster.frontend(0)
        run = fe.signal_with_start_workflow_execution(
            DOMAIN, "wf-sws-wire", signal_name="go",
            workflow_type="orders", task_list=TL)
        assert run
        fe.update_domain(DOMAIN, description="wire-updated")
        assert fe.describe_domain(DOMAIN).description == "wire-updated"
        assert fe.count_workflow_executions(DOMAIN) >= 0
        # drive the decision so visibility records the start (host-1 was
        # SIGKILLed by the steal test earlier in this module: the survivor
        # serving everything IS the point)
        drive_workflow(fe, "wf-sws-wire")
        # visibility trails the async close-task pump: poll briefly
        deadline = time.monotonic() + 10
        hits = []
        while time.monotonic() < deadline:
            hits = fe.list_workflow_executions(
                DOMAIN,
                "WorkflowType = 'orders' AND CloseStatus = 'Completed'")
            if hits:
                break
            time.sleep(0.1)
        assert "wf-sws-wire" in [r.workflow_id for r in hits]
        # batch signal over the wire (no open matches left: zero targets)
        from cadence_tpu.engine.batcher import Batcher
        report = Batcher(fe, rps=100).run(
            DOMAIN, "WorkflowType = 'orders'", "signal", signal_name="x")
        assert report.total == 0


class TestWireAuth:
    """The wire trust boundary is enforced: every connection opens with a
    server nonce challenge; a peer that cannot answer
    HMAC(secret, nonce || ctx) — or, while the legacy fallback is allowed,
    the static preamble — is dropped before any frame is unpickled
    (advisor r4; replay hardening this round)."""

    @staticmethod
    def _recv_after_handshake(sock):
        """Bytes the server sends AFTER its 32-byte nonce challenge
        (b"" = the connection was dropped without a response frame)."""
        nonce = b""
        while len(nonce) < 32:
            chunk = sock.recv(32 - len(nonce))
            if not chunk:
                return b""
            nonce += chunk
        sock.settimeout(2)
        try:
            return sock.recv(1024)
        except (TimeoutError, OSError):
            return b""

    def test_unauthenticated_peer_is_rejected(self):
        import pickle
        import socket
        import struct
        import threading

        from cadence_tpu.engine.persistence import Stores
        from cadence_tpu.rpc.storeserver import StoreServer
        from cadence_tpu.rpc.wire import call

        server = StoreServer(("127.0.0.1", 0), Stores())
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            addr = ("127.0.0.1", server.server_address[1])
            # authenticated challenge-response path works
            assert call(addr, ("ping",)) == "pong"
            # raw connection ignoring the challenge, garbage response: a
            # pickle frame is never processed — dropped, no response frame
            with socket.create_connection(addr, timeout=5) as sock:
                body = b"garbage-no-hello"
                sock.sendall(struct.pack(">I", len(body)) + body)
                assert self._recv_after_handshake(sock) == b""
            # wrong secret: a forged 32-byte response + a well-formed
            # frame is dropped without a response
            with socket.create_connection(addr, timeout=5) as sock:
                sock.sendall(b"\x00" * 32)
                body = pickle.dumps(("ping",))
                sock.sendall(struct.pack(">I", len(body)) + body)
                assert self._recv_after_handshake(sock) == b""
            assert call(addr, ("ping",)) == "pong"
        finally:
            server.shutdown()

    def test_challenge_response_blocks_replay(self, monkeypatch):
        """A captured handshake response must be useless on the NEXT
        connection (fresh nonce); the static legacy preamble is accepted
        only while CADENCE_TPU_WIRE_ALLOW_STATIC permits it."""
        import pickle
        import socket
        import struct
        import threading

        from cadence_tpu.engine.persistence import Stores
        from cadence_tpu.rpc.storeserver import StoreServer
        from cadence_tpu.rpc.wire import _challenge_mac, _hello_mac, call

        server = StoreServer(("127.0.0.1", 0), Stores())
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            addr = ("127.0.0.1", server.server_address[1])
            body = pickle.dumps(("ping",))
            frame = struct.pack(">I", len(body)) + body
            # legacy static preamble: accepted under the default fallback
            with socket.create_connection(addr, timeout=5) as sock:
                sock.recv(32)  # a legacy client ignores the challenge
                sock.sendall(_hello_mac() + frame)
                kind, payload = pickle.loads(sock.recv(4096)[4:])
                assert (kind, payload) == ("ok", "pong")
            monkeypatch.setenv("CADENCE_TPU_WIRE_ALLOW_STATIC", "0")
            # replay: a valid response for connection A fails on B
            with socket.create_connection(addr, timeout=5) as first:
                nonce = first.recv(32)
                captured = _challenge_mac(nonce)
            with socket.create_connection(addr, timeout=5) as sock:
                sock.sendall(captured + frame)  # stale nonce's MAC
                assert self._recv_after_handshake(sock) == b""
            # legacy preamble: rejected once the fallback is disabled
            with socket.create_connection(addr, timeout=5) as sock:
                sock.sendall(_hello_mac() + frame)
                assert self._recv_after_handshake(sock) == b""
            # the real client still authenticates
            assert call(addr, ("ping",)) == "pong"
        finally:
            server.shutdown()
