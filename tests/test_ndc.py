"""NDC divergent-branch conflict resolution tests.

Reference tier: host/ndc/integration_test.go — conflicting event suffixes
written by two clusters after a non-graceful failover must converge: both
sides fork at the common ancestor, keep both branches, and switch current
to the higher-version branch; device replay of the winning branch matches
the oracle state (BASELINE north-star parity on the NDC path)."""
import pytest

from cadence_tpu.core.checksum import payload_row
from cadence_tpu.core.enums import CloseStatus
from cadence_tpu.engine.multicluster import ReplicatedClusters
from cadence_tpu.models.deciders import SignalDecider
from tests.taskpoller import TaskPoller

DOMAIN = "ndc-domain"
TL = "ndc-tasklist"
WF = "ndc-split"


@pytest.fixture()
def clusters():
    c = ReplicatedClusters(num_hosts=1, num_shards=4)
    c.register_global_domain(DOMAIN)
    return c


def _start_and_replicate(clusters, expected_signals=2):
    """Common prefix on the active, replicated to the standby."""
    box = clusters.active
    box.frontend.start_workflow_execution(DOMAIN, WF, "signal", TL)
    poller = TaskPoller(box, DOMAIN, TL,
                        {WF: SignalDecider(expected_signals=expected_signals)})
    poller.drain()  # first decision completes; workflow awaits signals
    clusters.replicate()
    domain_id = box.stores.domain.by_name(DOMAIN).domain_id
    run_id = box.stores.execution.get_current_run_id(domain_id, WF)
    return domain_id, run_id


class TestDivergence:
    def test_split_brain_converges_to_higher_version_branch(self, clusters):
        domain_id, run_id = _start_and_replicate(clusters)
        prefix_end = clusters.active.stores.execution.get_workflow(
            domain_id, WF, run_id).execution_info.next_event_id - 1

        # non-graceful failover: standby promotes itself; active keeps going
        new_version = clusters.split_brain_promote(DOMAIN)
        assert new_version == 12

        # active writes a v1 suffix (one signal, decider wants 2 → no close)
        apoller = TaskPoller(clusters.active, DOMAIN, TL,
                             {WF: SignalDecider(expected_signals=2)})
        clusters.active.frontend.signal_workflow_execution(DOMAIN, WF, "a-1")
        apoller.drain()

        # standby writes a CONFLICTING v12 suffix that closes the workflow
        spoller = TaskPoller(clusters.standby, DOMAIN, TL,
                             {WF: SignalDecider(expected_signals=2)})
        clusters.standby.frontend.signal_workflow_execution(DOMAIN, WF, "b-1")
        clusters.standby.frontend.signal_workflow_execution(DOMAIN, WF, "b-2")
        spoller.drain()
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, WF, run_id)
        assert standby_ms.execution_info.close_status == CloseStatus.Completed

        # heal: both directions drain; both converge to the v12 branch
        clusters.heal(DOMAIN, "standby")

        active_ms = clusters.active.stores.execution.get_workflow(
            domain_id, WF, run_id)
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, WF, run_id)

        # the v12 branch won on both sides
        for ms in (active_ms, standby_ms):
            assert ms.execution_info.close_status == CloseStatus.Completed
            items = [(i.event_id, i.version)
                     for i in ms.version_histories.current().items]
            assert items[0] == (prefix_end, 1)
            assert items[-1][1] == 12
            # the losing v1 suffix is retained as a non-current branch
            assert len(ms.version_histories.histories) == 2
        # canonical state payloads identical across clusters
        assert (payload_row(active_ms) == payload_row(standby_ms)).all()

        # loser branch still ends at v1, beyond the fork point
        for ms in (active_ms, standby_ms):
            non_current = [h for i, h in enumerate(ms.version_histories.histories)
                           if i != ms.version_histories.current_index][0]
            assert non_current.last_item().version == 1
            assert non_current.last_item().event_id > prefix_end

    def test_winning_branch_replays_on_device(self, clusters):
        """Device replay of the post-conflict current branch matches the
        live mutable state on both clusters (kernel as the NDC bulk apply)."""
        domain_id, run_id = _start_and_replicate(clusters)
        clusters.split_brain_promote(DOMAIN)
        apoller = TaskPoller(clusters.active, DOMAIN, TL,
                             {WF: SignalDecider(expected_signals=2)})
        clusters.active.frontend.signal_workflow_execution(DOMAIN, WF, "a-1")
        apoller.drain()
        spoller = TaskPoller(clusters.standby, DOMAIN, TL,
                             {WF: SignalDecider(expected_signals=2)})
        clusters.standby.frontend.signal_workflow_execution(DOMAIN, WF, "b-1")
        clusters.standby.frontend.signal_workflow_execution(DOMAIN, WF, "b-2")
        spoller.drain()
        clusters.heal(DOMAIN, "standby")

        for box in (clusters.active, clusters.standby):
            result = box.tpu.verify_all()
            assert result.ok, f"{box.cluster_name}: {result}"
            assert result.verified_on_device == result.total == 1

    def test_lower_version_suffix_stays_non_current(self, clusters):
        """The direction matters: when only the LOSER's suffix crosses the
        wire, the winner's state must not move (no spurious rebuild)."""
        domain_id, run_id = _start_and_replicate(clusters)
        clusters.split_brain_promote(DOMAIN)
        # active (v1, loser) write
        apoller = TaskPoller(clusters.active, DOMAIN, TL,
                             {WF: SignalDecider(expected_signals=2)})
        clusters.active.frontend.signal_workflow_execution(DOMAIN, WF, "a-1")
        apoller.drain()
        # standby (v12) write
        spoller = TaskPoller(clusters.standby, DOMAIN, TL,
                             {WF: SignalDecider(expected_signals=2)})
        clusters.standby.frontend.signal_workflow_execution(DOMAIN, WF, "b-1")
        spoller.drain()
        before = payload_row(clusters.standby.stores.execution.get_workflow(
            domain_id, WF, run_id)).copy()

        clusters.replicate()  # active → standby only (loser suffix arrives)

        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, WF, run_id)
        assert (payload_row(standby_ms) == before).all()
        assert len(standby_ms.version_histories.histories) == 2
        assert standby_ms.version_histories.current().last_item().version == 12

    def test_duplicate_divergent_delivery_deduped(self, clusters):
        """Redelivering the loser's suffix after the fork must dedup against
        the forked branch, not fork again."""
        domain_id, run_id = _start_and_replicate(clusters)
        clusters.split_brain_promote(DOMAIN)
        apoller = TaskPoller(clusters.active, DOMAIN, TL,
                             {WF: SignalDecider(expected_signals=2)})
        clusters.active.frontend.signal_workflow_execution(DOMAIN, WF, "a-1")
        apoller.drain()
        spoller = TaskPoller(clusters.standby, DOMAIN, TL,
                             {WF: SignalDecider(expected_signals=2)})
        clusters.standby.frontend.signal_workflow_execution(DOMAIN, WF, "b-1")
        spoller.drain()
        clusters.replicate()
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, WF, run_id)
        branches_after = len(standby_ms.version_histories.histories)

        # replay the whole active stream again (at-least-once delivery)
        clusters.processor.ack_index = 0
        clusters.replicate()
        standby_ms = clusters.standby.stores.execution.get_workflow(
            domain_id, WF, run_id)
        assert len(standby_ms.version_histories.histories) == branches_after
        assert clusters.processor.deduped > 0
