"""Cross-PROCESS multi-cluster: two real wire clusters, replication over
sockets (VERDICT r4 missing #1 / top_next).

Two store-server processes + two service hosts each, composed into a
cluster group: every host's leader polls the PEER's store server over TCP
for history replication, domain metadata, and cross-cluster tasks — the
remote-poller shape of the reference's task_fetcher.go / worker
replicator against development_xdc_cluster{0,1}.yaml cluster groups.

Covered end-to-end, every byte crossing real sockets:
  - global-domain registration replicating to the peer,
  - a workflow replicated and kernel-CRC-verified on the standby,
  - managed failover (FailoverManager) mid-traffic,
  - a cross-cluster child start with the result leg routed back,
  - SIGKILL of an active-side host during replication, standby converges.
"""
import signal
import time

import numpy as np
import pytest

from cadence_tpu.core.checksum import DEFAULT_LAYOUT, crc32_of_rows, payload_row
from cadence_tpu.core.checksum import STICKY_ROW_INDEX
from cadence_tpu.core.codec import serialize_history
from cadence_tpu.core.enums import CloseStatus, DecisionType, EventType
from cadence_tpu.engine.history_engine import Decision
from cadence_tpu.rpc.cluster import launch_group
from tests.taskpoller import TaskPoller

TL = "xw-tl"


@pytest.fixture(scope="module")
def group():
    g = launch_group(num_hosts=2, num_shards=4, hb_interval=0.1, ttl=2.0)
    try:
        yield g
    finally:
        g.stop()


def _complete_many(fe, domain, workflow_ids, deadline_s=30.0):
    """Complete every workflow in the set, responding to WHATEVER task
    arrives (discarding another workflow's polled task would strand it
    until its decision timeout redelivers)."""
    remaining = set(workflow_ids)
    deadline = time.monotonic() + deadline_s
    while remaining and time.monotonic() < deadline:
        resp = fe.poll_for_decision_task(domain, TL, wait_seconds=0.5)
        if resp is None or resp.token is None:
            continue
        fe.respond_decision_task_completed(resp.token, [
            Decision(DecisionType.CompleteWorkflowExecution,
                     {"result": b"done"})])
        remaining.discard(resp.token.workflow_id)
    if remaining:
        raise TimeoutError(f"no decision task for {sorted(remaining)}")


def _complete_one(fe, domain, workflow_id, deadline_s=20.0):
    _complete_many(fe, domain, [workflow_id], deadline_s)


def _standby_history(group, domain_id, workflow_id, deadline_s=25.0):
    """Wait until the standby holds the workflow's full replicated history
    (the hosts' own pumps drain the stream); returns (run_id, batches)."""
    deadline = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < deadline:
        try:
            run = group.standby.stores.execution.get_current_run_id(
                domain_id, workflow_id)
            ms = group.standby.stores.execution.get_workflow(
                domain_id, workflow_id, run)
            if ms.execution_info.close_status != CloseStatus.Nothing:
                return run, group.standby.stores.history.as_history_batches(
                    domain_id, workflow_id, run)
            last = "open"
        except Exception as exc:
            last = exc
        time.sleep(0.1)
    raise TimeoutError(f"standby never converged on {workflow_id}: {last}")


def _kernel_crc(batches):
    """Replay one history through the DEVICE kernel → (crc32, error)."""
    import jax.numpy as jnp

    from cadence_tpu.ops.encode import encode_corpus
    from cadence_tpu.ops.replay import replay_to_payload

    rows, errors = replay_to_payload(jnp.asarray(encode_corpus([batches])),
                                     DEFAULT_LAYOUT)
    return crc32_of_rows(np.asarray(rows))[0], int(np.asarray(errors)[0])


class TestWireReplication:
    def test_global_domain_replicates(self, group):
        domain_id = group.register_global_domain("xw-base")
        d = group.standby.stores.domain.by_name("xw-base")
        assert d.domain_id == domain_id
        assert d.active_cluster == "primary" and not d.is_active

    def test_workflow_replicated_and_device_verified(self, group):
        """A workflow completed on the primary converges on the standby:
        codec-canonical histories byte-identical, kernel CRC identical on
        both sides, and both match the ORACLE replay of the active side."""
        domain_id = group.register_global_domain("xw-repl")
        fe = group.active.frontend
        fe.start_workflow_execution("xw-repl", "wf-r", "t", TL)
        _complete_one(fe, "xw-repl", "wf-r")
        run, standby_batches = _standby_history(group, domain_id, "wf-r")
        active_batches = group.active.stores.history.as_history_batches(
            domain_id, "wf-r", run)
        assert serialize_history(standby_batches) == serialize_history(
            active_batches)
        crc_a, err_a = _kernel_crc(active_batches)
        crc_s, err_s = _kernel_crc(standby_batches)
        assert err_a == 0 and err_s == 0
        assert crc_a == crc_s
        # the oracle agrees with the device on the replicated state
        from cadence_tpu.oracle.state_builder import StateBuilder

        ms = StateBuilder().replay_history(standby_batches)
        expected = payload_row(ms, DEFAULT_LAYOUT)
        expected[STICKY_ROW_INDEX] = 0
        assert np.uint32(crc32_of_rows(expected[None, :])[0]) == crc_s

    def test_signal_replicates_midstream(self, group):
        """Open-workflow replication: signals land on the standby while
        the workflow is still running on the primary."""
        domain_id = group.register_global_domain("xw-sig")
        fe = group.active.frontend
        fe.start_workflow_execution("xw-sig", "wf-s", "t", TL)
        fe.signal_workflow_execution("xw-sig", "wf-s", "ping-1")
        deadline = time.monotonic() + 25
        while time.monotonic() < deadline:
            try:
                run = group.standby.stores.execution.get_current_run_id(
                    domain_id, "wf-s")
                events = group.standby.stores.history.read_events(
                    domain_id, "wf-s", run)
                if any(e.event_type == EventType.WorkflowExecutionSignaled
                       for e in events):
                    break
            except Exception:
                pass
            time.sleep(0.1)
        else:
            raise TimeoutError("signal never replicated")
        _complete_one(fe, "xw-sig", "wf-s")
        _standby_history(group, domain_id, "wf-s")


class TestWireFailover:
    def test_managed_failover_mid_traffic(self, group):
        """FailoverManager against REAL processes: drain, flip through the
        active side's UpdateDomain, domain replication streams the flip,
        the standby's host promotes (task-refresher sweep), and traffic
        continues on the NEW active side."""
        from cadence_tpu.engine.failovermanager import (
            STATUS_SUCCESS,
            FailoverManager,
        )

        domain_id = group.register_global_domain("xw-fail")
        fe_a = group.active.frontend
        fe_a.start_workflow_execution("xw-fail", "wf-f", "t", TL)
        fe_a.signal_workflow_execution("xw-fail", "wf-f", "pre-failover")

        report = FailoverManager(group).managed_failover(
            ["xw-fail"], to_cluster="standby")
        assert report.ok, [r.detail for r in report.results]
        assert report.results[0].status == STATUS_SUCCESS

        # both clusters agree on the flip
        for box in (group.active, group.standby):
            d = box.stores.domain.by_name("xw-fail")
            assert d.active_cluster == "standby"
        # traffic continues on the NEW active side: the promoted standby
        # regenerated the pending decision task; complete it there
        fe_s = group.standby.frontend
        fe_s.signal_workflow_execution("xw-fail", "wf-f", "post-failover")
        _complete_one(fe_s, "xw-fail", "wf-f", deadline_s=25.0)
        run = group.standby.stores.execution.get_current_run_id(
            domain_id, "wf-f")
        ms = group.standby.stores.execution.get_workflow(
            domain_id, "wf-f", run)
        assert ms.execution_info.close_status == CloseStatus.Completed
        events = group.standby.stores.history.read_events(
            domain_id, "wf-f", run)
        signals = [e for e in events
                   if e.event_type == EventType.WorkflowExecutionSignaled]
        assert len(signals) == 2  # pre- AND post-failover both present
        # the OLD active side now refuses writes for this domain
        from cadence_tpu.engine.domain import DomainNotActiveError

        with pytest.raises(DomainNotActiveError):
            fe_a.signal_workflow_execution("xw-fail", "wf-f", "stale-write")


class _CrossChildDecider:
    def __init__(self, child_domain_id, child_wf):
        self.child_domain_id = child_domain_id
        self.child_wf = child_wf

    def decide(self, history):
        closes = [e for e in history if e.event_type in (
            EventType.ChildWorkflowExecutionCompleted,
            EventType.ChildWorkflowExecutionFailed,
            EventType.ChildWorkflowExecutionTerminated)]
        if closes:
            return [Decision(DecisionType.CompleteWorkflowExecution,
                             {"result": b""})]
        if any(e.event_type == EventType.StartChildWorkflowExecutionInitiated
               for e in history):
            return []
        return [Decision(DecisionType.StartChildWorkflowExecution,
                         {"workflow_id": self.child_wf,
                          "workflow_type": "xw-child-type",
                          "domain_id": self.child_domain_id,
                          "task_list": TL})]


class TestWireCrossCluster:
    def test_child_starts_on_peer_cluster(self, group):
        """A parent on the primary starts a child in a domain active on
        the STANDBY: the task parks on the primary's store, the standby's
        consumer executes it, and the result leg routes back through the
        primary's engine_routed door — all over sockets."""
        from cadence_tpu.engine.failovermanager import FailoverManager
        from cadence_tpu.models.deciders import CompleteDecider

        parent_id = group.register_global_domain("xw-par")
        child_id = group.register_global_domain("xw-chi")
        report = FailoverManager(group).managed_failover(
            ["xw-chi"], to_cluster="standby")
        assert report.ok, [r.detail for r in report.results]

        group.active.frontend.start_workflow_execution(
            "xw-par", "wf-xp", "par-type", TL)
        apoller = TaskPoller(group.active, "xw-par", TL,
                             {"wf-xp": _CrossChildDecider(child_id, "wf-xc")})
        spoller = TaskPoller(group.standby, "xw-chi", TL,
                             {"wf-xc": CompleteDecider()})
        deadline = time.monotonic() + 40
        ms = None
        while time.monotonic() < deadline:
            apoller.drain()
            spoller.drain()
            try:
                run = group.active.stores.execution.get_current_run_id(
                    parent_id, "wf-xp")
                ms = group.active.stores.execution.get_workflow(
                    parent_id, "wf-xp", run)
                if ms.execution_info.close_status == CloseStatus.Completed:
                    break
            except Exception:
                pass
            time.sleep(0.1)
        assert ms is not None
        assert ms.execution_info.close_status == CloseStatus.Completed
        # the child RAN on the standby with parent linkage to the primary
        crun = group.standby.stores.execution.get_current_run_id(
            child_id, "wf-xc")
        cms = group.standby.stores.execution.get_workflow(
            child_id, "wf-xc", crun)
        assert cms.execution_info.close_status == CloseStatus.Completed
        assert cms.execution_info.parent_workflow_id == "wf-xp"
        # the parent SAW start + close across the cluster boundary
        events = group.active.stores.history.read_events(
            parent_id, "wf-xp", run)
        types = [e.event_type for e in events]
        assert EventType.ChildWorkflowExecutionStarted in types
        assert EventType.ChildWorkflowExecutionCompleted in types


class TestWireKillDuringReplication:
    def test_sigkill_active_host_standby_converges(self, group):
        """SIGKILL an active-side host while its workflows' replication is
        in flight: the survivor steals the shards AND the leader pump, and
        the standby still converges to byte-identical histories with
        kernel-CRC parity for every workflow."""
        domain_id = group.register_global_domain("xw-kill")
        fe = group.active.frontend
        workflows = [f"wf-k{i}" for i in range(6)]
        # complete half BEFORE the kill so the stream is mid-flight; the
        # second half's starts land just before the kill
        for wf in workflows[:3]:
            fe.start_workflow_execution("xw-kill", wf, "t", TL)
        _complete_many(fe, "xw-kill", workflows[:3])
        for wf in workflows[3:]:
            fe.start_workflow_execution("xw-kill", wf, "t", TL)

        # kill the host the test's frontend is NOT connected to (the
        # frontend client pins host 0; the survivor serving through the
        # steal is the point)
        victim = sorted(group.active.wire.hosts)[1]
        group.active.wire.kill_host(victim, signal.SIGKILL)

        # the survivor serves the rest (shards steal over TTL)
        _complete_many(fe, "xw-kill", workflows[3:], deadline_s=40.0)

        for wf in workflows:
            run, standby_batches = _standby_history(group, domain_id, wf,
                                                    deadline_s=40.0)
            active_batches = group.active.stores.history.as_history_batches(
                domain_id, wf, run)
            assert serialize_history(standby_batches) == serialize_history(
                active_batches), f"{wf} diverged"
            crc_a, err_a = _kernel_crc(active_batches)
            crc_s, err_s = _kernel_crc(standby_batches)
            assert err_a == 0 and err_s == 0 and crc_a == crc_s, wf

    def test_sigkill_standby_leader_consumer_hands_off(self, group):
        """Kill the STANDBY's replication-consumer leader mid-stream: the
        surviving standby host steals shard 0, becomes the leader, and
        resumes consumption from the PERSISTED ack level — no events lost,
        none double-applied (the monotonic queue-ack contract)."""
        domain_id = group.register_global_domain("xw-kill2")
        fe = group.active.frontend
        fe.start_workflow_execution("xw-kill2", "wf-h1", "t", TL)
        _complete_one(fe, "xw-kill2", "wf-h1")
        _standby_history(group, domain_id, "wf-h1")  # leader consumed some

        # the standby's leader is whoever owns shard 0 — kill host 0 (its
        # initial owner); the test only talks to the standby's STORE
        victim = sorted(group.standby.wire.hosts)[0]
        group.standby.wire.kill_host(victim, signal.SIGKILL)

        fe.start_workflow_execution("xw-kill2", "wf-h2", "t", TL)
        _complete_one(fe, "xw-kill2", "wf-h2")
        for wf in ("wf-h1", "wf-h2"):
            run, standby_batches = _standby_history(group, domain_id, wf,
                                                    deadline_s=40.0)
            active_batches = group.active.stores.history.as_history_batches(
                domain_id, wf, run)
            assert serialize_history(standby_batches) == serialize_history(
                active_batches), f"{wf} diverged after leader handoff"
