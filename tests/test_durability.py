"""Durable persistence + crash recovery tests.

Reference tier: the persistence-conformance suite
(common/persistence/persistence-tests) + DR rehydration; recovery rebuilds
mutable state by replay (state_rebuilder.go:102) with the TPU engine as the
bulk verifier — VERDICT round-1 item 5's kill-restart scenario.

The kill-restart/NDC/quarantine matrix runs parametrized over BOTH
open_log backends (JSONL and SqliteLog) via the `wal` fixture — SQLite is
a first-class durability citizen, not a three-test afterthought. Only the
physically JSONL-specific torn-tail cases stay single-backend."""
import pytest

from cadence_tpu.core.enums import CloseStatus, EventType
from cadence_tpu.engine.durability import (
    open_durable_stores,
    recover_stores,
)
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import EchoDecider, RetryActivityDecider
from tests.taskpoller import TaskPoller

DOMAIN = "durable-domain"
TL = "durable-tl"

# the dual-backend `wal` fixture lives in tests/conftest.py


class TestKillRestart:
    def test_100_workflows_survive_crash_and_complete(self, wal):
        box = Onebox(num_hosts=1, num_shards=4,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        ids = [f"dur-{i}" for i in range(100)]
        deciders = {wid: EchoDecider(TL) for wid in ids}
        for wid in ids:
            box.frontend.start_workflow_execution(DOMAIN, wid, "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL, deciders)
        # drive halfway: first decisions run, activities dispatched, NOT run
        box.pump_once()
        while poller.poll_and_decide_once():
            pass
        box.pump_once()

        del box  # CRASH: process dies; matching backlog + queues are gone

        stores, report = recover_stores(wal)
        assert report.executions_rebuilt == 100
        assert report.open_workflows == 100
        assert report.ok, f"divergent after recovery: {report.divergent}"
        assert report.device_verified + report.oracle_fallback == 100

        box2 = Onebox(num_hosts=1, num_shards=4, stores=stores)
        assert box2.refresh_all_tasks() > 0
        poller2 = TaskPoller(box2, DOMAIN, TL, deciders)
        poller2.drain()
        for wid in ids:
            ms = box2.frontend.describe_workflow_execution(DOMAIN, wid)
            assert ms.execution_info.close_status == CloseStatus.Completed

    def test_completed_workflows_stay_completed(self, wal):
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "done-1", "echo", TL)
        TaskPoller(box, DOMAIN, TL, {"done-1": EchoDecider(TL)}).drain()
        del box

        stores, report = recover_stores(wal)
        assert report.executions_rebuilt == 1 and report.open_workflows == 0
        box2 = Onebox(num_hosts=1, num_shards=2, stores=stores)
        ms = box2.frontend.describe_workflow_execution(DOMAIN, "done-1")
        assert ms.execution_info.close_status == CloseStatus.Completed
        # recovered history is byte-for-byte usable: same event sequence
        events = box2.frontend.get_workflow_execution_history(DOMAIN, "done-1")
        assert events[0].event_type == EventType.WorkflowExecutionStarted
        assert events[-1].event_type == EventType.WorkflowExecutionCompleted

    def test_second_crash_after_recovery(self, wal):
        """The recovered process keeps logging to the same WAL; a second
        crash recovers the post-recovery work too."""
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "w2", "echo", TL)
        box.pump_once()
        del box

        stores, _ = recover_stores(wal)
        box2 = Onebox(num_hosts=1, num_shards=2, stores=stores)
        box2.refresh_all_tasks()
        TaskPoller(box2, DOMAIN, TL, {"w2": EchoDecider(TL)}).drain()
        ms = box2.frontend.describe_workflow_execution(DOMAIN, "w2")
        assert ms.execution_info.close_status == CloseStatus.Completed
        del box2

        stores3, report3 = recover_stores(wal)
        box3 = Onebox(num_hosts=1, num_shards=2, stores=stores3)
        ms = box3.frontend.describe_workflow_execution(DOMAIN, "w2")
        assert ms.execution_info.close_status == CloseStatus.Completed
        assert report3.ok

    def test_midretry_activity_restarts_from_attempt_zero(self, wal):
        """Documented deviation: transient retry state (no events) is not
        durable — after a crash the activity re-runs from attempt 0; the
        workflow still completes (at-least-once preserved)."""
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "flaky", "retry", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"flaky": RetryActivityDecider(TL)})
        box.pump_once()
        poller.poll_and_decide_once()
        box.pump_once()
        resp = box.frontend.poll_for_activity_task(DOMAIN, TL)
        box.frontend.respond_activity_task_failed(resp.token, "boom")  # attempt→1
        del box

        stores, report = recover_stores(wal)
        assert report.ok
        box2 = Onebox(num_hosts=1, num_shards=2, stores=stores)
        domain_id = box2.stores.domain.by_name(DOMAIN).domain_id
        run_id = box2.stores.execution.get_current_run_id(domain_id, "flaky")
        ms = box2.stores.execution.get_workflow(domain_id, "flaky", run_id)
        ai = next(iter(ms.pending_activity_info_ids.values()))
        assert ai.attempt == 0  # transient attempts reset by design
        box2.refresh_all_tasks()
        box2.pump_once()
        poller2 = TaskPoller(box2, DOMAIN, TL,
                             {"flaky": RetryActivityDecider(TL)})
        resp = box2.frontend.poll_for_activity_task(DOMAIN, TL)
        box2.frontend.respond_activity_task_completed(resp.token)
        poller2.drain()
        ms = box2.frontend.describe_workflow_execution(DOMAIN, "flaky")
        assert ms.execution_info.close_status == CloseStatus.Completed


class TestTornWrites:
    def test_torn_trailing_record_is_dropped(self, tmp_path):
        wal = str(tmp_path / "wal.jsonl")
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "torn", "echo", TL)
        TaskPoller(box, DOMAIN, TL, {"torn": EchoDecider(TL)}).drain()
        del box
        with open(wal, "a", encoding="utf-8") as fh:
            fh.write('{"t":"h","d":"x"')  # kill mid-append
        stores, report = recover_stores(wal)
        assert report.ok and report.executions_rebuilt == 1

    def test_mid_file_corruption_refuses_recovery(self, tmp_path):
        from cadence_tpu.engine.durability import CorruptLogError
        wal = str(tmp_path / "wal.jsonl")
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "c", "echo", TL)
        del box
        lines = open(wal).read().splitlines()
        lines[1] = lines[1][:10]  # corrupt a non-final record
        open(wal, "w").write("\n".join(lines) + "\n")
        with pytest.raises(CorruptLogError):
            recover_stores(wal)

    def test_pointer_without_history_is_dropped(self, tmp_path):
        """Torn start (pointer logged, history batch lost): the workflow id
        must become startable again, not wedge WorkflowAlreadyStarted."""
        import json
        wal = str(tmp_path / "wal.jsonl")
        box = Onebox(num_hosts=1, num_shards=2,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        del box
        with open(wal, "a", encoding="utf-8") as fh:
            fh.write(json.dumps({"t": "cur", "d": "some-domain",
                                 "w": "ghost", "r": "run-1",
                                 "st": 1, "cs": 0}) + "\n")
        stores, report = recover_stores(wal)
        from cadence_tpu.engine.persistence import EntityNotExistsError
        with pytest.raises(EntityNotExistsError):
            stores.execution.get_current_run_id("some-domain", "ghost")


class TestNDCDurability:
    def test_forked_branches_survive_crash(self, wal):
        """Split-brain divergence on a durable standby: branches, the
        current pointer, and the conflict-resolved state all recover."""
        from cadence_tpu.engine.multicluster import ReplicatedClusters
        from cadence_tpu.models.deciders import SignalDecider
        c = ReplicatedClusters(num_hosts=1, num_shards=4,
                               standby_stores=open_durable_stores(wal))
        c.register_global_domain(DOMAIN)
        c.active.frontend.start_workflow_execution(DOMAIN, "nd", "signal", TL)
        p = TaskPoller(c.active, DOMAIN, TL,
                       {"nd": SignalDecider(expected_signals=2)})
        p.drain()
        c.replicate()
        c.split_brain_promote(DOMAIN)
        c.active.frontend.signal_workflow_execution(DOMAIN, "nd", "a1")
        p.drain()
        sp = TaskPoller(c.standby, DOMAIN, TL,
                        {"nd": SignalDecider(expected_signals=2)})
        c.standby.frontend.signal_workflow_execution(DOMAIN, "nd", "b1")
        sp.drain()
        c.replicate()  # loser suffix arrives → fork on standby

        domain_id = c.standby.stores.domain.by_name(DOMAIN).domain_id
        run_id = c.standby.stores.execution.get_current_run_id(domain_id, "nd")
        before = c.standby.stores.execution.get_workflow(domain_id, "nd", run_id)
        n_branches = len(before.version_histories.histories)
        assert n_branches == 2
        cur_index = before.version_histories.current_index

        stores, report = recover_stores(wal)
        assert report.ok
        after = stores.execution.get_workflow(domain_id, "nd", run_id)
        assert len(after.version_histories.histories) == n_branches
        assert after.version_histories.current_index == cur_index
        assert ([(i.event_id, i.version)
                 for i in after.version_histories.current().items] ==
                [(i.event_id, i.version)
                 for i in before.version_histories.current().items])

    def test_replication_queue_survives_crash(self, wal):
        """The active's outbound replication queue is durable: a recovered
        active cluster can still feed a standby from the start."""
        from cadence_tpu.engine.multicluster import ReplicatedClusters
        c = ReplicatedClusters(num_hosts=1, num_shards=4,
                               active_stores=open_durable_stores(wal))
        c.register_global_domain(DOMAIN)
        c.active.frontend.start_workflow_execution(DOMAIN, "rq", "echo", TL)
        TaskPoller(c.active, DOMAIN, TL, {"rq": EchoDecider(TL)}).drain()
        # crash the active BEFORE replicating
        stores, report = recover_stores(wal)
        assert report.ok
        c2 = ReplicatedClusters(num_hosts=1, num_shards=4,
                                active_stores=stores)
        c2.register_global_domain(DOMAIN + "-2")  # fresh standby needs domain
        applied = c2.replicate()
        assert applied > 0
        domain_id = stores.domain.by_name(DOMAIN).domain_id
        run_id = stores.execution.get_current_run_id(domain_id, "rq")
        standby_ms = c2.standby.stores.execution.get_workflow(
            domain_id, "rq", run_id)
        assert standby_ms.execution_info.close_status == CloseStatus.Completed


class TestOrphanQuarantine:
    def test_orphan_history_not_resurrected_as_open(self, wal):
        """History appended by a start that died before its
        create_workflow commit point must not come back as an open
        workflow after recovery (ADVICE r3): it is quarantined — state
        kept, but excluded from open counts, visibility, and dispatch."""
        from cadence_tpu.gen.corpus import generate_corpus
        box = Onebox(num_hosts=1, num_shards=4,
                     stores=open_durable_stores(wal))
        box.frontend.register_domain(DOMAIN)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        # a real workflow, completed normally
        box.frontend.start_workflow_execution(DOMAIN, "wf-live", "echo", TL)
        TaskPoller(box, DOMAIN, TL, {"wf-live": EchoDecider(TL)}).drain()
        # forge a torn start: history lands in the WAL, but the process
        # dies before create_workflow ever writes a current-run record
        orphan = generate_corpus("basic", num_workflows=1, seed=3,
                                 target_events=20)[0]
        # only the start batch: the run is still OPEN when the crash hits
        box.stores.history.append_batch(domain_id, "wf-orphan",
                                        "orphan-run", orphan[0].events)
        # crash + recover
        stores, report = recover_stores(wal)
        assert (domain_id, "wf-orphan", "orphan-run") in report.quarantined
        assert report.open_workflows == 0
        open_wfs = stores.visibility.list_open(domain_id)
        assert [r.workflow_id for r in open_wfs] == []
        # the real workflow is still there and closed
        closed = stores.visibility.list_closed(domain_id)
        assert "wf-live" in [r.workflow_id for r in closed]


class TestTornTailHealing:
    """A kill mid-append leaves a partial final line; reopening the log
    must TRUNCATE it before appending, or the next record welds onto
    garbage and a recoverable torn tail becomes permanent MID-file
    corruption (code-review r5 finding)."""

    def test_append_after_torn_tail_stays_recoverable(self, tmp_path):
        import json as _json

        from cadence_tpu.engine.durability import DurableLog

        wal = str(tmp_path / "torn.jsonl")
        log = DurableLog(wal)
        log.append({"t": "ver", "v": 2})
        log.append({"t": "cfg", "k": "a", "v": 1, "dom": None})
        log.close()
        with open(wal, "a", encoding="utf-8") as fh:
            fh.write('{"t": "cfg", "k": "torn')  # no newline: torn tail
        # reopen + append (what a recovered process does)
        log = DurableLog(wal)
        log.append({"t": "cfg", "k": "b", "v": 2, "dom": None})
        log.close()
        records = DurableLog.read_all(wal)  # must NOT raise CorruptLog
        assert [r.get("k") for r in records] == [None, "a", "b"]

    def test_newline_terminated_torn_json_also_healed(self, tmp_path):
        from cadence_tpu.engine.durability import DurableLog

        wal = str(tmp_path / "torn2.jsonl")
        log = DurableLog(wal)
        log.append({"t": "ver", "v": 2})
        log.close()
        with open(wal, "a", encoding="utf-8") as fh:
            fh.write('{"t": "cfg", "k"\n')  # torn JSON, newline present
        log = DurableLog(wal)
        log.append({"t": "cfg", "k": "c", "v": 3, "dom": None})
        log.close()
        records = DurableLog.read_all(wal)
        assert [r["t"] for r in records] == ["ver", "cfg"]
