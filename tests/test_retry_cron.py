"""Activity retry + cron continuation tests.

Reference tier: host/retry_policy_workflow_test.go + canary retry/cron
(canary/retry.go, canary/cron.go); backoff math per
service/history/execution/retry.go:31-80 and common/backoff/cron.go:48.
"""
import pytest

from cadence_tpu.core.enums import (
    EMPTY_EVENT_ID,
    TRANSIENT_EVENT_ID,
    CloseStatus,
    ContinueAsNewInitiator,
    EventType,
)
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import (
    CompleteDecider,
    FailDecider,
    RetryActivityDecider,
)
from cadence_tpu.utils.backoff import (
    NO_BACKOFF,
    get_backoff_for_next_schedule,
    get_backoff_interval,
)
from tests.taskpoller import TaskPoller

DOMAIN = "retry-domain"
TL = "retry-tl"
SECOND = 1_000_000_000


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


def _schedule_flaky_activity(box, poller, workflow_id):
    """Start + first decision: one activity with a retry policy lands in
    matching."""
    box.pump_once()
    assert poller.poll_and_decide_once()
    box.pump_once()


class TestActivityRetry:
    def test_fails_twice_then_succeeds(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "flaky-1", "retry", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"flaky-1": RetryActivityDecider(TL)})
        _schedule_flaky_activity(box, poller, "flaky-1")

        for attempt in range(2):
            resp = box.frontend.poll_for_activity_task(DOMAIN, TL)
            assert resp is not None
            assert resp.token.started_id == TRANSIENT_EVENT_ID
            box.frontend.respond_activity_task_failed(resp.token, "boom")
            # transient retry: nothing new in history
            events = box.frontend.get_workflow_execution_history(DOMAIN, "flaky-1")
            assert not any(e.event_type in (EventType.ActivityTaskStarted,
                                            EventType.ActivityTaskFailed)
                           for e in events)
            # backoff 1s then 2s; advance past it and fire the retry timer
            box.advance_time(4)
            box.pump_once()

        resp = box.frontend.poll_for_activity_task(DOMAIN, TL)
        assert resp is not None
        box.frontend.respond_activity_task_completed(resp.token)
        poller.drain()

        ms = box.frontend.describe_workflow_execution(DOMAIN, "flaky-1")
        assert ms.execution_info.close_status == CloseStatus.Completed
        events = box.frontend.get_workflow_execution_history(DOMAIN, "flaky-1")
        started = [e for e in events
                   if e.event_type == EventType.ActivityTaskStarted]
        scheduled = [e for e in events
                     if e.event_type == EventType.ActivityTaskScheduled]
        # ONE scheduled event, ONE flushed started event carrying the final
        # attempt count and the last failure (transient retry semantics)
        assert len(scheduled) == 1 and len(started) == 1
        assert started[0].get("attempt") == 2
        assert started[0].get("last_failure_reason") == "boom"

    def test_retries_exhausted_fails_workflow(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "flaky-2", "retry", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"flaky-2": RetryActivityDecider(
                                TL, maximum_attempts=2)})
        _schedule_flaky_activity(box, poller, "flaky-2")

        resp = box.frontend.poll_for_activity_task(DOMAIN, TL)
        box.frontend.respond_activity_task_failed(resp.token, "boom")  # retries
        box.advance_time(2)
        box.pump_once()
        resp = box.frontend.poll_for_activity_task(DOMAIN, TL)
        box.frontend.respond_activity_task_failed(resp.token, "boom")  # final
        poller.drain()

        events = box.frontend.get_workflow_execution_history(DOMAIN, "flaky-2")
        failed = [e for e in events
                  if e.event_type == EventType.ActivityTaskFailed]
        started = [e for e in events
                   if e.event_type == EventType.ActivityTaskStarted]
        assert len(failed) == 1 and len(started) == 1
        assert started[0].get("attempt") == 1
        ms = box.frontend.describe_workflow_execution(DOMAIN, "flaky-2")
        assert ms.execution_info.close_status == CloseStatus.Failed

    def test_stale_attempt_token_rejected(self, box):
        """A superseded attempt's token must not close the current attempt:
        transient attempts share started_id, so the token's attempt field
        is the disambiguator (reference taskToken.ScheduleAttempt)."""
        from cadence_tpu.engine.history_engine import InvalidRequestError
        box.frontend.start_workflow_execution(DOMAIN, "flaky-s", "retry", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"flaky-s": RetryActivityDecider(TL)})
        _schedule_flaky_activity(box, poller, "flaky-s")
        stale = box.frontend.poll_for_activity_task(DOMAIN, TL).token
        box.frontend.respond_activity_task_failed(stale, "boom")  # → attempt 1
        box.advance_time(2)
        box.pump_once()
        fresh = box.frontend.poll_for_activity_task(DOMAIN, TL).token
        assert fresh.attempt == 1
        with pytest.raises(InvalidRequestError):
            box.frontend.respond_activity_task_completed(stale)
        box.frontend.respond_activity_task_completed(fresh)
        poller.drain()
        ms = box.frontend.describe_workflow_execution(DOMAIN, "flaky-s")
        assert ms.execution_info.close_status == CloseStatus.Completed

    def test_retry_history_replays_on_device(self, box):
        """Kernel/oracle parity on an ENGINE-generated retry-shaped history
        (the corpus no longer needs to fake these)."""
        box.frontend.start_workflow_execution(DOMAIN, "flaky-3", "retry", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"flaky-3": RetryActivityDecider(TL)})
        _schedule_flaky_activity(box, poller, "flaky-3")
        resp = box.frontend.poll_for_activity_task(DOMAIN, TL)
        box.frontend.respond_activity_task_failed(resp.token, "boom")
        box.advance_time(2)
        box.pump_once()
        resp = box.frontend.poll_for_activity_task(DOMAIN, TL)
        box.frontend.respond_activity_task_completed(resp.token)
        poller.drain()

        result = box.tpu.verify_all()
        assert result.ok and result.total >= 1


class TestCron:
    def test_cron_reruns_on_schedule(self, box):
        box.frontend.start_workflow_execution(
            DOMAIN, "cron-1", "cron-type", TL, cron_schedule="* * * * *")
        poller = TaskPoller(box, DOMAIN, TL, {"cron-1": CompleteDecider()})
        poller.drain()

        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run1 = None
        # first run closed as continued-as-new, not completed
        runs = [k for k in box.stores.execution.list_executions()
                if k[1] == "cron-1"]
        assert len(runs) == 2
        states = {k[2]: box.stores.execution.get_workflow(*k) for k in runs}
        closed = [ms for ms in states.values()
                  if ms.execution_info.close_status == CloseStatus.ContinuedAsNew]
        assert len(closed) == 1
        events = box.stores.history.read_events(*[
            k for k in runs
            if states[k[2]].execution_info.close_status == CloseStatus.ContinuedAsNew
        ][0])
        can = [e for e in events
               if e.event_type == EventType.WorkflowExecutionContinuedAsNew]
        assert len(can) == 1

        # second run waits on its cron backoff timer; fire it
        box.advance_time(61)
        box.pump_once()
        poller.drain()
        runs = [k for k in box.stores.execution.list_executions()
                if k[1] == "cron-1"]
        assert len(runs) == 3  # second completion chained a third run

    def test_cron_chain_recomputes_retry_expiration(self, box):
        """A cron-initiated continue-as-new must NOT inherit the first run's
        retry deadline: the reference recalculates it (now + expiration
        interval + first-decision backoff, mutable_state_builder.go:1646-1652)
        so later iterations keep their retry budget."""
        from cadence_tpu.core.events import RetryPolicy
        box.frontend.start_workflow_execution(
            DOMAIN, "cron-exp", "cron-type", TL, cron_schedule="* * * * *",
            retry_policy=RetryPolicy(initial_interval_seconds=1,
                                     backoff_coefficient=2.0,
                                     maximum_interval_seconds=10,
                                     expiration_interval_seconds=30))
        poller = TaskPoller(box, DOMAIN, TL, {"cron-exp": CompleteDecider()})
        poller.drain()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run1 = [box.stores.execution.get_workflow(*k)
                for k in box.stores.execution.list_executions()
                if k[1] == "cron-exp"
                and box.stores.execution.get_workflow(*k)
                .execution_info.close_status == CloseStatus.ContinuedAsNew][0]
        current = box.stores.execution.get_current_run_id(domain_id, "cron-exp")
        run2 = box.stores.execution.get_workflow(domain_id, "cron-exp", current)
        # the chained run's deadline is fresh (recomputed from its start,
        # which includes the cron backoff), not the first run's
        assert run2.execution_info.expiration_time > \
            run1.execution_info.expiration_time
        start2 = box.stores.history.read_events(
            domain_id, "cron-exp", current)[0]
        backoff = start2.get("first_decision_task_backoff_seconds") or 0
        assert run2.execution_info.expiration_time >= \
            run2.execution_info.start_timestamp + (30 + backoff) * SECOND

    def test_cron_second_run_carries_initiator(self, box):
        box.frontend.start_workflow_execution(
            DOMAIN, "cron-2", "cron-type", TL, cron_schedule="* * * * *")
        poller = TaskPoller(box, DOMAIN, TL, {"cron-2": CompleteDecider()})
        poller.drain()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        current = box.stores.execution.get_current_run_id(domain_id, "cron-2")
        start = box.stores.history.read_events(domain_id, "cron-2", current)[0]
        assert start.get("initiator") == ContinueAsNewInitiator.CronSchedule
        assert (start.get("first_decision_task_backoff_seconds") or 0) > 0


class TestWorkflowRetry:
    def test_failing_workflow_retries_then_gives_up(self, box):
        from cadence_tpu.core.events import RetryPolicy
        box.frontend.start_workflow_execution(
            DOMAIN, "wfr-1", "fail-type", TL,
            retry_policy=RetryPolicy(initial_interval_seconds=1,
                                     backoff_coefficient=2.0,
                                     maximum_interval_seconds=10,
                                     maximum_attempts=2))
        poller = TaskPoller(box, DOMAIN, TL, {"wfr-1": FailDecider()})
        poller.drain()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run1_keys = [k for k in box.stores.execution.list_executions()
                     if k[1] == "wfr-1"]
        assert len(run1_keys) == 2  # original + retry run
        current = box.stores.execution.get_current_run_id(domain_id, "wfr-1")
        start = box.stores.history.read_events(domain_id, "wfr-1", current)[0]
        assert start.get("initiator") == ContinueAsNewInitiator.RetryPolicy
        assert start.get("attempt") == 1

        # retry run waits on its backoff timer, then fails for real
        box.advance_time(2)
        box.pump_once()
        poller.drain()
        ms = box.frontend.describe_workflow_execution(DOMAIN, "wfr-1")
        assert ms.execution_info.close_status == CloseStatus.Failed
        assert len([k for k in box.stores.execution.list_executions()
                    if k[1] == "wfr-1"]) == 2  # attempts exhausted, no 3rd run


class TestBackoffMath:
    def test_exponential_with_cap(self):
        # attempt 0: 2s; attempt 3: 2*3^3=54 → capped at 30
        assert get_backoff_interval(0, 0, 0, 10, 2, 30, 3.0, "", []) == 2 * SECOND
        assert get_backoff_interval(0, 0, 3, 10, 2, 30, 3.0, "", []) == 30 * SECOND

    def test_max_attempts_counts_initial(self):
        # maxAttempts=3 allows attempts 0,1,2; currAttempt 2 → no backoff
        assert get_backoff_interval(0, 0, 2, 3, 1, 0, 2.0, "", []) == NO_BACKOFF
        assert get_backoff_interval(0, 0, 1, 3, 1, 0, 2.0, "", []) == 2 * SECOND

    def test_expiration_cuts_off(self):
        now = 100 * SECOND
        assert get_backoff_interval(now, now + 1 * SECOND, 0, 10, 5, 0,
                                    1.0, "", []) == NO_BACKOFF

    def test_non_retriable_reason(self):
        assert get_backoff_interval(0, 0, 0, 10, 1, 0, 2.0,
                                    "bad", ["bad"]) == NO_BACKOFF

    def test_no_policy_means_no_backoff(self):
        assert get_backoff_interval(0, 0, 0, 0, 1, 0, 2.0, "", []) == NO_BACKOFF

    def test_cron_every_minute(self):
        # close at t=90s → next minute boundary 120s → 30s backoff
        assert get_backoff_for_next_schedule("* * * * *", 0, 90 * SECOND) == 30

    def test_cron_every_five_minutes(self):
        assert get_backoff_for_next_schedule("*/5 * * * *", 0, 90 * SECOND) == 210

    def test_cron_hourly_at_minute(self):
        # "15 * * * *": close at 10:20 → next 11:15 → 3300s
        close = (10 * 3600 + 20 * 60) * SECOND
        assert get_backoff_for_next_schedule("15 * * * *", 0, close) == 3300

    def test_cron_step_star_keeps_star_bit(self):
        """robfig/cron v1.2.0 keeps the star bit for '*/n', and a star bit
        on either day field switches day matching to AND: '0 0 */2 * 1'
        fires on odd days that are ALSO Mondays — not Sat Jan 3 (odd,
        non-Monday, the OR answer) and not Mon Jan 12 (even Monday)."""
        from cadence_tpu.utils.backoff import CronSchedule
        s = CronSchedule("0 0 */2 * 1")
        assert s.dom_star and not s.dow_star
        from datetime import datetime, timezone
        nxt = s.next_after(datetime(2026, 1, 1, tzinfo=timezone.utc))
        assert (nxt.year, nxt.month, nxt.day) == (2026, 1, 5)
        nxt = s.next_after(nxt)
        assert (nxt.year, nxt.month, nxt.day) == (2026, 1, 19)

    def test_invalid_cron(self):
        assert get_backoff_for_next_schedule("bogus", 0, 0) == NO_BACKOFF
        assert get_backoff_for_next_schedule("", 0, 0) == NO_BACKOFF
