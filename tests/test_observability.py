"""Observability layer (ISSUE 1): histogram bucket math + percentiles,
Prometheus text rendering, trace propagation (in-process nesting, wire
envelope round-trip, cross-process stitching), the /metrics + /health
scrape surface, and the replay profiler's leg decomposition.
"""
import json
import socket
import urllib.request

import pytest

from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import CompleteDecider
from cadence_tpu.utils import metrics as m
from cadence_tpu.utils import tracing
from cadence_tpu.utils.metrics import HistogramStat, MetricsRegistry
from cadence_tpu.utils.profiler import ReplayProfiler
from tests.taskpoller import TaskPoller

DOMAIN = "obs-domain"
TL = "obs-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=2, num_shards=8)
    b.frontend.register_domain(DOMAIN)
    return b


def _run_one_workflow(b: Onebox, workflow_id: str = "obs-wf") -> None:
    b.frontend.start_workflow_execution(DOMAIN, workflow_id, "t", TL)
    TaskPoller(b, DOMAIN, TL, {workflow_id: CompleteDecider()}).drain()


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------

class TestHistogram:
    def test_bucket_math_le_semantics(self):
        h = HistogramStat(bounds=(0.005, 0.01, 0.05))
        h.observe(0.003)   # <= 0.005
        h.observe(0.005)   # boundary lands in its own bucket (le semantics)
        h.observe(0.02)    # <= 0.05
        h.observe(99.0)    # +Inf overflow
        assert h.count == 4
        assert h.bucket_counts == [2, 0, 1, 1]
        assert h.cumulative() == [("0.005", 2), ("0.01", 2),
                                  ("0.05", 3), ("+Inf", 4)]

    def test_percentile_interpolation(self):
        h = HistogramStat(bounds=(0.025, 0.05, 0.1))
        for _ in range(100):
            h.observe(0.03)  # all in the (0.025, 0.05] bucket
        # p50 target = 50th of 100 obs, halfway through the bucket:
        # 0.025 + (0.05 - 0.025) * 0.5
        assert h.percentile(0.5) == pytest.approx(0.0375)
        assert h.percentile(0.0) == pytest.approx(0.025, abs=0.025)
        # overflow clamps to the top finite bound
        h2 = HistogramStat(bounds=(0.01,))
        h2.observe(5.0)
        assert h2.percentile(0.99) == 0.01

    def test_empty_histogram_is_safe(self):
        h = HistogramStat()
        assert h.count == 0 and h.percentile(0.5) == 0.0

    def test_registry_record_feeds_histogram(self):
        r = MetricsRegistry()
        r.record("s", m.M_LATENCY, 0.004)
        r.record("s", m.M_LATENCY, 0.004)
        hist = r.histogram("s", m.M_LATENCY)
        assert hist.count == 2
        assert r.percentiles("s", m.M_LATENCY)["p50"] > 0
        snap = r.snapshot()["s"]
        assert snap["latency.count"] == 2
        assert snap["latency.p50"] > 0

    def test_registry_reset(self):
        r = MetricsRegistry()
        r.inc("s", "requests")
        r.record("s", "latency", 0.1)
        r.gauge("s", "g", 1.0)
        r.observe("s", "h", 2.0)
        r.reset()
        assert r.snapshot() == {}
        assert r.counter("s", "requests") == 0


# ---------------------------------------------------------------------------
# prometheus rendering
# ---------------------------------------------------------------------------

class TestPrometheus:
    def test_exact_text_format(self):
        r = MetricsRegistry()
        r.inc("history.start-workflow-execution", "requests", 3)
        r.gauge("tpu.replay-engine", "replay-events-per-sec", 12.5)
        r.observe("tpu.replay-engine", "latency", 0.004,
                  buckets=(0.005, 0.01))
        lines = r.to_prometheus().splitlines()
        assert "# TYPE cadence_requests_total counter" in lines
        assert ('cadence_requests_total'
                '{scope="history.start-workflow-execution"} 3') in lines
        assert "# TYPE cadence_replay_events_per_sec gauge" in lines
        assert ('cadence_replay_events_per_sec'
                '{scope="tpu.replay-engine"} 12.5') in lines
        assert "# TYPE cadence_latency histogram" in lines
        assert ('cadence_latency_bucket'
                '{scope="tpu.replay-engine",le="0.005"} 1') in lines
        assert ('cadence_latency_bucket'
                '{scope="tpu.replay-engine",le="0.01"} 1') in lines
        assert ('cadence_latency_bucket'
                '{scope="tpu.replay-engine",le="+Inf"} 1') in lines
        assert ('cadence_latency_sum'
                '{scope="tpu.replay-engine"} 0.004') in lines
        assert ('cadence_latency_count'
                '{scope="tpu.replay-engine"} 1') in lines

    def test_name_sanitization_and_type_dedup(self):
        r = MetricsRegistry()
        r.inc("a", "tasks-dropped-entity-not-exists")
        r.inc("b", "tasks-dropped-entity-not-exists")
        text = r.to_prometheus()
        assert text.count(
            "# TYPE cadence_tasks_dropped_entity_not_exists_total counter") == 1
        assert 'cadence_tasks_dropped_entity_not_exists_total{scope="a"} 1' in text

    def test_empty_registry_renders_empty(self):
        assert MetricsRegistry().to_prometheus() == ""


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

class TestTracing:
    def test_nested_spans_share_trace_and_parent(self):
        tr = tracing.Tracer()
        with tr.start_span("outer") as outer:
            with tr.start_span("inner") as inner:
                pass
        assert inner.context.trace_id == outer.context.trace_id
        assert inner.parent_id == outer.context.span_id
        assert outer.parent_id is None
        assert {s.operation for s in tr.finished_spans()} == {"outer", "inner"}
        assert all(s.duration_s >= 0 for s in tr.finished_spans())

    def test_error_tagging(self):
        tr = tracing.Tracer()
        with pytest.raises(ValueError):
            with tr.start_span("boom"):
                raise ValueError("x")
        (span,) = tr.finished_spans()
        assert span.tags["error"] == "ValueError"

    def test_inject_passthrough_without_active_span(self):
        tr = tracing.Tracer()
        assert tracing.inject(("ping",), tracer=tr) == ("ping",)
        assert tracing.extract(("ping",)) == (None, ("ping",))

    def test_wire_envelope_round_trip(self):
        """Inject → length-prefixed frame over a real socket → extract:
        the carrier survives the wire byte-for-byte."""
        from cadence_tpu.rpc import wire

        tr = tracing.Tracer()
        request = ("frontend", "start_workflow_execution", ("d", "w"), {})
        client, server = socket.socketpair()
        try:
            with tr.start_span("client.call") as span:
                wire.send_frame(client, tracing.inject(request, tracer=tr))
            ctx, inner = tracing.extract(wire.recv_frame(server))
        finally:
            client.close()
            server.close()
        assert inner == request
        assert ctx is not None
        assert ctx.trace_id == span.context.trace_id
        assert ctx.span_id == span.context.span_id
        # a server span parented on the extracted context stitches into
        # the client's trace
        tr2 = tracing.Tracer()
        with tr2.start_span("rpc.frontend", child_of=ctx) as server_span:
            pass
        assert server_span.context.trace_id == span.context.trace_id
        assert server_span.parent_id == span.context.span_id

    def test_malformed_carrier_is_tolerated(self):
        assert tracing.extract(("traced", "garbage", ("ping",))) == \
            (None, ("ping",))
        assert tracing.SpanContext.from_carrier({"trace_id": ""}) is None


class TestOneboxTraces:
    def test_frontend_history_matching_single_trace(self, box):
        """The acceptance trace: one poll chains frontend → matching →
        history synchronously, yielding ≥3 spans under one trace_id."""
        box.frontend.start_workflow_execution(DOMAIN, "tr-wf", "t", TL)
        box.pump_once()
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        assert resp is not None and resp.token is not None
        traces = box.tracer.traces()
        poll_traces = [spans for spans in traces.values()
                       if any(s.operation == m.SCOPE_FRONTEND_POLL_DECISION
                              for s in spans)]
        assert len(poll_traces) == 1
        ops = {s.operation for s in poll_traces[0]}
        assert {m.SCOPE_FRONTEND_POLL_DECISION,
                m.SCOPE_MATCHING_POLL_DECISION,
                m.SCOPE_HISTORY_RECORD_STARTED} <= ops
        assert len(poll_traces[0]) >= 3
        # the start call stitched its own frontend→history trace
        start_traces = [spans for spans in traces.values()
                        if any(s.operation == m.SCOPE_FRONTEND_START
                               for s in spans)]
        assert {m.SCOPE_FRONTEND_START, m.SCOPE_HISTORY_START_WORKFLOW} <= {
            s.operation for s in start_traces[0]}

    def test_traced_methods_record_latency_histograms(self, box):
        _run_one_workflow(box, "lat-wf")
        hist = box.metrics.histogram(m.SCOPE_HISTORY_START_WORKFLOW,
                                     m.M_LATENCY)
        assert hist.count >= 1 and hist.total > 0


# ---------------------------------------------------------------------------
# replay profiler
# ---------------------------------------------------------------------------

class TestReplayProfiler:
    def test_verify_all_records_leg_histograms(self, box):
        _run_one_workflow(box, "prof-wf")
        assert box.tpu.verify_all().ok
        for leg in (m.M_PROFILE_PACK, m.M_PROFILE_H2D,
                    m.M_PROFILE_KERNEL, m.M_PROFILE_READBACK):
            hist = box.metrics.histogram(m.SCOPE_TPU_REPLAY, leg)
            assert hist.count >= 1, f"missing {leg} leg"
        assert box.metrics.counter(m.SCOPE_TPU_REPLAY, m.M_H2D_BYTES) > 0
        summary = ReplayProfiler(box.metrics).summary()
        assert summary["kernel_launches"] >= 1
        assert summary["h2d_bytes"] > 0
        assert summary[m.M_PROFILE_KERNEL]["count"] >= 1
        assert summary[m.M_PROFILE_KERNEL]["total_s"] > 0

    def test_latency_histogram_decomposes(self, box):
        """The end-to-end replay latency carries a histogram (acceptance:
        a tpu.replay-engine latency histogram with non-zero counts)."""
        _run_one_workflow(box, "prof-wf2")
        box.tpu.verify_all()
        hist = box.metrics.histogram(m.SCOPE_TPU_REPLAY, m.M_LATENCY)
        assert hist.count >= 1


# ---------------------------------------------------------------------------
# scrape surface (the smoke target: deploy/smoke_observability.sh)
# ---------------------------------------------------------------------------

def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.status == 200
        return resp.read()


#: metric substrings /metrics MUST contain after one workflow + one replay
REQUIRED_METRICS = (
    'cadence_requests_total{scope="history.start-workflow-execution"}',
    'cadence_requests_total{scope="frontend.start-workflow-execution"}',
    'cadence_latency_bucket{scope="tpu.replay-engine"',
    'cadence_latency_count{scope="tpu.replay-engine"}',
    'cadence_kernel_launches_total{scope="tpu.replay-engine"}',
)


@pytest.mark.smoke
class TestScrapeSurface:
    def test_onebox_metrics_and_health_scrape(self, box):
        """Boot a cluster, run one workflow, replay it on device, scrape
        /metrics, fail on missing required metric names."""
        _run_one_workflow(box, "scrape-wf")
        assert box.tpu.verify_all().ok
        server = box.scrape_server().start()
        try:
            body = _get(
                f"http://127.0.0.1:{server.port}/metrics").decode()
            for required in REQUIRED_METRICS:
                assert required in body, f"/metrics missing {required}"
            # the tpu.replay-engine latency histogram has non-zero counts
            assert ('cadence_latency_count{scope="tpu.replay-engine"} 0'
                    not in body)
            health = json.loads(_get(
                f"http://127.0.0.1:{server.port}/health"))
            assert health["status"] == "ok"
            assert health["hosts"]
            traces = json.loads(_get(
                f"http://127.0.0.1:{server.port}/traces"))
            assert any(
                any(s["operation"] == m.SCOPE_FRONTEND_START for s in spans)
                for spans in traces.values())
        finally:
            server.stop()

    def test_admin_metrics_surface(self, box):
        from cadence_tpu.engine.admin import AdminHandler
        _run_one_workflow(box, "adm-wf")
        result = AdminHandler(box).metrics()
        assert result["snapshot"][m.SCOPE_HISTORY_START_WORKFLOW][
            m.M_REQUESTS] == 1
        assert "cadence_requests_total" in result["prometheus"]


# ---------------------------------------------------------------------------
# cross-process propagation (real sockets, real processes)
# ---------------------------------------------------------------------------

class TestCrossProcessTraces:
    def test_wire_cluster_stitches_one_trace(self, tmp_path, monkeypatch):
        """A traced client call crosses the wire: the ServiceHost parents
        its rpc.frontend span (and the in-host frontend/history spans) on
        the client's span — every process exports spans to
        CADENCE_TPU_TRACE_EXPORT and they stitch by trace_id. Also scrapes
        a real ServiceHost /metrics over HTTP."""
        monkeypatch.setenv("CADENCE_TPU_TRACE_EXPORT", str(tmp_path))
        from cadence_tpu.rpc.cluster import launch
        cluster = launch(num_hosts=1, num_shards=4)
        try:
            fe = cluster.frontend(0)
            fe.register_domain(DOMAIN)
            with tracing.DEFAULT_TRACER.start_span("client.start") as cs:
                fe.start_workflow_execution(DOMAIN, "mp-wf", "t", TL)
            trace_id = cs.context.trace_id
            spans = []
            for path in tmp_path.glob("spans-*.jsonl"):
                with open(path, "r", encoding="utf-8") as fh:
                    spans.extend(json.loads(line) for line in fh)
            stitched = [s for s in spans if s["trace_id"] == trace_id]
            ops = {s["operation"] for s in stitched}
            assert "rpc.frontend" in ops
            assert m.SCOPE_FRONTEND_START in ops
            assert m.SCOPE_HISTORY_START_WORKFLOW in ops
            # spans from another PROCESS joined the client's trace
            assert {s["pid"] for s in stitched} - {__import__("os").getpid()}
            # the server span parents directly on the client span
            rpc_span = next(s for s in stitched
                            if s["operation"] == "rpc.frontend")
            assert rpc_span["parent_id"] == cs.context.span_id
            # a running ServiceHost serves prometheus text over HTTP
            (name, http_port), = cluster.http_ports.items()
            body = _get(f"http://127.0.0.1:{http_port}/metrics").decode()
            assert ('cadence_requests_total'
                    '{scope="history.start-workflow-execution"} 1') in body
            health = json.loads(
                _get(f"http://127.0.0.1:{http_port}/health"))
            assert health["status"] == "ok" and health["name"] == name
        finally:
            cluster.stop()
