"""Matching fidelity: sync-match + partitions + forwarder (VERDICT ask #7).

Reference: taskListManager.go:530 trySyncMatch, forwarder.go:111,
matchingEngine.go:729 getAllPartitions.
"""
import pytest

from cadence_tpu.engine.matching import PARTITION_PREFIX, partition_name
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import CompleteDecider, EchoDecider
from cadence_tpu.utils.dynamicconfig import (
    KEY_MATCHING_NUM_PARTITIONS,
    DynamicConfig,
)
from tests.taskpoller import TaskPoller

DOMAIN = "match-domain"
TL = "match-tl"


def make_box(partitions: int = 1) -> Onebox:
    cfg = DynamicConfig({KEY_MATCHING_NUM_PARTITIONS: partitions})
    b = Onebox(num_hosts=1, num_shards=4, config=cfg)
    b.frontend.register_domain(DOMAIN)
    return b


class TestSyncMatch:
    def test_parked_poll_rendezvous_skips_persistence(self):
        """A task added while a poll is parked hands off directly: no
        write-through, no backlog (trySyncMatch)."""
        box = make_box()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked = box.matching.park_for_decision_task(domain_id, TL)
        assert parked.task is None

        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2)
        assert parked.task is not None
        assert parked.task.workflow_id == "wf-1"
        assert parked.task.schedule_id == 2
        # nothing persisted, nothing buffered
        assert box.matching.backlog() == 0
        assert box.stores.task.get_tasks(domain_id, TL, 0, 0, 10_000) == []

    def test_canceled_park_falls_through_to_backlog(self):
        box = make_box()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked = box.matching.park_for_decision_task(domain_id, TL)
        assert parked.cancel()
        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2)
        # canceled park is skipped; the task persists in the backlog
        assert box.matching.backlog() == 1
        task = box.matching.poll_for_decision_task(domain_id, TL)
        assert task is not None and task.workflow_id == "wf-1"

    def test_activity_sync_match(self):
        box = make_box()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked = box.matching.park_for_activity_task(domain_id, TL)
        box.matching.add_activity_task(domain_id, TL, "wf-1", "run-1", 5)
        assert parked.task is not None and parked.task.schedule_id == 5


class TestPartitionsAndForwarder:
    def test_nonroot_add_forwards_to_root_parked_poller(self):
        """The VERDICT 'Done' case: a task added on a NON-ROOT partition
        reaches a poller parked on the ROOT (ForwardTask sync-match)."""
        box = make_box(partitions=4)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked_root = box.matching.park_for_decision_task(domain_id, TL,
                                                          partition=0)
        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2,
                                       partition=3)
        assert parked_root.task is not None
        assert parked_root.task.workflow_id == "wf-1"
        assert box.matching.backlog() == 0

    def test_local_parked_poller_wins_before_forwarding(self):
        box = make_box(partitions=4)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked_local = box.matching.park_for_decision_task(domain_id, TL,
                                                           partition=2)
        parked_root = box.matching.park_for_decision_task(domain_id, TL,
                                                          partition=0)
        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2,
                                       partition=2)
        assert parked_local.task is not None
        assert parked_root.task is None

    def test_poll_forwards_to_root_backlog(self):
        """A poll landing on an empty partition drains the root's backlog
        (ForwardPoll)."""
        box = make_box(partitions=3)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        # park nothing; add straight to the root partition's backlog
        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2,
                                       partition=0)
        # polls round-robin over partitions; every poll either hits the
        # root directly or forwards to it — the task comes back within the
        # partition count
        got = None
        for _ in range(3):
            got = box.matching.poll_for_decision_task(domain_id, TL)
            if got:
                break
        assert got is not None and got.workflow_id == "wf-1"

    def test_partition_names(self):
        assert partition_name(TL, 0) == TL
        assert partition_name(TL, 2) == f"{PARTITION_PREFIX}{TL}/2"

    def test_backlog_drains_with_partitions_enabled(self):
        """End-to-end workflows complete with a partitioned task list
        (adds and polls spread over partitions; drain covers them all)."""
        box = make_box(partitions=4)
        for i in range(6):
            box.frontend.start_workflow_execution(DOMAIN, f"wf-{i}", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {f"wf-{i}": EchoDecider(TL) for i in range(6)})
        poller.drain()
        from cadence_tpu.core.enums import CloseStatus
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        for i in range(6):
            run = box.stores.execution.get_current_run_id(domain_id, f"wf-{i}")
            ms = box.stores.execution.get_workflow(domain_id, f"wf-{i}", run)
            assert ms.execution_info.close_status == CloseStatus.Completed
        assert box.matching.backlog() == 0
        assert box.tpu.verify_all().ok

    def test_describe_task_list_aggregates_partitions(self):
        box = make_box(partitions=3)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        for p in range(3):
            box.matching.add_decision_task(domain_id, TL, f"wf-{p}", "r", 2,
                                           partition=p)
        desc = box.matching.describe_task_list(domain_id, TL, 0)
        assert desc["backlog"] == 3
        assert desc["partitions"] == 3
