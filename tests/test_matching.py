"""Matching fidelity: sync-match + partitions + forwarder (VERDICT ask #7).

Reference: taskListManager.go:530 trySyncMatch, forwarder.go:111,
matchingEngine.go:729 getAllPartitions.
"""
import pytest

from cadence_tpu.engine.matching import PARTITION_PREFIX, partition_name
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import CompleteDecider, EchoDecider
from cadence_tpu.utils.dynamicconfig import (
    KEY_MATCHING_NUM_PARTITIONS,
    DynamicConfig,
)
from tests.taskpoller import TaskPoller

DOMAIN = "match-domain"
TL = "match-tl"


def make_box(partitions: int = 1) -> Onebox:
    cfg = DynamicConfig({KEY_MATCHING_NUM_PARTITIONS: partitions})
    b = Onebox(num_hosts=1, num_shards=4, config=cfg)
    b.frontend.register_domain(DOMAIN)
    return b


class TestSyncMatch:
    def test_parked_poll_rendezvous_skips_persistence(self):
        """A task added while a poll is parked hands off directly: no
        write-through, no backlog (trySyncMatch)."""
        box = make_box()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked = box.matching.park_for_decision_task(domain_id, TL)
        assert parked.task is None

        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2)
        assert parked.task is not None
        assert parked.task.workflow_id == "wf-1"
        assert parked.task.schedule_id == 2
        # nothing persisted, nothing buffered
        assert box.matching.backlog() == 0
        assert box.stores.task.get_tasks(domain_id, TL, 0, 0, 10_000) == []

    def test_canceled_park_falls_through_to_backlog(self):
        box = make_box()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked = box.matching.park_for_decision_task(domain_id, TL)
        assert parked.cancel()
        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2)
        # canceled park is skipped; the task persists in the backlog
        assert box.matching.backlog() == 1
        task = box.matching.poll_for_decision_task(domain_id, TL)
        assert task is not None and task.workflow_id == "wf-1"

    def test_activity_sync_match(self):
        box = make_box()
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked = box.matching.park_for_activity_task(domain_id, TL)
        box.matching.add_activity_task(domain_id, TL, "wf-1", "run-1", 5)
        assert parked.task is not None and parked.task.schedule_id == 5


class TestPartitionsAndForwarder:
    def test_nonroot_add_forwards_to_root_parked_poller(self):
        """The VERDICT 'Done' case: a task added on a NON-ROOT partition
        reaches a poller parked on the ROOT (ForwardTask sync-match)."""
        box = make_box(partitions=4)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked_root = box.matching.park_for_decision_task(domain_id, TL,
                                                          partition=0)
        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2,
                                       partition=3)
        assert parked_root.task is not None
        assert parked_root.task.workflow_id == "wf-1"
        assert box.matching.backlog() == 0

    def test_local_parked_poller_wins_before_forwarding(self):
        box = make_box(partitions=4)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        parked_local = box.matching.park_for_decision_task(domain_id, TL,
                                                           partition=2)
        parked_root = box.matching.park_for_decision_task(domain_id, TL,
                                                          partition=0)
        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2,
                                       partition=2)
        assert parked_local.task is not None
        assert parked_root.task is None

    def test_poll_forwards_to_root_backlog(self):
        """A poll landing on an empty partition drains the root's backlog
        (ForwardPoll)."""
        box = make_box(partitions=3)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        # park nothing; add straight to the root partition's backlog
        box.matching.add_decision_task(domain_id, TL, "wf-1", "run-1", 2,
                                       partition=0)
        # polls round-robin over partitions; every poll either hits the
        # root directly or forwards to it — the task comes back within the
        # partition count
        got = None
        for _ in range(3):
            got = box.matching.poll_for_decision_task(domain_id, TL)
            if got:
                break
        assert got is not None and got.workflow_id == "wf-1"

    def test_partition_names(self):
        assert partition_name(TL, 0) == TL
        assert partition_name(TL, 2) == f"{PARTITION_PREFIX}{TL}/2"

    def test_backlog_drains_with_partitions_enabled(self):
        """End-to-end workflows complete with a partitioned task list
        (adds and polls spread over partitions; drain covers them all)."""
        box = make_box(partitions=4)
        for i in range(6):
            box.frontend.start_workflow_execution(DOMAIN, f"wf-{i}", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {f"wf-{i}": EchoDecider(TL) for i in range(6)})
        poller.drain()
        from cadence_tpu.core.enums import CloseStatus
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        for i in range(6):
            run = box.stores.execution.get_current_run_id(domain_id, f"wf-{i}")
            ms = box.stores.execution.get_workflow(domain_id, f"wf-{i}", run)
            assert ms.execution_info.close_status == CloseStatus.Completed
        assert box.matching.backlog() == 0
        assert box.tpu.verify_all().ok

    def test_describe_task_list_aggregates_partitions(self):
        box = make_box(partitions=3)
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        for p in range(3):
            box.matching.add_decision_task(domain_id, TL, f"wf-{p}", "r", 2,
                                           partition=p)
        desc = box.matching.describe_task_list(domain_id, TL, 0)
        assert desc["backlog"] == 3
        assert desc["partitions"] == 3


class TestTwoPhaseAck:
    """The persisted task row must outlive delivery until the engine write
    behind it succeeds (taskListManager ack levels + taskGC: the reference
    only GCs below the ack level, so a crash between poll and handoff
    redelivers from the store — ADVICE r3)."""

    def _stores_engine(self):
        from cadence_tpu.engine.matching import MatchingEngine
        from cadence_tpu.engine.persistence import Stores
        stores = Stores()
        return stores, MatchingEngine(stores)

    def test_row_survives_poll_until_complete(self):
        from cadence_tpu.engine.matching import TASK_LIST_TYPE_DECISION
        stores, eng = self._stores_engine()
        eng.add_decision_task("d", TL, "wf", "run", 2)
        task = eng.poll_for_decision_task("d", TL)
        assert task is not None and task.task_id and task.source == TL
        # popped but NOT acked: the store row must still exist
        assert len(stores.task.get_tasks("d", TL, TASK_LIST_TYPE_DECISION, 0)) == 1
        eng.complete_task(task, TASK_LIST_TYPE_DECISION)
        assert stores.task.get_tasks("d", TL, TASK_LIST_TYPE_DECISION, 0) == []

    def test_requeue_preserves_persisted_identity(self):
        from cadence_tpu.engine.matching import TASK_LIST_TYPE_DECISION
        stores, eng = self._stores_engine()
        eng.add_decision_task("d", TL, "wf", "run", 2)
        task = eng.poll_for_decision_task("d", TL)
        eng.requeue_task(task, TASK_LIST_TYPE_DECISION)
        again = eng.poll_for_decision_task("d", TL)
        # the SAME persisted task comes back (not a task_id=0 synthetic)
        assert again.task_id == task.task_id and again.source == task.source
        assert len(stores.task.get_tasks("d", TL, TASK_LIST_TYPE_DECISION, 0)) == 1
        eng.complete_task(again, TASK_LIST_TYPE_DECISION)
        assert stores.task.get_tasks("d", TL, TASK_LIST_TYPE_DECISION, 0) == []

    def test_out_of_order_completion_gc_floor(self):
        """Completing a later task must not GC an earlier, still-inflight
        one; the floor advances only past the lowest outstanding id."""
        from cadence_tpu.engine.matching import TASK_LIST_TYPE_DECISION
        stores, eng = self._stores_engine()
        for i in range(3):
            eng.add_decision_task("d", TL, f"wf-{i}", "run", 2)
        t1 = eng.poll_for_decision_task("d", TL)
        t2 = eng.poll_for_decision_task("d", TL)
        t3 = eng.poll_for_decision_task("d", TL)
        eng.complete_task(t2, TASK_LIST_TYPE_DECISION)
        eng.complete_task(t3, TASK_LIST_TYPE_DECISION)
        remaining = stores.task.get_tasks("d", TL, TASK_LIST_TYPE_DECISION, 0)
        assert t1.task_id in {t.task_id for t in remaining}
        eng.complete_task(t1, TASK_LIST_TYPE_DECISION)
        assert stores.task.get_tasks("d", TL, TASK_LIST_TYPE_DECISION, 0) == []

    def test_new_lessee_redelivers_unacked_tasks_from_store(self):
        """A task popped but never acked before its owner died comes back
        from the store when a fresh lessee's taskReader pumps surviving
        rows (taskReader.go) — the crash-redelivery half of the two-phase
        ack."""
        from cadence_tpu.engine.matching import (
            TASK_LIST_TYPE_DECISION,
            MatchingEngine,
        )
        stores, eng = self._stores_engine()
        eng.add_decision_task("d", TL, "wf", "run", 2)
        task = eng.poll_for_decision_task("d", TL)
        assert task is not None
        # owner dies between pop and ack; a new engine leases over the
        # same persistence and must see the task again
        eng2 = MatchingEngine(stores)
        again = eng2.poll_for_decision_task("d", TL)
        assert again is not None and again.task_id == task.task_id
        eng2.complete_task(again, TASK_LIST_TYPE_DECISION)
        assert stores.task.get_tasks("d", TL, TASK_LIST_TYPE_DECISION, 0) == []

    def test_requeue_inversion_never_gcs_live_tasks(self):
        """Requeues can invert buffer order; the GC floor must still sit
        below EVERY live task (code-review r4: a positional buffer-min
        shortcut deleted a requeued task's persisted row)."""
        from cadence_tpu.engine.matching import TASK_LIST_TYPE_DECISION
        stores, eng = self._stores_engine()
        for i in range(3):
            eng.add_decision_task("d", TL, f"wf-{i}", "run", 2)
        t1 = eng.poll_for_decision_task("d", TL)
        t2 = eng.poll_for_decision_task("d", TL)
        t3 = eng.poll_for_decision_task("d", TL)
        assert t1.task_id < t2.task_id < t3.task_id
        # requeue t1 then t2: buffer becomes [t2, t1] — order inverted
        eng.requeue_task(t1, TASK_LIST_TYPE_DECISION)
        eng.requeue_task(t2, TASK_LIST_TYPE_DECISION)
        eng.complete_task(t3, TASK_LIST_TYPE_DECISION)
        remaining = {t.task_id
                     for t in stores.task.get_tasks("d", TL,
                                                    TASK_LIST_TYPE_DECISION, 0)}
        assert {t1.task_id, t2.task_id} <= remaining
        # drain the requeued pair; the store empties only then
        a = eng.poll_for_decision_task("d", TL)
        b = eng.poll_for_decision_task("d", TL)
        eng.complete_task(a, TASK_LIST_TYPE_DECISION)
        eng.complete_task(b, TASK_LIST_TYPE_DECISION)
        assert stores.task.get_tasks("d", TL, TASK_LIST_TYPE_DECISION, 0) == []


class TestPollerHistory:
    """Poller-identity history (matching/pollerHistory.go): recent worker
    identities surface in DescribeTaskList with last-access times."""

    def test_identities_recorded_and_surfaced(self):
        from cadence_tpu.engine.onebox import Onebox

        box = Onebox(num_hosts=1, num_shards=2)
        box.frontend.register_domain("ph-dom")
        domain_id = box.frontend.describe_domain("ph-dom").domain_id
        for worker in ("worker-a", "worker-b"):
            box.frontend.poll_for_decision_task("ph-dom", "ph-tl",
                                                identity=worker)
        box.frontend.poll_for_activity_task("ph-dom", "ph-tl",
                                            identity="worker-act")
        desc = box.matching.describe_task_list(domain_id, "ph-tl", 0)
        idents = [p["identity"] for p in desc["pollers"]]
        assert set(idents) == {"worker-a", "worker-b"}
        assert all(p["last_access_time"] > 0 for p in desc["pollers"])
        desc_act = box.matching.describe_task_list(domain_id, "ph-tl", 1)
        assert [p["identity"] for p in desc_act["pollers"]] == ["worker-act"]
        # anonymous polls don't pollute the history
        box.frontend.poll_for_decision_task("ph-dom", "ph-tl")
        desc = box.matching.describe_task_list(domain_id, "ph-tl", 0)
        assert len(desc["pollers"]) == 2
