"""Native packer parity: C++ decode+pack must be byte-identical to the
Python packer on every suite, and the codec must round-trip."""
import numpy as np
import pytest

from cadence_tpu.core.codec import deserialize_history, serialize_history
from cadence_tpu.gen.corpus import SUITES, generate_corpus, generate_history
from cadence_tpu.ops.encode import encode_corpus
from cadence_tpu.native import build as native_build
from cadence_tpu.native.packing import encode_corpus_native, pack_serialized

native = pytest.mark.skipif(native_build.load() is None,
                            reason="no C++ toolchain")


@native
@pytest.mark.parametrize("suite", SUITES)
def test_native_matches_python_packer(suite):
    histories = generate_corpus(suite, num_workflows=6, seed=31,
                                target_events=90)
    expected = encode_corpus(histories)
    got = encode_corpus_native(histories, max_events=expected.shape[1])
    mism = np.nonzero(got != expected)
    assert got.shape == expected.shape
    assert (got == expected).all(), (
        f"suite={suite}: first mismatches at {[m[:5] for m in mism]}"
    )


@native
def test_native_rejects_truncated_blob():
    histories = generate_corpus("basic", 2, seed=1, target_events=40)
    from cadence_tpu.core.codec import serialize_corpus
    blobs = serialize_corpus(histories)
    blobs[1] = blobs[1][:len(blobs[1]) // 2]
    with pytest.raises(ValueError, match="workflow 1"):
        pack_serialized(blobs, max_events=64)


@native
def test_native_rejects_overlong_history():
    histories = generate_corpus("basic", 1, seed=1, target_events=60)
    from cadence_tpu.core.codec import serialize_corpus
    with pytest.raises(ValueError, match="code 3"):
        pack_serialized(serialize_corpus(histories), max_events=8)


def test_codec_roundtrip():
    """serialize → deserialize preserves replay-relevant attributes: the
    round-tripped history replays to the same checksum payload."""
    from cadence_tpu.core.checksum import payload_row
    from cadence_tpu.oracle.state_builder import StateBuilder

    for suite in SUITES:
        h = generate_history(suite, seed=8, workflow_index=0, target_events=80)
        blob = serialize_history(h)
        h2 = deserialize_history(blob, h[0].domain_id, h[0].workflow_id,
                                 h[0].run_id)
        # request IDs differ (not serialized) but are checksum-irrelevant
        r1 = payload_row(StateBuilder().replay_history(h))
        r2 = payload_row(StateBuilder().replay_history(h2))
        assert (r1 == r2).all(), f"suite {suite} round-trip diverged"


def test_codec_roundtrip_parent_and_retry():
    """Parent linkage and retry policies survive the wire (regression:
    these used to decode to keys nothing read)."""
    from cadence_tpu.core.enums import EventType
    from cadence_tpu.core.events import HistoryBatch, HistoryEvent, RetryPolicy

    retry = RetryPolicy(initial_interval_seconds=2, backoff_coefficient=1.5,
                        maximum_interval_seconds=30, maximum_attempts=4,
                        expiration_interval_seconds=120)
    h = [HistoryBatch(domain_id="d", workflow_id="w", run_id="r", events=[
        HistoryEvent(id=1, event_type=EventType.WorkflowExecutionStarted,
                     timestamp=5, attrs=dict(
                         task_list="tl", workflow_type="wt",
                         execution_start_to_close_timeout_seconds=60,
                         task_start_to_close_timeout_seconds=10,
                         parent_workflow_id="papa", parent_run_id="papa-run",
                         parent_workflow_domain_id="papa-dom",
                         parent_initiated_event_id=7,
                         retry_policy=retry)),
    ])]
    h2 = deserialize_history(serialize_history(h), "d", "w", "r")
    ev = h2[0].events[0]
    assert ev.get("parent_workflow_id") == "papa"
    assert ev.get("parent_run_id") == "papa-run"
    assert ev.get("parent_workflow_domain_id") == "papa-dom"
    assert ev.get("parent_initiated_event_id") == 7
    rp = ev.get("retry_policy")
    assert rp is not None
    assert (rp.initial_interval_seconds, rp.backoff_coefficient,
            rp.maximum_interval_seconds, rp.maximum_attempts,
            rp.expiration_interval_seconds) == (2, 1.5, 30, 4, 120)


class TestNativePacker32:
    def test_wire32_matches_python_to_wire32(self):
        """C++ int32 emission must equal encode.to_wire32(python int64)."""
        import numpy as np

        from cadence_tpu.core.codec import serialize_corpus
        from cadence_tpu.gen.corpus import SUITES, generate_corpus
        from cadence_tpu.native.packing import pack_serialized32
        from cadence_tpu.ops.encode import encode_corpus, to_wire32

        for suite in SUITES:
            if suite == "ndc":
                continue  # branch lanes ride the python packer only
            hists = generate_corpus(suite, num_workflows=12, seed=21,
                                    target_events=70)
            hists = [h for h in hists
                     if not any(b.new_run_events for b in h)]
            ev = encode_corpus(hists)
            want = to_wire32(ev)
            got = pack_serialized32(serialize_corpus(hists), ev.shape[1])
            assert (got == want).all(), f"suite {suite} wire32 mismatch"

    def test_wire32_replays_to_same_crc(self):
        import jax.numpy as jnp
        import numpy as np

        from cadence_tpu.core.checksum import DEFAULT_LAYOUT, crc32_of_rows
        from cadence_tpu.core.codec import serialize_corpus
        from cadence_tpu.gen.corpus import generate_corpus
        from cadence_tpu.native.packing import pack_serialized32
        from cadence_tpu.ops.encode import encode_corpus
        from cadence_tpu.ops.replay import replay_to_crc32, replay_to_payload

        hists = generate_corpus("echo_signal", num_workflows=8, seed=4,
                                target_events=50)
        ev = encode_corpus(hists)
        rows, _ = replay_to_payload(jnp.asarray(ev), DEFAULT_LAYOUT)
        want = crc32_of_rows(np.asarray(rows))
        wire = pack_serialized32(serialize_corpus(hists), ev.shape[1])
        crc, errors = replay_to_crc32(jnp.asarray(wire), DEFAULT_LAYOUT)
        assert (np.asarray(crc) == want).all()
        assert (np.asarray(errors) == 0).all()

    def test_fully_loaded_start_event_packs(self):
        """A child-workflow Started event with retry policy + cron + parent
        linkage carries 20 wire attrs — the packer must accept it (the
        attr-list bound is kMaxAttrCode, not a smaller guess)."""
        import numpy as np

        from cadence_tpu.core.codec import serialize_corpus
        from cadence_tpu.core.enums import ContinueAsNewInitiator, EventType
        from cadence_tpu.core.events import HistoryBatch, HistoryEvent, RetryPolicy
        from cadence_tpu.native.packing import pack_serialized, pack_serialized32
        from cadence_tpu.ops.encode import encode_corpus, to_wire32

        start = HistoryEvent(
            id=1, event_type=EventType.WorkflowExecutionStarted,
            version=0, timestamp=1_700_000_000_000_000_000, task_id=1001,
            attrs=dict(
                execution_start_to_close_timeout_seconds=3600,
                task_start_to_close_timeout_seconds=10,
                first_decision_task_backoff_seconds=5,
                attempt=2,
                expiration_timestamp=1_700_000_900_000_000_000,
                task_list="tl", workflow_type="wt", cron_schedule="* * * * *",
                first_execution_run_id="r0",
                parent_workflow_id="pw", parent_run_id="pr",
                parent_domain_id="pd", parent_initiated_event_id=7,
                retry_policy=RetryPolicy(
                    initial_interval_seconds=1, backoff_coefficient=2.0,
                    maximum_interval_seconds=60, maximum_attempts=5,
                    expiration_interval_seconds=900),
                initiator=int(ContinueAsNewInitiator.RetryPolicy),
            ))
        sched = HistoryEvent(
            id=2, event_type=EventType.DecisionTaskScheduled, version=0,
            timestamp=1_700_000_000_000_001_000, task_id=1002,
            attrs=dict(task_list="tl", start_to_close_timeout_seconds=10,
                       attempt=0))
        hist = [[HistoryBatch(domain_id="d", workflow_id="w", run_id="r",
                              events=[start, sched])]]
        ev = encode_corpus(hist)
        blobs = serialize_corpus(hist)
        got = pack_serialized(blobs, ev.shape[1])
        assert (got == ev).all()
        got32 = pack_serialized32(blobs, ev.shape[1])
        assert (got32 == to_wire32(ev)).all()
