"""Native packer parity: C++ decode+pack must be byte-identical to the
Python packer on every suite, and the codec must round-trip; the native
wirec encoder (native/wirec.cc, ISSUE 9) must be byte-identical to
ops/wirec.pack_wirec — corpus bytes, pinned-profile streaming chunks,
ProfileMisfit refit signal, and the PackCache suffix-repack path."""
import numpy as np
import pytest

from cadence_tpu.core.codec import deserialize_history, serialize_history
from cadence_tpu.gen.corpus import SUITES, generate_corpus, generate_history
from cadence_tpu.ops.encode import encode_corpus
from cadence_tpu.native import build as native_build
from cadence_tpu.native.packing import encode_corpus_native, pack_serialized

native = pytest.mark.skipif(native_build.load() is None,
                            reason="no C++ toolchain")
native_wirec = pytest.mark.skipif(native_build.load_wirec() is None,
                                  reason="no C++ toolchain")


@native
@pytest.mark.parametrize("suite", SUITES)
def test_native_matches_python_packer(suite):
    histories = generate_corpus(suite, num_workflows=6, seed=31,
                                target_events=90)
    expected = encode_corpus(histories)
    got = encode_corpus_native(histories, max_events=expected.shape[1])
    mism = np.nonzero(got != expected)
    assert got.shape == expected.shape
    assert (got == expected).all(), (
        f"suite={suite}: first mismatches at {[m[:5] for m in mism]}"
    )


@native
def test_native_rejects_truncated_blob():
    histories = generate_corpus("basic", 2, seed=1, target_events=40)
    from cadence_tpu.core.codec import serialize_corpus
    blobs = serialize_corpus(histories)
    blobs[1] = blobs[1][:len(blobs[1]) // 2]
    with pytest.raises(ValueError, match="workflow 1"):
        pack_serialized(blobs, max_events=64)


@native
def test_native_rejects_overlong_history():
    histories = generate_corpus("basic", 1, seed=1, target_events=60)
    from cadence_tpu.core.codec import serialize_corpus
    with pytest.raises(ValueError, match="code 3"):
        pack_serialized(serialize_corpus(histories), max_events=8)


def test_codec_roundtrip():
    """serialize → deserialize preserves replay-relevant attributes: the
    round-tripped history replays to the same checksum payload."""
    from cadence_tpu.core.checksum import payload_row
    from cadence_tpu.oracle.state_builder import StateBuilder

    for suite in SUITES:
        h = generate_history(suite, seed=8, workflow_index=0, target_events=80)
        blob = serialize_history(h)
        h2 = deserialize_history(blob, h[0].domain_id, h[0].workflow_id,
                                 h[0].run_id)
        # request IDs differ (not serialized) but are checksum-irrelevant
        r1 = payload_row(StateBuilder().replay_history(h))
        r2 = payload_row(StateBuilder().replay_history(h2))
        assert (r1 == r2).all(), f"suite {suite} round-trip diverged"


def test_codec_roundtrip_parent_and_retry():
    """Parent linkage and retry policies survive the wire (regression:
    these used to decode to keys nothing read)."""
    from cadence_tpu.core.enums import EventType
    from cadence_tpu.core.events import HistoryBatch, HistoryEvent, RetryPolicy

    retry = RetryPolicy(initial_interval_seconds=2, backoff_coefficient=1.5,
                        maximum_interval_seconds=30, maximum_attempts=4,
                        expiration_interval_seconds=120)
    h = [HistoryBatch(domain_id="d", workflow_id="w", run_id="r", events=[
        HistoryEvent(id=1, event_type=EventType.WorkflowExecutionStarted,
                     timestamp=5, attrs=dict(
                         task_list="tl", workflow_type="wt",
                         execution_start_to_close_timeout_seconds=60,
                         task_start_to_close_timeout_seconds=10,
                         parent_workflow_id="papa", parent_run_id="papa-run",
                         parent_workflow_domain_id="papa-dom",
                         parent_initiated_event_id=7,
                         retry_policy=retry)),
    ])]
    h2 = deserialize_history(serialize_history(h), "d", "w", "r")
    ev = h2[0].events[0]
    assert ev.get("parent_workflow_id") == "papa"
    assert ev.get("parent_run_id") == "papa-run"
    assert ev.get("parent_workflow_domain_id") == "papa-dom"
    assert ev.get("parent_initiated_event_id") == 7
    rp = ev.get("retry_policy")
    assert rp is not None
    assert (rp.initial_interval_seconds, rp.backoff_coefficient,
            rp.maximum_interval_seconds, rp.maximum_attempts,
            rp.expiration_interval_seconds) == (2, 1.5, 30, 4, 120)


class TestNativePacker32:
    def test_wire32_matches_python_to_wire32(self):
        """C++ int32 emission must equal encode.to_wire32(python int64)."""
        import numpy as np

        from cadence_tpu.core.codec import serialize_corpus
        from cadence_tpu.gen.corpus import SUITES, generate_corpus
        from cadence_tpu.native.packing import pack_serialized32
        from cadence_tpu.ops.encode import encode_corpus, to_wire32

        for suite in SUITES:
            if suite == "ndc":
                continue  # branch lanes ride the python packer only
            hists = generate_corpus(suite, num_workflows=12, seed=21,
                                    target_events=70)
            hists = [h for h in hists
                     if not any(b.new_run_events for b in h)]
            ev = encode_corpus(hists)
            want = to_wire32(ev)
            got = pack_serialized32(serialize_corpus(hists), ev.shape[1])
            assert (got == want).all(), f"suite {suite} wire32 mismatch"

    def test_wire32_replays_to_same_crc(self):
        import jax.numpy as jnp
        import numpy as np

        from cadence_tpu.core.checksum import DEFAULT_LAYOUT, crc32_of_rows
        from cadence_tpu.core.codec import serialize_corpus
        from cadence_tpu.gen.corpus import generate_corpus
        from cadence_tpu.native.packing import pack_serialized32
        from cadence_tpu.ops.encode import encode_corpus
        from cadence_tpu.ops.replay import replay_to_crc32, replay_to_payload

        hists = generate_corpus("echo_signal", num_workflows=8, seed=4,
                                target_events=50)
        ev = encode_corpus(hists)
        rows, _ = replay_to_payload(jnp.asarray(ev), DEFAULT_LAYOUT)
        want = crc32_of_rows(np.asarray(rows))
        wire = pack_serialized32(serialize_corpus(hists), ev.shape[1])
        crc, errors = replay_to_crc32(jnp.asarray(wire), DEFAULT_LAYOUT)
        assert (np.asarray(crc) == want).all()
        assert (np.asarray(errors) == 0).all()

    def test_fully_loaded_start_event_packs(self):
        """A child-workflow Started event with retry policy + cron + parent
        linkage carries 20 wire attrs — the packer must accept it (the
        attr-list bound is kMaxAttrCode, not a smaller guess)."""
        import numpy as np

        from cadence_tpu.core.codec import serialize_corpus
        from cadence_tpu.core.enums import ContinueAsNewInitiator, EventType
        from cadence_tpu.core.events import HistoryBatch, HistoryEvent, RetryPolicy
        from cadence_tpu.native.packing import pack_serialized, pack_serialized32
        from cadence_tpu.ops.encode import encode_corpus, to_wire32

        start = HistoryEvent(
            id=1, event_type=EventType.WorkflowExecutionStarted,
            version=0, timestamp=1_700_000_000_000_000_000, task_id=1001,
            attrs=dict(
                execution_start_to_close_timeout_seconds=3600,
                task_start_to_close_timeout_seconds=10,
                first_decision_task_backoff_seconds=5,
                attempt=2,
                expiration_timestamp=1_700_000_900_000_000_000,
                task_list="tl", workflow_type="wt", cron_schedule="* * * * *",
                first_execution_run_id="r0",
                parent_workflow_id="pw", parent_run_id="pr",
                parent_domain_id="pd", parent_initiated_event_id=7,
                retry_policy=RetryPolicy(
                    initial_interval_seconds=1, backoff_coefficient=2.0,
                    maximum_interval_seconds=60, maximum_attempts=5,
                    expiration_interval_seconds=900),
                initiator=int(ContinueAsNewInitiator.RetryPolicy),
            ))
        sched = HistoryEvent(
            id=2, event_type=EventType.DecisionTaskScheduled, version=0,
            timestamp=1_700_000_000_000_001_000, task_id=1002,
            attrs=dict(task_list="tl", start_to_close_timeout_seconds=10,
                       attempt=0))
        hist = [[HistoryBatch(domain_id="d", workflow_id="w", run_id="r",
                              events=[start, sched])]]
        ev = encode_corpus(hist)
        blobs = serialize_corpus(hist)
        got = pack_serialized(blobs, ev.shape[1])
        assert (got == ev).all()
        got32 = pack_serialized32(blobs, ev.shape[1])
        assert (got32 == to_wire32(ev)).all()


def _assert_corpus_equal(a, b, ctx=""):
    assert a.profile == b.profile, f"{ctx}: profile drift"
    assert a.slab.shape == b.slab.shape, ctx
    assert (a.slab == b.slab).all(), f"{ctx}: slab bytes diverge"
    assert (a.bases == b.bases).all(), f"{ctx}: bases diverge"
    assert (a.n_events == b.n_events).all(), f"{ctx}: n_events diverge"


@native_wirec
class TestNativeWirec:
    """Byte-parity contract of the native wirec encoder (ISSUE 9): every
    slab byte, bases column, n_events entry, and the measured PROFILE
    itself must equal ops/wirec.pack_wirec's — profiles are static jit
    arguments, so profile drift would mean different executables (and a
    broken refit contract), not just different bytes."""

    @pytest.mark.parametrize("suite", SUITES)
    @pytest.mark.parametrize("seed", [31, 77])
    def test_byte_parity_fuzz_every_suite(self, suite, seed):
        from cadence_tpu.native.wirec import pack_wirec_native
        from cadence_tpu.ops.wirec import pack_wirec

        ev = encode_corpus(generate_corpus(suite, num_workflows=10,
                                           seed=seed, target_events=70))
        _assert_corpus_equal(pack_wirec(ev), pack_wirec_native(ev),
                             f"{suite}/{seed}")

    def test_measure_profile_matches_python(self):
        """The native plan (kind/width/scale/const per lane) is the exact
        decision procedure of _plan_lane — asserted standalone because a
        profile mismatch poisons every pinned-profile consumer."""
        from cadence_tpu.native.wirec import measure_profile_native
        from cadence_tpu.ops.wirec import pack_wirec

        for suite in SUITES:
            ev = encode_corpus(generate_corpus(suite, num_workflows=8,
                                               seed=13, target_events=50))
            assert measure_profile_native(ev) == pack_wirec(ev).profile

    def test_threaded_emit_byte_identical(self):
        """Multi-threaded native emit (workflow-row blocks) == serial."""
        from cadence_tpu.native.wirec import pack_wirec_native

        ev = encode_corpus(generate_corpus("timer_retry", num_workflows=96,
                                           seed=23, target_events=30))
        _assert_corpus_equal(pack_wirec_native(ev, num_threads=1),
                             pack_wirec_native(ev, num_threads=4),
                             "threaded")

    def test_adversarial_lanes_byte_parity(self):
        """Pathological lane values (wild 64-bit magnitudes, negatives,
        zero-escape TSREL shapes) — the degradation path must stay
        byte-identical, floor-division quotients included."""
        from cadence_tpu.native.wirec import pack_wirec_native
        from cadence_tpu.ops.encode import NUM_LANES
        from cadence_tpu.ops.wirec import decode_wirec, pack_wirec

        rng = np.random.default_rng(5)
        W, E = 12, 24
        ev = np.zeros((W, E, NUM_LANES), dtype=np.int64)
        n = rng.integers(3, E, size=W)
        for w in range(W):
            ev[w, :n[w], 0] = np.arange(1, n[w] + 1)
            ev[w, :n[w], 1] = rng.integers(0, 40, n[w])
            ev[w, :n[w], 3] = rng.integers(-2**62, 2**62, n[w])
            ev[w, :n[w], 7] = rng.integers(-2**31, 2**31, n[w])
            # sparse huge-absolute lane: the TSREL_NZ shape
            mask = rng.random(n[w]) < 0.5
            ev[w, :n[w], 8] = np.where(
                mask, 1_700_000_000_000_000_000
                + rng.integers(0, 1 << 40, n[w]), 0)
            ev[w, n[w]:, 1] = -1
        py = pack_wirec(ev)
        nat = pack_wirec_native(ev)
        _assert_corpus_equal(py, nat, "adversarial")
        back = np.asarray(decode_wirec(nat.slab, nat.bases, nat.n_events,
                                       nat.profile))
        assert (back == ev).all()

    def test_pinned_profile_streaming_chunks_fused(self):
        """The streaming shape: chunk 0 measures, later chunks emit under
        the PIN through the fused native call (blobs → lanes → wirec in
        one pass) into ONE reusable WirecBuffers slot — every chunk
        byte-identical to the numpy encoder under the same pin, with no
        stale bytes surviving slot reuse."""
        from cadence_tpu.core.codec import serialize_corpus
        from cadence_tpu.native.packing import pack_serialized
        from cadence_tpu.native.wirec import (
            WirecBuffers,
            pack_serialized_wirec,
        )
        from cadence_tpu.ops.encode import history_length
        from cadence_tpu.ops.wirec import pack_wirec

        hists = generate_corpus("basic", num_workflows=24, seed=41,
                                target_events=60)
        max_events = max(history_length(h) for h in hists)
        chunk_w = 8
        blobs = serialize_corpus(hists)
        buf = WirecBuffers(chunk_w, max_events)
        pinned = None
        for lo in range(0, len(blobs), chunk_w):
            chunk = blobs[lo:lo + chunk_w]
            corpus, total = pack_serialized_wirec(
                chunk, max_events, profile=pinned, out=buf)
            dense = pack_serialized(chunk, max_events)
            expect = pack_wirec(dense, profile=pinned)
            _assert_corpus_equal(expect, corpus, f"chunk@{lo}")
            assert total == int(expect.n_events.sum())
            if pinned is None:
                pinned = corpus.profile
            else:
                assert corpus.profile == pinned

    def test_profile_misfit_parity_and_refit(self):
        """A chunk outside the pinned widths must raise ProfileMisfit on
        BOTH encoders (the refit signal is path-independent), and the
        refit both sides then perform must land on identical bytes."""
        from cadence_tpu.native.wirec import pack_wirec_native
        from cadence_tpu.ops.encode import NUM_LANES
        from cadence_tpu.ops.wirec import ProfileMisfit, pack_wirec

        def corpus_with_ts_step(step):
            W, E = 6, 16
            ev = np.zeros((W, E, NUM_LANES), dtype=np.int64)
            for w in range(W):
                ev[w, :, 0] = np.arange(1, E + 1)
                ev[w, :, 1] = 5
                ev[w, :, 3] = 1_000_000 + np.arange(E) * step
            return ev

        narrow = corpus_with_ts_step(1)       # 1-byte deltas
        wide = corpus_with_ts_step(1 << 40)   # overflow the pinned width
        pin = pack_wirec(narrow).profile
        assert pack_wirec_native(narrow).profile == pin
        with pytest.raises(ProfileMisfit):
            pack_wirec(wide, profile=pin)
        with pytest.raises(ProfileMisfit):
            pack_wirec_native(wide, profile=pin)
        # the refit: fresh measurement on the misfitting chunk, both
        # sides, identical plan and bytes
        _assert_corpus_equal(pack_wirec(wide), pack_wirec_native(wide),
                             "refit")

    def test_scale_misfit_parity(self):
        """Scale (GCD) misfits — values that fit the width but break the
        pinned tick — must also raise on both sides."""
        from cadence_tpu.native.wirec import pack_wirec_native
        from cadence_tpu.ops.encode import NUM_LANES
        from cadence_tpu.ops.wirec import ProfileMisfit, pack_wirec

        def corpus(step):
            ev = np.zeros((4, 8, NUM_LANES), dtype=np.int64)
            for w in range(4):
                ev[w, :, 0] = np.arange(1, 9)
                ev[w, :, 1] = 5
                ev[w, :, 3] = 1_000 + np.arange(8) * step
            return ev

        pin = pack_wirec(corpus(1000)).profile   # tick of 1000
        off_tick = corpus(1001)                  # same widths, wrong tick
        raised_py = raised_nat = False
        try:
            pack_wirec(off_tick, profile=pin)
        except ProfileMisfit:
            raised_py = True
        try:
            pack_wirec_native(off_tick, profile=pin)
        except ProfileMisfit:
            raised_nat = True
        assert raised_py == raised_nat

    def test_suffix_repack_parity_via_packcache(self):
        """The append configuration: PackCache re-encodes only the
        appended suffix (resumed interner), and the wirec corpus built
        from those suffix-path lanes must be byte-identical native vs
        Python — the suffix-append feeder leg rides exactly this."""
        from cadence_tpu.engine.cache import PackCache
        from cadence_tpu.native.wirec import pack_wirec_native
        from cadence_tpu.ops.encode import assemble_corpus
        from cadence_tpu.ops.wirec import pack_wirec
        from cadence_tpu.utils import metrics as m

        hists = generate_corpus("concurrent_child", num_workflows=8,
                                seed=19, target_events=50)
        keys = [("d", f"w{i}", "r") for i in range(len(hists))]
        cache = PackCache(max_size=32)
        for k, h in zip(keys, hists):
            cache.encode(k, h[:-1])  # warm the prefix entries
        before = m.DEFAULT_REGISTRY.counter(m.SCOPE_PACK_CACHE,
                                            m.M_CACHE_SUFFIX_PACKS)
        suffixes = [cache.encode_suffix(k, h, len(h) - 1)
                    for k, h in zip(keys, hists)]
        assert m.DEFAULT_REGISTRY.counter(
            m.SCOPE_PACK_CACHE, m.M_CACHE_SUFFIX_PACKS) \
            >= before + len(hists)
        suf = assemble_corpus(suffixes,
                              max(r.shape[0] for r in suffixes))
        _assert_corpus_equal(pack_wirec(suf), pack_wirec_native(suf),
                             "suffix")
        # and the suffix-path lanes equal the tail of a cold full pack
        full = [cache.encode(k, h) for k, h in zip(keys, hists)]
        for i, (k, h) in enumerate(zip(keys, hists)):
            from cadence_tpu.ops.encode import (
                encode_batches_resumable,
                history_length,
            )
            cold, _ = encode_batches_resumable(h)
            assert (suffixes[i]
                    == cold[history_length(h[:-1]):]).all()

    def test_env_knob_pins_python_path(self, monkeypatch):
        """CADENCE_TPU_NATIVE_WIREC=0 must route pack_wirec_auto down the
        pure-Python encoder (counted under tpu.native/python-packs) and
        still produce the identical corpus."""
        from cadence_tpu.native.wirec import pack_wirec_auto
        from cadence_tpu.utils import metrics as m
        from cadence_tpu.utils.metrics import MetricsRegistry

        ev = encode_corpus(generate_corpus("basic", num_workflows=6,
                                           seed=3, target_events=40))
        reg_on, reg_off = MetricsRegistry(), MetricsRegistry()
        monkeypatch.delenv("CADENCE_TPU_NATIVE_WIREC", raising=False)
        on = pack_wirec_auto(ev, registry=reg_on)
        assert reg_on.counter(m.SCOPE_TPU_NATIVE, m.M_NATIVE_PACKS) == 1
        monkeypatch.setenv("CADENCE_TPU_NATIVE_WIREC", "0")
        off = pack_wirec_auto(ev, registry=reg_off)
        assert reg_off.counter(m.SCOPE_TPU_NATIVE, m.M_NATIVE_PY_PACKS) == 1
        _assert_corpus_equal(on, off, "env-knob")

    def test_device_crc_parity_native_corpus(self):
        """End to end: a natively packed corpus replays on device to the
        same CRCs as the Python-packed one, every suite."""
        import jax.numpy as jnp

        from cadence_tpu.core.checksum import DEFAULT_LAYOUT
        from cadence_tpu.native.wirec import pack_wirec_native
        from cadence_tpu.ops.replay import replay_wirec_to_crc
        from cadence_tpu.ops.wirec import pack_wirec

        for suite in SUITES:
            ev = encode_corpus(generate_corpus(suite, num_workflows=6,
                                               seed=29, target_events=40))
            py, nat = pack_wirec(ev), pack_wirec_native(ev)
            crc_p, err_p = replay_wirec_to_crc(
                jnp.asarray(py.slab), jnp.asarray(py.bases),
                jnp.asarray(py.n_events), py.profile, DEFAULT_LAYOUT)
            crc_n, err_n = replay_wirec_to_crc(
                jnp.asarray(nat.slab), jnp.asarray(nat.bases),
                jnp.asarray(nat.n_events), nat.profile, DEFAULT_LAYOUT)
            assert (np.asarray(crc_p) == np.asarray(crc_n)).all(), suite
            assert (np.asarray(err_p) == np.asarray(err_n)).all(), suite
