"""Native packer parity: C++ decode+pack must be byte-identical to the
Python packer on every suite, and the codec must round-trip."""
import numpy as np
import pytest

from cadence_tpu.core.codec import deserialize_history, serialize_history
from cadence_tpu.gen.corpus import SUITES, generate_corpus, generate_history
from cadence_tpu.ops.encode import encode_corpus
from cadence_tpu.native import build as native_build
from cadence_tpu.native.packing import encode_corpus_native, pack_serialized

native = pytest.mark.skipif(native_build.load() is None,
                            reason="no C++ toolchain")


@native
@pytest.mark.parametrize("suite", SUITES)
def test_native_matches_python_packer(suite):
    histories = generate_corpus(suite, num_workflows=6, seed=31,
                                target_events=90)
    expected = encode_corpus(histories)
    got = encode_corpus_native(histories, max_events=expected.shape[1])
    mism = np.nonzero(got != expected)
    assert got.shape == expected.shape
    assert (got == expected).all(), (
        f"suite={suite}: first mismatches at {[m[:5] for m in mism]}"
    )


@native
def test_native_rejects_truncated_blob():
    histories = generate_corpus("basic", 2, seed=1, target_events=40)
    from cadence_tpu.core.codec import serialize_corpus
    blobs = serialize_corpus(histories)
    blobs[1] = blobs[1][:len(blobs[1]) // 2]
    with pytest.raises(ValueError, match="workflow 1"):
        pack_serialized(blobs, max_events=64)


@native
def test_native_rejects_overlong_history():
    histories = generate_corpus("basic", 1, seed=1, target_events=60)
    from cadence_tpu.core.codec import serialize_corpus
    with pytest.raises(ValueError, match="code 3"):
        pack_serialized(serialize_corpus(histories), max_events=8)


def test_codec_roundtrip():
    """serialize → deserialize preserves replay-relevant attributes: the
    round-tripped history replays to the same checksum payload."""
    from cadence_tpu.core.checksum import payload_row
    from cadence_tpu.oracle.state_builder import StateBuilder

    for suite in SUITES:
        h = generate_history(suite, seed=8, workflow_index=0, target_events=80)
        blob = serialize_history(h)
        h2 = deserialize_history(blob, h[0].domain_id, h[0].workflow_id,
                                 h[0].run_id)
        # request IDs differ (not serialized) but are checksum-irrelevant
        r1 = payload_row(StateBuilder().replay_history(h))
        r2 = payload_row(StateBuilder().replay_history(h2))
        assert (r1 == r2).all(), f"suite {suite} round-trip diverged"


def test_codec_roundtrip_parent_and_retry():
    """Parent linkage and retry policies survive the wire (regression:
    these used to decode to keys nothing read)."""
    from cadence_tpu.core.enums import EventType
    from cadence_tpu.core.events import HistoryBatch, HistoryEvent, RetryPolicy

    retry = RetryPolicy(initial_interval_seconds=2, backoff_coefficient=1.5,
                        maximum_interval_seconds=30, maximum_attempts=4,
                        expiration_interval_seconds=120)
    h = [HistoryBatch(domain_id="d", workflow_id="w", run_id="r", events=[
        HistoryEvent(id=1, event_type=EventType.WorkflowExecutionStarted,
                     timestamp=5, attrs=dict(
                         task_list="tl", workflow_type="wt",
                         execution_start_to_close_timeout_seconds=60,
                         task_start_to_close_timeout_seconds=10,
                         parent_workflow_id="papa", parent_run_id="papa-run",
                         parent_workflow_domain_id="papa-dom",
                         parent_initiated_event_id=7,
                         retry_policy=retry)),
    ])]
    h2 = deserialize_history(serialize_history(h), "d", "w", "r")
    ev = h2[0].events[0]
    assert ev.get("parent_workflow_id") == "papa"
    assert ev.get("parent_run_id") == "papa-run"
    assert ev.get("parent_workflow_domain_id") == "papa-dom"
    assert ev.get("parent_initiated_event_id") == 7
    rp = ev.get("retry_policy")
    assert rp is not None
    assert (rp.initial_interval_seconds, rp.backoff_coefficient,
            rp.maximum_interval_seconds, rp.maximum_attempts,
            rp.expiration_interval_seconds) == (2, 1.5, 30, 4, 120)
