"""Device task generation must match the oracle's transfer/timer task
streams (numeric fields) on every suite — the full stateBuilder parity
contract: replay rebuilds state AND derives the same tasks."""
import numpy as np
import pytest

from cadence_tpu.gen.corpus import SUITES, generate_corpus
from cadence_tpu.oracle.state_builder import StateBuilder
from cadence_tpu.ops.encode import encode_corpus
from cadence_tpu.ops.replay import replay_events_with_tasks

import jax.numpy as jnp


def oracle_task_streams(history):
    ms = StateBuilder().replay_history(history)
    transfers = [(int(t.task_type), t.version, t.event_id)
                 for t in ms.transfer_tasks]
    timers = [(int(t.task_type), t.version, t.visibility_timestamp,
               t.event_id, int(t.timeout_type), t.attempt)
              for t in ms.timer_tasks]
    return transfers, timers


def device_task_streams(log, w):
    nt = int(log.tr_count[w])
    transfers = [
        (int(log.tr_type[w, i]), int(log.tr_version[w, i]),
         int(log.tr_event_id[w, i]))
        for i in range(nt)
    ]
    nm = int(log.tm_count[w])
    timers = [
        (int(log.tm_type[w, i]), int(log.tm_version[w, i]),
         int(log.tm_vis[w, i]), int(log.tm_event_id[w, i]),
         int(log.tm_timeout_type[w, i]), int(log.tm_attempt[w, i]))
        for i in range(nm)
    ]
    return transfers, timers


@pytest.mark.parametrize("suite", SUITES)
def test_task_stream_parity(suite):
    histories = generate_corpus(suite, num_workflows=8, seed=21,
                                target_events=80)
    events = jnp.asarray(encode_corpus(histories))
    state, log = replay_events_with_tasks(events, max_transfer=96, max_timer=96)
    log = type(log)(*[np.asarray(x) for x in log])
    errors = np.asarray(state.error)
    assert (errors == 0).all()
    assert not log.overflow.any()
    for w, h in enumerate(histories):
        otr, otm = oracle_task_streams(h)
        dtr, dtm = device_task_streams(log, w)
        assert dtr == otr, (
            f"suite={suite} wf={w}: transfer stream diverges\n"
            f" oracle[:6]={otr[:6]}\n device[:6]={dtr[:6]}"
        )
        assert dtm == otm, (
            f"suite={suite} wf={w}: timer stream diverges\n"
            f" oracle[:6]={otm[:6]}\n device[:6]={dtm[:6]}"
        )


def test_task_log_overflow_reported():
    histories = generate_corpus("basic", num_workflows=2, seed=3,
                                target_events=100)
    events = jnp.asarray(encode_corpus(histories))
    _, log = replay_events_with_tasks(events, max_transfer=4, max_timer=4)
    assert bool(np.asarray(log.overflow).all())
