"""Workflow reset + device-backed rebuilds on the hot path.

Round-3 VERDICT ask #2: the TPU engine must be the REBUILDER (not just the
verifier) for reset (reset/resetter.go:96), NDC conflict resolution
(conflict_resolver.go), and crash recovery (state_rebuilder.go) — asserted
via the DeviceRebuilder counters.
"""
import pytest

from cadence_tpu.core.checksum import payload_row
from cadence_tpu.core.enums import CloseStatus, EventType, WorkflowState
from cadence_tpu.engine.history_engine import InvalidRequestError
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import CompleteDecider, SignalDecider
from tests.taskpoller import TaskPoller

DOMAIN = "reset-domain"
TL = "reset-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


def _start_signal_workflow(box, wf="reset-1", expected=3):
    box.frontend.start_workflow_execution(DOMAIN, wf, "signal", TL)
    poller = TaskPoller(box, DOMAIN, TL, {wf: SignalDecider(expected_signals=expected)})
    poller.drain()  # first decision completes, workflow waits on signals
    domain_id = box.stores.domain.by_name(DOMAIN).domain_id
    run_id = box.stores.execution.get_current_run_id(domain_id, wf)
    return poller, domain_id, run_id


class TestReset:
    def test_reset_forks_and_reapplies_signals(self, box):
        poller, domain_id, run_id = _start_signal_workflow(box)
        # two signals recorded after the first decision
        box.frontend.signal_workflow_execution(DOMAIN, "reset-1", "s-1")
        box.frontend.signal_workflow_execution(DOMAIN, "reset-1", "s-2")
        poller.drain()

        # reset to the close of the FIRST decision: history 1=started,
        # 2=sched, 3=dt-started, 4=dt-completed → finish id 4
        new_run = box.frontend.reset_workflow_execution(
            DOMAIN, "reset-1", decision_finish_event_id=4, run_id=run_id,
            reason="test")

        # base run terminated, new run current
        base = box.stores.execution.get_workflow(domain_id, "reset-1", run_id)
        assert base.execution_info.close_status == CloseStatus.Terminated
        assert box.stores.execution.get_current_run_id(
            domain_id, "reset-1") == new_run

        events = box.stores.history.read_events(domain_id, "reset-1", new_run)
        kinds = [e.event_type for e in events]
        # forked prefix ends with the in-flight decision; then the reset
        # fails it and re-applies both signals
        assert kinds[:3] == [EventType.WorkflowExecutionStarted,
                             EventType.DecisionTaskScheduled,
                             EventType.DecisionTaskStarted]
        assert kinds[3] == EventType.DecisionTaskFailed
        assert kinds.count(EventType.WorkflowExecutionSignaled) == 2
        ms = box.stores.execution.get_workflow(domain_id, "reset-1", new_run)
        assert ms.execution_info.signal_count == 2
        assert ms.execution_info.state == WorkflowState.Running

        # the prefix rebuild ran on DEVICE
        assert box.rebuilder.stats.device >= 1
        assert box.rebuilder.stats.oracle_fallback == 0

    def test_reset_workflow_continues_to_completion(self, box):
        poller, domain_id, run_id = _start_signal_workflow(box, wf="reset-2",
                                                           expected=2)
        box.frontend.signal_workflow_execution(DOMAIN, "reset-2", "s-1")
        poller.drain()
        new_run = box.frontend.reset_workflow_execution(
            DOMAIN, "reset-2", decision_finish_event_id=4, run_id=run_id)

        # the transient decision dispatches; the decider sees the single
        # reapplied signal and needs one more to close
        poller = TaskPoller(box, DOMAIN, TL,
                            {"reset-2": SignalDecider(expected_signals=2)})
        poller.drain()
        box.frontend.signal_workflow_execution(DOMAIN, "reset-2", "s-2")
        poller.drain()
        ms = box.stores.execution.get_workflow(domain_id, "reset-2", new_run)
        assert ms.execution_info.close_status == CloseStatus.Completed

        result = box.tpu.verify_all()
        assert result.ok

    def test_reset_redispatches_pending_activity(self, box):
        """A pending (scheduled, un-started) activity forked into the
        prefix must be redispatched in the new run: reset regenerates all
        tasks via the refresher (the rebuilt state carries none)."""
        from cadence_tpu.models.deciders import EchoDecider

        box.frontend.start_workflow_execution(DOMAIN, "reset-act", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"reset-act": EchoDecider(TL)})
        # decisions ONLY (no activity polls): decision 1 schedules the
        # activity, which stays pending; a signal forces decision 2 so the
        # reset point lands past the activity-scheduled event
        box.pump_once()
        while poller.poll_and_decide_once():
            box.pump_once()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "reset-act")
        box.frontend.signal_workflow_execution(DOMAIN, "reset-act", "nudge")
        box.pump_once()
        while poller.poll_and_decide_once():
            box.pump_once()
        events = box.stores.history.read_events(domain_id, "reset-act", run_id)
        finish = max(e.id for e in events
                     if e.event_type == EventType.DecisionTaskCompleted)
        new_run = box.frontend.reset_workflow_execution(
            DOMAIN, "reset-act", decision_finish_event_id=finish, run_id=run_id)

        # the forked prefix still holds the pending activity, and the
        # activity task was re-inserted: the poller can run it to done
        ms = box.stores.execution.get_workflow(domain_id, "reset-act", new_run)
        assert len(ms.pending_activity_info_ids) == 1
        poller = TaskPoller(box, DOMAIN, TL, {"reset-act": EchoDecider(TL)})
        poller.drain()
        ms = box.stores.execution.get_workflow(domain_id, "reset-act", new_run)
        assert ms.execution_info.close_status == CloseStatus.Completed

    def test_reset_rejects_non_decision_boundary(self, box):
        poller, domain_id, run_id = _start_signal_workflow(box, wf="reset-3")
        with pytest.raises(InvalidRequestError):
            box.frontend.reset_workflow_execution(
                DOMAIN, "reset-3", decision_finish_event_id=3, run_id=run_id)

    def test_reset_closed_workflow(self, box):
        """Resetting an already-closed run: no terminate, new run current."""
        box.frontend.start_workflow_execution(DOMAIN, "reset-4", "t", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"reset-4": CompleteDecider()})
        poller.drain()
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "reset-4")
        base = box.stores.execution.get_workflow(domain_id, "reset-4", run_id)
        assert base.execution_info.state == WorkflowState.Completed

        new_run = box.frontend.reset_workflow_execution(
            DOMAIN, "reset-4", decision_finish_event_id=4, run_id=run_id)
        ms = box.stores.execution.get_workflow(domain_id, "reset-4", new_run)
        assert ms.execution_info.state == WorkflowState.Running
        assert box.stores.execution.get_current_run_id(
            domain_id, "reset-4") == new_run
        # base run unchanged (still completed, not terminated)
        base = box.stores.execution.get_workflow(domain_id, "reset-4", run_id)
        assert base.execution_info.close_status == CloseStatus.Completed


class TestDeviceRebuildHotPath:
    def test_recovery_rebuilds_on_device(self, tmp_path):
        """Crash recovery rebuilds every run's state via batched device
        replay (report.device_rebuilt), oracle fallback only when flagged."""
        from cadence_tpu.engine.durability import (
            open_durable_stores,
            recover_stores,
        )

        path = str(tmp_path / "wal.log")
        stores = open_durable_stores(path)
        box = Onebox(num_hosts=1, num_shards=4, stores=stores)
        box.frontend.register_domain(DOMAIN)
        for i in range(4):
            box.frontend.start_workflow_execution(DOMAIN, f"wf-{i}", "t", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {f"wf-{i}": CompleteDecider() for i in range(4)})
        poller.drain()

        recovered, report = recover_stores(path)
        assert report.executions_rebuilt == 4
        assert report.device_rebuilt == 4
        assert report.rebuild_fallback == 0
        assert report.ok

    def test_ndc_conflict_rebuild_on_device(self):
        """The winning-branch rebuild in conflict resolution runs through
        the device rebuilder."""
        from cadence_tpu.engine.multicluster import ReplicatedClusters

        clusters = ReplicatedClusters(num_hosts=1, num_shards=4)
        clusters.register_global_domain(DOMAIN)
        box = clusters.active
        box.frontend.start_workflow_execution(DOMAIN, "split", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"split": SignalDecider(expected_signals=2)})
        poller.drain()
        clusters.replicate()
        clusters.split_brain_promote(DOMAIN)
        apoller = TaskPoller(clusters.active, DOMAIN, TL,
                             {"split": SignalDecider(expected_signals=2)})
        clusters.active.frontend.signal_workflow_execution(DOMAIN, "split", "a")
        apoller.drain()
        spoller = TaskPoller(clusters.standby, DOMAIN, TL,
                             {"split": SignalDecider(expected_signals=2)})
        clusters.standby.frontend.signal_workflow_execution(DOMAIN, "split", "b1")
        clusters.standby.frontend.signal_workflow_execution(DOMAIN, "split", "b2")
        spoller.drain()
        clusters.heal(DOMAIN, "standby")

        # the conflict was resolved by device-replaying the winning branch
        replicators = [clusters.replicator, clusters.reverse_replicator]
        device = sum(r.rebuilder.stats.device for r in replicators)
        fallback = sum(r.rebuilder.stats.oracle_fallback for r in replicators)
        assert device >= 1
        assert fallback == 0
        for b in (clusters.active, clusters.standby):
            assert b.tpu.verify_all().ok
