"""History archival: archive-then-delete retention + read-through
(VERDICT r3 ask #5; common/archiver/interface.go:72, filestore provider,
service/worker/archiver pump).
"""
import pytest

from cadence_tpu.core.enums import CloseStatus, EventType
from cadence_tpu.engine.archival import (
    ArchivalError,
    FilestoreHistoryArchiver,
    archiver_for,
)
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import EchoDecider
from tests.taskpoller import TaskPoller

DOMAIN = "arc-domain"
TL = "arc-tl"
DAY_NANOS = 24 * 3600 * 1_000_000_000


def run_to_completion(box, wf):
    box.frontend.start_workflow_execution(DOMAIN, wf, "echo", TL)
    TaskPoller(box, DOMAIN, TL, {wf: EchoDecider(TL)}).drain()


class TestArchiverProvider:
    def test_uri_routing(self, tmp_path):
        assert archiver_for("") is None
        a = archiver_for(f"file://{tmp_path}")
        assert isinstance(a, FilestoreHistoryArchiver)
        with pytest.raises(ArchivalError):
            archiver_for("s3://bucket/prefix")

    def test_round_trip(self, tmp_path):
        from cadence_tpu.gen.corpus import generate_history

        batches = generate_history("basic", seed=5, workflow_index=0,
                                   target_events=40)
        a = FilestoreHistoryArchiver(str(tmp_path))
        a.archive("d", "w", "r", batches, visibility={"workflow_id": "w"})
        assert a.exists("d", "w", "r")
        back = a.read("d", "w", "r")
        assert [e.id for b in back for e in b.events] == \
               [e.id for b in batches for e in b.events]
        assert a.read_visibility("d", "w", "r")["workflow_id"] == "w"


class TestRetentionArchival:
    def test_archive_then_delete_with_read_through(self, tmp_path):
        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain(DOMAIN, retention_days=1)
        box.frontend.update_domain(
            DOMAIN, history_archival_uri=f"file://{tmp_path}/archive")
        run_to_completion(box, "wf-arc")
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "wf-arc")
        events_before = box.frontend.get_workflow_execution_history(
            DOMAIN, "wf-arc")

        box.clock.advance(2 * DAY_NANOS)
        deleted = box.scavenger.run_once()
        assert deleted == 1
        # the run is GONE from the live stores...
        assert (domain_id, "wf-arc", run_id) not in box.stores.history.list_runs()
        # ...but its history still reads, through the archive
        events_after = box.frontend.get_workflow_execution_history(
            DOMAIN, "wf-arc", run_id=run_id)
        assert [e.id for e in events_after] == [e.id for e in events_before]
        assert events_after[-1].event_type == EventType.WorkflowExecutionCompleted
        # archived visibility carries the closed record
        arc = archiver_for(f"file://{tmp_path}/archive")
        vis = arc.read_visibility(domain_id, "wf-arc", run_id)
        assert vis["close_status"] == int(CloseStatus.Completed)
        # the scanner stays clean after the scavenge
        assert box.scanner.run_once().ok

    def test_no_archival_uri_deletes_outright(self, tmp_path):
        from cadence_tpu.engine.persistence import EntityNotExistsError

        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain(DOMAIN, retention_days=1)
        run_to_completion(box, "wf-del")
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "wf-del")
        box.clock.advance(2 * DAY_NANOS)
        assert box.scavenger.run_once() == 1
        with pytest.raises(EntityNotExistsError):
            box.frontend.get_workflow_execution_history(DOMAIN, "wf-del",
                                                        run_id=run_id)

    def test_archive_failure_skips_delete(self, tmp_path, monkeypatch):
        """Archive-then-delete ordering: when the archive write fails, the
        run SURVIVES (retention never destroys the only copy)."""
        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain(DOMAIN, retention_days=1)
        box.frontend.update_domain(
            DOMAIN, history_archival_uri=f"file://{tmp_path}/archive")
        run_to_completion(box, "wf-keep")
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "wf-keep")
        box.clock.advance(2 * DAY_NANOS)
        monkeypatch.setattr(
            "cadence_tpu.engine.archival.FilestoreHistoryArchiver.archive",
            lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")))
        assert box.scavenger.run_once() == 0
        assert (domain_id, "wf-keep", run_id) in box.stores.history.list_runs()
        monkeypatch.undo()
        assert box.scavenger.run_once() == 1
        events = box.frontend.get_workflow_execution_history(
            DOMAIN, "wf-keep", run_id=run_id)
        assert events[-1].event_type == EventType.WorkflowExecutionCompleted
