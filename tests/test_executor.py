"""Pipelined bulk-replay executor + pack cache (ISSUE 4).

Covers: the depth-N ring discipline and error paths of
engine/executor.BulkReplayExecutor; pack-cache correctness (cold vs
warm vs suffix-extended packs byte-identical, CRC parity on both wire
formats); the chunked replay engine's bounded-footprint contract (a
long-tail history inflates only its own chunk); device-side verify_all
still detecting divergence through the mismatch bitmap; and the feeder
ring at depth > 2.
"""
import threading
import time

import numpy as np
import pytest

from cadence_tpu.engine.cache import PackCache
from cadence_tpu.engine.executor import BulkReplayExecutor, pipeline_depth
from cadence_tpu.engine.persistence import Stores
from cadence_tpu.engine.tpu_engine import TPUReplayEngine
from cadence_tpu.gen.corpus import generate_corpus
from cadence_tpu.ops.encode import assemble_corpus, encode_corpus, to_wire32
from cadence_tpu.utils import metrics as m

# ---------------------------------------------------------------------------
# executor mechanics (no device work: numpy stands in for device outputs)
# ---------------------------------------------------------------------------


class TestExecutorMechanics:
    def _run(self, depth, n_chunks, fail_at=None):
        log = []
        lock = threading.Lock()
        executor = BulkReplayExecutor(depth=depth)

        def pack(ci):
            with lock:
                log.append(("pack", ci))
            if fail_at is not None and ci == fail_at:
                raise ValueError(f"pack {ci} failed")
            return np.full((4,), ci)

        def launch(ci, packed):
            with lock:
                log.append(("launch", ci))
            return packed * 2

        def consume(ci, outs):
            return int(outs.sum())

        outs, report = executor.run(n_chunks, pack, launch, consume)
        return outs, report, log

    def test_results_ordered_and_consumed(self):
        outs, report, _ = self._run(depth=3, n_chunks=8)
        assert outs == [ci * 2 * 4 for ci in range(8)]
        assert report.chunks == 8 and report.depth == 3
        assert report.pack_s >= 0 and report.wall_s > 0

    def test_ring_discipline_depth_n(self):
        """pack(ci) must never start before chunk ci - depth was LAUNCHED
        (its outputs are what frees the ring slot) — at every depth."""
        for depth in (2, 3, 4):
            _, _, log = self._run(depth=depth, n_chunks=2 * depth + 3)
            for ci in range(depth, 2 * depth + 3):
                pack_at = log.index(("pack", ci))
                launch_at = log.index(("launch", ci - depth))
                assert launch_at < pack_at, (
                    f"depth={depth}: pack({ci}) ran before "
                    f"launch({ci - depth}) freed its ring slot")

    def test_pack_queue_wait_leg_recorded(self):
        m.DEFAULT_REGISTRY.reset()
        self._run(depth=2, n_chunks=5)
        hist = m.DEFAULT_REGISTRY.histogram(m.SCOPE_TPU_REPLAY,
                                            m.M_PROFILE_PACK_WAIT)
        assert hist.count == 5

    def test_pack_failure_propagates_without_hang(self):
        t0 = time.monotonic()
        with pytest.raises(ValueError, match="pack 2 failed"):
            self._run(depth=2, n_chunks=6, fail_at=2)
        assert time.monotonic() - t0 < 30  # pool must not wedge

    def test_pipeline_depth_floor(self):
        assert pipeline_depth(1) == 2
        assert pipeline_depth(5) == 5


# ---------------------------------------------------------------------------
# pack cache: cold == warm == suffix-extended, on every wire format
# ---------------------------------------------------------------------------


class TestPackCacheParity:
    def _corpus(self):
        return generate_corpus("basic", num_workflows=10, seed=17,
                               target_events=40)

    def test_suffix_pack_byte_identical_both_wire_formats(self):
        """A cache hit after appending a batch must produce byte-identical
        packed lanes and identical crc_xor to a cold pack — int64/wire32
        AND wirec."""
        import jax.numpy as jnp

        from cadence_tpu.ops.replay import replay_to_crc32, replay_wirec_to_crc
        from cadence_tpu.ops.wirec import pack_wirec

        hists = self._corpus()
        cache = PackCache()
        keys = [("d", "w", f"r{i}") for i in range(len(hists))]
        # warm the cache on a PREFIX (all but the last batch), then encode
        # the full history: the suffix path must extend the cached rows
        for key, h in zip(keys, hists):
            cache.encode(key, h[:-1])
        warm_rows = [cache.encode(k, h) for k, h in zip(keys, hists)]
        reg = m.DEFAULT_REGISTRY
        assert reg.counter(m.SCOPE_PACK_CACHE, m.M_CACHE_SUFFIX_PACKS) \
            == len(hists)

        cold = encode_corpus(hists)
        warm = assemble_corpus(warm_rows, cold.shape[1])
        assert warm.shape == cold.shape and (warm == cold).all()

        # wire32: identical int32 lanes, identical device CRCs
        w32_cold, w32_warm = to_wire32(cold), to_wire32(warm)
        assert (w32_cold == w32_warm).all()
        crc_cold, err_cold = replay_to_crc32(jnp.asarray(w32_cold))
        crc_warm, err_warm = replay_to_crc32(jnp.asarray(w32_warm))
        crc_cold, crc_warm = np.asarray(crc_cold), np.asarray(crc_warm)
        assert (np.asarray(err_cold) == 0).all()
        assert (crc_cold == crc_warm).all()
        assert (int(np.bitwise_xor.reduce(crc_cold.astype(np.uint32)))
                == int(np.bitwise_xor.reduce(crc_warm.astype(np.uint32))))

        # wirec: identical slab/bases/counts, identical device CRCs
        wc_cold = pack_wirec(cold)
        wc_warm = pack_wirec(warm, profile=wc_cold.profile)
        assert (wc_cold.slab == wc_warm.slab).all()
        assert (wc_cold.bases == wc_warm.bases).all()
        assert (wc_cold.n_events == wc_warm.n_events).all()
        crc_c, _ = replay_wirec_to_crc(
            jnp.asarray(wc_cold.slab), jnp.asarray(wc_cold.bases),
            jnp.asarray(wc_cold.n_events), wc_cold.profile)
        crc_w, _ = replay_wirec_to_crc(
            jnp.asarray(wc_warm.slab), jnp.asarray(wc_warm.bases),
            jnp.asarray(wc_warm.n_events), wc_warm.profile)
        assert (np.asarray(crc_c) == np.asarray(crc_w)).all()

    def test_exact_hit_returns_cached_rows(self):
        hists = self._corpus()
        cache = PackCache()
        a = cache.encode(("d", "w", "r0"), hists[0])
        b = cache.encode(("d", "w", "r0"), hists[0])
        assert a is b  # the cached array itself, no repack
        assert m.DEFAULT_REGISTRY.counter(
            m.SCOPE_PACK_CACHE, m.M_CACHE_HITS) == 1

    def test_tail_overwrite_invalidates(self):
        """A rewritten last batch (transaction-retry overwrite semantics)
        must MISS — the checksum changes."""
        hists = self._corpus()
        h = hists[0]
        cache = PackCache()
        cache.encode(("d", "w", "r0"), h)
        mutated = list(h[:-1]) + [h[-2]]  # different tail bytes
        cache.encode(("d", "w", "r0"), mutated)
        assert m.DEFAULT_REGISTRY.counter(
            m.SCOPE_PACK_CACHE, m.M_CACHE_MISSES) == 2

    def test_eviction_counter_on_metrics(self):
        cache = PackCache(max_size=2)
        hists = self._corpus()
        for i in range(4):
            cache.encode(("d", "w", f"r{i}"), hists[i])
        assert m.DEFAULT_REGISTRY.counter(
            m.SCOPE_PACK_CACHE, m.M_CACHE_EVICTIONS) == 2
        assert 'cadence_evictions_total{scope="tpu.pack-cache"}' in \
            m.DEFAULT_REGISTRY.to_prometheus()


# ---------------------------------------------------------------------------
# chunked replay engine: bounded footprint + unchanged results
# ---------------------------------------------------------------------------


def _stores_with_corpus(hists):
    stores = Stores()
    keys = []
    for i, h in enumerate(hists):
        key = ("dom", f"wf-{i}", f"run-{i}")
        for batch in h:
            stores.history.append_batch(*key, list(batch.events))
        keys.append(key)
    return stores, keys


class TestChunkedReplay:
    def test_long_tail_inflates_only_its_chunk(self):
        """Regression for the unbounded [W, E_max, L] corpus: with one
        long-tail history among many short ones, chunking sizes every
        other chunk's event axis to ITS OWN longest history."""
        short = generate_corpus("basic", num_workflows=11, seed=3,
                                target_events=12)
        long_h = generate_corpus("basic", num_workflows=1, seed=9,
                                 target_events=160)
        hists = short[:5] + long_h + short[5:]
        stores, keys = _stores_with_corpus(hists)

        chunked = TPUReplayEngine(stores, chunk_workflows=4)
        rows_c, err_c, br_c = chunked.replay_tree_payloads(keys)
        shapes = chunked.last_run_chunk_shapes
        assert len(shapes) == 3
        long_e = max(e for _, e in shapes)
        assert sum(1 for _, e in shapes if e == long_e) == 1
        # chunks without the long-tail history stay small: the peak
        # host/HBM footprint is bounded by chunk x its OWN max, not
        # W x corpus max
        assert all(e <= 32 for _, e in shapes if e != long_e)
        assert long_e >= 128

        single = TPUReplayEngine(stores, chunk_workflows=4096)
        rows_s, err_s, br_s = single.replay_tree_payloads(keys)
        assert len(single.last_run_chunk_shapes) == 1
        assert (rows_c == rows_s).all()
        assert (err_c == err_s).all() and (br_c == br_s).all()
        assert (err_c == 0).all()

    def test_chunked_matches_oracle_payloads(self):
        from cadence_tpu.core.checksum import STICKY_ROW_INDEX, payload_row
        from cadence_tpu.oracle.state_builder import StateBuilder

        hists = generate_corpus("timer_retry", num_workflows=9, seed=5,
                                target_events=24)
        stores, keys = _stores_with_corpus(hists)
        engine = TPUReplayEngine(stores, chunk_workflows=4)
        rows, errors, _ = engine.replay_tree_payloads(keys)
        assert (errors == 0).all()
        for i, h in enumerate(hists):
            ms = StateBuilder().replay_history(h)
            expected = payload_row(ms)
            expected[STICKY_ROW_INDEX] = 0
            assert (rows[i] == expected).all()


# ---------------------------------------------------------------------------
# engine-level verify_all: cache warm path + device-side divergence bitmap
# ---------------------------------------------------------------------------


DOMAIN = "exec-domain"
TL = "exec-tl"


@pytest.fixture()
def box():
    from cadence_tpu.engine.onebox import Onebox
    b = Onebox(num_hosts=2, num_shards=8)
    b.frontend.register_domain(DOMAIN)
    return b


class TestVerifyAllExecutor:
    def test_warm_verify_hits_pack_cache_and_suffix_packs(self, box):
        """Acceptance: a warm re-verify of an unchanged corpus is served
        by the resident-state cache (exact hits, zero repacking); an
        appended batch takes the suffix path end to end — a resident
        suffix hit whose lanes come from the pack cache's suffix repack
        (engine/cache.encode_suffix), so BOTH caches' counters move."""
        box.frontend.start_workflow_execution(DOMAIN, "wf-cache", "t", TL)
        result = box.tpu.verify_all()
        assert result.ok
        reg = box.tpu.pack_cache.metrics
        assert reg.counter(m.SCOPE_PACK_CACHE, m.M_CACHE_MISSES) >= 1
        assert reg.counter(m.SCOPE_PACK_CACHE, m.M_CACHE_HITS) == 0
        assert not result.resident  # cold: nothing was pinned yet

        # unchanged corpus: pure resident exact hits, no repacking
        result = box.tpu.verify_all()
        assert result.ok and result.resident
        assert reg.counter(m.SCOPE_TPU_RESIDENT, m.M_CACHE_HITS) >= 1
        assert 'cadence_hits_total{scope="tpu.resident"}' in \
            reg.to_prometheus()

        # append one batch (a signal) — only the suffix repacks, and it
        # replays against the resident state instead of from event 0
        box.frontend.signal_workflow_execution(DOMAIN, "wf-cache", "go")
        assert box.tpu.verify_all().ok
        assert reg.counter(m.SCOPE_PACK_CACHE, m.M_CACHE_SUFFIX_PACKS) >= 1
        assert reg.counter(m.SCOPE_TPU_RESIDENT,
                           m.M_RESIDENT_SUFFIX_HITS) >= 1

    def test_divergence_detected_via_device_bitmap(self, box):
        """verify_all compares on device now; a tampered live state must
        still surface as divergent."""
        from cadence_tpu.models.deciders import CompleteDecider
        from tests.taskpoller import TaskPoller

        box.frontend.start_workflow_execution(DOMAIN, "wf-div", "t", TL)
        TaskPoller(box, DOMAIN, TL, {"wf-div": CompleteDecider()}).drain()
        assert box.tpu.verify_all().ok
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "wf-div")
        key = (domain_id, "wf-div", run_id)
        ms = box.stores.execution.get_workflow(*key)
        ms.execution_info.signal_count += 1  # foreign corruption
        result = box.tpu.verify_all()
        assert key in result.divergent

    def test_branch_arbitration_mismatch_still_divergent(self, box):
        from cadence_tpu.models.deciders import CompleteDecider
        from tests.taskpoller import TaskPoller

        box.frontend.start_workflow_execution(DOMAIN, "wf-br", "t", TL)
        TaskPoller(box, DOMAIN, TL, {"wf-br": CompleteDecider()}).drain()
        import copy

        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "wf-br")
        key = (domain_id, "wf-br", run_id)
        ms = box.stores.execution.get_workflow(*key)
        # a phantom duplicate branch with the current pointer moved onto
        # it: the device arbitrates branch 0, the store claims 1 — the
        # on-device branch compare must flag it
        vhs = ms.version_histories
        vhs.histories.append(copy.deepcopy(vhs.histories[0]))
        vhs.current_index = 1
        result = box.tpu.verify_all()
        assert key in result.divergent


# ---------------------------------------------------------------------------
# feeder ring at depth > 2
# ---------------------------------------------------------------------------


class TestFeederDepth:
    @pytest.mark.parametrize("depth", [3, 4])
    def test_deep_ring_matches_direct_replay(self, depth):
        from cadence_tpu.native import packing
        from cadence_tpu.native.feeder import feed_corpus
        from cadence_tpu.ops.replay import replay_corpus

        if not packing.native_available():
            pytest.skip("native packer unavailable")
        hists = generate_corpus("basic", num_workflows=26, seed=7,
                                target_events=30)
        rows_direct, _, errors_direct = replay_corpus(hists)
        # 26 workflows / chunk 4 = 7 chunks: several full ring wraps
        rows, errors, report = feed_corpus(hists, chunk_workflows=4,
                                           depth=depth)
        assert report.depth == depth and report.chunks == 7
        assert (errors == errors_direct).all()
        assert (rows == rows_direct).all()
        assert report.pack_queue_wait_s >= 0

    @pytest.mark.parametrize("depth", [4])
    def test_deep_ring_wirec(self, depth):
        from cadence_tpu.core.checksum import crc32_of_rows
        from cadence_tpu.native import packing
        from cadence_tpu.native.feeder import feed_corpus_wirec
        from cadence_tpu.ops.replay import replay_corpus

        if not packing.native_available():
            pytest.skip("native packer unavailable")
        hists = generate_corpus("echo_signal", num_workflows=18, seed=11,
                                target_events=24)
        rows_direct, crcs_direct, _ = replay_corpus(hists)
        crcs, errors, report = feed_corpus_wirec(hists, chunk_workflows=4,
                                                 depth=depth)
        assert (errors == 0).all()
        assert (crcs == crcs_direct).all()
        assert report.depth == depth
