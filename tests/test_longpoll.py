"""Long-poll: history notifier + parked task polls (VERDICT missing #7).

Reference: events/notifier.go (NotifyNewHistoryEvent pub/sub behind
GetWorkflowExecutionHistory's long poll, workflowHandler.go:2106) and the
long-poll transport over matching's sync-match parking.
"""
import threading

import pytest

from cadence_tpu.core.enums import CloseStatus, DecisionType, EventType
from cadence_tpu.engine.history_engine import Decision
from cadence_tpu.engine.onebox import Onebox

DOMAIN = "lp-domain"
TL = "lp-tl"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=1, num_shards=4)
    b.frontend.register_domain(DOMAIN)
    return b


class TestHistoryLongPoll:
    def test_blocks_until_new_event(self, box):
        """A history long-poll parked past the known tail returns as soon
        as the next transaction commits."""
        box.frontend.start_workflow_execution(DOMAIN, "h-1", "signal", TL)
        events = box.frontend.get_workflow_execution_history(DOMAIN, "h-1")
        tail = events[-1].id

        result = {}

        def waiter():
            result["events"] = box.frontend.get_workflow_execution_history(
                DOMAIN, "h-1", wait_for_new_event=True, last_event_id=tail,
                timeout=5.0)

        t = threading.Thread(target=waiter)
        t.start()
        # let the waiter park, then produce an event
        import time
        time.sleep(0.05)
        box.frontend.signal_workflow_execution(DOMAIN, "h-1", "wake")
        t.join(timeout=5)
        assert not t.is_alive()
        assert result["events"][-1].id > tail
        assert result["events"][-1].event_type in (
            EventType.WorkflowExecutionSignaled, EventType.DecisionTaskScheduled)

    def test_close_wakes_waiters(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "h-2", "t", TL)
        events = box.frontend.get_workflow_execution_history(DOMAIN, "h-2")
        tail = events[-1].id
        result = {}

        def waiter():
            result["events"] = box.frontend.get_workflow_execution_history(
                DOMAIN, "h-2", wait_for_new_event=True, last_event_id=tail,
                timeout=5.0)

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.05)
        box.frontend.terminate_workflow_execution(DOMAIN, "h-2")
        t.join(timeout=5)
        assert not t.is_alive()
        kinds = [e.event_type for e in result["events"]]
        assert EventType.WorkflowExecutionTerminated in kinds

    def test_timeout_returns_unchanged_history(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "h-3", "t", TL)
        events = box.frontend.get_workflow_execution_history(DOMAIN, "h-3")
        tail = events[-1].id
        got = box.frontend.get_workflow_execution_history(
            DOMAIN, "h-3", wait_for_new_event=True, last_event_id=tail,
            timeout=0.05)
        assert got[-1].id == tail  # timed out without progress


class TestTaskLongPoll:
    def test_decision_long_poll_sync_matches(self, box):
        """A long-poll on an empty list parks; a workflow start's decision
        task sync-matches into it without touching the backlog."""
        result = {}

        def poller():
            result["resp"] = box.frontend.poll_for_decision_task(
                DOMAIN, TL, wait_seconds=5.0)

        t = threading.Thread(target=poller)
        t.start()
        import time
        time.sleep(0.05)
        box.frontend.start_workflow_execution(DOMAIN, "lp-1", "t", TL)
        box.pump_once()  # transfer task → matching → sync-match the park
        t.join(timeout=5)
        assert not t.is_alive()
        resp = result["resp"]
        assert resp is not None and resp.token.workflow_id == "lp-1"
        # complete it end to end
        box.frontend.respond_decision_task_completed(
            resp.token, [Decision(DecisionType.CompleteWorkflowExecution, {})])
        domain_id = box.frontend.describe_domain(DOMAIN).domain_id
        run = box.stores.execution.get_current_run_id(domain_id, "lp-1")
        ms = box.stores.execution.get_workflow(domain_id, "lp-1", run)
        assert ms.execution_info.close_status == CloseStatus.Completed

    def test_long_poll_times_out_clean(self, box):
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL,
                                                   wait_seconds=0.05)
        assert resp is None
        # the canceled park must not swallow the next task
        box.frontend.start_workflow_execution(DOMAIN, "lp-2", "t", TL)
        box.pump_once()
        resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
        assert resp is not None and resp.token.workflow_id == "lp-2"


class TestPerExecutionNotifier:
    """The notifier wakes ONLY the target execution's waiters (per-
    execution condvars, events/notifier.go subscriber channels — VERDICT
    r4 weak #6: a global condvar was O(all parked polls) per commit)."""

    def test_notify_wakes_only_target_execution(self):
        import threading
        import time as _time

        from cadence_tpu.engine.notifier import HistoryNotifier

        n = HistoryNotifier()
        results = {}
        threads = []
        keys = [("d", f"wf-{i}", "r") for i in range(50)]

        def wait(key):
            results[key] = n.wait_for(key, 2, timeout=8.0)

        for key in keys:
            t = threading.Thread(target=wait, args=(key,), daemon=True)
            t.start()
            threads.append(t)
        deadline = _time.monotonic() + 5
        while n.watched() < 50 and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert n.watched() == 50

        n.notify(keys[7], 5, False)
        threads[7].join(timeout=5)
        assert not threads[7].is_alive()
        assert results[keys[7]] is True
        # every OTHER waiter is still parked — none were woken spuriously
        # into completion, and the registry reflects exactly them
        _time.sleep(0.05)
        assert n.watched() == 49
        for key in keys:
            n.notify(key, 5, False)
        for t in threads:
            t.join(timeout=5)
        assert all(results[k] for k in keys)
        assert n.watched() == 0
