"""Onebox integration tests: full cluster in one process, worker loops
hand-rolled (the host/ integration-test tier), closing with the north-star
loop — every live workflow's persisted history device-replays to the same
checksum payload as its live mutable state."""
import pytest

from cadence_tpu.core.enums import CloseStatus, WorkflowState
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.models.deciders import (
    CancellationDecider,
    ChainedActivityDecider,
    ChildWorkflowDecider,
    ConcurrentActivityDecider,
    EchoDecider,
    SignalDecider,
    TimerDecider,
)

from tests.taskpoller import TaskPoller

DOMAIN = "it-domain"
TL = "it-tasklist"


@pytest.fixture()
def box():
    b = Onebox(num_hosts=2, num_shards=8)
    b.frontend.register_domain(DOMAIN)
    return b


def closed_status(box, workflow_id):
    ms = box.frontend.describe_workflow_execution(DOMAIN, workflow_id)
    assert ms.execution_info.state == WorkflowState.Completed
    return ms.execution_info.close_status


class TestWorkflowLifecycles:
    def test_echo_activity_workflow(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-echo", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"wf-echo": EchoDecider(TL)})
        poller.drain()
        assert closed_status(box, "wf-echo") == CloseStatus.Completed
        closed = box.frontend.list_closed_workflow_executions(DOMAIN)
        assert [r.workflow_id for r in closed] == ["wf-echo"]

    def test_chained_activities(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-chain", "basic", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"wf-chain": ChainedActivityDecider(TL, chain_length=5)})
        poller.drain()
        assert closed_status(box, "wf-chain") == CloseStatus.Completed
        history = box.frontend.get_workflow_execution_history(DOMAIN, "wf-chain")
        from cadence_tpu.core.enums import EventType
        assert sum(1 for e in history
                   if e.event_type == EventType.ActivityTaskCompleted) == 5

    def test_signal_workflow(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-sig", "signal", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"wf-sig": SignalDecider(expected_signals=3)})
        poller.drain()
        for i in range(3):
            box.frontend.signal_workflow_execution(DOMAIN, "wf-sig", f"s{i}")
            poller.drain()
        assert closed_status(box, "wf-sig") == CloseStatus.Completed
        ms = box.frontend.describe_workflow_execution(DOMAIN, "wf-sig")
        assert ms.execution_info.signal_count == 3

    def test_timer_workflow(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-timer", "timer", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"wf-timer": TimerDecider(fire_seconds=5)})
        poller.drain()
        # timer pending; nothing fires until the clock advances
        ms = box.frontend.describe_workflow_execution(DOMAIN, "wf-timer")
        assert len(ms.pending_timer_info_ids) == 1
        box.advance_time(6)
        poller.drain()
        assert closed_status(box, "wf-timer") == CloseStatus.Completed

    def test_concurrent_activities(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-conc", "conc", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"wf-conc": ConcurrentActivityDecider(TL, width=4)})
        poller.drain()
        assert closed_status(box, "wf-conc") == CloseStatus.Completed

    def test_child_workflow(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-parent", "parent", TL)
        poller = TaskPoller(box, DOMAIN, TL, {
            "wf-parent": ChildWorkflowDecider("wf-child"),
            "wf-child": EchoDecider(TL),
        })
        poller.drain()
        assert closed_status(box, "wf-parent") == CloseStatus.Completed
        assert closed_status(box, "wf-child") == CloseStatus.Completed
        # child history carries parent linkage
        child_ms = box.frontend.describe_workflow_execution(DOMAIN, "wf-child")
        assert child_ms.execution_info.parent_workflow_id == "wf-parent"

    def test_cancellation(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-cancel", "cancel", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {"wf-cancel": CancellationDecider(TL)})
        # run the first decision (schedules a long activity)
        box.pump_once()
        poller.poll_and_decide_once()
        box.frontend.request_cancel_workflow_execution(DOMAIN, "wf-cancel")
        poller.drain()
        assert closed_status(box, "wf-cancel") == CloseStatus.Canceled

    def test_activity_timeout_fires(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-tmo", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"wf-tmo": EchoDecider(TL)})
        box.pump_once()
        poller.poll_and_decide_once()  # schedules echo activity (timeouts 60/120)
        box.pump_once()  # activity task dispatched to matching; nobody polls it
        box.advance_time(130)  # blow through schedule-to-close
        box.pump_once()
        ms = box.frontend.describe_workflow_execution(DOMAIN, "wf-tmo")
        assert len(ms.pending_activity_info_ids) == 0  # timed out
        from cadence_tpu.core.enums import EventType
        history = box.frontend.get_workflow_execution_history(DOMAIN, "wf-tmo")
        assert any(e.event_type == EventType.ActivityTaskTimedOut for e in history)

    def test_terminate(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-term", "echo", TL)
        box.frontend.terminate_workflow_execution(DOMAIN, "wf-term", reason="ops")
        assert closed_status(box, "wf-term") == CloseStatus.Terminated

    def test_workflow_timeout(self, box):
        box.frontend.start_workflow_execution(DOMAIN, "wf-wtmo", "echo", TL,
                                              execution_timeout=50)
        box.advance_time(60)
        box.pump_once()
        assert closed_status(box, "wf-wtmo") == CloseStatus.TimedOut


class TestClusterMechanics:
    def test_shards_spread_across_hosts(self, box):
        for i in range(16):
            box.frontend.start_workflow_execution(DOMAIN, f"wf-{i}", "echo", TL)
        owned = {h: c.owned_shards() for h, c in box.controllers.items()}
        assert sum(len(s) for s in owned.values()) > 0
        # both hosts own at least one engine across 16 workflows
        assert all(len(s) > 0 for s in owned.values())

    def test_host_failure_shard_steal(self, box):
        for i in range(8):
            box.frontend.start_workflow_execution(DOMAIN, f"wf-{i}", "echo", TL)
        poller = TaskPoller(box, DOMAIN, TL,
                            {f"wf-{i}": EchoDecider(TL) for i in range(8)})
        # kill host-1; survivors steal its shards and finish the work
        box.remove_host("host-1")
        poller.drain()
        for i in range(8):
            assert closed_status(box, f"wf-{i}") == CloseStatus.Completed

    def test_stale_owner_fenced(self, box):
        """Range-ID fencing: writes from a deposed shard owner must fail
        (shard/context.go:586-700 contract)."""
        from cadence_tpu.engine.persistence import ShardOwnershipLostError
        box.frontend.start_workflow_execution(DOMAIN, "wf-fence", "echo", TL)
        engine = box.route("wf-fence")
        # a second owner acquires the same shard (range bump)
        from cadence_tpu.engine.shard import ShardContext
        usurper = ShardContext(engine.shard.shard_id, "usurper", box.stores)
        usurper.acquire()
        with pytest.raises(ShardOwnershipLostError):
            engine.signal_workflow(
                box.stores.domain.by_name(DOMAIN).domain_id, "wf-fence", "s")


    def test_concurrent_txn_loser_fails_before_clobbering_history(self, box):
        """Two transactions race on one workflow: the loser's commit must
        fail BEFORE its history append can truncate the winner's committed
        tail (shard.commit_workflow precheck; the reference serializes via
        the per-workflow context lock, execution/cache.go:182)."""
        import copy

        import pytest as _pytest

        from cadence_tpu.core.enums import EventType
        from cadence_tpu.core.events import HistoryEvent
        from cadence_tpu.engine.persistence import ConditionFailedError
        box.frontend.start_workflow_execution(DOMAIN, "wf-race", "echo", TL)
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        run_id = box.stores.execution.get_current_run_id(domain_id, "wf-race")
        engine = box.route("wf-race")
        # T2 loads its snapshot first (stale after T1 commits)
        stale = copy.deepcopy(box.stores.execution.get_workflow(
            domain_id, "wf-race", run_id))
        expected = stale.execution_info.next_event_id
        # T1 wins: a real signal through the engine
        engine.signal_workflow(domain_id, "wf-race", "winner")
        # T2 tries to commit at the same event id
        ev = HistoryEvent(id=expected,
                          event_type=EventType.WorkflowExecutionSignaled,
                          attrs={"signal_name": "loser"})
        with _pytest.raises(ConditionFailedError):
            engine.shard.commit_workflow(stale, expected, [ev], [], [])
        # the winner's tail is intact — no silent history/state divergence
        events = box.stores.history.read_events(domain_id, "wf-race", run_id)
        signals = [e for e in events
                   if e.event_type == EventType.WorkflowExecutionSignaled]
        assert [e.get("signal_name") for e in signals] == ["winner"]
        stored = box.stores.execution.get_workflow(domain_id, "wf-race", run_id)
        assert stored.execution_info.next_event_id == events[-1].id + 1


class TestNorthStarLoop:
    def test_device_replay_matches_live_state(self, box):
        """Run a mixed fleet to completion, then device-replay every
        persisted history and demand zero checksum divergence vs the live
        engine state — the north-star contract, end to end."""
        deciders = {}
        for i in range(4):
            wid = f"fleet-echo-{i}"
            box.frontend.start_workflow_execution(DOMAIN, wid, "echo", TL)
            deciders[wid] = EchoDecider(TL)
        for i in range(3):
            wid = f"fleet-sig-{i}"
            box.frontend.start_workflow_execution(DOMAIN, wid, "signal", TL)
            deciders[wid] = SignalDecider(expected_signals=2)
        wid = "fleet-timer"
        box.frontend.start_workflow_execution(DOMAIN, wid, "timer", TL)
        deciders[wid] = TimerDecider(fire_seconds=3)

        poller = TaskPoller(box, DOMAIN, TL, deciders)
        poller.drain()
        for i in range(2):
            for j in range(3):
                box.frontend.signal_workflow_execution(DOMAIN, f"fleet-sig-{j}", f"s{i}")
            poller.drain()
        box.advance_time(5)
        poller.drain()

        result = box.tpu.verify_all()
        assert result.total == 8
        assert result.ok, f"divergent workflows: {result.divergent}"
        assert result.verified_on_device == 8
        assert not result.fallback


class TestContinueAsNew:
    def test_continue_as_new_chains_recorded_run(self, box):
        """The run recorded in the ContinuedAsNew event must exist and be
        the current run (regression: a fresh uuid used to be minted)."""
        from cadence_tpu.core.enums import DecisionType, EventType
        from cadence_tpu.engine.history_engine import Decision

        class CanOnceDecider:
            def __init__(self):
                self.generation = 0

            def decide(self, history):
                started = history[0]
                if any(e.event_type == EventType.MarkerRecorded for e in history):
                    return [Decision(DecisionType.CompleteWorkflowExecution)]
                if started.get("marker_gen"):  # never set; first run continues
                    return [Decision(DecisionType.CompleteWorkflowExecution)]
                self.generation += 1
                if self.generation == 1:
                    return [Decision(DecisionType.ContinueAsNewWorkflowExecution,
                                     dict(task_list=TL))]
                return [Decision(DecisionType.CompleteWorkflowExecution)]

        box.frontend.start_workflow_execution(DOMAIN, "wf-can", "can", TL)
        poller = TaskPoller(box, DOMAIN, TL, {"wf-can": CanOnceDecider()})
        poller.drain()
        # first run closed as continued-as-new; its recorded new run exists
        domain_id = box.stores.domain.by_name(DOMAIN).domain_id
        runs = [k for k in box.stores.execution.list_executions()
                if k[1] == "wf-can"]
        assert len(runs) == 2
        first = next(
            ms for k in runs
            for ms in [box.stores.execution.get_workflow(*k)]
            if ms.execution_info.close_status == CloseStatus.ContinuedAsNew)
        history = box.stores.history.read_events(
            domain_id, "wf-can", first.execution_info.run_id)
        can_event = history[-1]
        recorded_run = can_event.get("new_execution_run_id")
        cur = box.stores.execution.get_current_run_id(domain_id, "wf-can")
        assert recorded_run == cur
        assert closed_status(box, "wf-can") == CloseStatus.Completed
