"""Perf gate (deploy/smoke_perf.sh, marker `perf`).

Three layers:

1. Always-on zero-divergence checks: the pipelined bulk executor's
   chunked, overlapped transfer path must produce exactly the CRCs of a
   one-shot replay, and the chunk-parallel wirec packer must emit
   byte-identical wire bytes — a perf path that changes results is not a
   perf path.

2. Fallback-under-pressure gate: a forced ≥2.5%-flagged corpus runs the
   capacity-escalation ladder (engine/ladder.py) end to end — the ladder
   result must be CRC-identical to the oracle-only arbitration path and
   warm trials must recompile nothing. With PERF_CURRENT / PERF_BASELINE
   set, the recorded `fallback_under_pressure.mixed_rate_median` must
   also stay within tolerance of the baseline: a reintroduced overflow
   cliff (BENCH_r05's 3x collapse) fails CI here.

3. Incremental (O(new events)) gate: append transactions through the
   HBM-resident state cache must cost by APPENDED events, not history
   length — equal suffixes launch identical shapes (structural, always
   on) and long-history appends stay within 1.5x of short-history
   appends (in-process and against the recorded bench JSON).

4. Baseline regression gate: when PERF_CURRENT / PERF_BASELINE point at
   bench JSON files (the smoke script runs the small bench and wires the
   output next to the BENCH_r*.json trajectory), every common suite's
   `transfer_included_rate` must stay within PERF_TOLERANCE (default
   0.5x) of the recorded baseline, and `crc_parity_wire32` must hold.
   Without the env vars the gates skip — rate asserts on shared CI boxes
   are noise, the smoke script is the place that pins hardware.
"""
import json
import os

import numpy as np
import pytest

from cadence_tpu.gen.corpus import generate_corpus
from cadence_tpu.ops.encode import encode_corpus

pytestmark = pytest.mark.perf


def _load_bench(env: str):
    path = os.environ.get(env, "")
    if not path or not os.path.exists(path):
        pytest.skip(f"{env} not set (run via deploy/smoke_perf.sh)")
    with open(path) as f:
        doc = json.load(f)
    if "detail" not in doc and "parsed" in doc:
        # driver-recorded BENCH_r0N.json wrapper: the bench JSON rides
        # in `parsed` (None when only an output tail was captured —
        # nothing to gate against, so skip rather than KeyError)
        if doc["parsed"] is None:
            pytest.skip(f"{env}: recorded baseline carries no parsed "
                        f"bench JSON (tail-only capture)")
        doc = doc["parsed"]
    return doc


class TestPipelinedParity:
    def test_chunk_parallel_pack_wirec_byte_identical(self):
        from cadence_tpu.ops.wirec import pack_wirec

        hists = generate_corpus("timer_retry", num_workflows=640, seed=23,
                                target_events=24)
        ev = encode_corpus(hists)
        serial = pack_wirec(ev)
        threaded = pack_wirec(ev, num_threads=4)
        assert serial.profile == threaded.profile
        assert (serial.slab == threaded.slab).all()
        assert (serial.bases == threaded.bases).all()
        assert (serial.n_events == threaded.n_events).all()

    def test_pipelined_transfer_crc_equals_oneshot(self):
        """bench's transfer-included measurement path: chunked executor
        streaming == single sharded launch, CRC for CRC."""
        import jax

        import bench
        from cadence_tpu.core.checksum import DEFAULT_LAYOUT
        from cadence_tpu.ops.wirec import pack_wirec
        from cadence_tpu.parallel.mesh import (
            make_mesh,
            replay_wirec_sharded_crc,
        )

        hists = generate_corpus("basic", num_workflows=64, seed=29,
                                target_events=24)
        corpus = pack_wirec(encode_corpus(hists))
        mesh = make_mesh()
        n_devices = jax.device_count()
        n_chunks = next(nc for nc in (4, 2, 1)
                        if 64 % nc == 0 and (64 // nc) % n_devices == 0)
        run = bench._pipelined_transfer(corpus, mesh, DEFAULT_LAYOUT,
                                        n_chunks, depth=3)
        crcs_p, errs_p = run()
        crc_1, err_1, _ = replay_wirec_sharded_crc(corpus, mesh,
                                                   DEFAULT_LAYOUT)
        assert (crcs_p == np.asarray(crc_1).astype(np.uint32)).all()
        assert (errs_p == np.asarray(err_1)).all()
        assert (int(np.bitwise_xor.reduce(crcs_p))
                == int(np.bitwise_xor.reduce(
                    np.asarray(crc_1).astype(np.uint32))))


class TestFallbackGate:
    def test_forced_fallback_ladder_parity_and_warm_compiles(self):
        """The fallback suite at CI scale: ≥2.5% of workflows forced past
        the device tables, the escalation ladder resolving ALL of them on
        device, CRC-identical to the oracle-only arbitration, and warm
        trials paying zero ladder recompiles."""
        import bench
        from cadence_tpu.core.checksum import DEFAULT_LAYOUT

        res = bench._fallback_suite(512, DEFAULT_LAYOUT)
        assert res["oracle_fallback_rate"] >= 0.025
        assert res["fallback_workflows"] >= 4
        assert res["crc_parity_oracle_only"], \
            "ladder arbitration diverged from oracle-only arbitration"
        assert res["crc_xor"] == res["crc_xor_oracle_only"]
        assert res["residual_oracle_rows"] == 0
        assert res["ladder_recompiles_warm"] == 0, \
            "warm fallback trials recompiled a ladder rung"
        assert sum(r["rows"] for r in res["ladder_rungs"]) \
            >= res["fallback_workflows"]

    def test_fallback_mixed_rate_vs_baseline(self):
        """The cliff gate: the recorded fallback mixed rate must stay
        within tolerance of the baseline's — BENCH_r05's 3x collapse
        (1.22M vs 3.9M device-only) fails here once a ladder-era
        baseline is recorded."""
        cur = _load_bench("PERF_CURRENT")["detail"].get(
            "fallback_under_pressure")
        base = _load_bench("PERF_BASELINE")["detail"].get(
            "fallback_under_pressure")
        assert cur, "current bench carries no fallback_under_pressure"
        tol = float(os.environ.get("PERF_TOLERANCE", "0.5"))
        assert cur["oracle_fallback_rate"] >= 0.02, \
            "fallback suite stopped forcing pressure"
        if "crc_parity_oracle_only" in cur:
            assert cur["crc_parity_oracle_only"]
            assert cur["ladder_recompiles_warm"] == 0
        if base:
            floor = tol * base["mixed_rate_median"]
            assert cur["mixed_rate_median"] >= floor, (
                f"fallback mixed_rate_median {cur['mixed_rate_median']} "
                f"regressed below {tol:.0%} of baseline "
                f"{base['mixed_rate_median']} — the overflow cliff is "
                f"back")


class TestIncrementalGate:
    """The O(new events) gate (ISSUE 6): an append transaction's replay
    cost must scale with the APPENDED events, not the total history
    length. Structural half always runs (launched suffix shapes are
    deterministic); the timing half compares long-history vs
    short-history appends at equal suffix size within 1.5x."""

    def test_append_cost_o_new_events(self):
        import bench
        from cadence_tpu.core.checksum import DEFAULT_LAYOUT

        res = bench._incremental_suite(DEFAULT_LAYOUT, workflows=48,
                                       short_events=24, long_events=160,
                                       txns=12)
        # structural: equal suffixes launch IDENTICAL corpus shapes no
        # matter the underlying history length — the device work cannot
        # depend on history size
        assert res["shapes_equal"], (res["short"]["chunk_shape"],
                                     res["long"]["chunk_shape"])
        assert res["short"]["chunk_shape"][1] <= 16
        # history lengths genuinely differ; suffix sizes don't
        assert res["long"]["history_events_mean"] \
            >= 4 * res["short"]["history_events_mean"]
        # timing: long-history appends within 1.5x of short-history
        # appends (+10ms absolute slack for shared-box scheduling noise;
        # the launched work is identical, so this is generous)
        p50_s = res["short"]["append_p50_ms"]
        p50_l = res["long"]["append_p50_ms"]
        assert p50_l <= max(1.5 * p50_s, p50_s + 10.0), (
            f"long-history append p50 {p50_l}ms vs short {p50_s}ms — "
            f"append cost is scaling with history length")

    def test_incremental_recorded_in_bench_json(self):
        """smoke_perf.sh's recorded run must carry the incremental suite
        and hold the same ratio gate (hardware-pinned CI)."""
        cur = _load_bench("PERF_CURRENT")["detail"].get("incremental")
        assert cur, "current bench carries no incremental suite"
        assert cur["shapes_equal"]
        p50_s = cur["short"]["append_p50_ms"]
        p50_l = cur["long"]["append_p50_ms"]
        assert p50_l <= max(1.5 * p50_s, p50_s + 10.0), (
            f"recorded long-history append p50 {p50_l}ms regressed past "
            f"1.5x of short {p50_s}ms")


class TestSnapshotGate:
    """The warm-restart gate (ISSUE 11): restarting with persisted
    mutable-state snapshots must hydrate + replay only the
    since-snapshot suffixes — warm rebuild time <= 0.3x cold full-replay
    time on a long-history corpus, with zero oracle<->device divergence
    and every workflow genuinely hydrated from its snapshot."""

    def test_warm_restart_within_budget_in_process(self):
        import bench
        from cadence_tpu.core.checksum import DEFAULT_LAYOUT

        res = bench._snapshot_suite(DEFAULT_LAYOUT, workflows=64,
                                    target_events=384, trials=3)
        assert res["divergent"] == 0
        assert res["hydrated"] == res["workflows"], res
        assert res["snapshot_records"] == res["workflows"]
        # the suffix is a fraction of the history: replayed events on
        # the warm path must be far below the corpus total
        assert res["suffix_events_replayed"] \
            <= res["workflows"] * res["history_events_mean"] * 0.5
        # warm <= 0.3x cold (+25ms absolute slack for shared-box noise;
        # the replayed work differs by an order of magnitude)
        assert res["warm_restart_s"] \
            <= 0.3 * res["cold_restart_s"] + 0.025, (
                f"warm restart {res['warm_restart_s']}s vs cold "
                f"{res['cold_restart_s']}s — the snapshot tier is not "
                f"buying the suffix-only restart")

    def test_snapshot_recorded_in_bench_json(self):
        """smoke_perf.sh's recorded run must carry the snapshot suite
        and hold the same contract (hardware-pinned CI)."""
        cur = _load_bench("PERF_CURRENT")["detail"].get("snapshot")
        assert cur, "current bench carries no snapshot suite"
        assert cur["divergent"] == 0
        assert cur["hydrated"] == cur["workflows"]
        assert cur["warm_restart_s"] \
            <= 0.3 * cur["cold_restart_s"] + 0.025, (
                f"recorded warm restart {cur['warm_restart_s']}s "
                f"regressed past 0.3x of cold {cur['cold_restart_s']}s")


class TestMeshGate:
    """The mesh-aware serving executor gate (ISSUE 7): mesh-of-1 must be
    byte-identical to the unsharded kernel (the pre-mesh single-chip
    executor's results), mesh shapes already seen must recompile nothing
    on a warm pass, and the recorded bench's mesh_serving section must
    hold its rate vs the baseline (and ≥ 0.7 per-device efficiency on a
    real multi-device mesh — virtual CPU meshes share physical cores and
    report overhead, so only checksum identity is gated there)."""

    def _events(self, n=48, seed=31):
        return encode_corpus(generate_corpus(
            "basic", num_workflows=n, seed=seed, target_events=24))

    def test_mesh_of_1_byte_parity_with_unsharded_kernel(self):
        import jax
        import jax.numpy as jnp

        from cadence_tpu.engine.executor import replay_corpus_mesh
        from cadence_tpu.ops.replay import replay_to_payload
        from cadence_tpu.parallel.mesh import make_mesh

        ev = self._events()
        rows_ref, err_ref = replay_to_payload(jnp.asarray(ev))
        rows_ref, err_ref = np.asarray(rows_ref), np.asarray(err_ref)
        rows, errors, _branch, _rep = replay_corpus_mesh(
            ev, make_mesh(jax.devices()[:1]), chunk_workflows=16)
        assert (rows == rows_ref).all()
        assert (errors == err_ref).all()

    def test_warm_pass_zero_recompiles_across_seen_mesh_shapes(self):
        import jax

        from cadence_tpu.engine.executor import replay_corpus_mesh
        from cadence_tpu.parallel.mesh import make_mesh
        from cadence_tpu.utils import metrics as cm

        ev = self._events()
        devices = jax.devices()
        meshes = [make_mesh(devices[:1])]
        if len(devices) >= 2:
            meshes.append(make_mesh(devices[:2]))
        for mesh in meshes:  # first pass: compiles allowed
            replay_corpus_mesh(ev, mesh, chunk_workflows=16)
        reg = cm.DEFAULT_REGISTRY
        misses0 = reg.counter(cm.SCOPE_TPU_EXECUTOR,
                              cm.M_LADDER_CACHE_MISSES)
        for mesh in meshes:  # warm pass: every variant must hit
            replay_corpus_mesh(ev, mesh, chunk_workflows=16)
        assert reg.counter(cm.SCOPE_TPU_EXECUTOR,
                           cm.M_LADDER_CACHE_MISSES) == misses0, \
            "a warm serving pass recompiled a mesh shape already seen"
        assert reg.counter(cm.SCOPE_TPU_EXECUTOR,
                           cm.M_LADDER_CACHE_HITS) >= len(meshes)

    def test_mesh_serving_rate_vs_baseline(self):
        """Recorded gate: the serving executor's mesh-of-1 rate must
        stay within PERF_TOLERANCE of the recorded baseline's — the
        mesh layer is a scaling axis, not a single-chip regression."""
        cur = _load_bench("PERF_CURRENT")["detail"].get("mesh_serving")
        assert cur, "current bench carries no mesh_serving section"
        assert cur["checksum_identity"], \
            "mesh-of-N checksums diverged from mesh-of-1"
        base = _load_bench("PERF_BASELINE").get("detail",
                                                {}).get("mesh_serving")
        if not base:
            pytest.skip("baseline predates the mesh_serving section")
        tol = float(os.environ.get("PERF_TOLERANCE", "0.5"))
        floor = tol * base["rate_n1"]
        assert cur["rate_n1"] >= floor, (
            f"mesh-of-1 serving rate {cur['rate_n1']} regressed below "
            f"{tol:.0%} of baseline {base['rate_n1']}")

    def test_per_device_efficiency_on_real_mesh(self):
        """≥ 0.7 per-device efficiency at the diagnostic's device count
        — on real accelerators only: a virtual CPU mesh time-shares
        physical cores, so its efficiency measures overhead and only
        the checksum-identity half of the contract applies."""
        cur = _load_bench("PERF_CURRENT")["detail"].get("mesh_serving")
        assert cur, "current bench carries no mesh_serving section"
        if cur["devices"] <= 1:
            pytest.skip("single-device bench run")
        assert cur["checksum_identity"]
        if cur.get("virtual_mesh"):
            pytest.skip("virtual CPU mesh: efficiency reports overhead, "
                        "not speedup (dryrun_multichip docstring)")
        assert cur["per_device_efficiency"] >= 0.7, (
            f"per-device efficiency {cur['per_device_efficiency']} "
            f"below 0.7 at {cur['devices']} devices")


class TestFeederGate:
    """The host-ingest gate (ISSUE 9): the native-wirec feeder closed
    the 6x pack/replay gap, so the feeder's sustained rate must stay
    within FEEDER_GATE_RATIO (default 0.5 — i.e. within 2x) of the
    recorded device transfer-included rate on the same corpus family,
    the suffix-append leg must cost by APPENDED events, and a warm
    homogeneous stream must recompile nothing (pinned profile ⇒ one
    executable; checked against the jit cache itself)."""

    def test_streaming_zero_warm_recompiles(self):
        """Two passes of the same homogeneous stream: zero refits on
        both, identical CRCs, and the decode/replay jit cache must not
        grow on the second — the pinned profile is provably one
        executable, not one per chunk."""
        from cadence_tpu.gen.corpus import generate_corpus
        from cadence_tpu.native import packing
        from cadence_tpu.native.feeder import feed_corpus_wirec
        from cadence_tpu.ops.replay import replay_wirec_to_crc

        if not packing.native_available():
            pytest.skip("no C++ toolchain")
        hists = generate_corpus("basic", num_workflows=96, seed=41,
                                target_events=30)
        crc1, err1, rep1 = feed_corpus_wirec(hists, chunk_workflows=32)
        assert rep1.profile_refits == 0, \
            "a homogeneous stream refit its pinned profile"
        assert (err1 == 0).all()
        size0 = replay_wirec_to_crc._cache_size()
        crc2, _err2, rep2 = feed_corpus_wirec(hists, chunk_workflows=32)
        assert rep2.profile_refits == 0
        assert replay_wirec_to_crc._cache_size() == size0, \
            "a warm streaming pass compiled a new wirec executable"
        assert (crc1 == crc2).all()

    def test_feeder_within_2x_of_device_rate(self):
        """Recorded gate: sustained feeder events/s vs the same bench
        run's device transfer-included rate on the matching corpus
        family — the 6x gap (BENCH_r05: 622k feed vs 3.9M replay) must
        not creep back."""
        cur = _load_bench("PERF_CURRENT")["detail"]
        feeder = cur.get("feeder")
        if not feeder:
            pytest.skip("bench recorded no feeder section "
                        "(no native toolchain on the recording box)")
        assert feeder["error_workflows"] == 0
        device = cur["suites"].get("basic", {}).get(
            "transfer_included_rate")
        assert device, "no basic-suite transfer rate to gate against"
        ratio = float(os.environ.get("FEEDER_GATE_RATIO", "0.5"))
        sustained = feeder["sustained_events_per_sec"]
        assert sustained >= ratio * device, (
            f"feeder sustained {sustained} events/s fell below "
            f"{ratio:.0%} of the device transfer-included rate {device} "
            f"— host packing is the bottleneck again")

    def test_feeder_sustained_vs_baseline(self):
        """Recorded regression gate: the feeder rate itself must hold
        within PERF_TOLERANCE of the recorded baseline's (baselines
        predating the feeder section skip)."""
        cur = _load_bench("PERF_CURRENT")["detail"].get("feeder")
        if not cur:
            pytest.skip("bench recorded no feeder section "
                        "(no native toolchain on the recording box)")
        base = _load_bench("PERF_BASELINE").get("detail", {}).get("feeder")
        if not base:
            pytest.skip("baseline predates the feeder section")
        tol = float(os.environ.get("PERF_TOLERANCE", "0.5"))
        floor = tol * base["sustained_events_per_sec"]
        assert cur["sustained_events_per_sec"] >= floor, (
            f"feeder sustained {cur['sustained_events_per_sec']} "
            f"regressed below {tol:.0%} of baseline "
            f"{base['sustained_events_per_sec']}")

    def test_suffix_append_recorded_o_new_events(self):
        """Recorded gate: the suffix-append feeder leg resolved every
        append and its wall time is set by APPENDED events — the
        history-equivalent rate (what an O(history) path would have had
        to sustain in the same wall time) dwarfs the appended rate,
        which is exactly the residency claim."""
        feeder = _load_bench("PERF_CURRENT")["detail"].get("feeder")
        if not feeder:
            pytest.skip("bench recorded no feeder section "
                        "(no native toolchain on the recording box)")
        sa = feeder.get("suffix_append")
        if not sa:
            pytest.skip("recorded feeder section predates suffix_append")
        assert sa["ok"] == sa["workflows"], sa
        assert sa["appended_events_per_sec"] > 0
        assert sa["history_events_per_sec"] \
            >= 4 * sa["appended_events_per_sec"], (
                "suffix appends are paying near full-history cost — "
                "the O(new events) path broke")


class TestServingGate:
    """The device-serving transaction tier gate (ISSUE 10): concurrent
    committed transactions must genuinely micro-batch — at concurrency
    >= 8 the scheduler coalesces multiple transactions per device
    launch (factor > 1.5 at saturation), batched p99 stays at or below
    the unbatched (one-launch-per-transaction) baseline, warm flushes
    recompile nothing, and every transaction's device payload checksum
    matches the oracle (parity divergence == 0)."""

    def test_serving_micro_batching_in_process(self):
        import bench
        from cadence_tpu.core.checksum import DEFAULT_LAYOUT

        res = bench._serving_suite(DEFAULT_LAYOUT, workflows=32,
                                   levels=(1, 8))
        top = next(lv for lv in res["levels"] if lv["concurrency"] == 8)
        assert top["coalescing_factor"] > 1.5, res["levels"]
        assert res["parity_divergence"] == 0
        assert res["warm_recompiles"] == 0, \
            "a warm serving flush compiled a new from-state executable"
        assert res["batched_p99_ms"] <= res["unbatched_p99_ms"], (
            f"micro-batched p99 {res['batched_p99_ms']}ms worse than "
            f"one-launch-per-transaction {res['unbatched_p99_ms']}ms — "
            f"the batching window is costing more than it amortizes")

    def test_serving_recorded_in_bench_json(self):
        """smoke_perf.sh's recorded run must carry the serving suite and
        hold the same contract (hardware-pinned CI)."""
        cur = _load_bench("PERF_CURRENT")["detail"].get("serving")
        assert cur, "current bench carries no serving suite"
        assert cur["parity_divergence"] == 0
        assert cur["warm_recompiles"] == 0
        assert cur["coalescing_factor_at_top"] > 1.5
        assert cur["batched_p99_ms"] <= cur["unbatched_p99_ms"], (
            f"recorded batched p99 {cur['batched_p99_ms']}ms regressed "
            f"past unbatched {cur['unbatched_p99_ms']}ms")


class TestVisibilityGate:
    """The device-visibility gate (ISSUE 12): every device-served
    List/Scan/Count must answer with exactly the host store's result
    ids (divergence counter pinned at 0 — parity always, on every
    platform), warm repeats of a seen query shape must recompile
    NOTHING, and the recorded bench's visibility section must hold the
    same contract. The rows/s rate gate engages only on recorded
    real-device runs — on the shared CPU CI box the device and host
    paths time-share the same cores, so only parity + recompiles gate
    there."""

    def test_device_parity_and_zero_warm_recompiles(self, monkeypatch):
        import random

        from cadence_tpu.engine.persistence import (
            VisibilityRecord,
            VisibilityStore,
        )
        from cadence_tpu.utils import metrics as cm

        monkeypatch.setenv("CADENCE_TPU_VISIBILITY", "1")
        monkeypatch.setenv("CADENCE_TPU_VISIBILITY_PARITY", "1")
        rng = random.Random(77)
        store = VisibilityStore()
        for i in range(400):
            store.record_started(VisibilityRecord(
                "d", f"wf-{i}", f"r-{i}", f"t-{i % 4}",
                start_time=1000 + i,
                search_attrs={"P": rng.randrange(8)}))
            if rng.random() < 0.5:
                store.record_closed("d", f"wf-{i}", f"r-{i}",
                                    close_time=2000 + i,
                                    close_status=rng.randrange(3))
        queries = ["", "CloseStatus = -1", "WorkflowType = 't-2'",
                   "P >= 5 AND CloseStatus = 0",
                   "StartTime > 1200 OR P < 2"]
        reg = cm.DEFAULT_REGISTRY
        for q in queries:  # cold pass compiles each shape once
            store.count("d", q)
            store.query("d", q)
        pre_miss = reg.counter(cm.SCOPE_TPU_VISIBILITY,
                               cm.M_LADDER_CACHE_MISSES)
        for _ in range(3):  # warm repeats: zero recompiles
            for q in queries:
                store.count("d", q)
                store.query("d", q)
        assert reg.counter(cm.SCOPE_TPU_VISIBILITY,
                           cm.M_LADDER_CACHE_MISSES) == pre_miss, \
            "warm visibility queries recompiled kernel variants"
        assert reg.counter(cm.SCOPE_TPU_VISIBILITY,
                           cm.M_VIS_DIVERGENCE) == 0
        assert reg.counter(cm.SCOPE_TPU_VISIBILITY,
                           cm.M_VIS_PARITY_CHECKS) >= 4 * len(queries)
        store._device.stop()

    def test_visibility_recorded_in_bench_json(self):
        """smoke_perf.sh's recorded run must carry the visibility suite
        with parity intact, zero warm recompiles, and — on a real
        device — the columnar scan beating the host store."""
        import jax

        cur = _load_bench("PERF_CURRENT")["detail"].get("visibility")
        assert cur, "current bench carries no visibility suite"
        assert cur["parity"], "recorded visibility parity broke"
        assert cur["warm_recompiles"] == 0, (
            "recorded visibility run recompiled on warm repeats")
        for row in cur["sizes"]:
            assert row["parity_divergence"] == 0, row
        if jax.devices()[0].platform != "cpu":
            worst = min(row["speedup"] for row in cur["sizes"])
            assert worst >= 1.0, (
                f"device scan slower than the host store on a real "
                f"device (worst speedup {worst})")


class TestBaselineGate:
    def _load(self, env):
        return _load_bench(env)

    def test_transfer_rate_within_tolerance_of_baseline(self):
        current = self._load("PERF_CURRENT")
        baseline = self._load("PERF_BASELINE")
        tol = float(os.environ.get("PERF_TOLERANCE", "0.5"))
        cur_suites = current["detail"]["suites"]
        base_suites = baseline["detail"]["suites"]
        checked = 0
        for suite, cur in cur_suites.items():
            assert cur["crc_parity_wire32"], f"{suite}: wire32 CRC parity broken"
            assert cur.get("crc_parity_pipelined", True), \
                f"{suite}: pipelined CRC parity broken"
            base = base_suites.get(suite)
            if base is None:
                continue
            if cur["workflows"] == base["workflows"]:
                # same corpus config ⇒ the checksum must not have moved
                assert cur["crc_xor"] == base["crc_xor"], \
                    f"{suite}: crc_xor drifted from baseline"
            floor = tol * base["transfer_included_rate"]
            assert cur["transfer_included_rate"] >= floor, (
                f"{suite}: transfer_included_rate "
                f"{cur['transfer_included_rate']} regressed below "
                f"{tol:.0%} of baseline {base['transfer_included_rate']}")
            checked += 1
        assert checked, "no common suites between current and baseline"
