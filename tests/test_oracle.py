"""Oracle replayer unit tests: hand-built fixtures checking the semantics
mirrored from the reference (state_builder_test.go / mutable_state_builder_test.go
scenarios, rebuilt by hand — not ported)."""
import pytest

from cadence_tpu.core.checksum import Checksum, payload_row, verify
from cadence_tpu.core.enums import (
    EMPTY_EVENT_ID,
    EventType,
    CloseStatus,
    TimeoutType,
    TimerTaskType,
    TransferTaskType,
    WorkflowState,
)
from cadence_tpu.core.events import HistoryBatch, HistoryEvent, RetryPolicy
from cadence_tpu.gen.corpus import SUITES, HistoryWriter, generate_history
from cadence_tpu.oracle.mutable_state import ReplayError
from cadence_tpu.oracle.state_builder import StateBuilder


def make_batch(events, wf="wf-1", run="run-1", new_run_events=None):
    return HistoryBatch(
        domain_id="dom-1", workflow_id=wf, run_id=run, events=events,
        new_run_events=new_run_events,
    )


def ev(eid, etype, ts=1_000_000_000, version=0, task_id=0, **attrs):
    return HistoryEvent(id=eid, event_type=etype, version=version,
                        timestamp=ts, task_id=task_id, attrs=attrs)


class TestStartAndDecision:
    def test_started_initializes_execution_info(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=60,
               task_start_to_close_timeout_seconds=10),
        ]))
        info = sb.ms.execution_info
        assert info.state == WorkflowState.Created
        assert info.close_status == CloseStatus.Nothing
        assert info.workflow_id == "wf-1"
        assert info.run_id == "run-1"
        assert info.workflow_timeout == 60
        assert info.decision_start_to_close_timeout == 10
        assert info.last_processed_event == EMPTY_EVENT_ID
        assert info.last_first_event_id == 1
        assert info.next_event_id == 2
        assert info.decision_schedule_id == EMPTY_EVENT_ID
        # start tasks: RecordWorkflowStarted transfer + WorkflowTimeout timer
        kinds = [(t.kind, t.task_type) for t in sb.ms.transfer_tasks + sb.ms.timer_tasks]
        assert ("transfer", TransferTaskType.RecordWorkflowStarted) in kinds
        assert ("timer", TimerTaskType.WorkflowTimeout) in kinds

    def test_decision_cycle(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=60,
               task_start_to_close_timeout_seconds=10),
            ev(2, EventType.DecisionTaskScheduled, task_list="tl",
               start_to_close_timeout_seconds=10, attempt=0),
        ]))
        info = sb.ms.execution_info
        assert info.state == WorkflowState.Running  # scheduled sets Running
        assert info.decision_schedule_id == 2
        assert info.decision_started_id == EMPTY_EVENT_ID

        sb.apply_batch(make_batch([
            ev(3, EventType.DecisionTaskStarted, scheduled_event_id=2,
               request_id="r1"),
        ]))
        assert info.decision_started_id == 3

        sb.apply_batch(make_batch([
            ev(4, EventType.DecisionTaskCompleted, scheduled_event_id=2,
               started_event_id=3),
        ]))
        assert info.decision_schedule_id == EMPTY_EVENT_ID
        assert info.decision_started_id == EMPTY_EVENT_ID
        assert info.decision_attempt == 0
        assert info.last_processed_event == 3
        assert info.next_event_id == 5
        # decision transfer task was generated on schedule
        dts = [t for t in sb.ms.transfer_tasks
               if t.task_type == TransferTaskType.DecisionTask]
        assert len(dts) == 1 and dts[0].event_id == 2
        # decision start-to-close timer generated on start
        timers = [t for t in sb.ms.timer_tasks
                  if t.task_type == TimerTaskType.DecisionTimeout]
        assert len(timers) == 1
        assert timers[0].timeout_type == TimeoutType.StartToClose

    def test_decision_failed_increments_attempt_and_transient_decision(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=60,
               task_start_to_close_timeout_seconds=10),
            ev(2, EventType.DecisionTaskScheduled, task_list="tl",
               start_to_close_timeout_seconds=10, attempt=0),
        ]))
        sb.apply_batch(make_batch([
            ev(3, EventType.DecisionTaskStarted, scheduled_event_id=2,
               request_id="r1"),
        ]))
        sb.apply_batch(make_batch([
            ev(4, EventType.DecisionTaskFailed, scheduled_event_id=2,
               started_event_id=3),
        ]))
        info = sb.ms.execution_info
        # FailDecision(increment=True) then ReplicateTransientDecisionTaskScheduled:
        # attempt was 0 before fail -> 1; transient decision created with
        # schedule ID == next event ID from previous batch end (4)
        assert info.decision_attempt == 1
        assert info.decision_schedule_id == 4
        assert info.decision_started_id == EMPTY_EVENT_ID

    def test_decision_timed_out_schedule_to_start(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=60,
               task_start_to_close_timeout_seconds=10),
            ev(2, EventType.DecisionTaskScheduled, task_list="tl",
               start_to_close_timeout_seconds=10, attempt=0),
        ]))
        sb.apply_batch(make_batch([
            ev(3, EventType.DecisionTaskTimedOut, scheduled_event_id=2,
               timeout_type=int(TimeoutType.ScheduleToStart)),
        ]))
        # a schedule-to-start timeout NEVER increments the attempt
        # (mutable_state_decision_task_manager.go:256-271: the sticky
        # dispatch deadline re-dispatches on the normal task list via an
        # explicit scheduled event, not a transient) — decision state
        # clears fully, attempt stays 0, no transient is created
        info = sb.ms.execution_info
        assert info.decision_attempt == 0
        assert info.decision_schedule_id == EMPTY_EVENT_ID


class TestActivitiesTimers:
    def _started_wf(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=600,
               task_start_to_close_timeout_seconds=10),
            ev(2, EventType.DecisionTaskScheduled, task_list="tl",
               start_to_close_timeout_seconds=10, attempt=0),
        ]))
        sb.apply_batch(make_batch([
            ev(3, EventType.DecisionTaskStarted, scheduled_event_id=2, request_id="r"),
        ]))
        return sb

    def test_activity_lifecycle(self):
        sb = self._started_wf()
        sb.apply_batch(make_batch([
            ev(4, EventType.DecisionTaskCompleted, scheduled_event_id=2,
               started_event_id=3),
            ev(5, EventType.ActivityTaskScheduled, activity_id="a1",
               task_list="tl", schedule_to_start_timeout_seconds=10,
               schedule_to_close_timeout_seconds=20,
               start_to_close_timeout_seconds=15, heartbeat_timeout_seconds=0),
        ]))
        assert 5 in sb.ms.pending_activity_info_ids
        ai = sb.ms.pending_activity_info_ids[5]
        assert ai.started_id == EMPTY_EVENT_ID
        assert ai.scheduled_event_batch_id == 4
        # ActivityTask transfer generated
        assert any(t.task_type == TransferTaskType.ActivityTask and t.event_id == 5
                   for t in sb.ms.transfer_tasks)
        # activity timer generated at end of batch: schedule-to-start is nearest
        at = [t for t in sb.ms.timer_tasks
              if t.task_type == TimerTaskType.ActivityTimeout]
        assert len(at) == 1 and at[0].timeout_type == TimeoutType.ScheduleToStart

        sb.apply_batch(make_batch([
            ev(6, EventType.ActivityTaskStarted, scheduled_event_id=5,
               request_id="ar", ts=2_000_000_000),
        ]))
        assert sb.ms.pending_activity_info_ids[5].started_id == 6

        sb.apply_batch(make_batch([
            ev(7, EventType.ActivityTaskCompleted, scheduled_event_id=5,
               started_event_id=6),
        ]))
        assert 5 not in sb.ms.pending_activity_info_ids
        assert "a1" not in sb.ms.pending_activity_id_to_event_id

    def test_activity_cancel_requested_unknown_id_tolerated(self):
        sb = self._started_wf()
        sb.apply_batch(make_batch([
            ev(4, EventType.ActivityTaskCancelRequested, activity_id="nope"),
        ]))  # must not raise (mutable_state_builder.go:2451-2454)

    def test_activity_complete_missing_raises(self):
        sb = self._started_wf()
        with pytest.raises(ReplayError):
            sb.apply_batch(make_batch([
                ev(4, EventType.ActivityTaskCompleted, scheduled_event_id=99,
                   started_event_id=98),
            ]))

    def test_timer_lifecycle(self):
        sb = self._started_wf()
        sb.apply_batch(make_batch([
            ev(4, EventType.DecisionTaskCompleted, scheduled_event_id=2,
               started_event_id=3),
            ev(5, EventType.TimerStarted, timer_id="t1",
               start_to_fire_timeout_seconds=30),
        ]))
        assert "t1" in sb.ms.pending_timer_info_ids
        ti = sb.ms.pending_timer_info_ids["t1"]
        assert ti.started_id == 5
        # user timer task generated at batch end
        ut = [t for t in sb.ms.timer_tasks if t.task_type == TimerTaskType.UserTimer]
        assert len(ut) == 1 and ut[0].event_id == 5
        assert ut[0].visibility_timestamp == ti.expiry_time

        sb.apply_batch(make_batch([
            ev(6, EventType.TimerFired, timer_id="t1", started_event_id=5),
        ]))
        assert "t1" not in sb.ms.pending_timer_info_ids
        assert 5 not in sb.ms.pending_timer_event_id_to_id


class TestCloseAndSignals:
    def _running(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=600,
               task_start_to_close_timeout_seconds=10),
            ev(2, EventType.DecisionTaskScheduled, task_list="tl",
               start_to_close_timeout_seconds=10, attempt=0),
        ]))
        sb.apply_batch(make_batch([
            ev(3, EventType.DecisionTaskStarted, scheduled_event_id=2, request_id="r"),
        ]))
        return sb

    def test_signal_increments_count(self):
        sb = self._running()
        sb.apply_batch(make_batch([
            ev(4, EventType.WorkflowExecutionSignaled, signal_name="s"),
            ev(5, EventType.WorkflowExecutionSignaled, signal_name="s"),
        ]))
        assert sb.ms.execution_info.signal_count == 2

    def test_cancel_requested_flag(self):
        sb = self._running()
        sb.apply_batch(make_batch([
            ev(4, EventType.WorkflowExecutionCancelRequested, cause="x"),
        ]))
        assert sb.ms.execution_info.cancel_requested is True

    def test_complete_workflow(self):
        sb = self._running()
        sb.apply_batch(make_batch([
            ev(4, EventType.DecisionTaskCompleted, scheduled_event_id=2,
               started_event_id=3),
            ev(5, EventType.WorkflowExecutionCompleted,
               decision_task_completed_event_id=4),
        ]))
        info = sb.ms.execution_info
        assert info.state == WorkflowState.Completed
        assert info.close_status == CloseStatus.Completed
        assert info.completion_event_batch_id == 4
        assert any(t.task_type == TransferTaskType.CloseExecution
                   for t in sb.ms.transfer_tasks)
        assert any(t.task_type == TimerTaskType.DeleteHistoryEvent
                   for t in sb.ms.timer_tasks)

    def test_invalid_close_from_created_raises(self):
        sb = StateBuilder()
        with pytest.raises(ReplayError):
            sb.apply_batch(make_batch([
                ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
                   workflow_type="wt", execution_start_to_close_timeout_seconds=600,
                   task_start_to_close_timeout_seconds=10),
                # Completed-with-Completed-status is invalid from Created
                # (workflowExecutionInfo.go:65-70 allows only terminated/
                # timedout/continuedasnew from Created)
                ev(2, EventType.WorkflowExecutionCompleted,
                   decision_task_completed_event_id=1),
            ]))

    def test_continue_as_new(self):
        sb = self._running()
        new_run = [
            ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=600,
               task_start_to_close_timeout_seconds=10, ts=9_000_000_000),
            ev(2, EventType.DecisionTaskScheduled, task_list="tl",
               start_to_close_timeout_seconds=10, attempt=0, ts=9_000_000_100),
        ]
        sb.apply_batch(make_batch([
            ev(4, EventType.DecisionTaskCompleted, scheduled_event_id=2,
               started_event_id=3),
            ev(5, EventType.WorkflowExecutionContinuedAsNew,
               new_execution_run_id="run-2",
               decision_task_completed_event_id=4),
        ], new_run_events=new_run))
        assert sb.ms.execution_info.close_status == CloseStatus.ContinuedAsNew
        assert sb.new_run_state is not None
        assert sb.new_run_state.execution_info.run_id == "run-2"
        assert sb.new_run_state.execution_info.decision_schedule_id == 2


class TestVersionHistories:
    def test_version_bump_appends_item(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, version=1, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=600,
               task_start_to_close_timeout_seconds=10),
            ev(2, EventType.DecisionTaskScheduled, version=1, task_list="tl",
               start_to_close_timeout_seconds=10, attempt=0),
        ]))
        sb.apply_batch(make_batch([
            ev(3, EventType.DecisionTaskStarted, version=2, scheduled_event_id=2,
               request_id="r"),
        ]))
        items = sb.ms.version_histories.current().items
        assert [(i.event_id, i.version) for i in items] == [(2, 1), (3, 2)]
        assert sb.ms.current_version == 2

    def test_lower_version_rejected(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, version=5, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=600,
               task_start_to_close_timeout_seconds=10),
        ]))
        with pytest.raises(ReplayError):
            sb.apply_batch(make_batch([
                ev(2, EventType.DecisionTaskScheduled, version=4, task_list="tl",
                   start_to_close_timeout_seconds=10, attempt=0),
            ]))


class TestChecksum:
    def test_checksum_roundtrip(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=600,
               task_start_to_close_timeout_seconds=10),
            ev(2, EventType.DecisionTaskScheduled, task_list="tl",
               start_to_close_timeout_seconds=10, attempt=0),
        ]))
        csum = Checksum.of(sb.ms)
        verify(sb.ms, csum)  # no raise
        sb.ms.execution_info.signal_count += 1
        with pytest.raises(ValueError):
            verify(sb.ms, csum)

    def test_payload_row_sorted_ids(self):
        sb = StateBuilder()
        sb.apply_batch(make_batch([
            ev(1, EventType.WorkflowExecutionStarted, task_list="tl",
               workflow_type="wt", execution_start_to_close_timeout_seconds=600,
               task_start_to_close_timeout_seconds=10),
            ev(2, EventType.DecisionTaskScheduled, task_list="tl",
               start_to_close_timeout_seconds=10, attempt=0),
        ]))
        sb.apply_batch(make_batch([
            ev(3, EventType.DecisionTaskStarted, scheduled_event_id=2, request_id="r"),
        ]))
        sb.apply_batch(make_batch([
            ev(4, EventType.DecisionTaskCompleted, scheduled_event_id=2,
               started_event_id=3),
            ev(5, EventType.ActivityTaskScheduled, activity_id="a1", task_list="tl",
               schedule_to_start_timeout_seconds=5,
               schedule_to_close_timeout_seconds=10,
               start_to_close_timeout_seconds=5, heartbeat_timeout_seconds=0),
            ev(6, EventType.ActivityTaskScheduled, activity_id="a2", task_list="tl",
               schedule_to_start_timeout_seconds=5,
               schedule_to_close_timeout_seconds=10,
               start_to_close_timeout_seconds=5, heartbeat_timeout_seconds=0),
        ]))
        row = payload_row(sb.ms)
        # activity list block: count 2 then ids 5, 6
        # offsets: 11 scalars, 1+16 version history, 1+16 timers => activity
        # count at 11 + 17 + 17 = 45
        assert row[45] == 2
        assert row[46] == 5 and row[47] == 6


class TestCorpusReplay:
    """All generated corpora replay cleanly through the oracle."""

    @pytest.mark.parametrize("suite", SUITES)
    def test_suite_replays(self, suite):
        for i in range(8):
            batches = generate_history(suite, seed=7, workflow_index=i,
                                       target_events=100)
            sb = StateBuilder()
            sb.replay_history(batches)
            info = sb.ms.execution_info
            assert info.state == WorkflowState.Completed
            assert info.next_event_id == batches[-1].events[-1].id + 1
            Checksum.of(sb.ms)  # payload within layout capacities

    @pytest.mark.parametrize("suite", SUITES)
    def test_determinism(self, suite):
        a = generate_history(suite, seed=3, workflow_index=2)
        b = generate_history(suite, seed=3, workflow_index=2)
        ra = StateBuilder().replay_history(a)
        rb = StateBuilder().replay_history(b)
        assert (payload_row(ra) == payload_row(rb)).all()
