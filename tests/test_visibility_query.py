"""Advanced visibility: query-filtered List/Scan/Count over search
attributes (VERDICT r3 ask #4; workflowHandler.go:2837-3322, ES query
surface reframed as an evaluated predicate).
"""
import pytest

from cadence_tpu.core.enums import CloseStatus, DecisionType
from cadence_tpu.engine.history_engine import Decision
from cadence_tpu.engine.onebox import Onebox
from cadence_tpu.engine.visibility_query import QueryParseError, compile_query
from cadence_tpu.engine.persistence import VisibilityRecord
from cadence_tpu.models.deciders import EchoDecider
from tests.taskpoller import TaskPoller

DOMAIN = "vq-domain"
TL = "vq-tl"


def rec(**kw):
    base = dict(domain_id="d", workflow_id="w", run_id="r",
                workflow_type="t", start_time=100)
    base.update(kw)
    return VisibilityRecord(**base)


class TestQueryLanguage:
    def test_builtin_fields_and_ops(self):
        p = compile_query("WorkflowType = 'order' AND StartTime >= 100")
        assert p(rec(workflow_type="order", start_time=100))
        assert not p(rec(workflow_type="order", start_time=99))
        assert not p(rec(workflow_type="other", start_time=200))

    def test_or_and_parens(self):
        p = compile_query(
            "(WorkflowID = 'a' OR WorkflowID = 'b') AND CloseStatus = 0")
        assert p(rec(workflow_id="a", close_status=0))
        assert p(rec(workflow_id="b", close_status=0))
        assert not p(rec(workflow_id="c", close_status=0))
        assert not p(rec(workflow_id="a", close_status=1))

    def test_close_status_by_name(self):
        p = compile_query("CloseStatus = 'Completed'")
        assert p(rec(close_status=int(CloseStatus.Completed)))
        assert not p(rec(close_status=int(CloseStatus.Failed)))

    def test_custom_search_attributes(self):
        p = compile_query("CustomKeywordField = 'v' AND Priority > 3")
        assert p(rec(search_attrs={"CustomKeywordField": b"v", "Priority": 5}))
        assert not p(rec(search_attrs={"CustomKeywordField": b"v"}))
        assert not p(rec(search_attrs={}))

    def test_parse_errors(self):
        for bad in ("WorkflowID ==", "AND", "WorkflowID = ", "(a = 1",
                    "CloseStatus = 'NotAStatus'", "x = 1 extra junk %"):
            with pytest.raises(QueryParseError):
                compile_query(bad)

    def test_empty_query_matches_all(self):
        assert compile_query("")(rec())


class TestListCountEndToEnd:
    def test_upserted_attributes_are_queryable(self):
        box = Onebox(num_hosts=1, num_shards=4)
        box.frontend.register_domain(DOMAIN)
        box.frontend.start_workflow_execution(DOMAIN, "wf-a", "order", TL)
        box.frontend.start_workflow_execution(DOMAIN, "wf-b", "refund", TL)
        box.pump_once()

        # first decision upserts a search attribute on wf-a, completes wf-b
        for _ in range(8):
            resp = box.frontend.poll_for_decision_task(DOMAIN, TL)
            if resp is None:
                if box.pump_once() == 0:
                    break
                continue
            if resp.token.workflow_id == "wf-a":
                box.frontend.respond_decision_task_completed(resp.token, [
                    Decision(DecisionType.UpsertWorkflowSearchAttributes,
                             {"search_attributes": {"Tier": b"gold",
                                                    "Priority": 7}})])
            else:
                box.frontend.respond_decision_task_completed(resp.token, [
                    Decision(DecisionType.CompleteWorkflowExecution,
                             {"result": b""})])
        box.pump_once()

        hits = box.frontend.list_workflow_executions(
            DOMAIN, "Tier = 'gold' AND Priority >= 5")
        assert [r.workflow_id for r in hits] == ["wf-a"]
        assert box.frontend.count_workflow_executions(
            DOMAIN, "Tier = 'gold'") == 1
        assert box.frontend.count_workflow_executions(
            DOMAIN, "CloseStatus = 'Completed'") == 1
        assert box.frontend.count_workflow_executions(DOMAIN) == 2
        assert box.frontend.count_workflow_executions(
            DOMAIN, "WorkflowType = 'order' AND CloseStatus = 'Completed'") == 0
        # scan shares list semantics
        assert [r.workflow_id for r in box.frontend.scan_workflow_executions(
            DOMAIN, "WorkflowType = 'refund'")] == ["wf-b"]
