"""Workload definitions: the framework's "model families".

These are executable workflow definitions mirroring the reference's canary
and bench workloads (canary/echo.go, canary/signal.go, canary/timeout.go,
canary/concurrentExec.go, bench/load/basic/stressWorkflow.go): a decider is
a function from visible history to the next decisions — exactly the
contract a workflow worker fulfills over PollForDecisionTask.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from ..core.enums import DecisionType, EventType
from ..core.events import HistoryEvent
from ..engine.history_engine import Decision


def _count(history: List[HistoryEvent], *types: EventType) -> int:
    return sum(1 for e in history if e.event_type in types)


def _activity(activity_id: str, task_list: str, timeout: int = 60) -> Decision:
    return Decision(DecisionType.ScheduleActivityTask, dict(
        activity_id=activity_id, task_list=task_list,
        schedule_to_start_timeout_seconds=timeout,
        schedule_to_close_timeout_seconds=2 * timeout,
        start_to_close_timeout_seconds=timeout,
        heartbeat_timeout_seconds=0,
    ))


def _complete() -> Decision:
    return Decision(DecisionType.CompleteWorkflowExecution)


@dataclass
class ChainedActivityDecider:
    """bench basic stress workflow: a chain of sequential activities
    (bench/load/basic/stressWorkflow.go chainSequence)."""

    task_list: str
    chain_length: int = 3

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        done = _count(history, EventType.ActivityTaskCompleted)
        pending = _count(history, EventType.ActivityTaskScheduled) - _count(
            history, EventType.ActivityTaskCompleted,
            EventType.ActivityTaskFailed, EventType.ActivityTaskTimedOut,
            EventType.ActivityTaskCanceled)
        if pending > 0:
            return []
        if done >= self.chain_length:
            return [_complete()]
        return [_activity(f"chain-{done}", self.task_list)]


@dataclass
class EchoDecider:
    """canary echo: one activity, then complete."""

    task_list: str

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        if _count(history, EventType.ActivityTaskCompleted) >= 1:
            return [_complete()]
        if _count(history, EventType.ActivityTaskScheduled) >= 1:
            return []
        return [_activity("echo", self.task_list)]


@dataclass
class ResilientEchoDecider:
    """echo under fault injection: RESCHEDULES the activity when an
    attempt times out (a lost worker respond surfaces as
    ActivityTaskTimedOut) — the shape a production workflow takes in a
    lossy cluster, and what the concurrency/fault property tests drive."""

    task_list: str

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        if _count(history, EventType.ActivityTaskCompleted) >= 1:
            return [_complete()]
        live = _count(history, EventType.ActivityTaskScheduled) - (
            _count(history, EventType.ActivityTaskTimedOut)
            + _count(history, EventType.ActivityTaskFailed)
            + _count(history, EventType.ActivityTaskCanceled))
        if live > 0:
            return []
        return [_activity("echo", self.task_list)]


@dataclass
class SignalDecider:
    """canary signal: wait for N signals, then complete."""

    expected_signals: int = 3

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        got = _count(history, EventType.WorkflowExecutionSignaled)
        if got >= self.expected_signals:
            return [_complete()]
        return []


@dataclass
class TimerDecider:
    """canary timeout: start a timer; complete when it fires."""

    fire_seconds: int = 5

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        if _count(history, EventType.TimerFired) >= 1:
            return [_complete()]
        if _count(history, EventType.TimerStarted) >= 1:
            return []
        return [Decision(DecisionType.StartTimer, dict(
            timer_id="t-0", start_to_fire_timeout_seconds=self.fire_seconds))]


@dataclass
class ConcurrentActivityDecider:
    """canary concurrentExec: a wide batch of parallel activities, then
    complete when all finish."""

    task_list: str
    width: int = 4

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        scheduled = _count(history, EventType.ActivityTaskScheduled)
        closed = _count(history, EventType.ActivityTaskCompleted,
                        EventType.ActivityTaskFailed,
                        EventType.ActivityTaskTimedOut)
        if scheduled == 0:
            return [_activity(f"conc-{i}", self.task_list)
                    for i in range(self.width)]
        if closed >= self.width:
            return [_complete()]
        return []


@dataclass
class ChildWorkflowDecider:
    """parent workflow: launch a child, complete when the child closes."""

    child_workflow_id: str

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        if _count(history, EventType.ChildWorkflowExecutionCompleted,
                  EventType.ChildWorkflowExecutionFailed,
                  EventType.ChildWorkflowExecutionTimedOut,
                  EventType.ChildWorkflowExecutionTerminated,
                  EventType.ChildWorkflowExecutionCanceled) >= 1:
            return [_complete()]
        if _count(history, EventType.StartChildWorkflowExecutionInitiated) >= 1:
            return []
        return [Decision(DecisionType.StartChildWorkflowExecution, dict(
            workflow_id=self.child_workflow_id, workflow_type="child-type"))]


@dataclass
class RetryActivityDecider:
    """canary retry: one activity carrying a retry policy; complete when it
    finally succeeds, fail the workflow if it exhausts its attempts."""

    task_list: str
    initial_interval: int = 1
    backoff_coefficient: float = 2.0
    maximum_attempts: int = 3

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        from ..core.events import RetryPolicy
        if _count(history, EventType.ActivityTaskCompleted) >= 1:
            return [_complete()]
        if _count(history, EventType.ActivityTaskFailed,
                  EventType.ActivityTaskTimedOut) >= 1:
            return [Decision(DecisionType.FailWorkflowExecution,
                             dict(reason="activity retries exhausted"))]
        if _count(history, EventType.ActivityTaskScheduled) >= 1:
            return []
        d = _activity("flaky", self.task_list)
        d.attrs["retry_policy"] = RetryPolicy(
            initial_interval_seconds=self.initial_interval,
            backoff_coefficient=self.backoff_coefficient,
            maximum_interval_seconds=60,
            maximum_attempts=self.maximum_attempts,
        )
        return [d]


@dataclass
class CompleteDecider:
    """cron body: complete on the first decision (canary cron.go runs)."""

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        return [_complete()]


@dataclass
class FailDecider:
    """workflow-retry body: fail on the first decision."""

    reason: str = "wf-boom"

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        return [Decision(DecisionType.FailWorkflowExecution,
                         dict(reason=self.reason))]


@dataclass
class CancellationDecider:
    """canary cancellation: on cancel request, cancel the workflow."""

    task_list: str

    def decide(self, history: List[HistoryEvent]) -> List[Decision]:
        if _count(history, EventType.WorkflowExecutionCancelRequested) >= 1:
            return [Decision(DecisionType.CancelWorkflowExecution)]
        if _count(history, EventType.ActivityTaskScheduled) == 0:
            return [_activity("long-op", self.task_list, timeout=3600)]
        return []
