"""cadence_tpu: a TPU-native workflow-history replay framework.

A ground-up reimplementation of the capabilities of the reference workflow
orchestration engine (Uber Cadence, mounted read-only at /root/reference),
designed TPU-first: the per-workflow replay loop
(historyEngineImpl → stateBuilder → mutableStateBuilder) becomes a batched
state-machine transition kernel in JAX that replays millions of workflow
histories in lockstep across TPU cores, with checksum parity against a
Python semantic oracle.

Layout:
  core/      enums, event model, canonical checksum
  oracle/    single-workflow Python reference replayer (semantic oracle)
  ops/       dense state layout, event encoder, JAX scan replay kernel
  parallel/  device mesh, shardings, collectives
  engine/    host-side control plane (shards, queues, matching, frontend)
  gen/       golden corpus generators (BASELINE workload suites)
  native/    C++ host components (batch packing, CRC)
"""

__version__ = "0.1.0"
