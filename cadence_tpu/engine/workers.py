"""System workers: retention scavenger + execution scanner.

Reference: service/worker/ — background system workflows running against
the cluster itself. Implemented here as explicit passes a host loop (or a
test) drives:

- **RetentionScavenger** (service/worker/scanner history scavenger): the
  backstop for lost DeleteHistoryEvent timers — sweeps closed runs whose
  retention elapsed (by visibility close time + domain retention) and
  deletes them through the owning engine;
- **ExecutionScanner** (service/worker/scanner executions scanner over
  common/reconciliation/invariant): checks concrete-execution invariants —
  every current pointer resolves to a persisted run, every persisted run
  has history — and runs the device bulk verify (verify_all) as the
  mutable-state invariant; `fix=True` drops orphaned current pointers
  (the concreteExecutionExists fixer).

Parent-close-policy fan-out lives on the close path itself
(queues._apply_parent_close_policy — the reference routes it through a
parentclosepolicy system workflow for scale; same semantic).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

from .persistence import EntityNotExistsError, Stores

_DAY_NANOS = 24 * 3600 * 1_000_000_000


class RetentionScavenger:
    """Sweep closed runs past retention (scanner/history scavenger)."""

    def __init__(self, stores: Stores, router, time_source, metrics=None) -> None:
        from ..utils.metrics import DEFAULT_REGISTRY
        self.stores = stores
        self.router = router
        self.clock = time_source
        self.metrics = metrics if metrics is not None else DEFAULT_REGISTRY

    def run_once(self) -> int:
        """Delete every closed run whose close time + domain retention is
        past; returns how many runs were deleted. Domains with an archival
        URI ARCHIVE history (and the closed-visibility record) BEFORE the
        delete (service/worker/archiver pump → common/archiver.Archive);
        an archive failure SKIPS the delete — retention never destroys the
        only copy (archive-then-delete ordering)."""
        from dataclasses import asdict

        from .archival import archiver_for

        now = self.clock.now()
        deleted = 0
        archived = 0
        for rec in self.stores.visibility.all_closed():
            try:
                domain = self.stores.domain.by_id(rec.domain_id)
                retention_days = domain.retention_days
                archival_uri = domain.history_archival_uri
            except EntityNotExistsError:
                retention_days, archival_uri = 1, ""
            if rec.close_time + retention_days * _DAY_NANOS > now:
                continue
            archiver = archiver_for(archival_uri)
            if archiver is not None:
                try:
                    batches = self.stores.history.as_history_batches(
                        rec.domain_id, rec.workflow_id, rec.run_id)
                    archiver.archive(rec.domain_id, rec.workflow_id,
                                     rec.run_id, batches,
                                     visibility=asdict(rec))
                    archived += 1
                except EntityNotExistsError:
                    pass  # history already gone; nothing to preserve
                except Exception:
                    # archive failed (I/O, serialization): keep THIS run
                    # and retry next pass — one bad record must not halt
                    # retention for every other domain
                    continue
            engine = self.router(rec.workflow_id)
            if engine.delete_workflow_execution(rec.domain_id,
                                                rec.workflow_id, rec.run_id):
                deleted += 1
        from ..utils import metrics as m
        self.metrics.inc(m.SCOPE_WORKER_SCAVENGER, m.M_RUNS_DELETED, deleted)
        self.metrics.inc(m.SCOPE_WORKER_SCAVENGER, m.M_RUNS_ARCHIVED, archived)
        return deleted


@dataclass
class ScanReport:
    """common/reconciliation invariant results."""

    executions: int = 0
    orphan_pointers: List[Tuple[str, str, str]] = field(default_factory=list)
    missing_history: List[Tuple[str, str, str]] = field(default_factory=list)
    state_divergent: List[Tuple[str, str, str]] = field(default_factory=list)
    #: OPEN runs holding no current pointer (invariant/openCurrentExecution
    #: .go): zombies are expected on a standby, orphans are not — both
    #: reported, neither dispatched
    open_without_pointer: List[Tuple[str, str, str]] = field(
        default_factory=list)
    #: pending activities/timers whose deadline math is inconsistent
    #: (invariant/timerInvalid.go analog): schedule ids beyond the
    #: history's next-event-id can never resolve
    invalid_pending: List[Tuple[str, str, str]] = field(default_factory=list)
    fixed: int = 0
    #: the device bulk-verify result backing state_divergent (one pass,
    #: shared with the watchdog rollup)
    verify: object = None

    @property
    def ok(self) -> bool:
        return not (self.orphan_pointers or self.missing_history
                    or self.state_divergent or self.invalid_pending)


class ExecutionScanner:
    """Concrete-execution invariants + device bulk verify."""

    def __init__(self, stores: Stores, tpu, metrics=None) -> None:
        from ..utils.metrics import DEFAULT_REGISTRY
        self.stores = stores
        self.tpu = tpu
        self.metrics = metrics if metrics is not None else DEFAULT_REGISTRY

    def run_once(self, fix: bool = False) -> ScanReport:
        report = ScanReport()
        # invariant: current pointer → persisted run
        # (invariant/openCurrentExecution.go / concreteExecutionExists.go)
        for (domain_id, workflow_id), cur in \
                self.stores.execution.list_current_pointers():
            try:
                self.stores.execution.get_workflow(domain_id, workflow_id,
                                                   cur.run_id)
            except EntityNotExistsError:
                report.orphan_pointers.append(
                    (domain_id, workflow_id, cur.run_id))
                if fix:
                    self.stores.execution.drop_current(domain_id, workflow_id)
                    report.fixed += 1
        # invariant: every persisted run has history
        # (invariant/historyExists.go)
        keys = self.stores.execution.list_executions()
        report.executions = len(keys)
        with_history = []
        for key in keys:
            if self.stores.history.branch_count(*key) == 0:
                report.missing_history.append(key)
            else:
                with_history.append(key)
        # per-key invariants off ONE state fetch: open run ⇒ current
        # pointer (openCurrentExecution.go; zombies visible, never
        # silently resident) and pending items reference events that
        # exist (timerInvalid.go analog — an entry past the history tail
        # can never resolve)
        from ..core.enums import WorkflowState
        for key in keys:
            ms = self.stores.execution.get_workflow(*key)
            info = ms.execution_info
            if info.state != WorkflowState.Completed:
                try:
                    is_current = (self.stores.execution.get_current_run_id(
                        key[0], key[1]) == key[2])
                except EntityNotExistsError:
                    is_current = False
                if not is_current:
                    report.open_without_pointer.append(key)
            next_id = info.next_event_id
            bad = any(sched >= next_id
                      for sched in ms.pending_activity_info_ids)
            bad = bad or any(ti.started_id >= next_id
                             for ti in ms.pending_timer_info_ids.values())
            if bad:
                report.invalid_pending.append(key)
        # invariant: mutable state replays bit-exact on device (the
        # checksum oracle as a scanner invariant, execution/checksum.go);
        # the result rides the report so callers (watchdog) never pay a
        # second full device pass
        if with_history:
            result = self.tpu.verify_all(with_history)
            report.state_divergent = list(result.divergent)
            report.verify = result
        from ..utils import metrics as m
        self.metrics.inc(m.SCOPE_WORKER_SCANNER, m.M_EXECUTIONS_SCANNED,
                         report.executions)
        self.metrics.inc(m.SCOPE_WORKER_SCANNER, m.M_INVARIANT_VIOLATIONS,
                         len(report.orphan_pointers)
                         + len(report.missing_history)
                         + len(report.state_divergent)
                         + len(report.invalid_pending))
        return report


class Watchdog:
    """Periodic health sweep (service/worker/watchdog + esanalyzer's
    corrective role, folded onto this framework's invariant surface):
    one pass = scanner invariants + device verification + retention
    scavenge, rolled into a single report the operator (or a cron'd CLI)
    can alert on."""

    def __init__(self, box) -> None:
        self.box = box

    def run_once(self, fix: bool = False) -> dict:
        scan = self.box.scanner.run_once(fix=fix)
        deleted = self.box.scavenger.run_once()
        verified = (scan.verify.verified_on_device
                    if scan.verify is not None else 0)
        report = {
            "ok": scan.ok,
            "executions": scan.executions,
            "orphan_pointers": len(scan.orphan_pointers),
            "missing_history": len(scan.missing_history),
            "state_divergent": len(scan.state_divergent),
            "open_without_pointer": len(scan.open_without_pointer),
            "invalid_pending": len(scan.invalid_pending),
            "verified_on_device": verified,
            "scavenged": deleted,
            "fixed": scan.fixed,
        }
        from ..utils.log import DEFAULT_LOGGER
        (DEFAULT_LOGGER.info if report["ok"] else DEFAULT_LOGGER.error)(
            "watchdog sweep", component="watchdog", **report)
        return report
