"""Frontend: the public API gateway.

Reference: service/frontend/workflowHandler.go (domain CRUD :265-437,
polls :471/:580, StartWorkflowExecution :1940, Signal :2378,
Terminate/Cancel :2674-2783, List :2837, GetWorkflowExecutionHistory :2106,
DescribeTaskList :3593). Requests route to the owning history host via the
membership ring (client/history peer resolver analog) — in this in-process
cluster, via the cluster-wide router over all controllers.
"""
from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, List, Optional

from ..core.enums import EMPTY_EVENT_ID, WorkflowState
from ..core.events import HistoryEvent, RetryPolicy
from ..oracle.mutable_state import MutableState
from ..utils import flightrecorder
from ..utils import metrics as m
from ..utils import tracing
from ..utils.clock import RealTimeSource
from ..utils.dynamicconfig import (
    KEY_FRONTEND_BURST,
    KEY_FRONTEND_DOMAIN_RPS,
    KEY_FRONTEND_RPS,
    KEY_HISTORY_PAGE_SIZE,
    KEY_RETENTION_DAYS_DEFAULT,
    KEY_VISIBILITY_PAGE_SIZE,
    DynamicConfig,
)
from ..utils.quotas import MultiStageRateLimiter, ServiceBusyError
from .authorization import (
    PERMISSION_ADMIN,
    PERMISSION_WRITE,
    AuthAttributes,
    NoopAuthorizer,
    check,
)
from .domain import (
    deprecate_domain,
    require_active,
    require_startable,
    update_domain,
)
from .history_engine import (
    Decision,
    HistoryEngine,
    InvalidRequestError,
    TaskToken,
)
from .limits import check_blob_size
from .matching import (
    TASK_LIST_TYPE_ACTIVITY,
    TASK_LIST_TYPE_DECISION,
    MatchedTask,
    MatchingEngine,
)
from .pagination import (
    HistoryPage,
    VisibilityPage,
    decode_token,
    encode_token,
)
from .archival import archiver_for
from .cluster import ClusterMetadata
from .persistence import DomainInfo, EntityNotExistsError, Stores, VisibilityRecord


class PollDecisionResponse:
    def __init__(self, token: Optional[TaskToken], history: List[HistoryEvent],
                 previous_started_event_id: int,
                 queries: Optional[List[tuple]] = None,
                 query_only: bool = False,
                 execution: Optional[tuple] = None) -> None:
        self.token = token
        self.history = history
        self.previous_started_event_id = previous_started_event_id
        #: (query_id, query_type, args) triples attached to this task
        self.queries = queries or []
        #: True for a query-only task (no decision token; answer via
        #: respond_query_task_completed)
        self.query_only = query_only
        #: (domain_id, workflow_id, run_id) for query-only responses
        self.execution = execution


class PollActivityResponse:
    def __init__(self, token: TaskToken, activity_id: str,
                 activity_type: str = "") -> None:
        self.token = token
        self.activity_id = activity_id
        self.activity_type = activity_type


class Frontend:
    def __init__(self, stores: Stores, matching: MatchingEngine,
                 router: Callable[[str], HistoryEngine],
                 config=None, metrics=None, time_source=None,
                 cluster_name: str = "primary") -> None:
        self.stores = stores
        self.matching = matching
        self.router = router
        self.cluster_name = cluster_name
        # authorization seam: Noop by default (reference posture); hosts
        # inject a real authorizer + per-connection actor identity
        self.authorizer = NoopAuthorizer()
        self.actor = ""
        #: the cluster group this frontend validates replication configs
        #: against (cluster/metadata.go); multi-cluster wiring replaces it
        self.cluster_meta = ClusterMetadata()
        #: set by multi-cluster wiring: domain mutations stream to peers
        #: (common/domain/replication_queue.go producer seam)
        self.domain_replication_publisher = None
        self.config = config if config is not None else DynamicConfig()
        self.metrics = metrics if metrics is not None else m.DEFAULT_REGISTRY
        clock = time_source if time_source is not None else RealTimeSource()
        # the quotas seat (common/quotas/ratelimiter.go:43): global +
        # per-domain token buckets with live-config limits; 0 = unlimited
        self.rate_limiter = MultiStageRateLimiter(
            clock,
            global_rps=lambda: self.config.get(KEY_FRONTEND_RPS),
            domain_rps=lambda d: self.config.get(KEY_FRONTEND_DOMAIN_RPS,
                                                 domain=d),
            burst=lambda: self.config.get(KEY_FRONTEND_BURST),
        )
        #: domains granted a per-domain metrics series, capped: the name
        #: comes straight from the request BEFORE the domain is validated,
        #: and a spray of junk domain names must never grow the registry
        #: (and every /metrics scrape) without bound — the same guard
        #: quotas.Collection applies to its buckets
        self._metric_domains: set = set()

    def _admit(self, domain: str, scope: str) -> None:
        """Admission control (quotas/multistageratelimiter.go seat): charge
        the request against the per-domain stage then the global stage.
        Over-limit requests shed with a typed ServiceBusyError carrying a
        retry-after estimate — overload degrades by rejecting cheaply at
        the door, never by queueing into latency collapse. Every decision
        lands on the `quotas` scope (admitted/shed + per-domain series),
        so a /metrics scrape shows WHICH domain is being shed."""
        try:
            self.rate_limiter.admit(domain)
        except ServiceBusyError:
            self.metrics.inc(scope, m.M_RATE_LIMITED)
            self.metrics.inc(m.SCOPE_QUOTAS, m.M_QUOTA_SHED)
            series = self._domain_series(m.M_QUOTA_SHED, domain)
            if series:
                self.metrics.inc(m.SCOPE_QUOTAS, series)
            flightrecorder.emit("quota-shed", domain=domain, api=scope)
            raise
        self.metrics.inc(m.SCOPE_QUOTAS, m.M_QUOTA_ADMITTED)
        series = self._domain_series(m.M_QUOTA_ADMITTED, domain)
        if series:
            self.metrics.inc(m.SCOPE_QUOTAS, series)

    #: per-domain quota series cap — beyond it only the totals count
    MAX_DOMAIN_SERIES = 256

    def _domain_series(self, name: str, domain: str) -> Optional[str]:
        """Per-domain series name, or None once the cap is hit (totals
        still count; only the per-domain breakdown saturates)."""
        if domain not in self._metric_domains:
            if len(self._metric_domains) >= self.MAX_DOMAIN_SERIES:
                return None
            self._metric_domains.add(domain)
        return m.domain_metric(name, domain)

    def _authorize(self, api: str, permission: str, domain: str = "") -> None:
        check(self.authorizer, AuthAttributes(api=api, permission=permission,
                                              domain=domain,
                                              actor=self.actor))

    # -- domains (workflowHandler.go:265-437) ------------------------------

    def register_domain(self, name: str, retention_days: int = 0,
                        is_active: bool = True,
                        clusters: tuple = ("primary",),
                        active_cluster: str = "primary",
                        failover_version: int = 0,
                        domain_id: str = "") -> str:
        """Domain CRUD (workflowHandler.go:265). Global domains pass the same
        domain_id on every cluster (the domain-replication invariant)."""
        self._authorize("RegisterDomain", PERMISSION_ADMIN, name)
        if retention_days <= 0:
            retention_days = int(self.config.get(KEY_RETENTION_DAYS_DEFAULT))
        domain_id = domain_id or str(uuid.uuid4())
        info = DomainInfo(
            domain_id=domain_id, name=name, retention_days=retention_days,
            is_active=is_active, active_cluster=active_cluster,
            clusters=tuple(clusters), failover_version=failover_version)
        self.stores.domain.register(info)
        # global domains replicate their REGISTRATION too (the processor's
        # register arm) — peers must not wait for the first update
        if self.domain_replication_publisher is not None and len(
                info.clusters) > 1:
            self.domain_replication_publisher.publish(info)
        return domain_id

    def describe_domain(self, name: str) -> DomainInfo:
        return self.stores.domain.by_name(name)

    def update_domain(self, name: str, retention_days: int = None,
                      description: str = None, clusters=None,
                      active_cluster: str = None,
                      history_archival_uri: str = None) -> DomainInfo:
        """UpdateDomain (workflowHandler.go:386): validated, live-effective
        (retention feeds the scavenger, failover-version bump stamps later
        events, archival URI arms archive-then-delete),
        notification-version ordered."""
        self._authorize("UpdateDomain", PERMISSION_ADMIN, name)
        info = update_domain(self.stores, name,
                             local_cluster=self.cluster_name,
                             meta=self.cluster_meta,
                             retention_days=retention_days,
                             description=description, clusters=clusters,
                             active_cluster=active_cluster,
                             history_archival_uri=history_archival_uri)
        if self.domain_replication_publisher is not None and len(
                info.clusters) > 1:
            self.domain_replication_publisher.publish(info)
        return info

    def deprecate_domain(self, name: str) -> DomainInfo:
        """DeprecateDomain: rejects new starts, running workflows finish."""
        self._authorize("DeprecateDomain", PERMISSION_ADMIN, name)
        info = deprecate_domain(self.stores, name)
        if self.domain_replication_publisher is not None and len(
                info.clusters) > 1:
            self.domain_replication_publisher.publish(info)
        return info

    def list_domains(self) -> List[DomainInfo]:
        return self.stores.domain.list_domains()

    # -- workflow lifecycle ------------------------------------------------

    @tracing.traced(m.SCOPE_FRONTEND_START)
    def start_workflow_execution(self, domain: str, workflow_id: str,
                                 workflow_type: str, task_list: str,
                                 execution_timeout: int = 3600,
                                 decision_timeout: int = 10,
                                 cron_schedule: str = "",
                                 first_decision_backoff: int = 0,
                                 retry_policy: Optional[RetryPolicy] = None,
                                 input_payload: bytes = b"",
                                 ) -> str:
        self._authorize("StartWorkflowExecution", PERMISSION_WRITE, domain)
        self._admit(domain, m.SCOPE_FRONTEND_START)
        self.metrics.inc(m.SCOPE_FRONTEND_START, m.M_REQUESTS)
        check_blob_size(input_payload, self.config,
                        "StartWorkflowExecution", domain,
                        metrics=self.metrics)
        info = self.stores.domain.by_name(domain)
        require_startable(info)
        require_active(info, self.cluster_name)
        domain_id = info.domain_id
        engine = self.router(workflow_id)
        return engine.start_workflow(
            domain_id=domain_id, workflow_id=workflow_id,
            workflow_type=workflow_type, task_list=task_list,
            execution_timeout=execution_timeout,
            decision_timeout=decision_timeout,
            cron_schedule=cron_schedule,
            first_decision_backoff=first_decision_backoff,
            retry_policy=retry_policy,
            input_payload=input_payload,
        )

    @tracing.traced(m.SCOPE_FRONTEND_SIGNAL)
    def signal_workflow_execution(self, domain: str, workflow_id: str,
                                  signal_name: str,
                                  run_id: Optional[str] = None,
                                  request_id: Optional[str] = None) -> None:
        """request_id (SignalWorkflowExecutionRequest.RequestId) dedups
        client retries: a signal already applied under the same id no-ops."""
        self._authorize("SignalWorkflowExecution", PERMISSION_WRITE, domain)
        self._admit(domain, m.SCOPE_FRONTEND_SIGNAL)
        info = self.stores.domain.by_name(domain)
        require_active(info, self.cluster_name)
        self.router(workflow_id).signal_workflow(info.domain_id, workflow_id,
                                                 signal_name, run_id,
                                                 request_id=request_id)

    def signal_with_start_workflow_execution(
            self, domain: str, workflow_id: str, signal_name: str,
            workflow_type: str, task_list: str,
            execution_timeout: int = 3600, decision_timeout: int = 10,
            cron_schedule: str = "", retry_policy=None,
            request_id: Optional[str] = None) -> str:
        """SignalWithStartWorkflowExecution (workflowHandler.go:2494):
        signal the running execution, or atomically start one whose first
        transaction carries the signal. Returns the run ID signaled or
        started. `request_id` dedups client retries on BOTH arms (the
        start's create request id and the signal's at-least-once set)."""
        self._authorize("SignalWithStartWorkflowExecution", PERMISSION_WRITE,
                        domain)
        self._admit(domain, m.SCOPE_FRONTEND_SIGNAL)
        info = self.stores.domain.by_name(domain)
        require_startable(info)
        require_active(info, self.cluster_name)
        return self.router(workflow_id).signal_with_start_workflow(
            info.domain_id, workflow_id, signal_name, workflow_type,
            task_list, execution_timeout=execution_timeout,
            decision_timeout=decision_timeout, cron_schedule=cron_schedule,
            retry_policy=retry_policy, request_id=request_id)

    def request_cancel_workflow_execution(self, domain: str, workflow_id: str,
                                          run_id: Optional[str] = None) -> None:
        self._authorize("RequestCancelWorkflowExecution", PERMISSION_WRITE,
                        domain)
        self._admit(domain, m.SCOPE_FRONTEND_SIGNAL)
        info = self.stores.domain.by_name(domain)
        require_active(info, self.cluster_name)
        self.router(workflow_id).request_cancel_workflow(info.domain_id,
                                                         workflow_id, run_id)

    def terminate_workflow_execution(self, domain: str, workflow_id: str,
                                     run_id: Optional[str] = None,
                                     reason: str = "") -> None:
        self._authorize("TerminateWorkflowExecution", PERMISSION_WRITE, domain)
        self._admit(domain, m.SCOPE_FRONTEND_SIGNAL)
        info = self.stores.domain.by_name(domain)
        require_active(info, self.cluster_name)
        self.router(workflow_id).terminate_workflow(info.domain_id,
                                                    workflow_id, run_id,
                                                    reason)

    def reset_workflow_execution(self, domain: str, workflow_id: str,
                                 decision_finish_event_id: int,
                                 run_id: Optional[str] = None,
                                 reason: str = "") -> str:
        """ResetWorkflowExecution (workflowHandler.go:2726): returns the new
        run ID."""
        self._authorize("ResetWorkflowExecution", PERMISSION_WRITE, domain)
        self._admit(domain, m.SCOPE_FRONTEND_RESET)
        info = self.stores.domain.by_name(domain)
        require_active(info, self.cluster_name)
        domain_id = info.domain_id
        return self.router(workflow_id).reset_workflow(
            domain_id, workflow_id, run_id,
            decision_finish_event_id=decision_finish_event_id, reason=reason)

    # -- worker polls ------------------------------------------------------

    @tracing.traced(m.SCOPE_FRONTEND_POLL_DECISION)
    def poll_for_decision_task(self, domain: str, task_list: str,
                               wait_seconds: float = 0, identity: str = ""
                               ) -> Optional[PollDecisionResponse]:
        """PollForDecisionTask (workflowHandler.go:580). With
        `wait_seconds` > 0 the poll LONG-POLLS: an empty task list parks
        the poll for sync-match instead of returning immediately (the
        reference's long-poll transport over taskListManager's matcher).
        `identity` lands in DescribeTaskList's poller history."""
        domain_id = self.stores.domain.by_name(domain).domain_id
        task = self.matching.poll_and_wait_decision(domain_id, task_list,
                                                    wait_seconds,
                                                    identity=identity)
        if task is None:
            return None
        try:
            engine = self.router(task.workflow_id)
        except Exception:
            # routing failed after the two-phase pop (shard mid-rebalance):
            # the task must not strand in the in-flight ledger, or it pins
            # the task-list GC level forever
            self.matching.requeue_task(task, TASK_LIST_TYPE_DECISION)
            raise
        key = (task.domain_id, task.workflow_id, task.run_id)
        if task.query_id:
            # query-only task: no history mutation, no decision token;
            # ship the buffered queries with current history so the worker
            # can answer (matchingEngine QueryWorkflow → worker)
            history = engine.get_history(task.domain_id, task.workflow_id,
                                         task.run_id)
            return PollDecisionResponse(
                token=None, history=history, previous_started_event_id=0,
                queries=engine.queries.attach(key), query_only=True,
                execution=key)
        try:
            token = engine.record_decision_task_started(
                task.domain_id, task.workflow_id, task.run_id,
                task.schedule_id, request_id=str(uuid.uuid4()))
        except (InvalidRequestError, EntityNotExistsError):
            # stale task (decision handled / run never committed) — ack it
            # away so its persisted row doesn't pin the task-list GC level
            self.matching.complete_task(task, TASK_LIST_TYPE_DECISION)
            return None
        except Exception:
            # transient engine/store failure: the consumed task must not be
            # lost — requeue for redelivery (matching acks only after a
            # successful RecordDecisionTaskStarted)
            self.matching.requeue_task(task, TASK_LIST_TYPE_DECISION)
            raise
        # successful engine write: second phase of the ack deletes the row
        self.matching.complete_task(task, TASK_LIST_TYPE_DECISION)
        ms = engine.get_mutable_state(task.domain_id, task.workflow_id,
                                      task.run_id)
        history = engine.get_history(task.domain_id, task.workflow_id,
                                     task.run_id)
        return PollDecisionResponse(
            token=token, history=history,
            previous_started_event_id=ms.execution_info.last_processed_event,
            queries=engine.queries.attach(key), execution=key)

    def respond_decision_task_completed(self, token: TaskToken,
                                        decisions: List[Decision],
                                        sticky_task_list: str = "",
                                        sticky_schedule_to_start_timeout: int = 0,
                                        query_results: Optional[Dict[str, bytes]] = None
                                        ) -> None:
        self.router(token.workflow_id).respond_decision_task_completed(
            token, decisions, sticky_task_list=sticky_task_list,
            sticky_schedule_to_start_timeout=sticky_schedule_to_start_timeout,
            query_results=query_results)
        # queries still buffered after the completion (arrived mid-decision,
        # unanswered by this worker) must not wait for a decision that may
        # never come: dispatch them directly (the reference forwards leftover
        # buffered queries through matching after decision completion)
        self._dispatch_buffered_queries(token.domain_id, token.workflow_id,
                                        token.run_id)

    def _dispatch_buffered_queries(self, domain_id: str, workflow_id: str,
                                   run_id: str) -> None:
        engine = self.router(workflow_id)
        key = (domain_id, workflow_id, run_id)
        buffered = engine.queries.buffered_ids(key)
        if not buffered:
            return
        try:
            ms = engine.get_mutable_state(domain_id, workflow_id, run_id)
        except Exception:
            return
        info = ms.execution_info
        if info.state == WorkflowState.Completed:
            engine.queries.fail_all(key, "workflow execution closed")
            return
        if info.decision_schedule_id != EMPTY_EVENT_ID:
            return  # a decision is coming; queries attach to its poll
        # one trigger task suffices: the poll's attach() ships every
        # buffered query. Always the NORMAL task list — a stale sticky
        # list would park the query behind a dead worker with no
        # schedule-to-start fallback (query tasks have no timer)
        self.matching.add_query_task(domain_id, info.task_list,
                                     workflow_id, run_id, buffered[0])

    # -- consistent query (workflowHandler.go:3454 QueryWorkflow →
    # query/registry.go buffered queries) ----------------------------------

    def query_workflow(self, domain: str, workflow_id: str, query_type: str,
                       args: bytes = b"", run_id: Optional[str] = None) -> str:
        """Register a query; returns its ID. A workflow with a decision
        pending or in flight answers with that decision's completion
        (consistent query); an idle workflow gets a query-only task
        dispatched directly through matching."""
        self._admit(domain, m.SCOPE_FRONTEND_QUERY)
        domain_id = self.stores.domain.by_name(domain).domain_id
        engine = self.router(workflow_id)
        ms = engine.get_mutable_state(domain_id, workflow_id, run_id)
        info = ms.execution_info
        if info.state == WorkflowState.Completed:
            raise InvalidRequestError("workflow execution already completed")
        key = (domain_id, workflow_id, info.run_id)
        query_id = engine.queries.buffer(key, query_type, args)
        if info.decision_schedule_id == EMPTY_EVENT_ID:
            # always the NORMAL task list: a stale sticky list would park
            # the query behind a dead worker (query tasks carry no
            # schedule-to-start fallback timer)
            self.matching.add_query_task(domain_id, info.task_list,
                                         workflow_id, info.run_id, query_id)
        return query_id

    def get_query_result(self, domain: str, workflow_id: str, query_id: str,
                         run_id: Optional[str] = None):
        """(state, result, failure) of a registered query."""
        domain_id = self.stores.domain.by_name(domain).domain_id
        engine = self.router(workflow_id)
        if run_id is None:
            run_id = self.stores.execution.get_current_run_id(
                domain_id, workflow_id)
        # engine-side unpack: the registry's PendingQuery carries a
        # threading.Event, so the OBJECT must never cross the wire when
        # the owner is a remote host — only the plain result tuple does
        return engine.query_result_tuple(domain_id, workflow_id, run_id,
                                         query_id)

    def respond_query_task_completed(self, execution: tuple, query_id: str,
                                     result: bytes) -> None:
        """Answer a query-only task (RespondQueryTaskCompleted analog)."""
        self.router(execution[1]).queries.complete(execution, query_id, result)

    def poll_for_activity_task(self, domain: str, task_list: str,
                               wait_seconds: float = 0, identity: str = ""
                               ) -> Optional[PollActivityResponse]:
        domain_id = self.stores.domain.by_name(domain).domain_id
        task = self.matching.poll_and_wait_activity(domain_id, task_list,
                                                    wait_seconds,
                                                    identity=identity)
        if task is None:
            return None
        try:
            engine = self.router(task.workflow_id)
        except Exception:
            self.matching.requeue_task(task, TASK_LIST_TYPE_ACTIVITY)
            raise
        try:
            token = engine.record_activity_task_started(
                task.domain_id, task.workflow_id, task.run_id,
                task.schedule_id, request_id=str(uuid.uuid4()))
        except (InvalidRequestError, EntityNotExistsError):
            # stale (timed out / closed / never committed): ack it away
            self.matching.complete_task(task, TASK_LIST_TYPE_ACTIVITY)
            return None
        except Exception:
            self.matching.requeue_task(task, TASK_LIST_TYPE_ACTIVITY)
            raise
        self.matching.complete_task(task, TASK_LIST_TYPE_ACTIVITY)
        ms = engine.get_mutable_state(task.domain_id, task.workflow_id,
                                      task.run_id)
        ai = ms.pending_activity_info_ids.get(task.schedule_id)
        return PollActivityResponse(token=token,
                                    activity_id=ai.activity_id if ai else "")

    def respond_activity_task_completed(self, token: TaskToken,
                                        result: bytes = b"") -> None:
        self.router(token.workflow_id).respond_activity_task_completed(
            token, result)

    def respond_activity_task_failed(self, token: TaskToken,
                                     reason: str = "") -> None:
        self.router(token.workflow_id).respond_activity_task_failed(token, reason)

    # -- reads -------------------------------------------------------------

    def get_workflow_execution_history(self, domain: str, workflow_id: str,
                                       run_id: Optional[str] = None,
                                       wait_for_new_event: bool = False,
                                       last_event_id: int = 0,
                                       timeout: float = 10.0
                                       ) -> List[HistoryEvent]:
        """GetWorkflowExecutionHistory (workflowHandler.go:2106). With
        `wait_for_new_event`, the call LONG-POLLS: it blocks on the history
        notifier until events beyond `last_event_id` exist or the workflow
        closes (the reference's close-event wait policy), instead of
        busy-reading."""
        # admission charges at ENTRY (one token per call, long-poll or
        # not): a parked long-poll holds a notifier slot, not a quota
        self._admit(domain, m.SCOPE_FRONTEND_READ)
        info = self.stores.domain.by_name(domain)
        domain_id = info.domain_id
        engine = self.router(workflow_id)

        def read_paged() -> List[HistoryEvent]:
            # the full convenience read drives the RANGED store read in
            # pages (state_rebuilder.go:114's paginated replay posture):
            # no single store call moves unbounded bytes
            cap = int(self.config.get(KEY_HISTORY_PAGE_SIZE, domain=domain))
            out: List[HistoryEvent] = []
            from_id = 1
            while True:
                page = self.stores.history.read_events_range(
                    domain_id, workflow_id, run_id, from_id, cap)
                out.extend(page)
                if len(page) < cap:
                    return out
                from_id = page[-1].id + 1

        try:
            if run_id is None:
                run_id = self.stores.execution.get_current_run_id(domain_id,
                                                                  workflow_id)
            events = read_paged()
        except EntityNotExistsError:
            # read-through to the archive: a retention-scavenged run whose
            # domain archives stays readable (common/archiver Get path).
            # With no run_id (the scavenge also dropped the current-run
            # pointer), the most recently closed archived run serves.
            archiver = archiver_for(info.history_archival_uri)
            if archiver is None:
                raise
            if run_id is None:
                archived = archiver.runs(domain_id, workflow_id)
                if not archived:
                    raise
                run_id = archived[0]
            return [e for b in archiver.read(domain_id, workflow_id, run_id)
                    for e in b.events]
        if wait_for_new_event and (not events or events[-1].id <= last_event_id):
            # an event BEYOND last_event_id exists iff the published
            # next_event_id reaches last_event_id + 2
            engine.notifier.wait_for((domain_id, workflow_id, run_id),
                                     last_event_id + 2, timeout=timeout)
            events = read_paged()
        return events

    def get_workflow_execution_history_page(self, domain: str,
                                            workflow_id: str,
                                            run_id: Optional[str] = None,
                                            page_size: int = 0,
                                            next_page_token: Optional[bytes]
                                            = None):
        """Paginated history read (workflowHandler.go:3745-3811 getHistory
        with nextPageToken): at most `page_size` events per call (the
        configured default/cap bounds it), with an opaque resume token.
        The store read itself is RANGED, so a page never moves more than
        page_size events — the contract the CLI, the archiver, and any
        long-history consumer page through."""

        cap = int(self.config.get(KEY_HISTORY_PAGE_SIZE, domain=domain))
        page_size = min(page_size, cap) if page_size > 0 else cap
        info = self.stores.domain.by_name(domain)
        domain_id = info.domain_id
        from_id = 1
        if next_page_token:
            tok = decode_token(next_page_token)
            run_id = tok["run_id"]
            from_id = int(tok["next_event_id"])
        elif run_id is None:
            run_id = self.stores.execution.get_current_run_id(domain_id,
                                                              workflow_id)
        events = self.stores.history.read_events_range(
            domain_id, workflow_id, run_id, from_id, page_size + 1)
        more = len(events) > page_size
        events = events[:page_size]
        token = (encode_token({"run_id": run_id,
                               "next_event_id": events[-1].id + 1})
                 if events and more else None)
        return HistoryPage(events, token, run_id)

    def describe_workflow_execution(self, domain: str, workflow_id: str,
                                    run_id: Optional[str] = None
                                    ) -> MutableState:
        self._admit(domain, m.SCOPE_FRONTEND_READ)
        domain_id = self.stores.domain.by_name(domain).domain_id
        return self.router(workflow_id).get_mutable_state(domain_id,
                                                          workflow_id, run_id)

    def list_open_workflow_executions(self, domain: str) -> List[VisibilityRecord]:
        domain_id = self.stores.domain.by_name(domain).domain_id
        return self.stores.visibility.list_open(domain_id)

    def list_closed_workflow_executions(self, domain: str) -> List[VisibilityRecord]:
        domain_id = self.stores.domain.by_name(domain).domain_id
        return self.stores.visibility.list_closed(domain_id)

    def list_workflow_executions(self, domain: str, query: str = ""
                                 ) -> List[VisibilityRecord]:
        """ListWorkflowExecutions with a query (workflowHandler.go:2837):
        SQL-ish filters over built-in columns AND custom search attributes
        (engine/visibility_query.py grammar). Index-planned: the query's
        equality hints intersect the store's (type, status) indexes."""
        domain_id = self.stores.domain.by_name(domain).domain_id
        return self.stores.visibility.query(domain_id, query)

    # ScanWorkflowExecutions (workflowHandler.go:3200) shares semantics
    # with List in this store (no pagination-ordering split to preserve)
    scan_workflow_executions = list_workflow_executions

    def list_workflow_executions_page(self, domain: str, query: str = "",
                                      page_size: int = 0,
                                      next_page_token: Optional[bytes] = None):
        """Paginated List/Scan: StartTime-DESC pages with an opaque resume
        token (the ES search_after token reframed onto the store's
        time-ordered index)."""

        cap = int(self.config.get(KEY_VISIBILITY_PAGE_SIZE, domain=domain))
        page_size = min(page_size, cap) if page_size > 0 else cap
        domain_id = self.stores.domain.by_name(domain).domain_id
        cursor = (decode_token(next_page_token)["after"]
                  if next_page_token else None)
        records, raw = self.stores.visibility.query_page(
            domain_id, query, page_size, cursor)
        token = encode_token({"after": list(raw)}) if raw else None
        return VisibilityPage(records, token)

    scan_workflow_executions_page = list_workflow_executions_page

    def count_workflow_executions(self, domain: str, query: str = "") -> int:
        """CountWorkflowExecutions (workflowHandler.go:3322)."""
        domain_id = self.stores.domain.by_name(domain).domain_id
        return self.stores.visibility.count(domain_id, query)

    def describe_task_list(self, domain: str, task_list: str,
                           task_type: int = TASK_LIST_TYPE_DECISION
                           ) -> Dict[str, int]:
        domain_id = self.stores.domain.by_name(domain).domain_id
        return self.matching.describe_task_list(domain_id, task_list, task_type)
