"""Two-cluster harness: active + standby with replication and failover.

Reference analog: the XDC integration setup
(config/development_xdc_cluster0/1.yaml cluster-group metadata +
docker-compose-multiclusters) collapsed into one process — two Onebox
clusters, the replication stream between them, and graceful failover
(domain failover version bump; common/domain/failover_watcher.go and the
failovermanager workflow drive the same transition in the reference).
"""
from __future__ import annotations

from typing import List, Optional

from ..core.events import HistoryBatch
from .cluster import ClusterMetadata
from .onebox import Onebox
from .replication import (
    HistoryReplicator,
    ReplicationPublisher,
    ReplicationTaskProcessor,
)
from .task_refresher import sweep_refresh


def _refresh_domain_tasks(box: Onebox, domain_name: str) -> None:
    """Promotion sweep for one domain (shared sweep in task_refresher)."""
    domain_id = box.stores.domain.by_name(domain_name).domain_id
    sweep_refresh(box.stores, box.route, domain_id)


def prehydrate_serving(box: Onebox) -> dict:
    """Warm promotion (tentpole 3): hydrate the promoting box's serving
    tier from its snapshot store — which snapshot-shipping replication
    has been filling continuously — BEFORE the active flip, so the first
    post-failover transactions land on resident rows instead of paying a
    cold replay storm. One pass of the migration tier's shared hydration
    primitive over every shard (seed_caches + batch-range suffix replay,
    oracle parity gated)."""
    from .migration import MigrationManager
    mgr = MigrationManager(box.cluster_name, box.num_shards, box.tpu,
                           registry=box.metrics)
    report = mgr.hydrate_shards(range(box.num_shards))
    return {"considered": report.considered, "hydrated": report.hydrated,
            "suffix_events": report.suffix_events, "cold": report.cold,
            "young": report.young, "stale": report.stale,
            "already_resident": report.already_resident,
            "parity_divergence": report.parity_divergence}


class ReplicatedClusters:
    def __init__(self, num_hosts: int = 1, num_shards: int = 4,
                 metadata: Optional[ClusterMetadata] = None,
                 active_stores=None, standby_stores=None) -> None:
        self.meta = metadata or ClusterMetadata()
        self.active = Onebox(num_hosts=num_hosts, num_shards=num_shards,
                             cluster_name="primary", stores=active_stores)
        self.standby = Onebox(num_hosts=num_hosts, num_shards=num_shards,
                              cluster_name="standby", stores=standby_stores)
        self.publisher = ReplicationPublisher(self.active.stores)
        self.active.set_replication_publisher(self.publisher)
        self.replicator = HistoryReplicator(self.standby.stores,
                                            rebuilder=self.standby.rebuilder,
                                            notifier=self.standby.notifier)
        self.processor = ReplicationTaskProcessor(
            self.replicator, self.publisher, self.standby.stores,
            source_history_reader=self._read_source_history,
            tpu=self.standby.tpu)
        self.processor.metrics = self.standby.metrics
        # snapshot-shipping replication: every record the active side's
        # post-append policy writes rides the same stream, so the
        # standby's cold admits and its promotion are suffix replays
        self.active.tpu.snapshotter().shipper = (
            lambda rec: self.publisher.publish_snapshot(rec, "primary"))
        # reverse direction (standby → active): every cluster in an NDC
        # group both publishes and consumes (task_fetcher.go polls every
        # remote cluster); needed for post-split-brain reconciliation
        self.reverse_publisher = ReplicationPublisher(self.standby.stores)
        self.standby.set_replication_publisher(self.reverse_publisher)
        self.reverse_replicator = HistoryReplicator(
            self.active.stores, rebuilder=self.active.rebuilder,
            notifier=self.active.notifier)
        self.reverse_processor = ReplicationTaskProcessor(
            self.reverse_replicator, self.reverse_publisher,
            self.active.stores,
            source_history_reader=self._read_standby_history,
            tpu=self.active.tpu)
        self.reverse_processor.metrics = self.active.metrics
        self.standby.tpu.snapshotter().shipper = (
            lambda rec: self.reverse_publisher.publish_snapshot(
                rec, "standby"))
        # domain-metadata replication (common/domain/replication_queue.go
        # + worker/replicator): active-side domain mutations stream to the
        # standby, which recomputes is_active from its own cluster name
        from .domainrepl import (
            DomainReplicationProcessor,
            DomainReplicationPublisher,
        )
        self.domain_publisher = DomainReplicationPublisher(self.active.stores)
        self.active.frontend.domain_replication_publisher = self.domain_publisher
        self.domain_processor = DomainReplicationProcessor(
            self.active.stores, self.standby.stores, "standby")
        self.reverse_domain_publisher = DomainReplicationPublisher(
            self.standby.stores)
        self.standby.frontend.domain_replication_publisher = (
            self.reverse_domain_publisher)
        self.reverse_domain_processor = DomainReplicationProcessor(
            self.standby.stores, self.active.stores, "primary")
        # cross-cluster task executors (cross_cluster_task_processor.go):
        # operations whose TARGET domain is active on the peer park on a
        # per-target queue; the peer's processor executes them and the
        # result applies back on the source workflow
        from .crosscluster import CrossClusterProcessor, CrossClusterPublisher
        self.cross_cluster_publisher = CrossClusterPublisher(self.active.stores)
        for p in self.active.processors:
            p.cross_cluster_publisher = self.cross_cluster_publisher
        self.reverse_cross_cluster_publisher = CrossClusterPublisher(
            self.standby.stores)
        for p in self.standby.processors:
            p.cross_cluster_publisher = self.reverse_cross_cluster_publisher
        # one consumer per (source store × executing cluster): the two
        # cross pairs carry normal traffic; the two SELF pairs drain tasks
        # re-homed after a failover flipped the target domain back
        def _xc(source_box, exec_box, exec_name):
            return CrossClusterProcessor(
                source_box.stores, exec_box.route, source_box.route,
                exec_name, target_stores=exec_box.stores)
        self.cross_cluster_processor = _xc(self.active, self.standby,
                                           "standby")
        self.reverse_cross_cluster_processor = _xc(self.standby, self.active,
                                                   "primary")
        self._self_cross_cluster_processors = [
            _xc(self.active, self.active, "primary"),
            _xc(self.standby, self.standby, "standby"),
        ]

    def _read_source_history(self, domain_id: str, workflow_id: str,
                             run_id: str, from_event_id: int,
                             to_event_id: int) -> List[HistoryBatch]:
        """Admin GetWorkflowExecutionRawHistoryV2 analog for the resender."""
        batches = self.active.stores.history.as_history_batches(
            domain_id, workflow_id, run_id)
        return [b for b in batches
                if from_event_id <= b.events[0].id < to_event_id]

    def _read_standby_history(self, domain_id: str, workflow_id: str,
                              run_id: str, from_event_id: int,
                              to_event_id: int) -> List[HistoryBatch]:
        batches = self.standby.stores.history.as_history_batches(
            domain_id, workflow_id, run_id)
        return [b for b in batches
                if from_event_id <= b.events[0].id < to_event_id]

    def register_global_domain(self, name: str, retention_days: int = 1) -> str:
        version = self.meta.initial_failover_version("primary")
        domain_id = self.active.frontend.register_domain(
            name, retention_days=retention_days, is_active=True,
            clusters=self.meta.cluster_names, active_cluster="primary",
            failover_version=version)
        self.standby.frontend.register_domain(
            name, retention_days=retention_days, is_active=False,
            clusters=self.meta.cluster_names, active_cluster="primary",
            failover_version=version, domain_id=domain_id)
        return domain_id

    def replicate(self) -> int:
        """Drain the replication stream into the standby (history AND
        domain metadata)."""
        total = self.domain_processor.process_once()
        while True:
            n = self.processor.process_once()
            total += n
            if n == 0:
                return total

    def replicate_domains(self) -> int:
        """Drain only the domain-metadata stream (both directions)."""
        return (self.domain_processor.process_once()
                + self.reverse_domain_processor.process_once())

    def replicate_reverse(self) -> int:
        """Drain the standby's outbound stream into the active cluster."""
        total = 0
        while True:
            n = self.reverse_processor.process_once()
            total += n
            if n == 0:
                return total

    def split_brain_promote(self, domain_name: str) -> int:
        """NON-graceful failover: ONLY the standby learns it is active (the
        old active keeps writing at its version) — the divergence generator
        for NDC conflict-resolution tests (host/ndc/integration_test.go
        crafts the same shape with conflicting event batches). Returns the
        standby's new failover version."""
        d = self.standby.stores.domain.by_name(domain_name)
        new_version = self.meta.next_failover_version(
            "standby", d.failover_version)
        d.failover_version = new_version
        d.active_cluster = "standby"
        d.is_active = True
        # notification-version ordering: a queued pre-promotion domain
        # task must never replay OVER this write on a receiving cluster
        d.notification_version += 1
        self.standby.stores.domain.update(d)
        _refresh_domain_tasks(self.standby, domain_name)
        return new_version

    def heal(self, domain_name: str, active_cluster: str = "standby") -> None:
        """Post-split-brain reconnection: converge domain metadata to the
        winner, then drain both replication directions so conflict
        resolution runs on both sides."""
        winner = (self.standby if active_cluster == "standby"
                  else self.active).stores.domain.by_name(domain_name)
        winner_nv = max(
            self.active.stores.domain.by_name(domain_name).notification_version,
            self.standby.stores.domain.by_name(domain_name).notification_version,
        ) + 1
        for box in (self.active, self.standby):
            d = box.stores.domain.by_name(domain_name)
            d.failover_version = winner.failover_version
            d.active_cluster = active_cluster
            d.is_active = box.cluster_name == active_cluster
            d.notification_version = winner_nv
            box.stores.domain.update(d)
        self.replicate()
        self.replicate_reverse()

    def process_cross_cluster(self) -> int:
        """Drain both clusters' parked cross-cluster tasks (including
        tasks re-homed after an intervening failover)."""
        total = (self.cross_cluster_processor.process_once()
                 + self.reverse_cross_cluster_processor.process_once())
        for proc in self._self_cross_cluster_processors:
            total += proc.process_once()
        return total

    def redirecting_frontend(self, cluster: str,
                             policy: str = "selected-apis-forwarding"):
        """The cluster-redirection wrapper for one side's frontend
        (clusterRedirectionHandler.go): global domains' active APIs
        forward to the active cluster."""
        from .redirection import ClusterRedirectionFrontend
        if cluster == "primary":
            local, remote = self.active.frontend, self.standby.frontend
            remotes = {"standby": remote}
        else:
            local, remote = self.standby.frontend, self.active.frontend
            remotes = {"primary": remote}
        return ClusterRedirectionFrontend(local, remotes, cluster,
                                          policy=policy)

    def failover(self, domain_name: str, to_cluster: str = "standby") -> int:
        """Graceful failover: bump the domain failover version into the
        target cluster's slot on BOTH clusters (domain metadata replication
        is synchronous here; the reference streams it via the worker
        replicator). Returns the new failover version."""
        current = self.active.stores.domain.by_name(domain_name).failover_version
        new_version = self.meta.next_failover_version(to_cluster, current)
        next_nv = max(
            self.active.stores.domain.by_name(domain_name).notification_version,
            self.standby.stores.domain.by_name(domain_name).notification_version,
        ) + 1
        for box in (self.active, self.standby):
            d = box.stores.domain.by_name(domain_name)
            d.failover_version = new_version
            d.active_cluster = to_cluster
            d.is_active = box.cluster_name == to_cluster
            # ahead of any queued pre-failover domain-replication task
            d.notification_version = next_nv
            box.stores.domain.update(d)
        # Standby promotion: the replicated state carries no tasks
        # (replication.py discards them), so every open workflow on the
        # newly-active cluster regenerates its outstanding tasks from
        # mutable state (RefreshTasks, mutable_state_task_refresher.go:77) —
        # without this, pre-failover pending work (in-flight activities,
        # user timers, pending decisions) never runs on the new active side.
        promoted = self.standby if to_cluster == "standby" else self.active
        _refresh_domain_tasks(promoted, domain_name)
        return new_version
