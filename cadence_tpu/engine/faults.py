"""Persistence decorator tier: fault injection + call metrics.

Reference: common/persistence wraps every manager in decorators —
`persistenceErrorInjectionClients.go:51-101` (configurable error rates on
every call) and `persistenceMetricClients.go` (per-call counters/latency).
Here the same stacking wraps the store bundle's sub-stores in proxies:

    injector = FaultInjector(rate=0.1, seed=7)
    inject_faults(stores, injector)          # error-injection decorator
    instrument_stores(stores, metrics)       # metrics decorator

Injected failures raise TransientStoreError BEFORE the target method runs
(the reference injects on the client side of the store call), so a failed
write leaves the store untouched and the caller's retry semantics are
exercised for real.
"""
from __future__ import annotations

import random
from typing import Dict, Iterable, Optional, Tuple

from ..utils.metrics import MetricsRegistry

#: sub-stores of the bundle the decorators cover
STORE_NAMES = ("execution", "history", "task", "queue", "domain",
               "shard", "shard_tasks", "visibility")

#: read-ish prefixes skipped by default injection (the reference's config
#: can target any call; failing only mutations keeps tests deterministic)
_WRITE_PREFIXES = ("create", "update", "upsert", "append", "delete",
                   "insert", "enqueue", "fork", "set_", "record", "complete",
                   "lease", "restore", "drop")


class TransientStoreError(Exception):
    """Injected store failure (the retryable persistence error class)."""


class FaultInjector:
    """Decides which store calls fail.

    Two modes, combinable:
    - `rate`: every targeted call fails with probability `rate` (seeded
      RNG — runs are reproducible);
    - `fail_next(store, method, times)`: scripted deterministic failures
      for targeted tests.
    """

    def __init__(self, rate: float = 0.0, seed: int = 0,
                 writes_only: bool = True) -> None:
        self.rate = rate
        self.writes_only = writes_only
        self._rng = random.Random(seed)
        self._scripted: Dict[Tuple[str, str], int] = {}
        self.injected = 0

    def fail_next(self, store: str, method: str, times: int = 1) -> None:
        self._scripted[(store, method)] = (
            self._scripted.get((store, method), 0) + times)

    def should_fail(self, store: str, method: str) -> bool:
        left = self._scripted.get((store, method), 0)
        if left > 0:
            self._scripted[(store, method)] = left - 1
            self.injected += 1
            return True
        if self.rate <= 0:
            return False
        if self.writes_only and not method.startswith(_WRITE_PREFIXES):
            return False
        if self._rng.random() < self.rate:
            self.injected += 1
            return True
        return False


class _StoreProxy:
    """Transparent method-intercepting wrapper over one sub-store."""

    def __init__(self, name: str, target, injector: Optional[FaultInjector],
                 metrics: Optional[MetricsRegistry]) -> None:
        object.__setattr__(self, "_name", name)
        object.__setattr__(self, "_target", target)
        object.__setattr__(self, "_injector", injector)
        object.__setattr__(self, "_metrics", metrics)

    def __getattr__(self, attr):
        value = getattr(object.__getattribute__(self, "_target"), attr)
        if not callable(value) or attr.startswith("__"):
            return value
        name = object.__getattribute__(self, "_name")
        injector = object.__getattribute__(self, "_injector")
        metrics = object.__getattribute__(self, "_metrics")

        def wrapped(*args, **kwargs):
            if injector is not None and injector.should_fail(name, attr):
                if metrics is not None:
                    metrics.inc(f"persistence.{name}", "errors-injected")
                raise TransientStoreError(
                    f"injected failure: {name}.{attr}")
            if metrics is not None:
                metrics.inc(f"persistence.{name}", "requests")
                try:
                    return value(*args, **kwargs)
                except Exception:
                    metrics.inc(f"persistence.{name}", "errors")
                    raise
            return value(*args, **kwargs)

        return wrapped

    def __setattr__(self, attr, value) -> None:
        # attach_wal and friends mutate sub-store state; forward it
        setattr(object.__getattribute__(self, "_target"), attr, value)


def inject_faults(stores, injector: FaultInjector,
                  names: Iterable[str] = STORE_NAMES,
                  metrics: Optional[MetricsRegistry] = None) -> None:
    """Wrap the bundle's sub-stores with the error-injection decorator
    (persistenceErrorInjectionClients.go analog). Mutates the bundle in
    place — every component resolving stores.<name> dynamically sees the
    decorated store."""
    for name in names:
        target = getattr(stores, name)
        setattr(stores, name, _StoreProxy(name, target, injector, metrics))


def instrument_stores(stores, metrics: MetricsRegistry,
                      names: Iterable[str] = STORE_NAMES) -> None:
    """Metrics-only decorator (persistenceMetricClients.go analog)."""
    for name in names:
        target = getattr(stores, name)
        setattr(stores, name, _StoreProxy(name, target, None, metrics))
