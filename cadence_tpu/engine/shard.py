"""Shard context: per-shard metadata, task ID allocation, range-ID fencing.

Reference: service/history/shard/context.go — the shard owns a range ID
renewed on acquisition (renewRangeLocked:1068); every persistence write is
fenced by it so a stale owner self-closes; transfer task IDs are allocated
from range-scoped blocks (GenerateTransferTaskID:68); ack levels checkpoint
queue progress in ShardInfo (dataManagerInterfaces.go:275-295).
"""
from __future__ import annotations

import threading
from typing import List, Optional

from ..oracle.mutable_state import GeneratedTask, MutableState
from .persistence import ShardInfo, ShardOwnershipLostError, Stores

# rangeSizeBits analog: each range owns this many task IDs
RANGE_SIZE = 1 << 20


class ShardContext:
    def __init__(self, shard_id: int, owner: str, stores: Stores) -> None:
        self.shard_id = shard_id
        self.owner = owner
        self._stores = stores
        self._lock = threading.RLock()
        self._info: Optional[ShardInfo] = None
        self._next_task_id = 0
        self._max_task_id = 0
        self._closed = False

    # -- lifecycle ---------------------------------------------------------

    def acquire(self) -> None:
        """Take ownership: bump range ID (renewRangeLocked)."""
        with self._lock:
            info = self._stores.shard.get_or_create(self.shard_id)
            prev_range = info.range_id
            prev_owner = info.owner
            info.range_id += 1
            info.owner = self.owner
            self._stores.shard.update(info, expected_range_id=prev_range)
            self._info = info
            self._next_task_id = info.range_id * RANGE_SIZE
            self._max_task_id = (info.range_id + 1) * RANGE_SIZE
            self._closed = False
        from ..utils.log import DEFAULT_LOGGER
        DEFAULT_LOGGER.info("shard acquired", component="shard",
                            shard_id=self.shard_id, owner=self.owner,
                            previous_owner=prev_owner or "<none>",
                            range_id=info.range_id)

    def _renew_range_locked(self) -> None:
        """Fresh task-ID block for the CURRENT owner: the CAS is against our
        cached range ID, so a deposed owner fails with ShardOwnershipLost
        instead of silently re-stealing the shard (shard/context.go:1068)."""
        info = ShardInfo(**vars(self._info))
        expected = info.range_id
        info.range_id += 1
        try:
            self._stores.shard.update(info, expected_range_id=expected)
        except ShardOwnershipLostError:
            self._closed = True
            raise
        self._info = info
        self._next_task_id = info.range_id * RANGE_SIZE
        self._max_task_id = (info.range_id + 1) * RANGE_SIZE

    def close(self) -> None:
        with self._lock:
            self._closed = True

    @property
    def is_closed(self) -> bool:
        """True once this context was deposed (fenced) or released — the
        controller evicts and re-acquires such contexts."""
        with self._lock:
            return self._closed

    @property
    def range_id(self) -> int:
        with self._lock:
            self._ensure_open()
            return self._info.range_id

    def _ensure_open(self) -> None:
        if self._closed or self._info is None:
            raise ShardOwnershipLostError(f"shard {self.shard_id} closed")

    # -- task IDs ----------------------------------------------------------

    def generate_task_id(self) -> int:
        """GenerateTransferTaskID: monotonic within the owned range."""
        with self._lock:
            self._ensure_open()
            if self._next_task_id >= self._max_task_id:
                self._renew_range_locked()
            tid = self._next_task_id
            self._next_task_id += 1
            return tid

    # -- fenced persistence ------------------------------------------------

    def create_workflow(self, ms: MutableState) -> None:
        with self._lock:
            self._ensure_open()
            try:
                self._stores.execution.create_workflow(
                    self.shard_id, self._info.range_id, ms
                )
            except ShardOwnershipLostError:
                self._closed = True
                raise

    def append_history(self, domain_id: str, workflow_id: str, run_id: str,
                       events, branch=None, blob=None) -> None:
        """Fenced history append: a deposed owner must NOT reach the
        history store — with node-overwrite append semantics a stale
        writer could truncate committed events before its state update
        hits the range fence. Ownership is re-validated against the shard
        store's CURRENT range id, the same check every write makes."""
        with self._lock:
            self._ensure_open()
            current = self._stores.shard.get_or_create(self.shard_id)
            if current.range_id != self._info.range_id:
                self._closed = True
                raise ShardOwnershipLostError(
                    f"shard {self.shard_id}: append fenced (range "
                    f"{self._info.range_id} != {current.range_id})")
            self._stores.history.append_batch(domain_id, workflow_id,
                                              run_id, events, branch=branch,
                                              blob=blob)

    def update_workflow(self, ms: MutableState,
                        expected_next_event_id: int) -> int:
        """Returns the store's new per-key write version (the execution
        cache's writeback token)."""
        with self._lock:
            self._ensure_open()
            try:
                return self._stores.execution.update_workflow(
                    self.shard_id, self._info.range_id, ms, expected_next_event_id
                )
            except ShardOwnershipLostError:
                self._closed = True
                raise

    def commit_workflow(self, ms: MutableState, expected_next_event_id: int,
                        events, transfer: List[GeneratedTask],
                        timer: List[GeneratedTask],
                        events_blob: Optional[bytes] = None) -> None:
        """Atomic transaction commit: events → tasks → fenced state update
        under ONE shard lock hold, with the state CAS prechecked first.

        The reference write order (execution/context.go:105) appends events
        before the conditional state update; it is safe there because the
        per-workflow context lock (execution/cache.go:182) serializes
        writers of the same workflow. This engine has no context cache, so
        the shard lock plays that role — and the precheck makes a
        concurrent loser fail BEFORE its append can truncate the winner's
        committed history tail (append_batch node-overwrite semantics)."""
        info = ms.execution_info
        with self._lock:
            self._ensure_open()
            self._stores.execution.check_next_event_id(
                info.domain_id, info.workflow_id, info.run_id,
                expected_next_event_id)
            self.append_history(info.domain_id, info.workflow_id,
                                info.run_id, events, blob=events_blob)
            self.insert_tasks(info.domain_id, info.workflow_id, info.run_id,
                              transfer, timer)
            return self.update_workflow(ms, expected_next_event_id)

    # -- shard task queues -------------------------------------------------

    def insert_tasks(self, domain_id: str, workflow_id: str, run_id: str,
                     transfer: List[GeneratedTask],
                     timer: List[GeneratedTask]) -> None:
        """Persist generated tasks into the shard's durable queues, stamping
        task IDs (shard/context.go allocates task IDs inside the update
        transaction); rows survive this owner's death."""
        with self._lock:
            self._ensure_open()
            self._stores.shard_tasks.insert_transfer(self.shard_id, [
                (self.generate_task_id(), domain_id, workflow_id, run_id, t)
                for t in transfer
            ])
            self._stores.shard_tasks.insert_timer(self.shard_id, [
                (t.visibility_timestamp, self.generate_task_id(),
                 domain_id, workflow_id, run_id, t)
                for t in timer
            ])

    def read_transfer_tasks(self, ack_level: int, batch: int = 100) -> List[tuple]:
        return self._stores.shard_tasks.read_transfer(self.shard_id, ack_level,
                                                      batch)

    def read_timer_tasks(self, now_nanos: int, ack_level: int,
                         batch: int = 100) -> List[tuple]:
        return self._stores.shard_tasks.read_timer_due(self.shard_id, now_nanos,
                                                       batch)

    def update_transfer_ack_level(self, level: int) -> None:
        with self._lock:
            self._ensure_open()
            info = self._info
            info.transfer_ack_level = max(info.transfer_ack_level, level)
            self._stores.shard.update(info, expected_range_id=info.range_id)
            self._stores.shard_tasks.complete_transfer_below(self.shard_id,
                                                             info.transfer_ack_level)

    def update_timer_ack_level(self, task_id: int) -> None:
        with self._lock:
            self._ensure_open()
            self._stores.shard_tasks.complete_timer(self.shard_id, task_id)

    @property
    def transfer_ack_level(self) -> int:
        with self._lock:
            self._ensure_open()
            return self._info.transfer_ack_level

    @property
    def transfer_queue_states(self) -> list:
        with self._lock:
            self._ensure_open()
            return [list(q) for q in self._info.transfer_queue_states]

    def update_transfer_queue_states(self, states: list,
                                     min_ack: int) -> None:
        """Persist every processing queue's (level, ack, filter) plus the
        GC floor = min over queues — the fenced write the next owner
        resumes from (queue/interface.go ProcessingQueueState)."""
        with self._lock:
            self._ensure_open()
            info = self._info
            info.transfer_queue_states = [list(q) for q in states]
            info.transfer_ack_level = max(info.transfer_ack_level, min_ack)
            self._stores.shard.update(info, expected_range_id=info.range_id)
            self._stores.shard_tasks.complete_transfer_below(
                self.shard_id, info.transfer_ack_level)
