"""Size and count limits with warn/error thresholds.

Reference: the limit checks threaded through the frontend and the
decision checker (service/history/decision/checker.go blob-size checks;
common/util.go CheckEventBlobSizeLimit) and the history size/count
enforcement that TERMINATES a workflow whose history outgrows the store's
contract (host/size_limit_test.go): exceeding warn logs + counts,
exceeding error refuses the write (blobs) or terminates the run
(history growth) — growth without bounds is how one workflow takes down
a shard.
"""
from __future__ import annotations

from ..utils.log import DEFAULT_LOGGER
from ..utils.metrics import DEFAULT_REGISTRY

TERMINATE_REASON = "history limit exceeded"


class LimitExceededError(Exception):
    """Request refused: a payload/size limit was breached
    (types.LimitExceededError / EntityNotExistsError in the reference)."""


def check_blob_size(payload: bytes, config, api: str, domain: str = "",
                    metrics=None, log=None) -> None:
    """Warn past the warn threshold; REFUSE past the error threshold
    (CheckEventBlobSizeLimit)."""
    from ..utils.dynamicconfig import (
        KEY_BLOB_SIZE_LIMIT_ERROR,
        KEY_BLOB_SIZE_LIMIT_WARN,
    )
    size = len(payload or b"")
    error_limit = int(config.get(KEY_BLOB_SIZE_LIMIT_ERROR, domain=domain))
    warn_limit = int(config.get(KEY_BLOB_SIZE_LIMIT_WARN, domain=domain))
    if error_limit and size > error_limit:
        (metrics or DEFAULT_REGISTRY).inc("limits", "blob-size-exceeded")
        raise LimitExceededError(
            f"{api}: payload {size}B exceeds the {error_limit}B blob limit")
    if warn_limit and size > warn_limit:
        (metrics or DEFAULT_REGISTRY).inc("limits", "blob-size-warnings")
        (log or DEFAULT_LOGGER).warning(
            "payload above warn threshold", api=api, domain=domain,
            size=size, warn_limit=warn_limit)


def history_limits(config, domain: str = ""):
    """(count_warn, count_error, size_warn, size_error) for one domain."""
    from ..utils.dynamicconfig import (
        KEY_HISTORY_COUNT_LIMIT_ERROR,
        KEY_HISTORY_COUNT_LIMIT_WARN,
        KEY_HISTORY_SIZE_LIMIT_ERROR,
        KEY_HISTORY_SIZE_LIMIT_WARN,
    )
    return (int(config.get(KEY_HISTORY_COUNT_LIMIT_WARN, domain=domain)),
            int(config.get(KEY_HISTORY_COUNT_LIMIT_ERROR, domain=domain)),
            int(config.get(KEY_HISTORY_SIZE_LIMIT_WARN, domain=domain)),
            int(config.get(KEY_HISTORY_SIZE_LIMIT_ERROR, domain=domain)))
