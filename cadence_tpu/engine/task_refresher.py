"""Task refresher: recompute all transfer/timer tasks from mutable state.

Reference: service/history/execution/mutable_state_task_refresher.go:77
(RefreshTasks) — called when a workflow changes hands: standby promotion
after failover, state rebuild, admin refresh. A standby applies replicated
state with no tasks (the replicator discards them, replication.py), so a
promoted standby must regenerate every dispatchable task — pending decision,
unstarted activities, user/activity timers, unstarted children, undelivered
external cancels/signals, the workflow-timeout timer — or pre-existing work
silently stalls after failover.

The refresher appends into ms.transfer_tasks / ms.timer_tasks exactly like
replay-time generation; the caller (HistoryEngine.refresh_tasks) drains
them into the owning shard's durable queues.
"""
from __future__ import annotations

from typing import Dict

from ..core.enums import (
    EMPTY_EVENT_ID,
    TIMER_TASK_STATUS_NONE,
    TimerTaskType,
    TransferTaskType,
    WorkflowState,
)
from ..core.events import HistoryEvent
from ..oracle import task_generator as taskgen
from ..oracle.mutable_state import GeneratedTask, MutableState, seconds_to_nanos


def sweep_refresh(stores, route, domain_id: str = None) -> int:
    """Refresh every CURRENT run (one domain, or all when domain_id is
    None): the promotion sweep after failover and the post-recovery sweep
    share this. Completed runs are included — their close fan-out /
    retention timer may not have run on this cluster yet. Zombie runs
    (not holding the current-run pointer after NDC arbitration) are
    skipped: refreshing them would execute a losing run. Returns the
    number of tasks created."""
    import time

    from .controller import ShardNotOwnedError
    from .persistence import EntityNotExistsError, ShardOwnershipLostError
    created = 0
    for d_id, wf_id, run_id in stores.execution.list_executions():
        if domain_id is not None and d_id != domain_id:
            continue
        try:
            if stores.execution.get_current_run_id(d_id, wf_id) != run_id:
                continue
        except EntityNotExistsError:
            continue
        # promotion sweeps run exactly while shards are changing hands, so
        # a fenced write (stale ring view on the routed host) is a ROUTINE
        # transient here, not a failure: the fence rejected the whole
        # update, and refresh is idempotent, so re-route and retry
        for attempt in range(8):
            try:
                created += route(wf_id).refresh_tasks(d_id, wf_id, run_id)
                break
            except (ShardOwnershipLostError, ShardNotOwnedError):
                if attempt == 7:
                    raise
                time.sleep(0.25 * (attempt + 1))
    return created


def refresh_tasks(ms: MutableState, events_by_id: Dict[int, HistoryEvent]) -> None:
    """Recompute every outstanding task from mutable state
    (mutable_state_task_refresher.go:77 RefreshTasks).

    `events_by_id` is the events-cache analog: external cancel/signal
    targets live only in their initiated events (the reference's refresher
    reads them through the events cache too, task_refresher.go:365-437).
    """
    info = ms.execution_info

    if info.state == WorkflowState.Completed:
        # refreshTasksForWorkflowClose: the close fan-out may not have run
        # on this cluster yet; CloseExecution delivery is idempotent
        # (visibility upsert; parent notification no-ops once resolved)
        ms.add_transfer_task(GeneratedTask(
            kind="transfer", task_type=TransferTaskType.CloseExecution,
            version=ms.current_version))
        retention_nanos = ms.domain_entry.retention_days * 24 * 3600 * 1_000_000_000
        close_ts = info.start_timestamp
        completion = events_by_id.get(info.next_event_id - 1)
        if completion is not None:
            close_ts = completion.timestamp
        ms.add_timer_task(GeneratedTask(
            kind="timer", task_type=TimerTaskType.DeleteHistoryEvent,
            version=ms.current_version,
            visibility_timestamp=close_ts + retention_nanos))
        return

    # refreshTasksForWorkflowStart: workflow-timeout timer + (when the first
    # decision is still pending its backoff) the backoff timer
    ms.add_timer_task(GeneratedTask(
        kind="timer", task_type=TimerTaskType.WorkflowTimeout,
        version=ms.current_version,
        visibility_timestamp=info.start_timestamp
        + seconds_to_nanos(info.workflow_timeout)))
    start_event = events_by_id.get(1)
    if (info.decision_schedule_id == EMPTY_EVENT_ID and start_event is not None
            and (start_event.get("first_decision_task_backoff_seconds", 0) or 0) > 0):
        taskgen.generate_delayed_decision_tasks(ms, start_event)

    # refreshTasksForRecordWorkflowStarted (visibility upsert is idempotent)
    ms.add_transfer_task(GeneratedTask(
        kind="transfer", task_type=TransferTaskType.RecordWorkflowStarted,
        version=ms.current_version))

    # refreshTasksForDecision (task_refresher.go:219-258)
    if info.decision_schedule_id != EMPTY_EVENT_ID:
        if info.decision_started_id == EMPTY_EVENT_ID:
            taskgen.generate_decision_schedule_tasks(ms, info.decision_schedule_id)
        else:
            taskgen.generate_decision_start_tasks(ms, info.decision_schedule_id)

    # refreshTasksForActivity (:260-306): clear created-bits, re-dispatch
    # unstarted activities through the same generator as replay, recreate
    # the earliest activity timer
    for ai in ms.pending_activity_info_ids.values():
        ai.timer_task_status = TIMER_TASK_STATUS_NONE
        if ai.started_id == EMPTY_EVENT_ID and ai.schedule_id != EMPTY_EVENT_ID:
            event = events_by_id.get(ai.schedule_id)
            if event is not None:
                taskgen.generate_activity_transfer_tasks(ms, event)
    taskgen.generate_activity_timer_tasks(ms)

    # refreshTasksForTimer (:308-336)
    for ti in ms.pending_timer_info_ids.values():
        ti.task_status = TIMER_TASK_STATUS_NONE
    taskgen.generate_user_timer_tasks(ms)

    # refreshTasksForChildWorkflow (:338-363): unstarted children re-dispatch
    for ci in ms.pending_child_execution_info_ids.values():
        if ci.started_id == EMPTY_EVENT_ID:
            event = events_by_id.get(ci.initiated_id)
            if event is not None:
                taskgen.generate_child_workflow_tasks(ms, event)

    # refreshTasksForRequestCancelExternalWorkflow (:365-400)
    for rci in ms.pending_request_cancel_info_ids.values():
        event = events_by_id.get(rci.initiated_id)
        if event is not None:
            taskgen.generate_request_cancel_external_tasks(ms, event)

    # refreshTasksForSignalExternalWorkflow (:402-437)
    for si in ms.pending_signal_info_ids.values():
        event = events_by_id.get(si.initiated_id)
        if event is not None:
            taskgen.generate_signal_external_tasks(ms, event)
