"""Crashpoints: named kill-anywhere injection sites on the durability tier.

Reference discipline: the same install/env/dynamicconfig arming contract as
the transport chaos layer (rpc/chaos.py) and the store fault injector
(engine/faults.py), one layer further down — at the WRITE-AHEAD LOG itself.
A crashpoint simulates the process dying at an exact byte position in the
commit protocol:

- ``wal.append.before-write``  — nothing of the record reached the file;
- ``wal.append.mid-record``    — a torn write: a PREFIX of the record's
  bytes is flushed (and fsynced, so recovery really sees it), then the
  process dies mid-record (JSONL only; SQLite appends are transactional,
  so its mid-record site fires after the INSERT but before COMMIT — the
  row is invisible to recovery, the strongest torn-write analog it has);
- ``wal.append.after-write``   — the full record is buffered+flushed but
  not yet fsynced (the page-cache window a power loss can eat);
- ``wal.append.after-fsync``   — the record is durable; the crash hits
  after the commit point.

Store-level sites (``store.execution.create_workflow`` & co, fired at the
top of the compound commit methods in engine/persistence.py) kill BETWEEN
wal records of one logical transaction — e.g. after the history batch is
logged but before the current-run pointer is.

Two modes:

- ``raise``: raise ``SimulatedCrash`` (a BaseException, so no store-level
  ``except Exception`` can accidentally swallow the "process death" and
  keep committing). The harness then discards the in-memory bundle and
  recovers from the WAL file — the in-process crash/recovery loop
  CrashSim drives at every cut point;
- ``kill``: ``SIGKILL`` the current process — the subprocess mode the
  multiprocess tests drive through the rpc/cluster launch seam.

Configuration (cross-process, so subprocess store servers inherit it):

    CADENCE_TPU_CRASHPOINT="site=wal.append.after-write,hit=3,mode=kill"

optional ``type=h`` filters to one WAL record type, ``torn=0.3`` sets the
fraction of the record written at the mid-record site. The same spec
string rides dynamicconfig (KEY_CRASHPOINT) or installs programmatically
via ``install(CrashPoint(...))``.
"""
from __future__ import annotations

import os
import signal
import threading
from typing import Optional

from ..utils import flightrecorder

SITE_BEFORE_WRITE = "wal.append.before-write"
SITE_MID_RECORD = "wal.append.mid-record"
SITE_AFTER_WRITE = "wal.append.after-write"
SITE_AFTER_FSYNC = "wal.append.after-fsync"

WAL_SITES = (SITE_BEFORE_WRITE, SITE_MID_RECORD, SITE_AFTER_WRITE,
             SITE_AFTER_FSYNC)


class SimulatedCrash(BaseException):
    """The process "died" at a crashpoint. Deliberately a BaseException:
    the whole point is that no layer between the WAL and the harness may
    catch it and carry on as if the write had finished."""


class CrashPoint:
    """One armed crash site: fires on the `hit`-th matching pass, once."""

    def __init__(self, site: str, hit: int = 1, mode: str = "raise",
                 record_type: str = "", torn_fraction: float = 0.5) -> None:
        if mode not in ("raise", "kill"):
            raise ValueError(f"unknown crashpoint mode {mode!r}")
        self.site = site
        self.hit = max(1, hit)
        self.mode = mode
        self.record_type = record_type
        self.torn_fraction = min(max(torn_fraction, 0.0), 1.0)
        self.fired = False
        self._count = 0
        self._lock = threading.Lock()

    def should_fire(self, site: str, record: Optional[dict] = None) -> bool:
        """Count a pass through `site`; True exactly once, on pass `hit`."""
        if site != self.site:
            return False
        if self.record_type and (record is None
                                 or record.get("t") != self.record_type):
            return False
        with self._lock:
            if self.fired:
                return False
            self._count += 1
            if self._count == self.hit:
                self.fired = True
                return True
            return False

    def crash(self, detail: str = "") -> None:
        """Die, per mode. Never returns."""
        flightrecorder.emit("crashpoint-fire", site=self.site,
                            mode=self.mode, hit=self.hit, detail=detail)
        if self.mode == "kill":
            # SIGKILL runs no handler: the black box must write out NOW
            # or the post-mortem loses this process's entire timeline
            flightrecorder.dump_on_crash()
            os.kill(os.getpid(), signal.SIGKILL)
        raise SimulatedCrash(
            f"crashpoint {self.site}"
            f"{f' ({detail})' if detail else ''} hit {self.hit}")


# -- process-wide installation (mirrors rpc/chaos.py) -----------------------

_ACTIVE: Optional[CrashPoint] = None
_ENV = "CADENCE_TPU_CRASHPOINT"
_LOADED_ENV = False
_LOAD_LOCK = threading.Lock()


def parse_spec(spec: str) -> CrashPoint:
    """"site=wal.append.after-write,hit=3,mode=kill,type=h,torn=0.5"."""
    from ..rpc.chaos import parse_kv_spec
    kv = parse_kv_spec(spec, {"site": str, "hit": int, "mode": str,
                              "type": str, "torn": float})
    if "site" not in kv:
        raise ValueError(f"crashpoint spec {spec!r} needs site=")
    return CrashPoint(site=kv["site"], hit=kv.get("hit", 1),
                      mode=kv.get("mode", "raise"),
                      record_type=kv.get("type", ""),
                      torn_fraction=kv.get("torn", 0.5))


def install(point: Optional[CrashPoint]) -> None:
    """Programmatic installation (tests/CrashSim); None uninstalls."""
    global _ACTIVE, _LOADED_ENV
    if point is not None:
        flightrecorder.emit("crashpoint-arm", site=point.site,
                            mode=point.mode, hit=point.hit,
                            record_type=point.record_type)
    _ACTIVE = point
    _LOADED_ENV = True  # explicit choice overrides the env default


def uninstall() -> None:
    install(None)


def active() -> Optional[CrashPoint]:
    """The process's armed crashpoint, lazily loaded from the env on first
    use so subprocess store servers pick it up with zero plumbing."""
    global _ACTIVE, _LOADED_ENV
    if not _LOADED_ENV:
        with _LOAD_LOCK:
            if not _LOADED_ENV:
                spec = os.environ.get(_ENV, "")
                if spec:
                    _ACTIVE = parse_spec(spec)
                _LOADED_ENV = True
    return _ACTIVE


def fire(site: str, record: Optional[dict] = None) -> None:
    """Pass through a named site: crash here iff the armed point matches.
    The no-crashpoint fast path is one global read."""
    point = active()
    if point is not None and point.should_fire(site, record):
        point.crash()
