"""The TPU execution-engine plugin: bulk replay/verify on device.

This is the north-star component (BASELINE.json): alongside the per-workflow
engine path, a bulk path that reads MANY workflows' persisted histories,
packs them, replays them in lockstep on the accelerator, and compares the
resulting canonical checksum payloads against the live mutable states.

Reference seams it occupies:
- EngineFactory (shard/controller.go:55-58): constructed per controller and
  offered through it;
- stateRebuilder.Rebuild (execution/state_rebuilder.go:102): the bulk
  analog of single-workflow rebuild;
- scanner/reconciliation (common/reconciliation/invariant): verify_all is a
  concrete-execution invariant check executed on device;
- the mutable-state checksum (execution/checksum.go:36) is the comparison
  oracle on both sides.

Workflows whose histories exceed kernel capacities (pending tables, event
length) or trip the error flag fall back to the per-workflow oracle path —
measured and reported, never silent.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.checksum import (
    DEFAULT_LAYOUT,
    STICKY_ROW_INDEX,
    PayloadLayout,
    payload_row,
)
from ..oracle.state_builder import StateBuilder
from .persistence import Stores


@dataclass
class BulkVerifyResult:
    total: int
    verified_on_device: int
    divergent: List[Tuple[str, str, str]] = field(default_factory=list)
    fallback: List[Tuple[str, str, str]] = field(default_factory=list)
    device_errors: List[Tuple[Tuple[str, str, str], int]] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.divergent


class TPUReplayEngine:
    """Bulk device replay over persisted histories."""

    def __init__(self, stores: Stores,
                 layout: PayloadLayout = DEFAULT_LAYOUT) -> None:
        from ..utils.metrics import DEFAULT_REGISTRY
        self.stores = stores
        self.layout = layout
        self.metrics = DEFAULT_REGISTRY

    def _load_histories(self, keys: Sequence[Tuple[str, str, str]]):
        return [
            self.stores.history.as_history_batches(*key) for key in keys
        ]

    def tree_segments(self, key: Tuple[str, str, str]) -> list:
        """One run's full branch tree as encode_segments input: the current
        branch's lineage replays state-carrying; every other branch's
        events beyond the shared prefix are emitted VH-only with
        fork-inheritance from the current branch — the device then holds
        the complete VersionHistories (winner state + loser branch items),
        matching the post-conflict-resolution mutable state
        (ndc/conflict_resolver.go + versionHistories.go on device)."""
        from ..core.events import HistoryBatch

        hs = self.stores.history
        current = hs.get_current_branch(*key)
        cur_lineage = hs.as_history_batches(*key, branch=current)
        segments = [(cur_lineage, current, current, False)]
        cur_events = [e for b in cur_lineage for e in b.events]
        for index in range(hs.branch_count(*key)):
            if index == current:
                continue
            events = hs.read_events(*key, branch=index)
            shared = 0
            while (shared < min(len(events), len(cur_events))
                   and events[shared].id == cur_events[shared].id
                   and events[shared].version == cur_events[shared].version):
                shared += 1
            unique = events[shared:]
            if not unique:
                continue
            segments.append((
                [HistoryBatch(domain_id=key[0], workflow_id=key[1],
                              run_id=key[2], events=unique)],
                index, current, True,
            ))
        return segments

    def replay_tree_payloads(self, keys: Sequence[Tuple[str, str, str]]
                             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Device-replay full branch trees (divergent histories included);
        returns (payload rows, errors, device-chosen current branch).

        Each launch is decomposed into pack/h2d/kernel/readback legs by a
        ReplayProfiler, so the end-to-end latency timer can be diffed
        leg-by-leg from any scrape."""
        import jax
        import jax.numpy as jnp

        from ..ops.encode import encode_segment_corpus
        from ..ops.payload import payload_rows
        from ..ops.replay import replay_events

        from ..utils import metrics as m
        from ..utils.profiler import ReplayProfiler
        scope = self.metrics.scope(m.SCOPE_TPU_REPLAY)
        prof = ReplayProfiler(self.metrics)
        with prof.leg(m.M_PROFILE_PACK):
            corpus = encode_segment_corpus(
                [self.tree_segments(k) for k in keys])
        real_events = int((corpus[:, :, 0] > 0).sum())
        scope.inc(m.M_KERNEL_LAUNCHES)
        scope.inc(m.M_EVENTS_REPLAYED, real_events)
        with scope.timed():
            with prof.leg(m.M_PROFILE_H2D):
                device_corpus = jax.device_put(jnp.asarray(corpus))
                prof.h2d(corpus.nbytes)
            with prof.leg(m.M_PROFILE_KERNEL):
                state = replay_events(device_corpus, self.layout)
                rows_dev = payload_rows(state, self.layout)
                jax.block_until_ready(rows_dev)
            with prof.leg(m.M_PROFILE_READBACK):
                rows = np.asarray(rows_dev)
                errors = np.asarray(state.error)
        t = self.metrics.timer(m.SCOPE_TPU_REPLAY, m.M_LATENCY)
        if t.total_s > 0:
            self.metrics.gauge(
                m.SCOPE_TPU_REPLAY, m.M_REPLAY_THROUGHPUT,
                self.metrics.counter(m.SCOPE_TPU_REPLAY, m.M_EVENTS_REPLAYED)
                / t.total_s)
        return (rows, errors, np.asarray(state.current_branch))

    def verify_all(self, keys: Optional[Sequence[Tuple[str, str, str]]] = None
                   ) -> BulkVerifyResult:
        """Replay persisted histories on device and compare against the live
        mutable states (zero-divergence contract). Errored rows are re-run
        through the oracle (per-workflow fallback path)."""
        if keys is None:
            keys = self.stores.execution.list_executions()
        keys = list(keys)
        if not keys:
            return BulkVerifyResult(total=0, verified_on_device=0)
        rows, errors, device_branch = self.replay_tree_payloads(keys)

        result = BulkVerifyResult(total=len(keys), verified_on_device=0)
        for i, key in enumerate(keys):
            live_ms = self.stores.execution.get_workflow(*key)
            expected = payload_row(live_ms, self.layout)
            # sticky state is active-side only; replay clears it
            # (STICKY_ROW_INDEX note in core/checksum.py)
            expected[STICKY_ROW_INDEX] = 0
            if errors[i] != 0:
                # device flagged this workflow: oracle fallback
                result.device_errors.append((key, int(errors[i])))
                result.fallback.append(key)
                oracle_ms = StateBuilder().replay_history(
                    self.stores.history.as_history_batches(*key))
                if not (payload_row(oracle_ms, self.layout) == expected).all():
                    result.divergent.append(key)
            else:
                result.verified_on_device += 1
                if not (rows[i] == expected).all():
                    result.divergent.append(key)
                elif device_branch[i] != live_ms.version_histories.current_index:
                    # device-side branch arbitration must agree with the
                    # store's conflict-resolution outcome
                    result.divergent.append(key)
        return result
