"""The TPU execution-engine plugin: bulk replay/verify on device.

This is the north-star component (BASELINE.json): alongside the per-workflow
engine path, a bulk path that reads MANY workflows' persisted histories,
packs them, replays them in lockstep on the accelerator, and compares the
resulting canonical checksum payloads against the live mutable states.

Reference seams it occupies:
- EngineFactory (shard/controller.go:55-58): constructed per controller and
  offered through it;
- stateRebuilder.Rebuild (execution/state_rebuilder.go:102): the bulk
  analog of single-workflow rebuild;
- scanner/reconciliation (common/reconciliation/invariant): verify_all is a
  concrete-execution invariant check executed on device;
- the mutable-state checksum (execution/checksum.go:36) is the comparison
  oracle on both sides.

The hot path runs on the pipelined bulk-replay executor
(engine/executor.py): keys are CHUNKED (bounding peak host+HBM footprint —
one long-tail history no longer sizes the whole corpus), host packing of
chunk N+1 overlaps the device replay of chunk N, per-workflow encoded
lanes come from the content-addressed pack cache (engine/cache.PackCache —
a warm re-verify of an unchanged corpus skips repacking entirely; an
appended batch repacks only the suffix), and verify_all compares payload
rows ON DEVICE, reading back a mismatch bitmap plus the error lanes
instead of the full [W, width] tensor.

Workflows whose histories exceed kernel capacities no longer fall off to
the per-workflow oracle: capacity-flagged rows gather into a compact
sub-corpus and re-replay ON DEVICE at widened K through the escalation
ladder (engine/ladder.py; rung-1 dispatch rides the executor's escalate
hook, overlapping later chunks' pack/replay). Only rows that still
overflow at the top rung — or whose error no capacity can fix — arbitrate
through the oracle, measured and reported under `tpu.fallback/*`.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core.checksum import (
    DEFAULT_LAYOUT,
    STICKY_ROW_INDEX,
    PayloadLayout,
    payload_row,
)
from ..oracle.state_builder import StateBuilder
from ..ops.encode import (
    LANE_EVENT_ID,
    LANE_EVENT_TYPE,
    NUM_LANES,
    assemble_corpus,
    encode_segments,
    gather_subcorpus,
)
from ..ops.payload import payload_rows
from ..ops.replay import replay_events, verify_rows
from ..utils import metrics as m
from ..utils.profiler import ReplayProfiler
from . import resident as resident_mod
from .cache import PackCache, content_address
from .executor import BulkReplayExecutor
from .ladder import EscalationLadder
from .persistence import Stores
from .resident import ResidentStateCache

#: max workflows per device launch on the bulk path; bounds peak host
#: corpus bytes and HBM per chunk (the regression the chunked executor
#: fixes: one [W, E_max, L] corpus sized by the longest history)
CHUNK_ENV = "CADENCE_TPU_REPLAY_CHUNK"
DEFAULT_CHUNK = 4096


def _bucket_events(n: int) -> int:
    """Round the chunk's event axis up to a power of two (min 16): chunks
    with similar histories share one compiled executable instead of one
    per exact max length, and padding rows are no-ops in the kernel."""
    return max(16, 1 << (max(1, int(n)) - 1).bit_length())


@dataclass
class BulkVerifyResult:
    total: int
    verified_on_device: int
    divergent: List[Tuple[str, str, str]] = field(default_factory=list)
    #: keys arbitrated by the per-workflow oracle: the escalation
    #: ladder's RESIDUE (top-rung overflow or non-capacity errors) —
    #: before the ladder this held every device-flagged key
    fallback: List[Tuple[str, str, str]] = field(default_factory=list)
    device_errors: List[Tuple[Tuple[str, str, str], int]] = field(default_factory=list)
    #: keys resolved ON DEVICE by the widened-K re-replay ladder
    escalated: List[Tuple[str, str, str]] = field(default_factory=list)
    #: keys served from the HBM-resident state cache (exact hits replay
    #: nothing; suffix hits replay only the appended batches)
    resident: List[Tuple[str, str, str]] = field(default_factory=list)
    #: subset of `resident` whose entry was hydrated from a PERSISTED
    #: snapshot during this verify (engine/snapshot.py): the cold
    #: partition became a suffix partition for these keys
    snapshot: List[Tuple[str, str, str]] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.divergent


@dataclass
class _ChunkPlan:
    """One chunk of the mesh-aware serving run: which keys it carries
    (global indices into the run's key list), which corpus ROW each key
    occupies, and the padded workflow axis. On a mesh of 1 rows are the
    contiguous prefix (today's layout, byte for byte); on a mesh of N
    the chunk is N per-shard slices of P rows each — key k sits in slice
    workflow_shard(k, N), so sharded placement lands every workflow on
    its owning device and the resident pool stays device-local."""

    idx: List[int]
    rows: np.ndarray
    W: int


class TPUReplayEngine:
    """Bulk device replay over persisted histories, served from the
    device mesh (mesh of 1 = the single-chip configuration)."""

    def __init__(self, stores: Stores,
                 layout: PayloadLayout = DEFAULT_LAYOUT,
                 chunk_workflows: Optional[int] = None,
                 pipeline_depth: Optional[int] = None,
                 mesh=None) -> None:
        self.stores = stores
        self.layout = layout
        self.pack_cache = PackCache()
        self.ladder = EscalationLadder(layout)
        #: HBM-resident per-workflow states: verify_all serves unchanged
        #: workflows from the cache and replays only appended batches for
        #: suffix hits; full replay remains the cold-miss and
        #: parity-audit path (engine/resident.py). Sharded across the
        #: mesh with the engine (per-device slices, split budget).
        self.resident = ResidentStateCache(layout, ladder=self.ladder,
                                           pipeline_depth=pipeline_depth)
        self.metrics = m.DEFAULT_REGISTRY
        self.chunk_workflows = (chunk_workflows if chunk_workflows
                                else int(os.environ.get(CHUNK_ENV,
                                                        str(DEFAULT_CHUNK))))
        self.pipeline_depth = pipeline_depth
        #: serving mesh (parallel/mesh.serving_mesh resolves the
        #: CADENCE_TPU_MESH_DEVICES knob); resolved LAZILY so engine
        #: construction never forces JAX backend init
        self._mesh = mesh
        if mesh is not None:
            self._wire_mesh(mesh)
        #: (W, E) of each chunk of the last bulk run — the test seam for
        #: the bounded-footprint contract (a long-tail history inflates
        #: only its own chunk's E)
        self.last_run_chunk_shapes: List[Tuple[int, int]] = []
        #: lazy device-serving scheduler (engine/serving.py); created on
        #: first request so engines that never serve pay nothing
        self._serving = None
        #: lazy checksum-gated snapshot writer (engine/snapshot.py)
        self._snapshotter = None

    def serving_scheduler(self):
        """The micro-batching transaction scheduler bound to THIS
        engine's resident cache / pack cache / ladder / mesh — the
        device-serving tier clusters wire into their history engines
        (engine/serving.ServingScheduler). One per engine: the scheduler
        and verify_all must share the resident pool, or a transaction's
        append and a verify's admit could race different caches."""
        if self._serving is None:
            from .serving import ServingScheduler
            self._serving = ServingScheduler(self)
        return self._serving

    def snapshotter(self):
        """The checksum-gated snapshot writer bound to THIS engine's
        stores / resident pool / pack cache (engine/snapshot.Snapshotter)
        — one per engine for the same reason the serving scheduler is:
        writer and verify must share the resident pool."""
        if self._snapshotter is None:
            from .snapshot import Snapshotter
            self._snapshotter = Snapshotter(
                self.stores, self.resident, self.pack_cache, self.layout,
                registry=self.metrics)
        return self._snapshotter

    def snapshot_sweep(self, keys=None, force: bool = False):
        """Persist snapshots for every resident workflow (or `keys`):
        the deploy/admin warm-up verb — run after a verify pass seeds
        the pool, so the next restart is a warm start."""
        return self.snapshotter().sweep(keys=keys, force=force)

    @property
    def mesh(self):
        if self._mesh is None:
            from ..parallel.mesh import serving_mesh
            self._mesh = serving_mesh()
            self._wire_mesh(self._mesh)
        return self._mesh

    def _wire_mesh(self, mesh) -> None:
        """One mesh through every layer: the escalation ladder re-replays
        flagged rows under the same 'shard' axis (the already-sharded
        replay_sharded_escalated kernels) and the resident pool splits
        its HBM budget into per-device slices."""
        if int(mesh.devices.size) > 1:
            self.ladder.mesh = mesh
        self.resident.set_mesh(mesh)

    @property
    def mesh_size(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def metrics(self):
        return self._metrics

    @metrics.setter
    def metrics(self, registry) -> None:
        """Clusters wire their own registry post-construction (Onebox/
        ServiceHost set `tpu.metrics = ...`); the pack cache's hit/miss
        counters must land on the SAME registry or they never reach that
        cluster's /metrics scrape."""
        self._metrics = registry
        self.pack_cache.metrics = registry
        self.ladder.metrics = registry
        if hasattr(self, "resident"):
            self.resident.metrics = registry
        if getattr(self, "_serving", None) is not None:
            self._serving.metrics = registry
        if getattr(self, "_snapshotter", None) is not None:
            self._snapshotter.metrics = registry

    def _load_histories(self, keys: Sequence[Tuple[str, str, str]]):
        return [
            self.stores.history.as_history_batches(*key) for key in keys
        ]

    def tree_segments(self, key: Tuple[str, str, str]) -> list:
        """One run's full branch tree as encode_segments input: the current
        branch's lineage replays state-carrying; every other branch's
        events beyond the shared prefix are emitted VH-only with
        fork-inheritance from the current branch — the device then holds
        the complete VersionHistories (winner state + loser branch items),
        matching the post-conflict-resolution mutable state
        (ndc/conflict_resolver.go + versionHistories.go on device)."""
        from ..core.events import HistoryBatch

        hs = self.stores.history
        current = hs.get_current_branch(*key)
        cur_lineage = hs.as_history_batches(*key, branch=current)
        segments = [(cur_lineage, current, current, False)]
        cur_events = [e for b in cur_lineage for e in b.events]
        for index in range(hs.branch_count(*key)):
            if index == current:
                continue
            events = hs.read_events(*key, branch=index)
            shared = 0
            while (shared < min(len(events), len(cur_events))
                   and events[shared].id == cur_events[shared].id
                   and events[shared].version == cur_events[shared].version):
                shared += 1
            unique = events[shared:]
            if not unique:
                continue
            segments.append((
                [HistoryBatch(domain_id=key[0], workflow_id=key[1],
                              run_id=key[2], events=unique)],
                index, current, True,
            ))
        return segments

    def _encode_key_rows(self, key: Tuple[str, str, str]) -> np.ndarray:
        """One workflow's encoded [n, L] lane rows. Single-lineage
        histories go through the content-addressed pack cache (append-only
        ⇒ a warm re-verify reuses the rows; an appended batch packs only
        the suffix); multi-branch trees — post-conflict-resolution shapes
        that are not append-only in the cached sense — encode fresh."""
        hs = self.stores.history
        if hs.branch_count(*key) <= 1 and hs.get_current_branch(*key) == 0:
            return self.pack_cache.encode(
                key, hs.as_history_batches(*key))
        segs = self.tree_segments(key)
        total = sum(len(b.events) for seg in segs for b in seg[0])
        return encode_segments(segs, total)

    def _chunk_spans(self, n: int) -> List[Tuple[int, int]]:
        c = max(1, self.chunk_workflows)
        return [(lo, min(lo + c, n)) for lo in range(0, n, c)]

    def _plan_chunks(self, keys: List[Tuple[str, str, str]]
                     ) -> List[_ChunkPlan]:
        """Chunk the key list for the mesh. Mesh of 1: contiguous spans
        padded to the run-constant width — exactly the pre-mesh layout.
        Mesh of N: keys bucket by workflow_shard (the stable key→device
        hash mirroring numHistoryShards→host), each chunk takes up to P
        keys of EVERY bucket so row s*P+i belongs to shard s and sharded
        placement puts each workflow on its owning device."""
        n = self.mesh_size
        if n <= 1:
            pad_to = min(max(1, self.chunk_workflows), len(keys))
            return [_ChunkPlan(idx=list(range(lo, hi)),
                               rows=np.arange(hi - lo), W=pad_to)
                    for lo, hi in self._chunk_spans(len(keys))]
        from ..parallel.mesh import workflow_shard
        buckets: List[List[int]] = [[] for _ in range(n)]
        for i, key in enumerate(keys):
            buckets[workflow_shard(key, n)].append(i)
        per = max(1, -(-self.chunk_workflows // n))
        P = min(per, max((len(b) for b in buckets), default=1))
        plans: List[_ChunkPlan] = []
        off = 0
        while any(len(b) > off for b in buckets):
            idx: List[int] = []
            rows: List[int] = []
            for s, b in enumerate(buckets):
                sl = b[off:off + P]
                idx.extend(sl)
                rows.extend(s * P + j for j in range(len(sl)))
            plans.append(_ChunkPlan(idx=idx, rows=np.asarray(rows,
                                                             dtype=np.int64),
                                    W=n * P))
            off += P
        return plans

    def _pack_chunk(self, chunk_keys: Sequence[Tuple[str, str, str]],
                    rows: np.ndarray, pad_to: int) -> np.ndarray:
        """Encode one chunk of keys into [pad_to, E, L], key j landing
        on corpus row rows[j] (its shard's slice); E is the pow2 bucket
        of THIS chunk's longest history, not the corpus-wide max — the
        bounded-memory contract. All other rows are padding (the kernel
        no-ops them)."""
        rows_list = [self._encode_key_rows(k) for k in chunk_keys]
        E = _bucket_events(max((r.shape[0] for r in rows_list), default=1))
        sub = assemble_corpus(rows_list, E)
        corpus = np.zeros((pad_to, E, NUM_LANES), dtype=np.int64)
        corpus[:, :, LANE_EVENT_TYPE] = -1
        corpus[np.asarray(rows)] = sub
        return corpus

    def _run_chunks(self, keys: List[Tuple[str, str, str]], pack_extra,
                    launch_fn, readback_fn, escalate_fn=None, plans=None):
        """Drive the pipelined executor over key chunks, fanned across
        the serving mesh (per-device H2D slice copies; a mesh of 1 is
        the single-chip configuration, byte for byte).

        pack_extra(chunk_keys, plan) -> host-side extras packed
        alongside the corpus (runs in the pack pool, overlapped with
        device compute; extras sized [plan.W, ...] in ROW space);
        launch_fn(corpus_dev, extras) -> device outs (async);
        readback_fn(outs) -> numpy results per chunk (row space);
        escalate_fn(ci, corpus_np, consumed) -> consumed — optional
        capacity-escalation seam: called right after chunk ci's readback
        with its HOST corpus (held only until then — at most `depth`
        corpora are ever retained, the ring bound), so flagged rows can
        gather and dispatch widened re-replays while later chunks still
        pack and replay.
        Returns (per-chunk results, per-chunk plans)."""
        from ..parallel.mesh import place_corpus

        if plans is None:
            plans = self._plan_chunks(keys)
        mesh = self.mesh
        prof = ReplayProfiler(self.metrics)
        scope = self.metrics.scope(m.SCOPE_TPU_REPLAY)
        executor = BulkReplayExecutor(depth=self.pipeline_depth,
                                      registry=self.metrics, mesh=mesh)
        shapes: List[Optional[Tuple[int, int]]] = [None] * len(plans)
        events: List[int] = [0] * len(plans)
        corpora: dict = {}

        n_dev = int(mesh.devices.size)

        def pack(ci):
            plan = plans[ci]
            chunk_keys = [keys[i] for i in plan.idx]
            corpus = self._pack_chunk(chunk_keys, plan.rows, plan.W)
            shapes[ci] = (corpus.shape[0], corpus.shape[1])
            events[ci] = int((corpus[:, :, LANE_EVENT_ID] > 0).sum())
            if n_dev > 1:
                # per-device real-row counters (shard-population skew is
                # a scrape away: tpu.executor/rows-dispatched-dev{d}),
                # scanned in the overlapped pack pool, off the serial
                # dispatch path
                exec_scope = self.metrics.scope(m.SCOPE_TPU_EXECUTOR)
                slice_w = corpus.shape[0] // n_dev
                for d in range(n_dev):
                    rows_d = int((corpus[d * slice_w:(d + 1) * slice_w,
                                         :, LANE_EVENT_ID] > 0)
                                 .any(axis=1).sum())
                    exec_scope.inc(m.device_metric(m.M_EXEC_ROWS, d),
                                   rows_d)
            if escalate_fn is not None:
                corpora[ci] = corpus
            extras = pack_extra(chunk_keys, plan) if pack_extra else None
            return corpus, extras

        def launch(ci, packed):
            corpus, extras = packed
            scope.inc(m.M_KERNEL_LAUNCHES)
            scope.inc(m.M_EVENTS_REPLAYED, events[ci])
            with prof.leg(m.M_PROFILE_H2D):
                corpus_dev = place_corpus(corpus, mesh)
                prof.h2d(corpus.nbytes)
            return launch_fn(corpus_dev, extras)

        def consume(ci, outs):
            with prof.leg(m.M_PROFILE_KERNEL):
                jax.block_until_ready(outs)
            with prof.leg(m.M_PROFILE_READBACK):
                return readback_fn(outs)

        def escalate(ci, consumed):
            return escalate_fn(ci, corpora.pop(ci), consumed)

        with scope.timed():
            results, _report = executor.run(
                len(plans), pack, launch, consume,
                escalate if escalate_fn is not None else None)
        self.last_run_chunk_shapes = [s for s in shapes if s is not None]
        t = self.metrics.timer(m.SCOPE_TPU_REPLAY, m.M_LATENCY)
        if t.total_s > 0:
            self.metrics.gauge(
                m.SCOPE_TPU_REPLAY, m.M_REPLAY_THROUGHPUT,
                self.metrics.counter(m.SCOPE_TPU_REPLAY, m.M_EVENTS_REPLAYED)
                / t.total_s)
        return results, plans

    def replay_tree_payloads(self, keys: Sequence[Tuple[str, str, str]]
                             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Device-replay full branch trees (divergent histories included);
        returns (payload rows, errors, device-chosen current branch).

        Chunked through the bulk executor: host packing overlaps device
        replay, each chunk's event axis is sized to ITS longest history
        (one long-tail workflow no longer inflates the whole corpus), and
        every launch is decomposed into pack/pack-queue-wait/h2d/kernel/
        readback legs so scrapes show which pipeline side is starving."""
        keys = list(keys)
        if not keys:
            width = self.layout.width
            return (np.zeros((0, width), dtype=np.int64),
                    np.zeros((0,), dtype=np.int32),
                    np.zeros((0,), dtype=np.int32))

        def launch(corpus_dev, _extras):
            state = replay_events(corpus_dev, self.layout)
            return (payload_rows(state, self.layout), state.error,
                    state.current_branch)

        def readback(outs):
            rows_dev, err_dev, branch_dev = outs
            return (np.asarray(rows_dev), np.asarray(err_dev),
                    np.asarray(branch_dev))

        results, plans = self._run_chunks(keys, None, launch, readback)
        rows = np.zeros((len(keys), self.layout.width), dtype=np.int64)
        errors = np.zeros((len(keys),), dtype=np.int32)
        branch = np.zeros((len(keys),), dtype=np.int32)
        for plan, (r, e, b) in zip(plans, results):
            rows[plan.idx] = r[plan.rows]
            errors[plan.idx] = e[plan.rows]
            branch[plan.idx] = b[plan.rows]
        return rows, errors, branch

    def _expected_row(self, key: Tuple[str, str, str]
                      ) -> Tuple[np.ndarray, int]:
        """The live mutable state's canonical payload row (sticky masked:
        replay always clears stickiness) and current branch index."""
        live_ms = self.stores.execution.get_workflow(*key)
        row = payload_row(live_ms, self.layout)
        row[STICKY_ROW_INDEX] = 0
        return row, live_ms.version_histories.current_index

    def _partition_resident(self, keys: List[Tuple[str, str, str]]):
        """Split keys by what the resident cache can serve: exact hits
        (no device work), suffix hits (replay appended batches only),
        and cold keys for the full-replay path. Non-single-lineage keys
        (an NDC branch switch happened since the state was pinned) and
        stale addresses (tail overwrite, reset rewrite) invalidate their
        entries here — the cache never serves across those mutations.

        Persisted snapshots turn the cold partition into a SUFFIX
        partition: a would-be-cold key with a valid snapshot hydrates
        the durable state row into the pool (engine/snapshot.py) and
        re-partitions as an exact/suffix hit — the warm-restart path of
        verify_all. Hydrated keys are returned so the result can report
        them."""
        from . import snapshot as snapshot_mod

        exact: List[Tuple[Tuple[str, str, str], object]] = []
        suffix: List[Tuple[Tuple[str, str, str], object, list]] = []
        cold: List[Tuple[str, str, str]] = []
        addresses: dict = {}
        hydrated: List[Tuple[str, str, str]] = []
        snapshots = getattr(self.stores, "snapshot", None)
        hs = self.stores.history
        for key in keys:
            if (hs.branch_count(*key) > 1
                    or hs.get_current_branch(*key) != 0):
                self.resident.invalidate(key)  # NDC branch switch
                cold.append(key)
                continue
            batches = hs.as_history_batches(*key)
            hit = self.resident.lookup(key, batches)
            if hit is None and snapshot_mod.seed_from_batches(
                    snapshots, self.resident, self.pack_cache, key,
                    batches, self.layout, self.metrics):
                hit = self.resident.lookup(key, batches)
                if hit is not None:
                    hydrated.append(key)
            if hit is None:
                addresses[key] = content_address(batches)
                cold.append(key)
            elif hit[0] == "exact":
                exact.append((key, hit[1]))
            else:
                suffix.append((key, hit[1], batches))
        return exact, suffix, cold, addresses, hydrated

    def verify_all(self, keys: Optional[Sequence[Tuple[str, str, str]]] = None
                   ) -> BulkVerifyResult:
        """Replay persisted histories on device and compare against the live
        mutable states (zero-divergence contract). The compare itself runs
        ON DEVICE: expected payload rows ship with the corpus and the host
        reads back a mismatch bitmap plus the error lanes — not the full
        [W, width] payload tensor.

        Incremental serving path: workflows whose final state is pinned
        in the HBM-resident cache (engine/resident.py) skip full replay —
        an unchanged history verifies against the cached payload with
        zero device work, an appended history replays ONLY the new
        batches against the resident state (O(new events) per
        transaction). Cold misses run the full chunked path below and
        seed the cache from their verified final states.

        Capacity-flagged rows (pending-table / version-history / branch
        overflow) escalate through the widened-K ladder: their rung-1
        re-replay is DISPATCHED from the executor's escalate hook as each
        chunk's errors read back — overlapping later chunks — and rungs
        ≥ 2 run once, batched across all chunks' survivors. Rows the
        ladder resolves verify against the live state at the base payload
        width, byte-identically to the oracle; only the ladder's residue
        (plus non-capacity errors) re-runs through the per-workflow
        oracle."""
        if keys is None:
            keys = self.stores.execution.list_executions()
        all_keys = list(keys)
        if not all_keys:
            return BulkVerifyResult(total=0, verified_on_device=0)
        # resolve (and wire) the serving mesh BEFORE the resident
        # partition: the pool's shard structure must be bound before any
        # lookup/admit decides which device slice a key belongs to
        self.mesh
        result = BulkVerifyResult(total=len(all_keys), verified_on_device=0)
        if resident_mod.enabled():
            exact, suffix, keys, addresses, hydrated = \
                self._partition_resident(all_keys)
            result.snapshot = hydrated
        else:
            exact, suffix, keys, addresses = [], [], all_keys, {}

        for key, entry in exact:
            row, br = self._expected_row(key)
            result.verified_on_device += 1
            result.resident.append(key)
            if not (entry.payload == row).all() or entry.branch != br:
                result.divergent.append(key)

        if suffix:
            outcomes = self.resident.replay_append(
                suffix, encode_suffix=self.pack_cache.encode_suffix)
            for (key, _entry, batches), res in zip(suffix, outcomes):
                row, br = self._expected_row(key)
                if not res.ok:
                    # entry already invalidated; the per-workflow oracle
                    # arbitrates, exactly like the cold path's residue
                    result.device_errors.append((key, int(res.error)))
                    result.fallback.append(key)
                    oracle_ms = StateBuilder().replay_history(batches)
                    if not (payload_row(oracle_ms, self.layout)
                            == row).all():
                        result.divergent.append(key)
                    continue
                result.verified_on_device += 1
                result.resident.append(key)
                if res.escalated:
                    result.escalated.append(key)
                if not (res.payload == row).all() or res.branch != br:
                    result.divergent.append(key)

        if not keys:
            return result
        from ..parallel.mesh import place_corpus
        mesh = self.mesh
        #: ci -> (capacity-flagged local key indices, pending rung-1
        #: dispatch)
        pending: dict = {}

        def pack_extra(chunk_keys, plan):
            # expected rows live in ROW space ([plan.W, ...]), scattered
            # to each key's shard slice so the on-device compare stays
            # local to the owning device; padding rows' entries are
            # zero-filled garbage the result loop never reads
            expected = np.zeros((plan.W, self.layout.width),
                                dtype=np.int64)
            exp_branch = np.zeros((plan.W,), dtype=np.int32)
            for j, key in enumerate(chunk_keys):
                live_ms = self.stores.execution.get_workflow(*key)
                row = payload_row(live_ms, self.layout)
                # sticky state is active-side only; replay clears it
                # (STICKY_ROW_INDEX note in core/checksum.py)
                row[STICKY_ROW_INDEX] = 0
                expected[plan.rows[j]] = row
                exp_branch[plan.rows[j]] = \
                    live_ms.version_histories.current_index
            return expected, exp_branch

        def launch(corpus_dev, extras):
            expected, exp_branch = extras
            state = replay_events(corpus_dev, self.layout)
            rows_dev = payload_rows(state, self.layout)
            mismatch = verify_rows(rows_dev, place_corpus(expected, mesh),
                                   state.current_branch,
                                   place_corpus(exp_branch, mesh))
            return mismatch, state.error, expected, exp_branch, state

        def readback(outs):
            mismatch_dev, err_dev, expected, exp_branch, state = outs
            return (np.asarray(mismatch_dev), np.asarray(err_dev),
                    expected, exp_branch, state)

        def escalate(ci, corpus, consumed):
            mismatch, errors, expected, exp_branch, state = consumed
            plan = plans_by_ci[ci]
            # errors come back in row space; flag capacity overflow on
            # REAL rows only and remember the flagged keys' positions
            cap_local = self.ladder.capacity_flagged(errors[plan.rows])
            if len(cap_local):
                cap_rows = np.asarray(plan.rows)[cap_local]
                pending[ci] = (cap_local, self.ladder.submit(
                    gather_subcorpus(corpus, cap_rows)))
            # seed the resident cache from this chunk's verified-clean
            # rows: the device row equals the shipped expected row
            # whenever the mismatch bit is clear, so admission costs one
            # state-row slice per key and zero extra readback (the cache
            # re-places the row on the key's owning device). The state
            # reference is dropped here (the ring keeps O(depth) alive).
            for j, i in enumerate(plan.idx):
                key = keys[i]
                r = int(plan.rows[j])
                if (errors[r] == 0 and not mismatch[r]
                        and key in addresses):
                    self.resident.admit(
                        key, addresses[key],
                        self.resident.extract_row(state, r),
                        expected[r], int(exp_branch[r]))
            return mismatch, errors, expected, exp_branch

        plans_by_ci = self._plan_chunks(keys)
        results, plans = self._run_chunks(keys, pack_extra, launch,
                                          readback, escalate,
                                          plans=plans_by_ci)
        ordered = sorted(pending.items())
        outcomes = self.ladder.finish([p for _, (_, p) in ordered])
        resolved = {}  # (ci, local j) -> (base-width ladder row, branch)
        for (ci, (cap, _)), outcome in zip(ordered, outcomes):
            for k, j in enumerate(cap):
                if outcome.resolved[k]:
                    resolved[(ci, int(j))] = (outcome.rows[k],
                                              outcome.branch[k])

        for ci, (plan, (mismatch, errors, expected, exp_branch)
                 ) in enumerate(zip(plans, results)):
            for j, i in enumerate(plan.idx):
                key = keys[i]
                r = int(plan.rows[j])
                if errors[r] != 0 and (ci, j) in resolved:
                    # the widened-K re-replay cleared the capacity flag:
                    # this row verified on device, no oracle involved.
                    # Same contract as verify_rows: payload rows AND the
                    # device-chosen branch must match the live state
                    result.verified_on_device += 1
                    result.escalated.append(key)
                    rows_l, branch_l = resolved[(ci, j)]
                    if (not (rows_l == expected[r]).all()
                            or branch_l != exp_branch[r]):
                        result.divergent.append(key)
                elif errors[r] != 0:
                    # top-rung overflow or a non-capacity error: the
                    # per-workflow oracle arbitrates, as before
                    result.device_errors.append((key, int(errors[r])))
                    result.fallback.append(key)
                    oracle_ms = StateBuilder().replay_history(
                        self.stores.history.as_history_batches(*key))
                    if not (payload_row(oracle_ms, self.layout)
                            == expected[r]).all():
                        result.divergent.append(key)
                else:
                    result.verified_on_device += 1
                    if mismatch[r]:
                        result.divergent.append(key)
        return result
