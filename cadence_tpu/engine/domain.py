"""Domain update/deprecate + attribute validation.

Reference: common/domain/handler.go (UpdateDomain/DeprecateDomain) and
common/domain/attrValidator.go — retention bounds, replication-config
rules (clusters can be added, never removed; the active cluster must be a
member), and the failover-version bump when the active cluster moves.
Updates bump the notification version so caches/watchers can observe
change order (DomainCache refresh contract).
"""
from __future__ import annotations

from dataclasses import replace
from typing import Optional, Sequence

from .cluster import ClusterMetadata
from .persistence import (
    DOMAIN_STATUS_DEPRECATED,
    DOMAIN_STATUS_REGISTERED,
    DomainInfo,
)

MIN_RETENTION_DAYS = 1  # attrValidator.go minRetentionDays


class DomainValidationError(Exception):
    """attrValidator rejection (BadRequestError in the reference)."""


class DomainNotActiveError(Exception):
    """A global domain's active-cluster API hit a PASSIVE cluster
    (types.DomainNotActiveError): callers should retry against the
    active cluster — or go through the cluster redirection frontend,
    which forwards for them (engine/redirection.py)."""

    def __init__(self, domain: str, active_cluster: str,
                 current_cluster: str) -> None:
        super().__init__(
            f"domain {domain} is active in {active_cluster!r}, not "
            f"{current_cluster!r}")
        self.domain = domain
        self.active_cluster = active_cluster
        self.current_cluster = current_cluster

    def __reduce__(self):
        # pickle-safe across the wire: default exception reduction passes
        # self.args (the formatted message) to __init__, whose signature
        # is the three fields — reconstruct from those instead
        return (DomainNotActiveError,
                (self.domain, self.active_cluster, self.current_cluster))


def require_active(info, local_cluster: str) -> None:
    """Active-cluster gate for mutating APIs on GLOBAL domains
    (historyEngine's domain-active check). Local (single-cluster)
    domains are always active wherever they live."""
    if len(info.clusters) > 1 and info.active_cluster != local_cluster:
        raise DomainNotActiveError(info.name, info.active_cluster,
                                   local_cluster)


def validate_retention(retention_days: int) -> None:
    if retention_days < MIN_RETENTION_DAYS:
        raise DomainValidationError(
            f"retention {retention_days}d below minimum "
            f"{MIN_RETENTION_DAYS}d (attrValidator.go)")


def validate_cluster_change(info: DomainInfo,
                            clusters: Optional[Sequence[str]],
                            active_cluster: Optional[str],
                            meta: ClusterMetadata) -> None:
    new_clusters = tuple(clusters) if clusters is not None else info.clusters
    if not new_clusters:
        raise DomainValidationError("domain must have at least one cluster")
    for c in new_clusters:
        if c not in meta.cluster_names:
            raise DomainValidationError(
                f"cluster {c!r} not in the cluster group {meta.cluster_names}")
    removed = set(info.clusters) - set(new_clusters)
    if removed:
        # validateDomainReplicationConfigClustersDoesNotRemove
        raise DomainValidationError(
            f"clusters can only be added, not removed (removing {sorted(removed)})")
    target_active = (active_cluster if active_cluster is not None
                     else info.active_cluster)
    if target_active not in new_clusters:
        raise DomainValidationError(
            f"active cluster {target_active!r} is not in {new_clusters}")


def update_domain(stores, name: str, *, local_cluster: str,
                  meta: Optional[ClusterMetadata] = None,
                  retention_days: Optional[int] = None,
                  description: Optional[str] = None,
                  clusters: Optional[Sequence[str]] = None,
                  active_cluster: Optional[str] = None,
                  history_archival_uri: Optional[str] = None) -> DomainInfo:
    """UpdateDomain (workflowHandler.go:386 → domain/handler.go): validate,
    apply, bump notification version; moving the active cluster is a
    FAILOVER and advances the failover version to the target's next slot
    (so events written after the update stamp the new version — the NDC
    ordering contract)."""
    meta = meta or ClusterMetadata()
    info = stores.domain.by_name(name)
    if info.status == DOMAIN_STATUS_DEPRECATED:
        raise DomainValidationError(f"domain {name} is deprecated")
    if retention_days is not None:
        validate_retention(retention_days)
    if clusters is not None or active_cluster is not None:
        # replication-config rules apply only when the config changes: a
        # description-only update must not re-litigate an existing cluster
        # set against a different cluster group's metadata
        validate_cluster_change(info, clusters, active_cluster, meta)
    if history_archival_uri:
        from .archival import ArchivalError, archiver_for
        try:
            archiver_for(history_archival_uri)
        except ArchivalError as exc:
            raise DomainValidationError(str(exc))

    updated = replace(info)
    if retention_days is not None:
        updated.retention_days = retention_days
    if description is not None:
        updated.description = description
    if clusters is not None:
        updated.clusters = tuple(clusters)
    if history_archival_uri is not None:
        updated.history_archival_uri = history_archival_uri
    if active_cluster is not None and active_cluster != info.active_cluster:
        updated.active_cluster = active_cluster
        updated.failover_version = meta.next_failover_version(
            active_cluster, info.failover_version)
        updated.is_active = active_cluster == local_cluster
    updated.notification_version = info.notification_version + 1
    stores.domain.update(updated)
    return updated


def deprecate_domain(stores, name: str) -> DomainInfo:
    """DeprecateDomain: new starts are rejected; running workflows finish
    (domain/handler.go DeprecateDomain)."""
    info = stores.domain.by_name(name)
    updated = replace(info,
                      status=DOMAIN_STATUS_DEPRECATED,
                      notification_version=info.notification_version + 1)
    stores.domain.update(updated)
    return updated


def require_startable(info: DomainInfo) -> None:
    """Starts (incl. signal-with-start's start arm) need a live domain."""
    if info.status != DOMAIN_STATUS_REGISTERED:
        raise DomainValidationError(
            f"domain {info.name} is deprecated; new workflows are rejected")
