"""Managed failover: the failover workflow as an explicit coordinator.

Reference: service/worker/failovermanager/workflow.go — an operator
kicks off a failover workflow that processes domains in batches: drain
replication, flip the active cluster, verify, report per-domain status;
`rebalance` moves every mis-homed domain. The reference runs this as a
system workflow on the Cadence SDK; here it is a coordinator with the
same step structure and per-domain failure isolation, driven by the
operator (or a cron'd host loop).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..utils.log import DEFAULT_LOGGER

STATUS_SUCCESS = "success"
STATUS_FAILED = "failed"
STATUS_SKIPPED = "skipped"


@dataclass
class DomainFailoverResult:
    domain: str
    status: str
    detail: str = ""
    new_failover_version: Optional[int] = None


@dataclass
class FailoverReport:
    to_cluster: str
    results: List[DomainFailoverResult] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(r.status != STATUS_FAILED for r in self.results)

    @property
    def succeeded(self) -> int:
        return sum(1 for r in self.results if r.status == STATUS_SUCCESS)


class FailoverManager:
    def __init__(self, clusters) -> None:
        self.clusters = clusters
        self.log = DEFAULT_LOGGER.with_tags(component="failovermanager")

    def _box(self, cluster: str):
        return (self.clusters.active if cluster == "primary"
                else self.clusters.standby)

    def managed_failover(self, domains: List[str],
                         to_cluster: str = "standby",
                         batch_size: int = 2) -> FailoverReport:
        """Failover workflow body (failovermanager/workflow.go): domains
        process in batches; per domain — drain replication so the target
        is caught up, flip the active cluster through the ACTIVE side's
        UpdateDomain (stamping the next failover version), stream the
        flip to the peer, regenerate the new active side's tasks, and
        verify both sides agree. One bad domain never aborts the rest."""
        report = FailoverReport(to_cluster=to_cluster)
        for lo in range(0, len(domains), batch_size):
            # ONE full replication drain per BATCH — the cost batching
            # amortizes (the reference pages domains for the same reason)
            try:
                self.clusters.replicate()
                self.clusters.replicate_reverse()
            except Exception as exc:
                for name in domains[lo:lo + batch_size]:
                    report.results.append(DomainFailoverResult(
                        name, STATUS_FAILED, f"drain failed: {exc}"))
                continue
            for name in domains[lo:lo + batch_size]:
                report.results.append(self._failover_one(name, to_cluster))
        self.log.info("managed failover finished", to=to_cluster,
                      succeeded=report.succeeded,
                      failed=sum(1 for r in report.results
                                 if r.status == STATUS_FAILED))
        return report

    def _failover_one(self, name: str,
                      to_cluster: str) -> DomainFailoverResult:
        from .multicluster import _refresh_domain_tasks
        try:
            current = self.clusters.active.stores.domain.by_name(name)
        except Exception as exc:
            return DomainFailoverResult(name, STATUS_FAILED, str(exc))
        if len(current.clusters) < 2:
            return DomainFailoverResult(name, STATUS_SKIPPED,
                                        "local (single-cluster) domain")
        if current.active_cluster == to_cluster:
            return DomainFailoverResult(name, STATUS_SKIPPED,
                                        f"already active in {to_cluster}")
        try:
            # (the batch loop already drained replication for this batch)
            # flip through the active side's UpdateDomain (validated,
            #    notification-ordered, failover-version advanced)
            source = self._box(current.active_cluster)
            updated = source.frontend.update_domain(
                name, active_cluster=to_cluster)
            # 3. stream the flip to the peer
            self.clusters.replicate_domains()
            # 4. the new active side regenerates outstanding tasks
            #    (standby promotion sweep, task_refresher)
            _refresh_domain_tasks(self._box(to_cluster), name)
            # 5. verify convergence
            for box in (self.clusters.active, self.clusters.standby):
                d = box.stores.domain.by_name(name)
                if d.active_cluster != to_cluster:
                    raise RuntimeError(
                        f"{box.cluster_name} still says active="
                        f"{d.active_cluster}")
            self.log.info("domain failed over", domain=name, to=to_cluster,
                          failover_version=updated.failover_version)
            return DomainFailoverResult(name, STATUS_SUCCESS,
                                        new_failover_version=(
                                            updated.failover_version))
        except Exception as exc:  # per-domain isolation, batcher posture
            self.log.error("domain failover failed", domain=name,
                           error=str(exc))
            return DomainFailoverResult(name, STATUS_FAILED, str(exc))

    def rebalance(self, home_cluster: str = "primary") -> FailoverReport:
        """Rebalance workflow (failovermanager/rebalance.go): move every
        GLOBAL domain whose active cluster is not its home back home."""
        mis_homed = [d.name
                     for d in self.clusters.active.stores.domain.list_domains()
                     if len(d.clusters) > 1 and d.active_cluster != home_cluster]
        return self.managed_failover(mis_homed, to_cluster=home_cluster)
