"""Managed failover: the failover workflow as an explicit coordinator.

Reference: service/worker/failovermanager/workflow.go — an operator
kicks off a failover workflow that processes domains in batches: drain
replication, flip the active cluster, verify, report per-domain status;
`rebalance` moves every mis-homed domain. The reference runs this as a
system workflow on the Cadence SDK; here it is a coordinator with the
same step structure and per-domain failure isolation, driven by the
operator (or a cron'd host loop).

Warm promotion (ROADMAP item 2): the graceful path drains in-flight
replication acks under a BOUNDED deadline — a source that cannot drain
in time degrades to NDC conflict resolution on the promoted side
instead of blocking the failover — and pre-hydrates the promoting
cluster's serving tier from its shipped snapshots before the flip, so
the first post-failover transactions land on resident HBM rows.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..utils.log import DEFAULT_LOGGER
from .multicluster import _refresh_domain_tasks, prehydrate_serving

STATUS_SUCCESS = "success"
STATUS_FAILED = "failed"
STATUS_SKIPPED = "skipped"

#: default bounded-drain deadline per batch: long enough for any sane
#: in-flight backlog, short enough that a wedged peer never turns a
#: planned failover into an outage (the degrade path is NDC conflict
#: resolution, which the replicator runs anyway on late arrivals)
DRAIN_DEADLINE_S = 10.0


@dataclass
class DomainFailoverResult:
    domain: str
    status: str
    detail: str = ""
    new_failover_version: Optional[int] = None


@dataclass
class FailoverReport:
    to_cluster: str
    results: List[DomainFailoverResult] = field(default_factory=list)
    #: batches whose replication drain hit the deadline and degraded to
    #: NDC conflict resolution instead of blocking the flip
    drain_degraded: int = 0
    #: pre-flip serving-tier hydration rollup (multicluster.
    #: prehydrate_serving) — None when the promoting box has no snapshots
    prehydration: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return all(r.status != STATUS_FAILED for r in self.results)

    @property
    def succeeded(self) -> int:
        return sum(1 for r in self.results if r.status == STATUS_SUCCESS)


class FailoverManager:
    def __init__(self, clusters) -> None:
        self.clusters = clusters
        self.log = DEFAULT_LOGGER.with_tags(component="failovermanager")

    def _box(self, cluster: str):
        return (self.clusters.active if cluster == "primary"
                else self.clusters.standby)

    def _bounded_drain(self, deadline_s: float) -> bool:
        """Drain both replication directions until quiet or the deadline.
        Returns True when fully drained; False degrades the batch to NDC
        conflict resolution (the standby replicator reconciles whatever
        arrives after the flip via branch selection + version arbitration)
        — a slow peer costs consistency work, never availability."""
        deadline = time.monotonic() + max(0.0, deadline_s)
        proc = getattr(self.clusters, "processor", None)
        if proc is None:
            # wire group: the consumers run inside the service hosts'
            # leader pumps; bound their drain wait with OUR deadline by
            # shadowing the group's timeout for this pass
            saved = self.clusters.DRAIN_TIMEOUT_S
            self.clusters.DRAIN_TIMEOUT_S = max(0.0, deadline_s)
            try:
                self.clusters.replicate_domains()
                self.clusters.replicate()
                self.clusters.replicate_reverse()
                return True
            except TimeoutError:
                return False
            finally:
                self.clusters.DRAIN_TIMEOUT_S = saved
        # incremental passes, not the unbounded replicate() loop: each
        # process_once is one queue page, so the deadline is honored even
        # against a source that keeps publishing
        self.clusters.replicate_domains()
        while time.monotonic() < deadline:
            moved = (proc.process_once()
                     + self.clusters.reverse_processor.process_once())
            if moved == 0:
                return True
        return False

    def _prehydrate(self, box) -> Optional[dict]:
        """Pre-flip serving-tier hydration for either box flavor: an
        in-process Onebox hydrates directly; a WireBox fans the
        admin_prehydrate op to every live host (each hydrates its OWN
        shards — only the leader would see a replicated flip)."""
        if getattr(box, "tpu", None) is not None:
            return prehydrate_serving(box)
        wire = getattr(box, "wire", None)
        if wire is None:
            return None
        rollup = {"considered": 0, "hydrated": 0, "suffix_events": 0,
                  "cold": 0, "young": 0, "stale": 0, "already_resident": 0,
                  "parity_divergence": 0, "hosts": 0}
        for name in sorted(wire.hosts):
            if wire.procs[name].poll() is not None:
                continue
            try:
                rep = wire.admin(name, "admin_prehydrate")
            except Exception:
                continue  # serving tier off (or host mid-restart)
            rollup["hosts"] += 1
            for k, v in rep.items():
                if k in rollup and k != "hosts":
                    rollup[k] += int(v)
        return rollup if rollup["hosts"] else None

    def managed_failover(self, domains: List[str],
                         to_cluster: str = "standby",
                         batch_size: int = 2,
                         drain_deadline_s: float = DRAIN_DEADLINE_S
                         ) -> FailoverReport:
        """Failover workflow body (failovermanager/workflow.go): domains
        process in batches; per domain — drain replication so the target
        is caught up (bounded; a deadline miss degrades to NDC conflict
        resolution rather than blocking), flip the active cluster through
        the ACTIVE side's UpdateDomain (stamping the next failover
        version), stream the flip to the peer, regenerate the new active
        side's tasks, and verify both sides agree. One bad domain never
        aborts the rest. The promoting cluster's serving tier pre-hydrates
        from shipped snapshots ONCE, before any flip."""
        report = FailoverReport(to_cluster=to_cluster)
        try:
            report.prehydration = self._prehydrate(self._box(to_cluster))
        except Exception as exc:
            # hydration is an optimization: a failure costs cold admits
            # on first touch, never the failover itself
            self.log.error("pre-flip hydration failed", error=str(exc))
        for lo in range(0, len(domains), batch_size):
            # ONE bounded replication drain per BATCH — the cost batching
            # amortizes (the reference pages domains for the same reason)
            try:
                if not self._bounded_drain(drain_deadline_s):
                    report.drain_degraded += 1
                    self.log.info(
                        "drain deadline hit; degrading to NDC "
                        "conflict resolution", deadline_s=drain_deadline_s)
            except Exception as exc:
                for name in domains[lo:lo + batch_size]:
                    report.results.append(DomainFailoverResult(
                        name, STATUS_FAILED, f"drain failed: {exc}"))
                continue
            for name in domains[lo:lo + batch_size]:
                report.results.append(self._failover_one(name, to_cluster))
        self.log.info("managed failover finished", to=to_cluster,
                      succeeded=report.succeeded,
                      degraded_drains=report.drain_degraded,
                      failed=sum(1 for r in report.results
                                 if r.status == STATUS_FAILED))
        return report

    def _failover_one(self, name: str,
                      to_cluster: str) -> DomainFailoverResult:
        try:
            current = self.clusters.active.stores.domain.by_name(name)
        except Exception as exc:
            return DomainFailoverResult(name, STATUS_FAILED, str(exc))
        if len(current.clusters) < 2:
            return DomainFailoverResult(name, STATUS_SKIPPED,
                                        "local (single-cluster) domain")
        if current.active_cluster == to_cluster:
            return DomainFailoverResult(name, STATUS_SKIPPED,
                                        f"already active in {to_cluster}")
        try:
            # (the batch loop already drained replication for this batch)
            # flip through the active side's UpdateDomain (validated,
            #    notification-ordered, failover-version advanced)
            source = self._box(current.active_cluster)
            updated = source.frontend.update_domain(
                name, active_cluster=to_cluster)
            # 3. stream the flip to the peer
            self.clusters.replicate_domains()
            # 4. the new active side regenerates outstanding tasks
            #    (standby promotion sweep, task_refresher)
            _refresh_domain_tasks(self._box(to_cluster), name)
            # 5. verify convergence
            for box in (self.clusters.active, self.clusters.standby):
                d = box.stores.domain.by_name(name)
                if d.active_cluster != to_cluster:
                    raise RuntimeError(
                        f"{box.cluster_name} still says active="
                        f"{d.active_cluster}")
            self.log.info("domain failed over", domain=name, to=to_cluster,
                          failover_version=updated.failover_version)
            return DomainFailoverResult(name, STATUS_SUCCESS,
                                        new_failover_version=(
                                            updated.failover_version))
        except Exception as exc:  # per-domain isolation, batcher posture
            self.log.error("domain failover failed", domain=name,
                           error=str(exc))
            return DomainFailoverResult(name, STATUS_FAILED, str(exc))

    def rebalance(self, home_cluster: str = "primary") -> FailoverReport:
        """Rebalance workflow (failovermanager/rebalance.go): move every
        GLOBAL domain whose active cluster is not its home back home."""
        mis_homed = [d.name
                     for d in self.clusters.active.stores.domain.list_domains()
                     if len(d.clusters) > 1 and d.active_cluster != home_cluster]
        return self.managed_failover(mis_homed, to_cluster=home_cluster)
