"""Shard controller: shard ownership driven by the membership hashring.

Reference: service/history/shard/controller.go — each history host runs a
controller that acquires the shards the hashring assigns to it
(acquireShards:381) and releases the rest (shardClosedCallback:258);
engines are created per shard through the EngineFactory seam (:55-58,
default factory at handler.go:266). That seam is exactly where this
framework's TPU engine plugs in (tpu_engine.py).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..utils import flightrecorder
from ..utils import metrics as cm
from ..utils.clock import TimeSource
from .history_engine import HistoryEngine
from .membership import HashRing, shard_id_for_workflow
from .persistence import Stores
from .shard import ShardContext

EngineFactory = Callable[[ShardContext], HistoryEngine]


class ShardController:
    def __init__(self, host: str, num_shards: int, stores: Stores,
                 ring: HashRing, time_source: TimeSource,
                 engine_factory: Optional[EngineFactory] = None) -> None:
        self.host = host
        self.num_shards = num_shards
        self.stores = stores
        self.ring = ring
        self.clock = time_source
        self._factory = engine_factory or self._default_factory
        self._lock = threading.Lock()
        self._engines: Dict[int, HistoryEngine] = {}
        #: shard-movement hooks (engine/migration.MigrationManager):
        #: `on_shards_released(ids)` fires after the ring takes shards
        #: away (engines closed — the losing side persists its resident
        #: rows), `on_shards_acquired(ids)` after eager acquisition
        #: creates engines for newly assigned shards (the gaining side
        #: hydrates). Both best-effort: a hook failure must never block
        #: membership convergence.
        self.on_shards_released: Optional[Callable[[list], None]] = None
        self.on_shards_acquired: Optional[Callable[[list], None]] = None
        #: shards whose acquire hook has fired for the CURRENT ownership
        #: epoch (cleared on release) — membership, not engine presence,
        #: decides hook delivery: a routed request racing the ring flip
        #: can create the engine before ensure_assigned looks, and an
        #: existence check would then suppress the hook forever
        self._acquire_notified: set = set()
        #: counter sink — rebindable so a ServiceHost's own registry (the
        #: one its /metrics scrape serves) sees the eviction witness
        self.metrics = cm.DEFAULT_REGISTRY
        ring.subscribe(self._on_membership_change)

    def _default_factory(self, shard: ShardContext) -> HistoryEngine:
        return HistoryEngine(shard, self.stores, self.clock)

    def _owns(self, shard_id: int) -> bool:
        return self.ring.lookup(f"shard-{shard_id}") == self.host

    def shard_for(self, workflow_id: str) -> int:
        return shard_id_for_workflow(workflow_id, self.num_shards)

    def engine_for_shard(self, shard_id: int) -> HistoryEngine:
        """GetEngineForShard (controller.go:199-211): create+acquire lazily.

        A cached engine whose shard context was FENCED (another owner bumped
        the range while this host was partitioned/paused) is evicted and
        re-acquired — a restored host must not serve a deposed context
        forever (controller.go shardClosedCallback:258)."""
        if not self._owns(shard_id):
            raise ShardNotOwnedError(
                f"host {self.host} does not own shard {shard_id} "
                f"(owner: {self.ring.lookup(f'shard-{shard_id}')})"
            )
        with self._lock:
            engine = self._engines.get(shard_id)
            if engine is not None and engine.shard.is_closed:
                del self._engines[shard_id]
                engine = None
                # flap-back witness: a deposed context got evicted and is
                # about to re-acquire — the counter lets chaos campaigns
                # assert the fence actually fired on a restored host
                self.metrics.inc(cm.SCOPE_CONTROLLER,
                                 cm.M_FENCED_EVICTIONS)
                flightrecorder.emit("shard-fenced-evict", host=self.host,
                                    shard=shard_id)
            if engine is None:
                ctx = ShardContext(shard_id, self.host, self.stores)
                ctx.acquire()
                engine = self._factory(ctx)
                self._engines[shard_id] = engine
            return engine

    def cached_engine(self, shard_id: int) -> Optional[HistoryEngine]:
        """The engine object currently cached for a shard, WITHOUT ring
        validation or acquisition — admin/introspection only (the
        deposed-owner fencing probe and DescribeHistoryHost analog)."""
        with self._lock:
            return self._engines.get(shard_id)

    def engine_for_workflow(self, workflow_id: str) -> HistoryEngine:
        return self.engine_for_shard(self.shard_for(workflow_id))

    def owned_shards(self):
        with self._lock:
            return sorted(self._engines.keys())

    def assigned_shards(self):
        """All shards the ring currently assigns to this host (whether or not
        an engine exists yet) — what the queue processors must sweep."""
        return [s for s in range(self.num_shards) if self._owns(s)]

    def _on_membership_change(self) -> None:
        """acquireShards (controller.go:381): release shards the ring no
        longer assigns here and eagerly acquire newly assigned ones, so
        their queues resume from persisted ack levels without waiting for a
        routed request."""
        released = []
        with self._lock:
            for shard_id in list(self._engines.keys()):
                if not self._owns(shard_id):
                    self._engines[shard_id].shard.close()
                    del self._engines[shard_id]
                    released.append(shard_id)
                    self._acquire_notified.discard(shard_id)
        if released and self.on_shards_released is not None:
            try:
                self.on_shards_released(released)
            except Exception:
                pass  # migration is best-effort; convergence is not
        self.ensure_assigned()

    def ensure_assigned(self) -> None:
        """Idempotent eager acquisition of every assigned shard. Per-shard
        failures (store briefly unreachable, ring moved mid-loop) skip that
        shard — the next call, routed request, or queue pump retries; one
        bad shard must never abort acquisition of the rest. Newly created
        engines fire the acquire hook (the in-migration seam) — also on a
        later retry beat, so a shard whose first acquisition failed still
        hydrates when it finally lands."""
        acquired = []
        for shard_id in self.assigned_shards():
            try:
                self.engine_for_shard(shard_id)
            except Exception:
                continue
            with self._lock:
                if shard_id not in self._acquire_notified:
                    self._acquire_notified.add(shard_id)
                    acquired.append(shard_id)
        if acquired and self.on_shards_acquired is not None:
            try:
                self.on_shards_acquired(acquired)
            except Exception:
                pass


class ShardNotOwnedError(Exception):
    """Routing error: caller must redirect to the owning host (the
    client/history peer-resolver redirect analog)."""
